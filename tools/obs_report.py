"""obs_report — join a flight-recorder dump with a telemetry trace.

The post-mortem tool of the observability stack: the flight recorder
(``bigdl_tpu/telemetry/flight.py``) leaves a crash-surviving JSONL
stream of structured events (failovers, quarantines, breaker trips,
checkpoint commits, ...), and the tracer leaves a Chrome-trace JSON of
spans/instants.  Each alone is half the story — this tool merges them
onto ONE wall-clock axis and groups by ``trace_id``, so "what happened
to request X" reads as a timeline:

    12:03:01.123  [resilience] request_route   replica=0   trace=ab12…
    12:03:01.640  [resilience] replica_death   replica=0
    12:03:01.641  [resilience] failover        replica=0 → retry
    12:03:01.644  [resilience] request_route   replica=2
    12:03:01.650  [serving]    dispatch        ok

Clock alignment: the flight meta header records a paired
``(unix_ns, perf_ns)`` anchor sampled at recorder creation; tracer
timestamps are ``perf_counter_ns``-based microseconds, so
``wall = (ts_us·1e3 − perf_ns + unix_ns) / 1e9`` places trace events on
the same axis (only valid for a trace from the SAME process as the
dump — obs_report says so when the pids disagree is unknowable, so it
just aligns).

Usage::

    python -m tools.obs_report flight.jsonl
    python -m tools.obs_report flight.jsonl --trace trace.json
    python -m tools.obs_report flight.jsonl --trace-id ab12cd34ef56aa01
    python -m tools.obs_report flight.jsonl --json

Exit codes: 0 = report printed, 2 = unreadable/invalid input.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from collections import defaultdict
from typing import Dict, List, Optional

from bigdl_tpu.telemetry.flight import load_dump

# trace categories worth folding into a post-mortem timeline (driver
# pipeline spans are volume, not story — trace_report covers those)
_STORY_CATS = {"resilience", "serving", "driver"}


def _wall_from_trace_ts(ts_us: float, meta: dict) -> Optional[float]:
    """Chrome-trace ts (µs, perf_counter base) → unix seconds, via the
    flight meta's paired clock anchor.  None when the dump predates the
    anchor fields."""
    if not meta or "perf_ns" not in meta or "unix_ns" not in meta:
        return None
    return (ts_us * 1e3 - meta["perf_ns"] + meta["unix_ns"]) / 1e9


def _trace_story_rows(trace: dict, meta: dict) -> List[dict]:
    rows = []
    for e in trace.get("traceEvents", []):
        ph = e.get("ph")
        cat = e.get("cat")
        if cat not in _STORY_CATS or ph not in ("X", "i"):
            continue
        wall = _wall_from_trace_ts(e.get("ts", 0.0), meta)
        if wall is None:
            continue
        args = e.get("args") or {}
        row = {"t_unix": wall, "src": "trace",
               "kind": "span" if ph == "X" else "instant",
               "name": e.get("name"), "cat": cat}
        detail = {k: v for k, v in args.items() if k != "trace_ids"}
        if detail.get("trace_id"):
            row["trace_id"] = detail.pop("trace_id")
        if detail:
            row["args"] = detail
        fan_in = args.get("trace_ids") or []
        if fan_in:
            # a serving dispatch span fans in N requests — one timeline
            # row per request so every story sees its dispatch
            rows.extend({**row, "trace_id": t} for t in fan_in)
        else:
            rows.append(row)
    return rows


def summarize(flight_blob: dict, trace: Optional[dict] = None,
              trace_id: Optional[str] = None,
              tenant: Optional[str] = None) -> dict:
    """Merge one flight dump (``telemetry.flight.load_dump``) and an
    optional Chrome trace into the report dict (the schema the fixture
    test gates).

    ``tenant`` narrows the report to one tenant's request stories: a
    trace id belongs to tenant T when ANY of its rows carries
    ``tenant: T`` (the wire frontend stamps it on ``wire_request`` /
    ``request_submit`` spans via the RequestContext), and the timeline
    keeps only those requests' rows — so "what happened to acme's
    traffic during the incident" is one flag."""
    meta = flight_blob.get("meta") or {}
    events = list(flight_blob.get("events") or [])
    if not events and trace is None:
        raise ValueError("flight dump contains no events")

    timeline: List[dict] = []
    for e in events:
        row = {"t_unix": float(e.get("t_unix", 0.0)), "src": "flight",
               "kind": "event", "name": e.get("event"),
               "cat": e.get("cat", "event")}
        if e.get("trace_id"):
            row["trace_id"] = e["trace_id"]
        detail = {k: v for k, v in e.items()
                  if k not in ("event", "cat", "t_unix", "perf_ns",
                               "trace_id")}
        if detail:
            row["args"] = detail
        timeline.append(row)
    if trace is not None:
        timeline.extend(_trace_story_rows(trace, meta))
    timeline.sort(key=lambda r: r["t_unix"])

    if trace_id is not None:
        timeline = [r for r in timeline
                    if r.get("trace_id") == trace_id]
    if tenant is not None:
        tenant_tids = {r["trace_id"] for r in timeline
                       if r.get("trace_id")
                       and (r.get("args") or {}).get("tenant") == tenant}
        timeline = [r for r in timeline
                    if r.get("trace_id") in tenant_tids]

    counts: Dict[str, int] = defaultdict(int)
    cats: Dict[str, int] = defaultdict(int)
    for r in timeline:
        counts[r["name"]] += 1
        cats[r["cat"]] += 1

    # per-request stories: every trace_id seen, with its ordered rows;
    # "failed_over" flags the ones worth reading first
    stories: Dict[str, List[dict]] = defaultdict(list)
    for r in timeline:
        if r.get("trace_id"):
            stories[r["trace_id"]].append(r)
    requests = []
    for tid, rows in sorted(stories.items()):
        names = [r["name"] for r in rows]
        requests.append({
            "trace_id": tid,
            "n_events": len(rows),
            "failed_over": "failover" in names,
            "events": names,
            "t_first": rows[0]["t_unix"],
            "t_last": rows[-1]["t_unix"],
        })

    return {
        "meta": {"pid": meta.get("pid"), "schema": meta.get("schema"),
                 "trace_joined": trace is not None},
        "event_counts": dict(sorted(counts.items())),
        "categories": dict(sorted(cats.items())),
        "n_rows": len(timeline),
        "n_requests": len(requests),
        "n_failed_over": sum(1 for r in requests if r["failed_over"]),
        "requests": requests,
        "timeline": timeline,
    }


def _fmt_t(t_unix: float) -> str:
    frac = f"{t_unix % 1:.3f}"[1:]
    return time.strftime("%H:%M:%S", time.localtime(t_unix)) + frac


def _render(report: dict, limit: int = 200) -> str:
    lines = [f"flight dump: {report['n_rows']} timeline rows, "
             f"{report['n_requests']} traced request(s), "
             f"{report['n_failed_over']} failed over"
             + ("" if report["meta"]["trace_joined"]
                else "  (no trace joined — pass --trace)")]
    lines.append("event counts: " + (", ".join(
        f"{k}×{v}" for k, v in report["event_counts"].items())
        or "none"))
    lines.append("timeline:")
    for r in report["timeline"][:limit]:
        tid = f"  trace={r['trace_id'][:8]}…" if r.get("trace_id") else ""
        args = ""
        if r.get("args"):
            args = "  " + json.dumps(r["args"], sort_keys=True,
                                     default=str)
        src = "fl" if r["src"] == "flight" else "tr"
        lines.append(f"  {_fmt_t(r['t_unix'])}  {src} [{r['cat']:<10}] "
                     f"{r['name']}{tid}{args}")
    if len(report["timeline"]) > limit:
        lines.append(f"  ... {len(report['timeline']) - limit} more "
                     f"(use --json)")
    failed = [r for r in report["requests"] if r["failed_over"]]
    if failed:
        lines.append("failed-over requests:")
        for r in failed:
            lines.append(f"  {r['trace_id']}: " + " → ".join(r["events"]))
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tools.obs_report",
        description="Join a flight-recorder dump with a telemetry "
                    "trace into a post-mortem timeline")
    p.add_argument("flight", help="flight-recorder JSONL stream or "
                                  "dump() JSON (FlightRecorder)")
    p.add_argument("--trace", help="Chrome-trace JSON from the same "
                                   "process (Tracer.dump / /trace)")
    p.add_argument("--trace-id", dest="trace_id",
                   help="only the timeline of one request/run")
    p.add_argument("--tenant",
                   help="only requests tagged with this tenant "
                        "(wire frontend X-Tenant / RequestContext)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the report as JSON")
    p.add_argument("--limit", type=int, default=200,
                   help="max timeline rows in the human rendering")
    args = p.parse_args(argv)
    try:
        blob = load_dump(args.flight)
        trace = None
        if args.trace:
            from tools.trace_report import load_trace
            trace = load_trace(args.trace)
        report = summarize(blob, trace=trace, trace_id=args.trace_id,
                           tenant=args.tenant)
    except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
        print(f"obs_report: {e}", file=sys.stderr)
        return 2
    print(json.dumps(report, default=str) if args.as_json
          else _render(report, limit=args.limit))
    return 0


if __name__ == "__main__":
    sys.exit(main())
