"""Cross-process SPMD divergence model (the GL4xx family's engine).

Multi-host SPMD has one cardinal invariant: **every process issues the
same collectives in the same order**.  The failure mode is a branch
whose predicate only one host can evaluate truthfully — process index,
a clock, a filesystem probe, a per-host counter — sitting above a
collective: the processes disagree, the collective goes one-sided, and
the pod deadlocks (the PR-7 ``last_saved_step`` class).  Pure AST, per
file, never imports the linted code — same ground rules as the traced/
thread/resource models.

Three ingredients:

1. **Collective reachability.**  Host-side multihost collectives
   (``process_allgather``, ``sync_global_devices``,
   ``broadcast_one_to_all``, ``make_array_from_process_local_data``)
   and the in-program ``lax`` collectives, closed over same-file calls
   (callables handed to ``tree_map``/combinators count as called), plus
   the documented cross-file boundary methods
   (:data:`COLLECTIVE_BOUNDARY_METHODS` — catalog note "multihost
   collective boundaries").

2. **Process-local taint.**  Expressions derived from sources only one
   host can see (:data:`PROCESS_LOCAL_CALLS`,
   :data:`DIVERGENT_ATTRS`), propagated through same-function name
   assignments.  Everything else is assumed uniform — divergence
   enters through sources, not through arithmetic.

3. **The ``# replicated-by: <mechanism>`` convention.**  A predicate
   the model cannot prove uniform is declared uniform by annotating
   the branch line (or the assignment that produced the predicate's
   value): ``# replicated-by: checkpoint-step-mirror``.  Mechanisms
   named ``*-mirror`` additionally claim a mirroring WRITE exists
   somewhere in the tree; that write site carries the provider twin
   ``# replicates: <mechanism>`` and the repo-level ledger check
   (:func:`mechanism_ledger`) fails any used-but-unprovided mirror —
   so deleting the mirror write (reverting PR 7) fails GL401 even
   though the consumer annotation lives in another file.

Annotation binding copies the ``# guarded-by:`` physical-line rules
(threads.py): a trailing comment binds to that statement's line span; a
standalone comment binds to the next statement.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from typing import Dict, List, Optional, Set, Tuple

from tools.graftlint.tracing import (collect_functions, dotted, iter_scope,
                                     last_seg)

# consumer: declares the annotated predicate/value provably uniform
_REPLICATED_RE = re.compile(
    r"#.*?\breplicated-by\s*:\s*([A-Za-z0-9][A-Za-z0-9_.-]*)")
# provider: the write site that implements a *-mirror mechanism
_REPLICATES_RE = re.compile(
    r"#.*?\breplicates\s*:\s*([A-Za-z0-9][A-Za-z0-9_.-]*)")
# replay-boundary def marker (GL403): host fetch / checkpoint capture /
# membership adoption is legal inside an annotated def
_BOUNDARY_RE = re.compile(r"#.*?\breplay-boundary\s*:")

# host-side multihost collectives: a call to one of these participates
# in a cross-process rendezvous on the spot
HOST_COLLECTIVES = {
    "process_allgather", "sync_global_devices", "broadcast_one_to_all",
    "make_array_from_process_local_data",
}
# in-program collectives (jax.lax / shard_map bodies).  Divergence for
# these is a host phenomenon too: the hazard is the host branch deciding
# WHETHER to dispatch the program that contains them.
LAX_COLLECTIVES = {
    "psum", "psum_scatter", "all_gather", "pmean", "pmin", "pmax",
    "all_to_all", "ppermute", "pshuffle",
}
# cross-file collective boundaries, documented in the catalog notes
# ("multihost collective boundaries"): methods whose multi-host
# implementation allgathers even though a given file only sees the call
COLLECTIVE_BOUNDARY_METHODS = {
    "_do_checkpoint",       # DistriOptimizer override allgathers state
    "_host_global",         # process_allgather wrapper
    "_make_global",         # make_array_from_process_local_data wrapper
    "_place_eval_input", "_place_eval_target", "_gather_eval_output",
    "_place_train_block",   # ride _make_global/_host_global
}

# calls whose RESULT only one host can see — the divergence sources
PROCESS_LOCAL_CALLS = {
    "process_index",                                   # the archetype
    "local_device_count", "local_devices", "addressable_devices",
    "time", "monotonic", "perf_counter", "time_ns", "monotonic_ns",
    "getpid", "gethostname", "getenv", "uname",
    "exists", "isfile", "isdir", "listdir", "stat", "getmtime",
    "getsize", "glob", "open",
    "random", "randint", "randrange", "uniform", "choice", "shuffle",
    "rand", "randn",
}
# calls that are uniform BY CONSTRUCTION even though they look dynamic
UNIFORM_CALLS = {
    "process_count", "device_count", "axis_size", "len", "isinstance",
    "hasattr", "getattr", "int", "float", "bool", "str", "tuple",
    "sorted", "min", "max", "sum", "abs", "type", "range",
}
# attribute names that are per-host state unless a mirror replicates
# them — the model's seed registry (catalog note "per-host state"):
# ``last_saved_step`` is written by whichever process performs the save
# (process 0 alone, absent a mirror); ``triggered`` is a per-host signal
# flag; ``environ`` reads are per-host by definition.
DIVERGENT_ATTRS = {"last_saved_step", "triggered", "environ"}

# GL403: calls that capture/fetch/adopt and therefore must sit at a
# replay boundary; the boundary defs the catalog already names
REPLAY_SINKS = {"capture_to_host", "device_get", "restore_into"}
REPLAY_BOUNDARY_DEFS = {"_replay_block", "_do_checkpoint",
                        "capture_to_host"}

# GL404: consumers whose argument positions the dataset/schedule moves
# by — a floored share must be exactness-guarded before it feeds one
SCHEDULE_CONSUMERS = {"fast_forward_records"}


def _comment_map(source: str) -> Dict[int, str]:
    """line (1-based) → comment text, via the tokenizer.  Regex over raw
    lines would treat a docstring that MENTIONS the convention (this
    module's own, say) as an annotation; only COMMENT tokens count."""
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # pathological source: fall back to raw-line scanning (strings
        # may leak through, but the file likely fails GL000 anyway)
        for i, line in enumerate(source.splitlines(), start=1):
            if "#" in line:
                out[i] = line[line.index("#"):]
    return out


def _annotation_lines(source: str, regex: re.Pattern,
                      comments: Optional[Dict[int, str]] = None,
                      ) -> Dict[int, Set[str]]:
    """line (1-based) → mechanisms bound there.  Trailing comments bind
    to their own line; standalone comment lines bind to the NEXT
    non-comment, non-blank line (the `# guarded-by:` convention)."""
    if comments is None:
        comments = _comment_map(source)
    bound: Dict[int, Set[str]] = {}
    lines = source.splitlines()
    pending: Set[str] = set()
    pending_standalone = False
    for i, line in enumerate(lines, start=1):
        stripped = line.strip()
        toks = set(regex.findall(comments.get(i, "")))
        if stripped.startswith("#"):
            if toks:
                pending |= toks
                pending_standalone = True
            continue
        if not stripped:
            continue
        here = set(toks)
        if pending_standalone:
            here |= pending
            pending = set()
            pending_standalone = False
        if here:
            bound[i] = bound.get(i, set()) | here
    return bound


class SpmdModel:
    """Per-file cross-process divergence model."""

    def __init__(self, tree: ast.Module, source: str, path: str):
        self.tree = tree
        self.path = path.replace("\\", "/")
        self.funcs, self.by_name = collect_functions(tree)
        # ---- annotations, bound by physical line
        comments = _comment_map(source)
        self.replicated_lines = _annotation_lines(source, _REPLICATED_RE,
                                                  comments)
        self.replicates_lines = _annotation_lines(source, _REPLICATES_RE,
                                                  comments)
        boundary_lines = {i for i, c in comments.items()
                          if _BOUNDARY_RE.search(c)}
        # a `# replay-boundary:` comment binds to the def whose header
        # region (the contiguous comment block above the decorators, or
        # the decorators..first-statement span itself) it touches
        src_lines = source.splitlines()
        comment_only = {i for i, line in enumerate(src_lines, start=1)
                        if line.lstrip().startswith("#")}
        self.boundary_defs: Set[int] = set()
        for fi in self.funcs.values():
            node = fi.node
            first = min([node.lineno]
                        + [d.lineno for d in node.decorator_list])
            header = set(range(first, node.body[0].lineno))
            j = first - 1
            while j >= 1 and j in comment_only:
                header.add(j)
                j -= 1
            if header & boundary_lines:
                self.boundary_defs.add(id(node))
        # ---- same-file collective closure
        self.collective_ids: Set[int] = set()
        self._close_collectives()

    # ------------------------------------------------------ collectives
    def _direct_collective_call(self, call: ast.Call) -> bool:
        fn = last_seg(call.func)
        if fn in HOST_COLLECTIVES:
            return True
        if fn in LAX_COLLECTIVES:
            d = dotted(call.func) or ""
            # bare names and lax./jax.lax. spellings; psum etc. are
            # distinctive enough that the bare form counts too
            return d == fn or d.startswith(("lax.", "jax.lax.",
                                            "multihost_utils."))
        return False

    def _callees(self, node) -> Set[str]:
        """Names this function calls, including callables handed to
        tree_map/combinators (``tmap(self._host_global, x)`` calls
        ``_host_global``)."""
        out: Set[str] = set()
        for n in iter_scope(node):
            if not isinstance(n, ast.Call):
                continue
            fn = last_seg(n.func)
            if fn:
                out.add(fn)
            if fn in {"tmap", "tree_map", "tree_multimap", "map",
                      "tree_map_with_path"}:
                for a in n.args:
                    s = last_seg(a)
                    if s:
                        out.add(s)
        return out

    def _close_collectives(self) -> None:
        """Fixpoint: a function is collective-bearing when it calls a
        collective directly, a boundary method, or a same-file
        collective-bearing function."""
        direct: Set[int] = set()
        callee_map: Dict[int, Set[str]] = {}
        for fid, fi in self.funcs.items():
            callee_map[fid] = self._callees(fi.node)
            for n in iter_scope(fi.node):
                if isinstance(n, ast.Call) \
                        and self._direct_collective_call(n):
                    direct.add(fid)
                    break
        self.collective_ids = set(direct)
        changed = True
        while changed:
            changed = False
            bearing_names = {self.funcs[fid].name
                             for fid in self.collective_ids}
            for fid, callees in callee_map.items():
                if fid in self.collective_ids:
                    continue
                if callees & bearing_names:
                    self.collective_ids.add(fid)
                    changed = True

    def is_collective_call(self, call: ast.Call) -> bool:
        """Direct collective, documented boundary method, or same-file
        collective-bearing function."""
        if self._direct_collective_call(call):
            return True
        fn = last_seg(call.func)
        if fn in COLLECTIVE_BOUNDARY_METHODS:
            return True
        return any(id(fi.node) in self.collective_ids
                   for fi in self.by_name.get(fn or "", []))

    def collective_calls(self, func_node) -> List[ast.Call]:
        return [n for n in iter_scope(func_node)
                if isinstance(n, ast.Call) and self.is_collective_call(n)]

    # ------------------------------------------------- replicated-by uses
    def _stmt_lines(self, node: ast.stmt) -> range:
        """Physical lines of a statement HEADER (test/decorators span,
        not the body) an annotation may bind to."""
        if isinstance(node, (ast.If, ast.While)):
            end = getattr(node.test, "end_lineno", node.lineno)
        else:
            end = getattr(node, "end_lineno", node.lineno)
        return range(node.lineno, end + 1)

    def declared_replicated(self, stmt: ast.stmt) -> Set[str]:
        """Mechanisms bound to this statement's header lines."""
        out: Set[str] = set()
        for ln in self._stmt_lines(stmt):
            out |= self.replicated_lines.get(ln, set())
        return out

    def declared_names(self, func_node) -> Tuple[Set[str], Set[str]]:
        """(names, attrs) declared uniform at their assignment site via
        a `# replicated-by:` annotation inside this function."""
        names: Set[str] = set()
        attrs: Set[str] = set()
        for n in iter_scope(func_node):
            if not isinstance(n, (ast.Assign, ast.AnnAssign,
                                  ast.AugAssign)):
                continue
            if not self.declared_replicated(n):
                continue
            targets = (n.targets if isinstance(n, ast.Assign)
                       else [n.target])
            for t in targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
                elif isinstance(t, ast.Attribute):
                    attrs.add(t.attr)
        return names, attrs

    # -------------------------------------------------- uniformity check
    def _call_is_process_local(self, call: ast.Call) -> bool:
        fn = last_seg(call.func)
        if fn in UNIFORM_CALLS:
            return False
        if fn in PROCESS_LOCAL_CALLS:
            d = dotted(call.func) or fn or ""
            # bare `open`/`random` style builtins and dotted time.*/
            # os.*/random.*/np.random.* all count; uniform-looking
            # method names (`.exists` on a set?) are rare enough in
            # predicate position that the name match is the model
            return True if d else False
        return False

    def is_uniform(self, expr: ast.AST, fi_node,
                   local_taint: Optional[Set[str]] = None,
                   declared: Optional[Tuple[Set[str], Set[str]]] = None,
                   ) -> bool:
        """True when every process provably computes the same value."""
        taint = local_taint if local_taint is not None \
            else self.process_local_names(fi_node)
        decl_names, decl_attrs = declared if declared is not None \
            else self.declared_names(fi_node)

        def uni(e) -> bool:
            if e is None or isinstance(e, (ast.Constant, ast.JoinedStr,
                                           ast.Lambda)):
                return True
            if isinstance(e, ast.Name):
                return e.id in decl_names or e.id not in taint
            if isinstance(e, ast.Attribute):
                if e.attr in decl_attrs:
                    return True
                if e.attr in DIVERGENT_ATTRS:
                    return False
                return uni(e.value)
            if isinstance(e, ast.Subscript):
                return uni(e.value) and uni(e.slice)
            if isinstance(e, ast.Compare):
                # `is None` / `is not None` checks are structural
                return uni(e.left) and all(uni(c) for c in e.comparators)
            if isinstance(e, (ast.BoolOp, ast.Tuple, ast.List, ast.Set)):
                vals = e.values if isinstance(e, ast.BoolOp) else e.elts
                return all(uni(v) for v in vals)
            if isinstance(e, ast.BinOp):
                return uni(e.left) and uni(e.right)
            if isinstance(e, ast.UnaryOp):
                return uni(e.operand)
            if isinstance(e, ast.IfExp):
                return uni(e.test) and uni(e.body) and uni(e.orelse)
            if isinstance(e, ast.Call):
                if self._call_is_process_local(e):
                    return False
                return (uni(e.func) if isinstance(e.func, ast.Attribute)
                        else True) and all(uni(a) for a in e.args) \
                    and all(uni(k.value) for k in e.keywords)
            if isinstance(e, ast.Starred):
                return uni(e.value)
            return True

        return uni(expr)

    def process_local_names(self, func_node) -> Set[str]:
        """Names in this function assigned from a process-local source
        (one forward pass; enough for straight-line driver code)."""
        decl_names, decl_attrs = self.declared_names(func_node)
        taint: Set[str] = set()

        def divergent(e) -> bool:
            for n in ast.walk(e):
                if isinstance(n, ast.Call) \
                        and self._call_is_process_local(n):
                    return True
                if isinstance(n, ast.Attribute) \
                        and n.attr in DIVERGENT_ATTRS \
                        and n.attr not in decl_attrs:
                    return True
                if isinstance(n, ast.Name) and n.id in taint \
                        and n.id not in decl_names:
                    return True
            return False

        for n in iter_scope(func_node):
            if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                value = n.value
                if value is None:
                    continue
                if self.declared_replicated(n):
                    continue  # annotation beats taint at the same site
                targets = (n.targets if isinstance(n, ast.Assign)
                           else [n.target])
                if divergent(value):
                    for t in targets:
                        if isinstance(t, ast.Name):
                            taint.add(t.id)
        return taint

    # -------------------------------------------------------- GL403 bits
    def is_boundary_def(self, func_node) -> bool:
        return (id(func_node) in self.boundary_defs
                or getattr(func_node, "name", None)
                in REPLAY_BOUNDARY_DEFS)

    # ------------------------------------------------- mechanism ledger
    def mechanism_uses(self) -> Set[str]:
        out: Set[str] = set()
        for toks in self.replicated_lines.values():
            out |= toks
        return out

    def mechanism_providers(self) -> Set[str]:
        out: Set[str] = set()
        for toks in self.replicates_lines.values():
            out |= toks
        return out


def mechanism_ledger(models: List[SpmdModel]
                     ) -> List[Tuple[str, int, str]]:
    """Repo-level check behind GL401's mirror contract: every
    ``*-mirror`` mechanism some file RELIES on (``# replicated-by:``)
    must have at least one provider write site (``# replicates:``)
    somewhere in the scanned set.  Returns ``(path, line, mechanism)``
    per unprovided use — deleting a mirror write (the PR-7 revert)
    surfaces here."""
    provided: Set[str] = set()
    for m in models:
        provided |= m.mechanism_providers()
    missing: List[Tuple[str, int, str]] = []
    for m in models:
        for line, toks in sorted(m.replicated_lines.items()):
            for mech in sorted(toks):
                if mech.endswith("-mirror") and mech not in provided:
                    missing.append((m.path, line, mech))
    return missing
