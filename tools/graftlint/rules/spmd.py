"""GL4xx SPMD/collective correctness: the multi-host divergence family.

Multi-host SPMD dies differently from single-host code: not a crash but
a **one-sided collective** — one process takes a branch the others
don't, issues (or skips) an allgather, and the pod deadlocks with no
stack worth reading.  Both historical bugs in this repo's lineage are
this class:

- the ``last_saved_step`` dedup (fixed by the PR-7 mirror): process 0
  advanced a counter after saving, processes 1..N-1 kept the stale
  value, and the next "did we already save?" branch diverged right
  above the checkpoint allgather;
- the ``_fast_forward`` divisibility hole (fixed by the PR-16 assert):
  a mid-epoch resume divided a record count by a new world's records
  scale, truncation gave hosts different skip counts, and the training
  collectives slid out of phase.

GL401-GL404 catch the class statically from the cross-process
divergence model in tools/graftlint/spmd.py; the runtime twin is
``BIGDL_TPU_SPMDCHECK=1`` (bigdl_tpu/utils/spmdcheck.py), which records
per-process collective schedules and fails on the first mismatch.

Escape hatch (mirrors ``# guarded-by:``): annotate the branch — or the
assignment producing its predicate — with ``# replicated-by:
<mechanism>`` once the value is provably uniform (mirrored on every
process, derived from config, membership-epoch-gated).  Mechanisms
named ``*-mirror`` are a contract, not a comment: some write site must
carry the provider twin ``# replicates: <mechanism>`` or GL401 fails
at the use site (the repo-level mechanism ledger) — deleting the
mirror write fails lint even though the consumer lives in another
file.
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.graftlint import spmd
from tools.graftlint.core import Rule, register
from tools.graftlint.tracing import iter_scope, last_seg


def _in_spmd_scope(ctx) -> bool:
    """GL4xx runs on library code only: tests and dataset pipelines are
    per-host by design (the loader is SUPPOSED to read local shards)."""
    return ctx.is_library


def _in_replay_scope(ctx) -> bool:
    """GL403's blast radius: the training driver, checkpointing, and
    resilience planes — where host fetch / capture / adoption must sit
    at replay boundaries.  Serving and nn layers fetch freely."""
    norm = ctx.path.replace("\\", "/")
    return _in_spmd_scope(ctx) and any(
        f"/{p}/" in norm or norm.startswith(f"{p}/")
        for p in ("optim", "checkpoint", "resilience"))


# statements that own nested statement blocks we must descend through
# while carrying the divergence context
_BLOCK_FIELDS = {
    ast.If: ("body", "orelse"),
    ast.While: ("body", "orelse"),
    ast.For: ("body", "orelse"),
    ast.With: ("body",),
    ast.Try: ("body", "handlers", "orelse", "finalbody"),
    ast.ExceptHandler: ("body",),
}


@register
class DivergentCollectiveRule(Rule):
    id = "GL401"
    name = "divergent-collective"
    severity = "error"
    description = ("collective reachable under a branch whose predicate "
                   "is process-local (process_index, clock, filesystem, "
                   "per-host counter) — annotate a provably uniform "
                   "predicate with `# replicated-by: <mechanism>`")

    def check(self, ctx):
        if not _in_spmd_scope(ctx):
            return
        model = ctx.spmd
        for fi in model.funcs.values():
            if id(fi.node) in ctx.traced.traced_ids:
                continue  # traced/shard_map code is lock-step
            taint = model.process_local_names(fi.node)
            declared = model.declared_names(fi.node)

            def divergent(test: ast.AST, stmt: ast.stmt) -> bool:
                if model.declared_replicated(stmt):
                    return False
                return not model.is_uniform(test, fi.node, taint,
                                            declared)

            def flag(call: ast.Call, branch: ast.stmt):
                kind = ("while" if isinstance(branch, ast.While)
                        else "if")
                return self.violation(
                    ctx, call,
                    f"collective `{last_seg(call.func)}` reachable "
                    f"under process-local `{kind}` at line "
                    f"{branch.lineno}: if any process skips it the "
                    "rendezvous goes one-sided and the pod deadlocks; "
                    "mirror the predicate on every process and "
                    "annotate the branch `# replicated-by: "
                    "<mechanism>`")

            def visit(stmts, branch: Optional[ast.stmt]):
                for s in stmts:
                    here = branch
                    if isinstance(s, (ast.If, ast.While)) \
                            and here is None \
                            and divergent(s.test, s):
                        here = s
                    if here is not None:
                        for n in ast.walk(s):
                            if isinstance(n, (ast.FunctionDef,
                                              ast.AsyncFunctionDef,
                                              ast.Lambda)):
                                # nested defs are their own scope; a
                                # def under a divergent branch only
                                # diverges when CALLED, and the call
                                # site is what we flag
                                continue
                            if isinstance(n, ast.Call) \
                                    and model.is_collective_call(n):
                                yield flag(n, here)
                        continue
                    for typ, fields in _BLOCK_FIELDS.items():
                        if isinstance(s, typ):
                            for f in fields:
                                yield from visit(getattr(s, f, []), here)
                            break

            yield from visit(fi.node.body, None)
            # expression-level branches: `x() if process_index() else y()`
            for n in iter_scope(fi.node):
                if isinstance(n, ast.IfExp) \
                        and not model.is_uniform(n.test, fi.node, taint,
                                                 declared):
                    for arm in (n.body, n.orelse):
                        for c in ast.walk(arm):
                            if isinstance(c, ast.Call) \
                                    and model.is_collective_call(c):
                                yield self.violation(
                                    ctx, c,
                                    "collective in a conditional "
                                    "expression with a process-local "
                                    "test; both arms must issue the "
                                    "same collectives, or the test "
                                    "must be `# replicated-by:` "
                                    "uniform")


@register
class WorldSizeDependentStateRule(Rule):
    id = "GL402"
    name = "world-size-dependent-state"
    severity = "error"
    description = ("checkpoint schema / wire-bucket state depends on "
                   "world size without the reshard_state/elastic-schema "
                   "path (bucket_content fingerprint) — breaks elastic "
                   "resume at a different world size")

    # build_schema kwargs that encode the CURRENT world's layout
    WORLD_KWARGS = {"n_shard", "bucket_sizes"}
    # world-size sources: uniform across processes, but tied to THIS
    # world's size — poison for anything a different-sized world resumes
    WORLD_CALLS = {"process_count", "device_count", "axis_size"}
    # names that mark a persisted container
    PERSISTED = ("state", "schema", "ckpt", "checkpoint")

    def check(self, ctx):
        if not _in_spmd_scope(ctx):
            return
        for fi in ctx.spmd.funcs.values():
            calls_reshard = any(
                isinstance(n, ast.Call)
                and last_seg(n.func) == "reshard_state"
                for n in iter_scope(fi.node))
            for n in iter_scope(fi.node):
                if isinstance(n, ast.Call) \
                        and last_seg(n.func) == "build_schema":
                    kw = {k.arg for k in n.keywords}
                    if kw & self.WORLD_KWARGS \
                            and "bucket_content" not in kw:
                        yield self.violation(
                            ctx, n,
                            "schema carries world-size-dependent "
                            f"layout ({', '.join(sorted(kw & self.WORLD_KWARGS))}) "
                            "without the world-size-invariant "
                            "bucket_content fingerprint — a resume at "
                            "a different world size cannot validate "
                            "or reshard this checkpoint "
                            "(see grad_sync.reshard_state)")
                    continue
                if not isinstance(n, ast.Assign) or calls_reshard:
                    continue
                stores = any(
                    isinstance(t, ast.Subscript)
                    and any(p in (last_seg(t.value) or "").lower()
                            for p in self.PERSISTED)
                    for t in n.targets)
                if not stores:
                    continue
                world = [c for c in ast.walk(n.value)
                         if isinstance(c, ast.Call)
                         and last_seg(c.func) in self.WORLD_CALLS]
                for c in world:
                    yield self.violation(
                        ctx, n,
                        f"`{last_seg(c.func)}()` stored into persisted "
                        "state: the value is this world's size and a "
                        "resume at a different size inherits it — "
                        "recompute at restore or route through "
                        "reshard_state")


@register
class ReplayBoundaryViolationRule(Rule):
    id = "GL403"
    name = "replay-boundary-violation"
    severity = "error"
    description = ("host fetch / checkpoint capture / restore outside a "
                   "replay boundary (annotate the def `# replay-"
                   "boundary: <why>` if it IS one) — generalizes GL107 "
                   "to the checkpoint/membership planes")

    def check(self, ctx):
        if not _in_replay_scope(ctx):
            return
        model = ctx.spmd
        for fi in model.funcs.values():
            if id(fi.node) in ctx.traced.traced_ids:
                continue
            anc, bounded = fi, False
            while anc is not None:
                if model.is_boundary_def(anc.node):
                    bounded = True
                    break
                anc = anc.parent
            if bounded:
                continue
            for n in iter_scope(fi.node):
                if isinstance(n, ast.Call) \
                        and last_seg(n.func) in spmd.REPLAY_SINKS:
                    yield self.violation(
                        ctx, n,
                        f"`{last_seg(n.func)}` in `{fi.name}`, which "
                        "is not a replay boundary: state captured or "
                        "adopted here is unreplayable after preemption "
                        "— move it into a boundary def or annotate "
                        "this def `# replay-boundary: <why>` if every "
                        "caller reaches it only at block edges")


@register
class CollectiveInDivergentLoopRule(Rule):
    id = "GL404"
    name = "collective-in-divergent-loop"
    severity = "error"
    description = ("floored per-host share feeds a schedule consumer or "
                   "collective loop without a divisibility guard — "
                   "truncation gives hosts different trip counts (the "
                   "_fast_forward class)")

    def _floordivs(self, fi):
        """name -> (numerator, denominator) for `x = a // b` assigns."""
        out = {}
        for n in iter_scope(fi.node):
            if isinstance(n, ast.Assign) \
                    and isinstance(n.value, ast.BinOp) \
                    and isinstance(n.value.op, ast.FloorDiv):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = (n.value.left, n.value.right, n)
        return out

    def _guarded(self, fi, num, den) -> bool:
        """True when `num % den` is checked for exactness: an `if` whose
        body raises, or an assert."""
        want = (ast.dump(num), ast.dump(den))

        def mods(e):
            for n in ast.walk(e):
                if isinstance(n, ast.BinOp) \
                        and isinstance(n.op, ast.Mod):
                    yield (ast.dump(n.left), ast.dump(n.right))

        for n in iter_scope(fi.node):
            if isinstance(n, ast.Assert) and want in mods(n.test):
                return True
            if isinstance(n, ast.If) and want in mods(n.test) \
                    and any(isinstance(s, ast.Raise) for s in n.body):
                return True
        return False

    def check(self, ctx):
        if not _in_spmd_scope(ctx):
            return
        model = ctx.spmd
        for fi in model.funcs.values():
            if id(fi.node) in ctx.traced.traced_ids:
                continue
            shares = self._floordivs(fi)
            if not shares:
                continue
            for n in iter_scope(fi.node):
                # floored share handed to a schedule consumer
                if isinstance(n, ast.Call) \
                        and last_seg(n.func) in spmd.SCHEDULE_CONSUMERS:
                    for a in n.args:
                        if isinstance(a, ast.Name) and a.id in shares:
                            num, den, site = shares[a.id]
                            if not self._guarded(fi, num, den):
                                yield self.violation(
                                    ctx, n,
                                    f"`{a.id}` = floor division at "
                                    f"line {site.lineno} feeds "
                                    f"`{last_seg(n.func)}` without a "
                                    "divisibility guard: when the "
                                    "division is inexact, hosts "
                                    "fast-forward by different "
                                    "amounts and every later "
                                    "collective is one-sided — guard "
                                    "with `if a % b: raise` or "
                                    "`assert a % b == 0`")
                # floored share as a collective loop's trip count
                if isinstance(n, ast.For) and isinstance(n.iter, ast.Call) \
                        and last_seg(n.iter.func) == "range":
                    trip = [a.id for a in n.iter.args
                            if isinstance(a, ast.Name) and a.id in shares]
                    if not trip:
                        continue
                    has_coll = any(
                        isinstance(c, ast.Call)
                        and model.is_collective_call(c)
                        for c in ast.walk(n))
                    if not has_coll:
                        continue
                    for name in trip:
                        num, den, site = shares[name]
                        if not self._guarded(fi, num, den):
                            yield self.violation(
                                ctx, n,
                                f"loop trip count `{name}` is a "
                                f"floored share (line {site.lineno}) "
                                "and the body issues collectives: "
                                "hosts with different remainders run "
                                "different iteration counts — guard "
                                "divisibility or derive the count "
                                "from a global value")
