"""GL101 host-sync + GL107 driver-loop host sync.

GL101: inside jit, ``.item()`` / ``.tolist()`` / ``float(x)`` /
``np.asarray(x)`` on a tracer either raises (ConcretizationTypeError)
or — worse, when the value happens to be concrete on some call paths —
silently inserts a blocking device→host sync into the step loop.  That
is the throughput cliff tools/byte_audit.py exists to post-mortem;
catch it at PR time.

Only *tainted* receivers/arguments are flagged: ``np.asarray(table)`` on
a static config list at trace time is normal constant folding.

GL107: the *driver-side* sibling.  A training driver loop (``optim/``)
that dispatches a donated jit step and then immediately blocks on one of
its outputs (``float(loss)``, ``.item()``, ``np.asarray``) drains the
device pipeline once per iteration — legal Python, no tracer involved,
but it serializes host dispatch against device compute (the exact stall
class the fused K-step loop + one-block-behind loss fetch removes).
The heuristic: inside a ``while``/``for`` body, a host sync on a name
produced EARLIER IN THE SAME ITERATION by a call to a donating jit
callable.  The deferred pattern — sync the *previous* iteration's value
before the dispatch rebinds it — reads in source order as sync-above-
producer and is deliberately clean.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import Rule, register
from tools.graftlint.tracing import dotted, iter_scope, last_seg

SYNC_METHODS = {"item", "tolist", "block_until_ready"}
SYNC_CASTS = {"float", "int", "bool", "complex"}
NP_SYNC_FUNCS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}


@register
class HostSyncRule(Rule):
    id = "GL101"
    name = "host-sync"
    severity = "error"
    description = ("device→host sync (.item()/float()/np.asarray/"
                   "jax.device_get) reachable from a traced function")

    def check(self, ctx):
        for fi in ctx.traced.iter_traced():
            tainted = ctx.traced.tainted_names(fi.node)
            for n in iter_scope(fi.node):
                if not isinstance(n, ast.Call):
                    continue
                v = self._check_call(ctx, fi, n, tainted)
                if v is not None:
                    yield v

    def _check_call(self, ctx, fi, n, tainted):
        static = lambda e: ctx.traced.is_static(e, tainted)  # noqa: E731
        if isinstance(n.func, ast.Attribute) and \
                n.func.attr in SYNC_METHODS:
            if not static(n.func.value):
                return self.violation(
                    ctx, n, f".{n.func.attr}() on a tensor inside traced "
                    f"`{fi.name}` blocks on device→host transfer; keep "
                    "the value on device or move the readout out of the "
                    "step")
        fn = dotted(n.func)
        if fn in SYNC_CASTS and len(n.args) == 1 and not static(n.args[0]):
            return self.violation(
                ctx, n, f"{fn}() on a tensor inside traced `{fi.name}` "
                "forces concretization (host sync / trace error); use "
                "jnp casts or keep it an array")
        if fn in NP_SYNC_FUNCS and n.args and not static(n.args[0]):
            return self.violation(
                ctx, n, f"{fn}() on a tensor inside traced `{fi.name}` "
                "pulls the value to host; use jnp.asarray (stays on "
                "device) or hoist the conversion out of the traced path")
        if fn is not None and last_seg(n.func) == "device_get" and \
                fn.split(".")[0] in ("jax", "api"):
            return self.violation(
                ctx, n, f"jax.device_get inside traced `{fi.name}` is a "
                "blocking transfer; fetch results after the step returns")
        return None


def _is_jit_call(n: ast.AST) -> bool:
    """``jax.jit(...)`` or ``functools.partial(jax.jit, ...)``."""
    if not isinstance(n, ast.Call):
        return False
    if last_seg(n.func) == "jit":
        return True
    return (last_seg(n.func) == "partial"
            and any(last_seg(a) == "jit" for a in n.args))


def _donates(call: ast.Call) -> bool:
    return any(k.arg in ("donate_argnums", "donate_argnames")
               for k in call.keywords)


def _target_name_nodes(t: ast.AST):
    if isinstance(t, ast.Name):
        yield t
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            yield from _target_name_nodes(e)
    elif isinstance(t, ast.Starred):
        yield from _target_name_nodes(t.value)


@register
class DriverLoopHostSyncRule(Rule):
    id = "GL107"
    name = "driver-loop-host-sync"
    severity = "error"
    description = ("blocking float()/.item()/np.asarray on a donated-jit "
                   "step output inside a while/for training-driver loop "
                   "(optim/) — drains the dispatch pipeline every "
                   "iteration; fetch one step behind instead")

    SYNC_FUNCS = {"float", "int"}
    SYNC_NP = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}

    def check(self, ctx):
        norm = ctx.path.replace("\\", "/")
        if ctx.is_test or ("/optim/" not in norm
                           and not norm.startswith("optim/")):
            return
        for fi in ctx.traced.funcs.values():
            if ctx.traced.is_traced(fi.node):
                continue  # traced code is GL101's jurisdiction
            steps = self._donating_step_names(fi.node)
            if not steps:
                continue
            for loop in iter_scope(fi.node):
                if isinstance(loop, (ast.While, ast.For)):
                    yield from self._check_loop(ctx, fi, loop, steps)

    def _donating_step_names(self, func: ast.AST) -> set:
        """Names that invoke a DONATING jit in this function's scope —
        the training-step signature (eval forwards don't donate, so
        predict/evaluate fetch loops stay out of scope).  Shapes:
        ``@partial(jax.jit, donate_argnums=...)`` on a nested def, and
        ``step = jax.jit(f, donate_argnums=...)`` bindings."""
        out = set()
        for n in ast.walk(func):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in n.decorator_list:
                    if _is_jit_call(dec) and _donates(dec):
                        out.add(n.name)
            elif isinstance(n, ast.Assign) and _is_jit_call(n.value) \
                    and _donates(n.value):
                for t in n.targets:
                    for nm in _target_name_nodes(t):
                        out.add(nm.id)
        return out

    def _check_loop(self, ctx, fi, loop, steps):
        # outputs of a donating-step call, keyed by the line the call
        # rebinds them on — a sync is only a pipeline stall when it
        # happens AFTER the producing dispatch in the same iteration
        produced: dict = {}
        for n in iter_scope(loop):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                    and isinstance(n.value.func, ast.Name) \
                    and n.value.func.id in steps:
                for t in n.targets:
                    for nm in _target_name_nodes(t):
                        produced[nm.id] = min(n.lineno,
                                              produced.get(nm.id, n.lineno))
        if not produced:
            return
        for n in iter_scope(loop):
            if not isinstance(n, ast.Call):
                continue
            name = self._synced_name(n)
            if name in produced and n.lineno > produced[name]:
                yield self.violation(
                    ctx, n, f"blocking host fetch of `{name}` right after "
                    f"its producing dispatch in `{fi.name}`'s driver loop "
                    "— the device queue drains every iteration; fetch one "
                    "step/block behind (see Optimizer._replay_block) or "
                    "move the readout out of the loop")

    def _synced_name(self, call: ast.Call):
        """The Name a sync call blocks on, else None."""
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in SYNC_METHODS \
                and isinstance(call.func.value, ast.Name):
            return call.func.value.id
        fn = dotted(call.func)
        if call.args and isinstance(call.args[0], ast.Name):
            if fn in self.SYNC_FUNCS or fn in self.SYNC_NP:
                return call.args[0].id
            if fn is not None and last_seg(call.func) == "device_get" \
                    and fn.split(".")[0] == "jax":
                return call.args[0].id
        return None
