"""GL101 host-sync: device→host transfers reachable from traced code.

Inside jit, ``.item()`` / ``.tolist()`` / ``float(x)`` / ``np.asarray(x)``
on a tracer either raises (ConcretizationTypeError) or — worse, when the
value happens to be concrete on some call paths — silently inserts a
blocking device→host sync into the step loop.  That is the throughput
cliff tools/byte_audit.py exists to post-mortem; catch it at PR time.

Only *tainted* receivers/arguments are flagged: ``np.asarray(table)`` on
a static config list at trace time is normal constant folding.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import Rule, register
from tools.graftlint.tracing import dotted, iter_scope, last_seg

SYNC_METHODS = {"item", "tolist", "block_until_ready"}
SYNC_CASTS = {"float", "int", "bool", "complex"}
NP_SYNC_FUNCS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}


@register
class HostSyncRule(Rule):
    id = "GL101"
    name = "host-sync"
    severity = "error"
    description = ("device→host sync (.item()/float()/np.asarray/"
                   "jax.device_get) reachable from a traced function")

    def check(self, ctx):
        for fi in ctx.traced.iter_traced():
            tainted = ctx.traced.tainted_names(fi.node)
            for n in iter_scope(fi.node):
                if not isinstance(n, ast.Call):
                    continue
                v = self._check_call(ctx, fi, n, tainted)
                if v is not None:
                    yield v

    def _check_call(self, ctx, fi, n, tainted):
        static = lambda e: ctx.traced.is_static(e, tainted)  # noqa: E731
        if isinstance(n.func, ast.Attribute) and \
                n.func.attr in SYNC_METHODS:
            if not static(n.func.value):
                return self.violation(
                    ctx, n, f".{n.func.attr}() on a tensor inside traced "
                    f"`{fi.name}` blocks on device→host transfer; keep "
                    "the value on device or move the readout out of the "
                    "step")
        fn = dotted(n.func)
        if fn in SYNC_CASTS and len(n.args) == 1 and not static(n.args[0]):
            return self.violation(
                ctx, n, f"{fn}() on a tensor inside traced `{fi.name}` "
                "forces concretization (host sync / trace error); use "
                "jnp casts or keep it an array")
        if fn in NP_SYNC_FUNCS and n.args and not static(n.args[0]):
            return self.violation(
                ctx, n, f"{fn}() on a tensor inside traced `{fi.name}` "
                "pulls the value to host; use jnp.asarray (stays on "
                "device) or hoist the conversion out of the traced path")
        if fn is not None and last_seg(n.func) == "device_get" and \
                fn.split(".")[0] in ("jax", "api"):
            return self.violation(
                ctx, n, f"jax.device_get inside traced `{fi.name}` is a "
                "blocking transfer; fetch results after the step returns")
        return None
