"""GL106 recompile-hazard: jit usage patterns that defeat the compile
cache.

Three statically-checkable shapes:

1. ``jax.jit(f)(x)`` inside a function body — a fresh jit wrapper (and
   a fresh cache) per call, so every call recompiles.  The benchmarked
   pattern is: build the jitted callable once (module level, or once in
   ``__init__``/setup like optim/predictor.py), then call it in the
   loop.
2. ``jax.jit(...)`` / ``partial(jax.jit, ...)`` created inside a
   ``for``/``while`` body (including an ``@jax.jit`` def in a loop) —
   same failure with a loop around it.
3. A literal Python scalar passed positionally to a same-file jitted
   function in a position not covered by ``static_argnums`` /
   ``static_argnames``.  Scalar config flags baked per call either
   retrace (when used in shapes) or silently dedupe into one trace;
   declare them static, or pass data as arrays.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.graftlint.core import Rule, register
from tools.graftlint.tracing import iter_scope, last_seg


def _is_jit_call(n: ast.AST) -> bool:
    """jax.jit(...) or functools.partial(jax.jit, ...)."""
    if not isinstance(n, ast.Call):
        return False
    if last_seg(n.func) == "jit":
        return True
    return (last_seg(n.func) == "partial"
            and any(last_seg(a) == "jit" for a in n.args))


def _static_decl(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    nums: Set[int] = set()
    names: Set[str] = set()
    for k in call.keywords:
        if k.arg == "static_argnums":
            for c in ast.walk(k.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, int):
                    nums.add(c.value)
        elif k.arg == "static_argnames":
            for c in ast.walk(k.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    names.add(c.value)
    return nums, names


@register
class RecompileRule(Rule):
    id = "GL106"
    name = "recompile-hazard"
    severity = "error"
    description = ("jit wrapper built per call / per loop iteration, or a "
                   "Python scalar literal passed to a jitted function "
                   "without a static declaration")

    def check(self, ctx):
        yield from self._inline_and_loop(ctx)
        yield from self._scalar_args(ctx)

    # -- shapes 1 & 2 ----------------------------------------------------
    def _inline_and_loop(self, ctx):
        # jit-call nodes that are immediately invoked (shape 1's anchor;
        # excluded from shape 2 so jax.jit(f)(x) in a loop reports once)
        invoked = {id(n.func) for n in ast.walk(ctx.tree)
                   if isinstance(n, ast.Call) and _is_jit_call(n.func)}
        for fi in ctx.traced.funcs.values():
            for n in iter_scope(fi.node):
                if isinstance(n, ast.Call) and _is_jit_call(n.func):
                    yield self.violation(
                        ctx, n, f"jax.jit(...)(...) inside `{fi.name}` "
                        "builds a fresh jit cache per call — every call "
                        "recompiles; build the jitted callable once and "
                        "reuse it")
        seen = set()
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for n in ast.walk(loop):
                if n is loop or id(n) in seen:
                    continue
                hazard = (isinstance(n, ast.Call) and _is_jit_call(n)
                          and id(n) not in invoked)
                hazard = hazard or (
                    isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and any(_is_jit_call(d) or last_seg(d) == "jit"
                            for d in n.decorator_list))
                if hazard:
                    seen.add(id(n))
                    yield self.violation(
                        ctx, n, "jax.jit created inside a loop body — a "
                        "fresh wrapper (and compile) per iteration; hoist "
                        "the jit out of the loop")

    # -- shape 3 ---------------------------------------------------------
    def _scalar_args(self, ctx):
        jitted: Dict[str, Tuple[Set[int], Set[str],
                                Optional[List[str]]]] = {}
        # `g = jax.jit(f, ...)` bindings: when f is a same-file def, its
        # param names let static_argnames exonerate positional literals
        defs = {fi.name: [a.arg for a in fi.node.args.args]
                for fi in ctx.traced.funcs.values()}
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Assign) and _is_jit_call(n.value) \
                    and last_seg(n.value.func) == "jit":
                nums, names = _static_decl(n.value)
                params = None
                if n.value.args and isinstance(n.value.args[0], ast.Name):
                    params = defs.get(n.value.args[0].id)
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        jitted[t.id] = (nums, names, params)
        # `@jax.jit` / `@partial(jax.jit, static_argnums=...)` defs
        for fi in ctx.traced.funcs.values():
            for dec in fi.node.decorator_list:
                if isinstance(dec, ast.Call) and _is_jit_call(dec):
                    nums, names = _static_decl(dec)
                elif _is_jit_call(dec) or last_seg(dec) == "jit":
                    nums, names = set(), set()
                else:
                    continue
                params = [a.arg for a in fi.node.args.args]
                jitted[fi.name] = (nums, names, params)
                break
        for call in ast.walk(ctx.tree):
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Name)
                    and call.func.id in jitted):
                continue
            nums, names, params = jitted[call.func.id]
            for i, a in enumerate(call.args):
                if not (isinstance(a, ast.Constant)
                        and isinstance(a.value, (bool, int, float))):
                    continue
                pname = params[i] if params and i < len(params) else None
                if i in nums or (pname is not None and pname in names):
                    continue
                yield self.violation(
                    ctx, a, f"Python scalar literal {a.value!r} passed to "
                    f"jitted `{call.func.id}` (arg {i}) without "
                    "static_argnums/static_argnames; declare it static "
                    "if it is config, or pass an array if it is data")
