"""Rule modules self-register with tools.graftlint.core.REGISTRY on
import.  Importing this package loads the full default ruleset."""

from tools.graftlint.rules import (  # noqa: F401
    concurrency,
    dtype_hygiene,
    host_sync,
    purity,
    recompile,
    resource_safety,
    spmd,
    tensor_branch,
)
