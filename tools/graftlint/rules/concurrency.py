"""GL201-GL206: concurrency hazards in the threaded host-side plane.

Every confirmed-by-repro bug in the PR 5/10/11 review rounds was a
*concurrency* bug in host thread code — leaked probation probes, futures
stranded RUNNING at replica death, a non-reentrant-lock re-take deadlock
in ``ModelRegistry._resolve``, orphaned batcher threads pinning
services.  This family converts those review rounds' contracts into
checkers, keyed off the shared thread/lock model in
``tools/graftlint/threads.py`` (the GL2xx analog of ``tracing.py``):

- GL201 unguarded-shared-state — ``# guarded-by:`` /
  ``# write-guarded-by:`` annotated attributes accessed outside their
  lock, plus a heuristic for unannotated attributes written both on a
  spawned thread and off it with no common lock;
- GL202 lock-retake/ordering — calling a method that acquires
  non-reentrant lock L while already holding L (the ``_resolve``
  deadlock class), and inconsistent two-lock acquisition order;
- GL203 future-settlement — a request/future popped off a queue or
  inflight map must be settled (``set_result``/``set_exception``/
  ``cancel``/``settle_future``) or provably handed off — the "accepted
  requests ALWAYS resolve" invariant;
- GL204 thread-lifecycle — ``Thread(...)`` objects must be bound (so
  stop/close can reach them) and daemonized-or-joined — the
  orphaned-batcher class;
- GL205 wait-predicate — ``Condition.wait``/``wait_for`` outside a
  ``while``-predicate loop (missed/spurious wakeups);
- GL206 blocking-under-lock — sleeps, fsync, HTTP, subprocesses,
  ``Future.result()``, thread joins, device fetches or XLA compiles
  while holding a lock.

Scope: all non-test code (``bigdl_tpu/`` including ``dataset/``, and
``tools/``).  These are host-side rules — the traced-scope model is
irrelevant here; threaded code must never be traced in the first place.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from tools.graftlint import threads
from tools.graftlint.core import Rule, register
from tools.graftlint.tracing import dotted, iter_scope, last_seg


def _in_scope(ctx) -> bool:
    return not ctx.is_test


def _root_name(node: ast.AST) -> Optional[str]:
    """Base Name of an attribute/subscript chain: ``req.future.cancel``
    -> ``req``."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


# ============================================================= GL201
@register
class UnguardedSharedStateRule(Rule):
    id = "GL201"
    name = "unguarded-shared-state"
    severity = "error"
    description = ("`# guarded-by:` annotated attribute accessed outside "
                   "its lock (write-guarded-by: writes only), or an "
                   "attribute written both on a spawned thread and off "
                   "it with no common lock")

    # attrs whose unannotated cross-thread writes we tolerate: none —
    # the heuristic is annotation-free by design
    def check(self, ctx):
        if not _in_scope(ctx):
            return
        model: threads.ThreadModel = ctx.threads
        for cls in sorted(model.class_names() | {None},
                          key=lambda c: (c is None, c or "")):
            yield from self._check_scope(ctx, model, cls)
        yield from self._heuristic(ctx, model)

    def _check_scope(self, ctx, model, cls):
        guards = model.guards_for(cls)
        if not guards:
            return
        for fi in self._funcs_in(model, cls):
            if fi.name == "__init__" and fi.class_name == cls:
                continue
            held = model.held_map(fi.node, fi.class_name)
            shadowed = (self._local_shadows(fi.node, set(guards))
                        if cls is None else frozenset())
            for n in iter_scope(fi.node):
                name, is_write = self._guarded_access(n, cls)
                if name is None or name not in guards \
                        or name in shadowed:
                    continue
                lock, mode = guards[name]
                if mode == threads.GUARD_WRITE and not is_write:
                    continue
                if lock in held.get(id(n), frozenset()):
                    continue
                what = "write to" if is_write else "read of"
                yield self.violation(
                    ctx, n, f"{what} `{self._render(cls, name)}` outside "
                    f"its declared guard `{lock}` (annotated "
                    f"{'write-' if mode == threads.GUARD_WRITE else ''}"
                    f"guarded-by in `{fi.class_name or 'module'}`); take "
                    "the lock or move the access into a locked method")

    @staticmethod
    def _render(cls, name):
        return f"self.{name}" if cls is not None else name

    @staticmethod
    def _funcs_in(model, cls):
        if cls is None:
            # module globals: every function in the file can touch them
            return list(model.funcs.values())
        return model.methods_of(cls)

    @staticmethod
    def _local_shadows(func, names: Set[str]) -> Set[str]:
        """Guarded-global names that are LOCALS of this function —
        bound by a parameter or a plain assignment with no ``global``
        declaration — so every occurrence refers to the shadow, not
        the guarded module global."""
        declared_global: Set[str] = set()
        bound: Set[str] = set()
        for n in iter_scope(func):
            if isinstance(n, ast.Global):
                declared_global.update(n.names)
            elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                bound.add(n.id)
        a = getattr(func, "args", None)
        if a is not None:
            bound.update(x.arg for x in
                         list(getattr(a, "posonlyargs", [])) + a.args
                         + a.kwonlyargs)
            for x in (a.vararg, a.kwarg):
                if x is not None:
                    bound.add(x.arg)
        return (bound - declared_global) & names

    def _guarded_access(self, n, cls) -> Tuple[Optional[str], bool]:
        """(accessed guarded name, is_write) for one AST node within
        class scope ``cls`` (None = module globals)."""
        if cls is not None:
            if isinstance(n, ast.Attribute) \
                    and isinstance(n.value, ast.Name) \
                    and n.value.id == "self":
                return n.attr, isinstance(n.ctx, (ast.Store, ast.Del))
            return None, False
        if isinstance(n, ast.Name):
            return n.id, isinstance(n.ctx, (ast.Store, ast.Del))
        return None, False

    # --- heuristic: cross-thread writes without a common lock -----------
    def _heuristic(self, ctx, model):
        for cls in sorted(model.class_names()):
            writes: Dict[str, List[Tuple[ast.AST, frozenset, bool]]] = {}
            annotated = set(model.guards_for(cls))
            for fi in model.methods_of(cls):
                if fi.name == "__init__":
                    continue
                held = model.held_map(fi.node, fi.class_name)
                on_thread = model.on_thread(fi.node)
                for n in iter_scope(fi.node):
                    if isinstance(n, ast.Attribute) \
                            and isinstance(n.ctx, ast.Store) \
                            and isinstance(n.value, ast.Name) \
                            and n.value.id == "self" \
                            and n.attr not in annotated:
                        writes.setdefault(n.attr, []).append(
                            (n, held.get(id(n), frozenset()), on_thread))
            for attr, sites in sorted(writes.items()):
                thread_sites = [s for s in sites if s[2]]
                other_sites = [s for s in sites if not s[2]]
                if not thread_sites or not other_sites:
                    continue
                # locks held at EVERY spawned-thread write
                common = frozenset.intersection(
                    *[h for (_n, h, _t) in thread_sites])
                for n, held, _t in other_sites:
                    if held & common:
                        continue
                    yield self.violation(
                        ctx, n, f"`self.{attr}` is written on a spawned "
                        f"thread (in `{cls}`) and here with no common "
                        "lock — guard both writes with one lock and "
                        "annotate the attribute `# guarded-by: <lock>` "
                        "(or justify the race with a suppression)")


# ===================================================== GL202 retake/order
@register
class LockRetakeRule(Rule):
    id = "GL202"
    name = "lock-retake"
    severity = "error"
    description = ("acquiring (or calling a method that acquires) a "
                   "non-reentrant lock already held — the "
                   "ModelRegistry._resolve deadlock class — and "
                   "inconsistent two-lock acquisition order")

    def check(self, ctx):
        if not _in_scope(ctx):
            return
        model: threads.ThreadModel = ctx.threads
        # per class: ordered acquisition pairs for the ordering check
        pairs: Dict[Optional[str],
                    Dict[Tuple[str, str], ast.AST]] = {}
        for fi in model.funcs.values():
            cls = fi.class_name
            held = model.held_map(fi.node, cls)
            for n in iter_scope(fi.node):
                if isinstance(n, (ast.With, ast.AsyncWith)):
                    outer = held.get(id(n), frozenset())
                    for item in n.items:
                        lk = model.canon_lock(cls, item.context_expr)
                        if lk is None:
                            continue
                        info = model.lock_info(cls, lk)
                        reentrant = info.reentrant if info else False
                        family = info.family if info else False
                        if lk in outer and not reentrant and not family:
                            yield self.violation(
                                ctx, n, f"`with {lk}` while `{lk}` is "
                                "already held and the lock is not "
                                "reentrant — this deadlocks at runtime")
                        for o in outer:
                            if o != lk:
                                pairs.setdefault(cls, {}).setdefault(
                                    (o, lk), n)
                elif isinstance(n, ast.Call):
                    yield from self._check_call(ctx, model, fi, n,
                                                held.get(id(n),
                                                         frozenset()),
                                                pairs)
        yield from self._order_cycles(ctx, pairs)

    def _check_call(self, ctx, model, fi, call, outer, pairs):
        cls = fi.class_name
        cands = []
        callee = None
        if isinstance(call.func, ast.Attribute) \
                and isinstance(call.func.value, ast.Name) \
                and call.func.value.id == "self" and cls is not None:
            callee = call.func.attr
            cands = [c for c in model.by_name.get(callee, [])
                     if c.class_name == cls]
        elif isinstance(call.func, ast.Name):
            callee = call.func.id
            cands = [c for c in model.by_name.get(callee, [])
                     if c.class_name is None]
        for c in cands:
            # the inverse contract: a held-on-entry (`# guarded-by:` on
            # the def) method called WITHOUT its lock.  __init__ is
            # exempt — the object is not shared yet.
            entry = model.entry_held.get(id(c.node), set())
            missing = sorted(entry - outer)
            if missing and fi.name != "__init__":
                yield self.violation(
                    ctx, call, f"`{callee}()` declares "
                    f"{'/'.join(f'`{lk}`' for lk in missing)} held on "
                    "entry (`# guarded-by:` on its def) but the lock "
                    "is not held here — take it around the call")
            if not outer:
                continue
            acq = model.acquires(c.node, c.class_name)
            for lk in sorted(acq):
                info = model.lock_info(cls, lk)
                reentrant = info.reentrant if info else False
                family = info.family if info else False
                if lk in outer and not reentrant and not family:
                    yield self.violation(
                        ctx, call, f"`{callee}()` acquires `{lk}` which "
                        f"is already held here — a non-reentrant re-take "
                        "deadlock (the ModelRegistry._resolve class); "
                        "hoist the call out of the locked region or "
                        "split a `_locked` variant that the caller's "
                        "lock covers")
                else:
                    for o in outer:
                        if o != lk:
                            pairs.setdefault(cls, {}).setdefault((o, lk),
                                                                 call)

    def _order_cycles(self, ctx, pairs):
        for cls, ps in sorted(pairs.items(),
                              key=lambda kv: (kv[0] is None, kv[0] or "")):
            seen = set()
            for (a, b), node in sorted(
                    ps.items(), key=lambda kv: kv[1].lineno):
                if (b, a) in ps and frozenset((a, b)) not in seen:
                    seen.add(frozenset((a, b)))
                    yield self.violation(
                        ctx, node, f"inconsistent lock order: `{a}` -> "
                        f"`{b}` here but `{b}` -> `{a}` at line "
                        f"{ps[(b, a)].lineno} — two threads taking them "
                        "in opposite order deadlock; pick one order")


# ======================================================= GL203 settlement
_QUEUE_NAME_RE = re.compile(
    r"(^|_)(q|queue|deque|backlog|inflight|in_flight|pending|waiters?|"
    r"requests?|futures?|futs?)(_|s$|$)")
_POP_METHODS = {"popleft", "pop", "get", "get_nowait"}
_SETTLE_METHODS = {"set_result", "set_exception", "cancel",
                   "set_running_or_notify_cancel"}
_SETTLE_FUNCS = {"settle_future", "_settle"}


@register
class FutureSettlementRule(Rule):
    id = "GL203"
    name = "future-settlement"
    severity = "error"
    description = ("a request/future popped from a queue or inflight "
                   "map is neither settled (set_result/set_exception/"
                   "cancel/settle_future) nor handed off — accepted "
                   "requests must ALWAYS resolve")

    def check(self, ctx):
        if not _in_scope(ctx):
            return
        model: threads.ThreadModel = ctx.threads
        for fi in model.funcs.values():
            yield from self._check_func(ctx, fi.node)

    def _is_pop(self, call: ast.Call) -> bool:
        if not isinstance(call.func, ast.Attribute):
            return False
        meth = call.func.attr
        if meth not in _POP_METHODS:
            return False
        recv = call.func.value
        recv_name = (recv.attr if isinstance(recv, ast.Attribute)
                     else recv.id if isinstance(recv, ast.Name) else None)
        if recv_name is None \
                or not _QUEUE_NAME_RE.search(recv_name.lower()):
            return False
        if meth == "get":
            # dict.get(key[, default]) is a lookup, not a removal; a
            # blocking queue.get() has no positional key (timeout/block
            # ride as keywords)
            return not call.args
        return True

    def _check_func(self, ctx, func):
        pops: List[Tuple[ast.Call, Set[str]]] = []  # (node, handles)
        stmts = list(iter_scope(func))
        parent_expr = {id(n.value): n for n in stmts
                       if isinstance(n, ast.Expr)}
        assigns = {id(n.value): n for n in stmts
                   if isinstance(n, (ast.Assign, ast.AnnAssign))
                   and n.value is not None}
        for n in stmts:
            if isinstance(n, ast.Call) and self._is_pop(n):
                if id(n) in parent_expr:
                    # bare statement: popped and dropped on the floor
                    yield self.violation(
                        ctx, n, "popped from "
                        f"`{dotted(n.func) or 'queue'}` and discarded — "
                        "if the item carries a future it can never "
                        "resolve; settle it, hand it off, or justify "
                        "the drain with a suppression")
                    continue
                holder = assigns.get(id(n))
                handles: Set[str] = set()
                if holder is not None:
                    targets = (holder.targets
                               if isinstance(holder, ast.Assign)
                               else [holder.target])
                    for t in targets:
                        handles |= set(self._target_names(t))
                if handles:
                    pops.append((n, handles))
                # a pop consumed as a subexpression
                # (`inflight.pop(0).result()`) resolves through its
                # consumer — nothing to track
        if not pops:
            return
        resolved = self._resolved_names(func, stmts)
        for n, handles in pops:
            # derived handles: unpacking/iteration extends the set
            closure = self._derive(handles, stmts)
            if not (closure & resolved):
                yield self.violation(
                    ctx, n, f"`{'/'.join(sorted(handles))}` popped from "
                    f"`{dotted(n.func) or 'queue'}` is never settled or "
                    "handed off in this function — every path that "
                    "takes a request out of a queue must resolve its "
                    "future (set_result/set_exception/cancel/"
                    "settle_future) or pass it on")

    @staticmethod
    def _target_names(t):
        if isinstance(t, ast.Name):
            yield t.id
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield from FutureSettlementRule._target_names(e)
        elif isinstance(t, ast.Starred):
            yield from FutureSettlementRule._target_names(t.value)

    def _derive(self, handles: Set[str], stmts) -> Set[str]:
        """Close handles over unpacking (`a, b = item`) and iteration
        (`for r in batch:`)."""
        out = set(handles)
        changed = True
        while changed:
            changed = False
            for n in stmts:
                src = None
                tgt = None
                if isinstance(n, ast.Assign) and len(n.targets) == 1:
                    src, tgt = n.value, n.targets[0]
                elif isinstance(n, ast.For):
                    src, tgt = n.iter, n.target
                if src is None:
                    continue
                root = _root_name(src)
                if root in out:
                    for nm in self._target_names(tgt):
                        if nm not in out:
                            out.add(nm)
                            changed = True
        return out

    def _resolved_names(self, func, stmts) -> Set[str]:
        """Names that reach a settlement or hand-off anywhere in the
        function (order-insensitive: the rule is per-function, not
        per-path)."""
        out: Set[str] = set()
        for n in stmts:
            if isinstance(n, ast.Call):
                # settle: req.future.cancel() / fut.set_result(...)
                if isinstance(n.func, ast.Attribute) \
                        and n.func.attr in _SETTLE_METHODS:
                    root = _root_name(n.func.value)
                    if root:
                        out.add(root)
                # settle_future(req.future, ...) and hand-off via any
                # call argument (dispatch_fn(batch), batch.append(req))
                for a in list(n.args) + [k.value for k in n.keywords]:
                    root = _root_name(a)
                    if root:
                        out.add(root)
                # hand-off by invocation: job()
                if isinstance(n.func, ast.Name):
                    out.add(n.func.id)
                # receiver of a call keeps its own handle live only for
                # settles (handled above), not for reads like req.n_rows
            elif isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom)) \
                    and n.value is not None:
                # only returning/yielding the handle ITSELF is a
                # hand-off; `return req.n_rows` reads a field and
                # still drops the request
                if isinstance(n.value, ast.Name):
                    out.add(n.value.id)
                elif isinstance(n.value, (ast.Tuple, ast.List)):
                    for e in n.value.elts:
                        if isinstance(e, ast.Name):
                            out.add(e.id)
            elif isinstance(n, ast.Assign):
                # stored into an attribute/container: someone else can
                # still settle it
                for t in n.targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        root = _root_name(n.value)
                        if root:
                            out.add(root)
        return out


# ======================================================= GL204 lifecycle
@register
class ThreadLifecycleRule(Rule):
    id = "GL204"
    name = "thread-lifecycle"
    severity = "error"
    description = ("threading.Thread objects must be bound (so stop/"
                   "close can reach them) and daemonized or joined — "
                   "the orphaned-batcher class")

    def check(self, ctx):
        if not _in_scope(ctx):
            return
        model: threads.ThreadModel = ctx.threads
        for fi in model.funcs.values():
            yield from self._check_func(ctx, model, fi)
        # module-level Thread(...) statements (iter_scope stops at
        # def/class boundaries, so functions are not double-checked)
        yield from self._check_body(ctx, model, ctx.tree,
                                    scope_src=ctx.source)

    def _check_func(self, ctx, model, fi):
        yield from self._check_body(ctx, model, fi.node,
                                    scope_src=self._scope_source(ctx, fi))

    def _scope_source(self, ctx, fi):
        """Source text the join/daemon search may scan: the function
        itself, or — for methods — the ENCLOSING class body (a
        `self._t` thread may be joined by a sibling stop()/close(),
        but a same-named binding joined in a DIFFERENT class must not
        exonerate this one)."""
        if fi.class_name is not None:
            cls = self._enclosing_class(ctx, fi)
            if cls is not None:
                seg = ast.get_source_segment(ctx.source, cls)
                if seg:
                    return seg
        seg = ast.get_source_segment(ctx.source, fi.node)
        return seg or ctx.source

    @staticmethod
    def _enclosing_class(ctx, fi):
        best = None
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.ClassDef) and n.name == fi.class_name \
                    and n.lineno <= fi.node.lineno \
                    <= (getattr(n, "end_lineno", None) or n.lineno):
                # innermost match wins (nested same-named classes)
                if best is None or n.lineno >= best.lineno:
                    best = n
        return best

    def _check_body(self, ctx, model, scope, scope_src):
        for n in iter_scope(scope):
            if not (isinstance(n, ast.Call)
                    and last_seg(n.func) == "Thread"
                    and (dotted(n.func) or "").split(".")[-1] == "Thread"):
                continue
            # exclude non-threading "Thread" lookalikes when clearly
            # namespaced elsewhere
            d = dotted(n.func) or "Thread"
            if "." in d and not d.startswith("threading."):
                continue
            binding = self._binding(scope, n)
            daemon = any(k.arg == "daemon"
                         and isinstance(k.value, ast.Constant)
                         and k.value.value is True
                         for k in n.keywords)
            if binding is None:
                yield self.violation(
                    ctx, n, "Thread object is never bound — nothing can "
                    "join or stop it (orphaned-thread hazard); assign "
                    "it to a field your stop()/close() reaps")
                continue
            names = {binding} | self._iter_aliases(scope, binding)
            joined = any(re.search(
                re.escape(nm) + r"\s*\.\s*join\s*\(", scope_src)
                for nm in names)
            daemon_set = any(re.search(
                re.escape(nm) + r"\s*\.\s*daemon\s*=\s*True", scope_src)
                for nm in names)
            if not (daemon or daemon_set or joined):
                yield self.violation(
                    ctx, n, f"thread bound to `{binding}` is neither "
                    "daemon=True nor ever joined — it outlives shutdown "
                    "and pins the process; daemonize it AND join it "
                    "from stop()/close() (the batcher discipline)")

    @staticmethod
    def _binding(scope, call) -> Optional[str]:
        """`self._t` / `t` when the Thread() call is the RHS of an
        assignment — directly or as an element of a list/comprehension
        RHS (`ts = [Thread(...) for ...]`) — else None."""
        def names_of(t):
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                return f"self.{t.attr}"
            if isinstance(t, ast.Name):
                return t.id
            return None

        for n in iter_scope(scope):
            if isinstance(n, ast.Assign):
                v = n.value
                container = (
                    v is call
                    or (isinstance(v, ast.ListComp) and v.elt is call)
                    or (isinstance(v, (ast.List, ast.Tuple))
                        and call in v.elts))
                if container:
                    return names_of(n.targets[0])
            elif isinstance(n, ast.NamedExpr) and n.value is call \
                    and isinstance(n.target, ast.Name):
                return n.target.id
        return None

    @staticmethod
    def _iter_aliases(scope, binding: str) -> Set[str]:
        """Loop targets iterating the binding (`for t in threads:`) —
        a `.join()` on the loop variable joins the container's
        threads."""
        out: Set[str] = set()
        for n in iter_scope(scope):
            if isinstance(n, ast.For) and _root_name(n.iter) == binding \
                    and isinstance(n.target, ast.Name):
                out.add(n.target.id)
        return out


# ==================================================== GL205 wait-predicate
_COND_NAME_RE = re.compile(r"cond|cv|wake|not_empty|not_full")


@register
class WaitPredicateRule(Rule):
    id = "GL205"
    name = "wait-predicate"
    severity = "error"
    description = ("Condition.wait()/wait_for() outside a while-"
                   "predicate loop — wakeups are advisory (spurious or "
                   "stale); re-check the predicate in a while")

    def check(self, ctx):
        if not _in_scope(ctx):
            return
        model: threads.ThreadModel = ctx.threads
        for fi in model.funcs.values():
            cond_keys = model.condition_keys(fi.class_name)
            for n, in_while in self._walk(fi.node, False):
                if not (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr in ("wait", "wait_for")):
                    continue
                recv = n.func.value
                key = model.canon_lock(fi.class_name, recv)
                is_cond = False
                if key is not None:
                    info = model.lock_info(fi.class_name, key)
                    # post-alias info may be the backing Lock; the attr
                    # itself being declared a Condition is the signal
                    raw = (recv.attr if isinstance(recv, ast.Attribute)
                           else recv.id if isinstance(recv, ast.Name)
                           else None)
                    raw_info = None
                    if raw is not None:
                        raw_info = model.class_locks.get(
                            fi.class_name or "", {}).get(raw) \
                            or model.module_locks.get(raw)
                    is_cond = bool((raw_info and raw_info.condition)
                                   or (info and info.condition))
                else:
                    nm = (recv.attr if isinstance(recv, ast.Attribute)
                          else recv.id if isinstance(recv, ast.Name)
                          else "") or ""
                    is_cond = bool(_COND_NAME_RE.search(nm.lower()))
                if is_cond and not in_while:
                    yield self.violation(
                        ctx, n, "Condition wait outside a `while` "
                        "predicate loop — a spurious or stale wakeup "
                        "proceeds on a false predicate; use `while not "
                        "pred: cond.wait()` (see RequestBatcher."
                        "_collect)")

    def _walk(self, node, in_while):
        """Yield (node, lexically-inside-a-while) without entering
        nested defs."""
        for child in ast.iter_child_nodes(node):
            yield child, in_while
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            yield from self._walk(
                child, in_while or isinstance(child, ast.While))


# ================================================ GL206 blocking-under-lock
_BLOCKING_DOTTED = {
    "time.sleep", "os.fsync", "urllib.request.urlopen", "urlopen",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "requests.get", "requests.post",
    "requests.put", "requests.request", "jax.device_get",
}
_BLOCKING_METHODS = {"result", "block_until_ready", "compile"}
_THREADISH_RE = re.compile(r"thread|worker|proc(ess)?$|supervisor")


@register
class BlockingUnderLockRule(Rule):
    id = "GL206"
    name = "blocking-under-lock"
    severity = "error"
    description = ("blocking call (sleep/fsync/HTTP/subprocess/"
                   "Future.result/thread join/device fetch/XLA compile) "
                   "while holding a lock — every other thread needing "
                   "the lock stalls behind the slow operation")

    def check(self, ctx):
        if not _in_scope(ctx):
            return
        model: threads.ThreadModel = ctx.threads
        for fi in model.funcs.values():
            held = model.held_map(fi.node, fi.class_name)
            for n in iter_scope(fi.node):
                if not isinstance(n, ast.Call):
                    continue
                locks = held.get(id(n), frozenset())
                if not locks:
                    continue
                why = self._blocking(model, fi, n, locks)
                if why:
                    yield self.violation(
                        ctx, n, f"{why} while holding "
                        f"{'/'.join(f'`{lk}`' for lk in sorted(locks))} "
                        "— the lock serializes every other thread "
                        "behind this; move the slow work outside the "
                        "locked region (collect under the lock, act "
                        "outside it)")

    def _blocking(self, model, fi, call, locks) -> Optional[str]:
        d = dotted(call.func)
        seg = last_seg(call.func)
        if d in _BLOCKING_DOTTED or (seg == "fsync" and d == seg):
            return f"`{d}()` blocks"
        if not isinstance(call.func, ast.Attribute):
            return None
        # last_seg is None when the receiver chain contains a call
        # (`jit.lower(...).compile()`); the method name is what matters
        seg = call.func.attr
        recv = call.func.value
        if seg == "join":
            # thread join only: known Thread attrs or thread-ish names
            if isinstance(recv, ast.Attribute) \
                    and isinstance(recv.value, ast.Name) \
                    and recv.value.id == "self":
                tattrs = model.class_threads.get(fi.class_name or "",
                                                 set())
                if recv.attr in tattrs \
                        or _THREADISH_RE.search(recv.attr.lower()):
                    return f"`self.{recv.attr}.join()` blocks"
            elif isinstance(recv, ast.Name) \
                    and _THREADISH_RE.search(recv.id.lower()):
                return f"`{recv.id}.join()` blocks"
            return None
        if seg in ("wait", "wait_for"):
            # waiting on a DIFFERENT condition than (one of) the held
            # locks blocks without releasing them; waiting on the held
            # condition releases it and is the normal pattern
            key = model.canon_lock(fi.class_name, recv)
            if key is not None and key not in locks:
                return f"waiting on `{key}`"
            return None
        if seg in _BLOCKING_METHODS:
            if seg == "compile" and isinstance(recv, ast.Name) \
                    and recv.id == "re":
                return None  # re.compile is instant
            if seg == "result":
                return "`.result()` blocks on a future"
            if seg == "block_until_ready":
                return "`.block_until_ready()` drains the device queue"
            return "`.compile()` runs an XLA compile"
        return None
