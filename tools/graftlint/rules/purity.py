"""GL103 impure-forward: state mutation inside traced code.

The Module contract (nn/module.py) is explicit: everything under
``apply``/``update`` must be a pure function of its inputs — new state
is *returned*, never written.  ``self.x = ...`` inside a traced method
runs once at trace time and then silently never again (jit caches the
trace), which is the classic "my running mean stopped updating" bug.
The reference BigDL contract (``updateOutput`` writing ``this.output``)
is exactly what this rule exists to keep out.

Flags: assignments/aug-assignments/deletes through ``self``, in-place
container mutation on ``self`` attributes (``.append``/``.update``/…),
and ``global``/``nonlocal`` declarations.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import Rule, register
from tools.graftlint.tracing import iter_scope

MUTATORS = {"append", "extend", "update", "add", "insert", "pop", "clear",
            "remove", "setdefault", "popitem", "discard", "sort",
            "reverse", "fill", "setflags"}


def _rooted_at_self(node: ast.AST) -> bool:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return isinstance(node, ast.Name) and node.id == "self"


@register
class PurityRule(Rule):
    id = "GL103"
    name = "impure-forward"
    severity = "error"
    description = ("mutation of self attributes or module-level state "
                   "inside a traced function (jit caches the trace; the "
                   "write happens once, then never again)")

    def check(self, ctx):
        for fi in ctx.traced.iter_traced():
            for n in iter_scope(fi.node):
                v = self._check_node(ctx, fi, n)
                if v is not None:
                    yield v

    def _check_node(self, ctx, fi, n):
        msg = ("traced `{f}` mutates `{what}`; return the new value "
               "instead (pure-function contract, nn/module.py)")
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (n.targets if isinstance(n, ast.Assign)
                       else [n.target])
            for t in targets:
                elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) \
                    else [t]
                for e in elts:
                    if _rooted_at_self(e) and not isinstance(e, ast.Name):
                        return self.violation(
                            ctx, n, msg.format(
                                f=fi.name,
                                what=ast.unparse(e) if hasattr(ast,
                                                               "unparse")
                                else "a self attribute"))
        elif isinstance(n, ast.Delete):
            for t in n.targets:
                if _rooted_at_self(t) and not isinstance(t, ast.Name):
                    return self.violation(
                        ctx, n, msg.format(f=fi.name, what="del self.*"))
        elif isinstance(n, ast.Call):
            f = n.func
            # container mutators take <=2 args; a 5-arg .update() is an
            # optimizer's functional update, not a dict write
            if (isinstance(f, ast.Attribute) and f.attr in MUTATORS
                    and len(n.args) + len(n.keywords) <= 2
                    and _rooted_at_self(f.value)
                    and not isinstance(f.value, ast.Name)):
                return self.violation(
                    ctx, n, f"traced `{fi.name}` mutates a self attribute "
                    f"in place via .{f.attr}(); build a new value and "
                    "return it")
        elif isinstance(n, ast.Global):
            return self.violation(
                ctx, n, f"traced `{fi.name}` declares `global "
                f"{', '.join(n.names)}`; module-level state does not "
                "survive tracing — thread it through the carry instead")
        elif isinstance(n, ast.Nonlocal):
            return self.violation(
                ctx, n, f"traced `{fi.name}` declares `nonlocal "
                f"{', '.join(n.names)}`; closure state mutated under a "
                "trace is applied once at trace time only")
        return None
