"""GL301-GL303: exception-path resource safety in the wire/serving
plane.

PR 13 encoded the concurrency bugs of review rounds 10-12 as GL2xx;
PR 14's review round then shipped a bug class those rules cannot see:

- ``_backend_max_batch`` ran BETWEEN a wire-inflight pin acquire and
  its ``try/finally`` — a raise there leaked the pin and wedged
  ``HotCutover`` until timeout (GL301's class);
- ``_classify`` mapped blanket ``ValueError/TypeError`` to HTTP 400 —
  internal bugs masqueraded as client errors and never hit the 5xx SLO
  or the traceback log (GL302's class);
- the probe-slot leak of PR 10 review round 1 was the same shape one
  layer down: a paired counter incremented on a path that never
  decremented (GL303's class).

The family keys off ``tools/graftlint/resources.py`` (the GL3xx analog
of ``threads.py``): ``# acquires:`` / ``# releases:`` annotations on
defs declare ownership-transferring APIs, the same annotations on
statements mark the primitive inc/dec sites of paired counters, and
``# graftlint: client-error=`` extends the wire error taxonomy.

- GL301 leaked-acquire — a call to an ``# acquires:``-annotated
  function whose acquisition is not covered by a ``try/finally`` that
  releases the resource (and the caller does not itself transfer
  ownership via its own ``# acquires:`` def annotation);
- GL302 error-taxonomy — in wire/serving modules, a 4xx response fed
  by a blanket ``except`` (``Exception``/``BaseException``/bare) or
  selected by an ``isinstance`` test on a function parameter against a
  type outside the declared client-error taxonomy.  Wrapping a
  NARROWLY-typed exception from a specific client-input parse into
  ``_HTTPError(400)``/``RequestSpecError`` at its origin is the
  blessed pattern and stays silent;
- GL303 release-on-all-paths — a marked paired counter with acquire
  sites but no release site in the file (one-way resource), or an
  unannotated mutation of a marked attribute (an inc/dec added outside
  the discipline; ``__init__`` exempt — construction precedes
  sharing).

Scope: all non-test code for GL301/GL303; GL302 is scoped to the wire
plane (``frontend/`` + ``serving/``) where HTTP statuses mean
something.
"""

from __future__ import annotations

import ast
import types
from typing import Iterator, List, Optional, Set, Tuple

from tools.graftlint import resources
from tools.graftlint.core import Rule, register
from tools.graftlint.tracing import iter_scope, last_seg

#: exception types allowed to select a 4xx status — the declared wire
#: client-error taxonomy (extend per file with
#: ``# graftlint: client-error=Name``)
CLIENT_ERROR_TYPES = {
    "RequestSpecError", "_HTTPError", "HTTPError",
    "UnknownTenantError", "TenantRateLimited", "ServiceOverloaded",
}

_BLANKET = {"Exception", "BaseException"}

_SENDERS = {"send_json", "send_body", "send_error", "send_response",
            "start_chunked"}

_SIMPLE_STMTS = (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Expr,
                 ast.Return, ast.Raise, ast.Assert, ast.Delete)

_TRY_STAR = getattr(ast, "TryStar", None)  # except* is py3.11+


def _in_scope(ctx) -> bool:
    return not ctx.is_test


def _callee_name(call: ast.Call) -> Optional[str]:
    seg = last_seg(call.func)
    if seg is None and isinstance(call.func, ast.Attribute):
        seg = call.func.attr
    return seg


# ============================================================= GL301
@register
class LeakedAcquireRule(Rule):
    id = "GL301"
    name = "leaked-acquire"
    severity = "error"
    description = ("a tracked resource (`# acquires:`-annotated call) "
                   "acquired outside a try/finally that releases it on "
                   "every raise path — the PR-14 wire-inflight pin-leak "
                   "class")

    def check(self, ctx) -> Iterator:
        if not _in_scope(ctx):
            return
        model: resources.ResourceModel = ctx.resources
        if not (model.name_acquires or model.stmt_sites):
            return
        for fi in model.funcs.values():
            yield from self._check_func(ctx, model, fi)

    def _check_func(self, ctx, model, fi):
        owned = model.def_acquires.get(id(fi.node), set())
        body = getattr(fi.node, "body", [])
        yield from self._walk_block(ctx, model, owned, body, [])

    def _walk_block(self, ctx, model, owned, block, tries):
        for i, stmt in enumerate(block):
            nxt = block[i + 1] if i + 1 < len(block) else None
            for call in self._own_calls(stmt):
                for r in sorted(model.call_acquires(call) - owned):
                    if self._protected(model, r, tries, nxt):
                        continue
                    yield self.violation(
                        ctx, call, f"`{_callee_name(call)}()` acquires "
                        f"`{r}` but no try/finally on this path "
                        f"releases it — a raise between here and the "
                        "release leaks the resource (the PR-14 "
                        "wire-inflight pin-leak class); make the next "
                        "statement a `try:` whose `finally` releases "
                        f"`{r}`, or annotate this function "
                        f"`# acquires: {r}` to transfer ownership to "
                        "its caller")
            # recurse into compound bodies with updated try context
            if isinstance(stmt, ast.Try):
                inner = tries + ([stmt] if stmt.finalbody else [])
                yield from self._walk_block(ctx, model, owned,
                                            stmt.body, inner)
                for h in stmt.handlers:
                    yield from self._walk_block(ctx, model, owned,
                                                h.body, inner)
                yield from self._walk_block(ctx, model, owned,
                                            stmt.orelse, inner)
                yield from self._walk_block(ctx, model, owned,
                                            stmt.finalbody, tries)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                yield from self._walk_block(ctx, model, owned,
                                            stmt.body, tries)
                yield from self._walk_block(ctx, model, owned,
                                            stmt.orelse, tries)
            elif isinstance(stmt, ast.If):
                yield from self._walk_block(ctx, model, owned,
                                            stmt.body, tries)
                yield from self._walk_block(ctx, model, owned,
                                            stmt.orelse, tries)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                yield from self._walk_block(ctx, model, owned,
                                            stmt.body, tries)
            elif isinstance(stmt, ast.Match):
                for case in stmt.cases:
                    yield from self._walk_block(ctx, model, owned,
                                                case.body, tries)
            elif _TRY_STAR is not None and isinstance(stmt, _TRY_STAR):
                inner = tries + ([stmt] if stmt.finalbody else [])
                for blk in (stmt.body, *[h.body for h in stmt.handlers],
                            stmt.orelse):
                    yield from self._walk_block(ctx, model, owned, blk,
                                                inner)
                yield from self._walk_block(ctx, model, owned,
                                            stmt.finalbody, tries)

    @staticmethod
    def _own_calls(stmt) -> List[ast.Call]:
        """Calls belonging to THIS statement: the whole subtree for
        simple statements, only the header expressions for compound
        ones (their bodies are walked as blocks of their own).
        ``iter_scope`` keeps nested defs/lambdas out — their bodies
        run later, under whatever protection their caller sets up."""
        if isinstance(stmt, _SIMPLE_STMTS):
            roots: List[ast.AST] = [stmt]
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            roots = [stmt.iter]
        elif isinstance(stmt, (ast.While, ast.If)):
            roots = [stmt.test]
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            roots = [item.context_expr for item in stmt.items]
        elif isinstance(stmt, ast.Match):
            roots = [stmt.subject] + [c.guard for c in stmt.cases
                                      if c.guard is not None]
        else:
            return []
        out: List[ast.Call] = []
        for root in roots:
            for n in [root, *iter_scope(root)]:
                if isinstance(n, ast.Call):
                    out.append(n)
        return out

    @staticmethod
    def _protected(model, resource, tries, nxt) -> bool:
        for t in tries:
            if model.releases_in(t.finalbody, resource):
                return True
        if isinstance(nxt, ast.Try) and nxt.finalbody \
                and model.releases_in(nxt.finalbody, resource):
            return True
        return False


# ============================================================= GL302
@register
class ErrorTaxonomyRule(Rule):
    id = "GL302"
    name = "error-taxonomy"
    severity = "error"
    description = ("wire/serving 4xx fed by a blanket except or "
                   "selected by an isinstance test on an undeclared "
                   "exception type — internal bugs must report 5xx, "
                   "not hide as client errors (the PR-14 blanket-400 "
                   "class)")

    def check(self, ctx) -> Iterator:
        if not _in_scope(ctx) or not ctx.is_wire:
            return
        declared = CLIENT_ERROR_TYPES | ctx.resources.client_errors
        for fi in ctx.resources.funcs.values():
            params = self._params(fi.node)
            for n in iter_scope(fi.node):
                if isinstance(n, ast.ExceptHandler):
                    yield from self._check_handler(ctx, n)
                elif isinstance(n, ast.If):
                    yield from self._check_classifier(ctx, n, params,
                                                      declared)

    @staticmethod
    def _params(func) -> Set[str]:
        a = func.args
        names = {x.arg for x in
                 list(getattr(a, "posonlyargs", [])) + a.args
                 + a.kwonlyargs}
        for x in (a.vararg, a.kwarg):
            if x is not None:
                names.add(x.arg)
        names.discard("self")
        return names

    # --- blanket except feeding 4xx ------------------------------------
    def _check_handler(self, ctx, handler):
        types_ = self._handler_types(handler)
        if types_ is not None and not (types_ & _BLANKET):
            return  # narrowly typed: wrapping at origin is blessed
        for node, status in self._fourxx(handler.body):
            caught = "/".join(sorted(types_)) if types_ else "bare"
            yield self.violation(
                ctx, node, f"{status} fed by a blanket `except "
                f"{caught}` — an internal bug here would masquerade as "
                "a client error and dodge the 5xx SLO and traceback "
                "log; catch the SPECIFIC exception the guarded "
                "operation raises (or raise a declared client-error "
                "type at the parse site)")

    @staticmethod
    def _handler_types(handler) -> Optional[Set[str]]:
        """Set of caught type names, or None for a bare ``except:``."""
        t = handler.type
        if t is None:
            return None
        elts = t.elts if isinstance(t, ast.Tuple) else [t]
        out: Set[str] = set()
        for e in elts:
            seg = last_seg(e)
            if seg:
                out.add(seg)
        return out

    # --- isinstance classifier mapping to 4xx --------------------------
    def _check_classifier(self, ctx, if_node, params, declared):
        tested = self._isinstance_types(if_node.test, params)
        if not tested:
            return
        undeclared = sorted(tested - declared)
        if not undeclared:
            return
        for node, status in self._fourxx(if_node.body):
            yield self.violation(
                ctx, node, f"{status} selected by `isinstance` on "
                f"{'/'.join(f'`{t}`' for t in undeclared)} — not a "
                "declared client-error type (see the GL302 taxonomy "
                "in tools/graftlint/README.md); raise a declared type "
                "at the client-input site instead of widening the 4xx "
                "mapping, or declare it with `# graftlint: "
                "client-error=<Type>`")

    @staticmethod
    def _isinstance_types(test, params) -> Set[str]:
        """Type names from ``isinstance(<param>, T | (T, ...))`` tests
        anywhere in the If test expression."""
        out: Set[str] = set()
        for n in ast.walk(test):
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Name)
                    and n.func.id == "isinstance"
                    and len(n.args) == 2):
                continue
            obj, typ = n.args
            if not (isinstance(obj, ast.Name) and obj.id in params):
                continue
            elts = typ.elts if isinstance(typ, ast.Tuple) else [typ]
            for e in elts:
                seg = last_seg(e)
                if seg:
                    out.add(seg)
        return out

    # --- 4xx production detection --------------------------------------
    @staticmethod
    def _const_4xx(node) -> Optional[int]:
        if isinstance(node, ast.Constant) and isinstance(node.value, int) \
                and 400 <= node.value <= 499:
            return node.value
        return None

    def _fourxx(self, body) -> List[Tuple[ast.AST, int]]:
        out: List[Tuple[ast.AST, int]] = []
        for stmt in body:
            for n in [stmt, *iter_scope(stmt)]:
                if isinstance(n, ast.Call):
                    seg = _callee_name(n)
                    if seg and (seg.endswith("HTTPError")
                                or seg in _SENDERS) and n.args:
                        status = self._const_4xx(n.args[0])
                        if status is not None:
                            out.append((n, status))
                elif isinstance(n, ast.Return) and n.value is not None:
                    v = n.value
                    first = (v.elts[0] if isinstance(v, ast.Tuple)
                             and v.elts else v)
                    status = self._const_4xx(first)
                    if status is not None:
                        out.append((n, status))
        return out


# ============================================================= GL303
@register
class ReleaseOnAllPathsRule(Rule):
    id = "GL303"
    name = "release-on-all-paths"
    severity = "error"
    description = ("a tracked paired counter with acquire sites but no "
                   "release site in the file, or an unannotated "
                   "mutation of a tracked counter attribute — the "
                   "wire_inflight/_probe_inflight inc/dec class")

    def check(self, ctx) -> Iterator:
        if not _in_scope(ctx):
            return
        model: resources.ResourceModel = ctx.resources
        if not model.has_annotations():
            return
        yield from self._check_pairing(ctx, model)
        yield from self._check_discipline(ctx, model)

    def _check_pairing(self, ctx, model):
        released: Set[str] = set()
        for _line, toks in model.release_stmt_sites():
            released |= toks
        for toks in model.name_releases.values():
            released |= toks
        for line, toks in model.acquire_stmt_sites():
            for r in sorted(toks - released):
                fake = types.SimpleNamespace(lineno=line, col_offset=0)
                yield self.violation(
                    ctx, fake, f"resource `{r}` is acquired here but "
                    "nothing in this file releases it — a one-way "
                    "counter only ever leaks (the probe-slot class); "
                    f"annotate the decrement `# releases: {r}` or "
                    "remove the tracking if the resource is not paired")

    def _check_discipline(self, ctx, model):
        if not model.marked_attrs:
            return
        for fi in model.funcs.values():
            if fi.name == "__init__":
                continue  # construction happens-before sharing
            for stmt in iter_scope(fi.node):
                if not isinstance(stmt, (ast.Assign, ast.AugAssign,
                                         ast.AnnAssign, ast.Delete)):
                    continue
                attr = resources.ResourceModel._mutated_attr(stmt)
                if attr is None:
                    continue
                key = (fi.class_name, attr)
                if key not in model.marked_attrs:
                    continue
                if stmt.lineno in model.stmt_sites:
                    continue
                rs = "/".join(sorted(model.marked_attrs[key]))
                yield self.violation(
                    ctx, stmt, f"unannotated mutation of tracked "
                    f"counter `self.{attr}` (resource {rs}) — every "
                    "inc/dec of a paired counter must declare its side "
                    "with `# acquires:` / `# releases:` so the pairing "
                    "stays checkable; annotate this site or move the "
                    "mutation into the annotated acquire/release "
                    "methods")
