"""GL104 float64-promotion and GL105 nondeterministic-rng.

GL104: numpy float64 scalars/arrays are *strongly* typed — mixed into a
``jax.numpy`` expression they promote bf16/f32 operands upward (or,
with x64 disabled, silently truncate to f32, so the annotation lies
either way).  Python float literals are weak-typed and fine; it is
specifically ``np.float64(...)`` / ``dtype=np.float64`` /
``dtype="float64"`` / ``.astype("float64")`` in library code that
leaks.  Host-side f64 precompute that is explicitly cast before use is
a reviewed exception (inline-suppress it with a justification).

GL105: ``np.random.*`` draws in library (non-test, non-dataset) code
break run-to-run determinism — the repo's convention is jax PRNG keys
threaded through ``apply``/``update`` (or utils/imgops.py's salted
SeedSequence for host-side image ops).  Seeded constructions
(``np.random.default_rng(seed)``, ``SeedSequence(seed)``) are allowed;
seedless ones and the global-state module functions are not.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import Rule, register
from tools.graftlint.tracing import dotted

NP_NAMES = {"np", "numpy"}
# seeded construction of these is deterministic and allowed
SEEDED_CTORS = {"default_rng", "Generator", "SeedSequence", "PCG64",
                "Philox", "MT19937", "SFC64", "BitGenerator"}

# wire-format modules OUTSIDE interop/ whose f64 is mandated by an
# external schema, exactly like interop/ itself: the tensorboard event
# proto stores scalars as doubles (utils/summary.py) and the TF
# DataType wire enum table needs DT_DOUBLE (ops/registry.py).  Values
# never reach a jnp expression — they are serialized or mapped on the
# host.
WIRE_FORMAT_MODULES = frozenset({
    "bigdl_tpu/utils/summary.py",
    "bigdl_tpu/ops/registry.py",
})


@register
class Float64Rule(Rule):
    id = "GL104"
    name = "float64-promotion"
    severity = "error"
    description = ("np.float64 / dtype='float64' in library code promotes "
                   "under jax.numpy (or silently truncates with x64 off)")

    def check(self, ctx):
        # interop/ is the wire-format boundary: TF DataType enums, torch
        # t7 storage classes and protobuf schemas mandate f64 there, and
        # everything is converted on import — exempt the whole dir, plus
        # the named wire-format modules with the same external-schema
        # obligation (WIRE_FORMAT_MODULES)
        if not ctx.is_library or ctx.is_interop:
            return
        norm = ctx.path.replace("\\", "/")
        if any(norm.endswith(m) for m in WIRE_FORMAT_MODULES):
            return
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Attribute) and n.attr == "float64":
                base = dotted(n.value)
                if base in NP_NAMES or base == "jnp":
                    yield self.violation(
                        ctx, n, f"{base}.float64 in library code: numpy "
                        "f64 scalars are strongly typed and promote jnp "
                        "operands (with x64 disabled the dtype is a lie); "
                        "use explicit f32/bf16, or suppress with a "
                        "justification for host-side precompute")
            elif (isinstance(n, ast.keyword) and n.arg == "dtype"
                  and isinstance(n.value, ast.Constant)
                  and n.value.value == "float64"):
                yield self.violation(
                    ctx, n.value, "dtype='float64' in library code; use "
                    "an explicit f32/bf16 dtype")
            elif (isinstance(n, ast.Call)
                  and isinstance(n.func, ast.Attribute)
                  and n.func.attr == "astype" and n.args
                  and isinstance(n.args[0], ast.Constant)
                  and n.args[0].value == "float64"):
                yield self.violation(
                    ctx, n, ".astype('float64') in library code; use an "
                    "explicit f32/bf16 dtype")


@register
class NpRandomRule(Rule):
    id = "GL105"
    name = "nondeterministic-rng"
    severity = "error"
    description = ("np.random.* in library (non-test, non-dataset) code "
                   "breaks determinism; thread jax PRNG keys or a seeded "
                   "Generator instead")

    def check(self, ctx):
        if not ctx.is_library:
            return
        for n in ast.walk(ctx.tree):
            if not isinstance(n, ast.Call):
                continue
            fn = dotted(n.func)
            if fn is None:
                continue
            parts = fn.split(".")
            if len(parts) < 3 or parts[0] not in NP_NAMES \
                    or parts[1] != "random":
                continue
            tail = parts[2]
            if tail in SEEDED_CTORS:
                if n.args or n.keywords:
                    continue  # explicitly seeded → deterministic
                yield self.violation(
                    ctx, n, f"np.random.{tail}() without a seed is "
                    "entropy-seeded; pass an explicit seed (see "
                    "utils/imgops.py for the salted-SeedSequence idiom)")
            else:
                yield self.violation(
                    ctx, n, f"np.random.{tail}(...) uses numpy's global "
                    "RNG state in library code; thread a jax PRNG key "
                    "(apply/update rng arg) or a seeded np Generator")
