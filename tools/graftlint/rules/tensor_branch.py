"""GL102 tensor-branch: Python control flow on tensor values in traced
code.

``if x.sum() > 0:`` under jit raises TracerBoolConversionError; under
partial evaluation it silently bakes one branch into the compiled
program.  The fix is structural: ``lax.cond`` / ``jnp.where`` for
branches, ``lax.while_loop`` / bounded ``lax.scan`` for loops (see
nn/control_flow.py for the framework's own wrappers).

Static branches stay legal: hyper-parameter checks (``self.momentum ==
0``), shape/rank dispatch (``x.ndim == 3``), ``rng is None`` plumbing —
the taint model in tracing.py distinguishes them.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import Rule, register
from tools.graftlint.tracing import iter_scope


def _is_none_check(test: ast.AST) -> bool:
    return (isinstance(test, ast.Compare)
            and all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops))


@register
class TensorBranchRule(Rule):
    id = "GL102"
    name = "tensor-branch"
    severity = "error"
    description = ("Python if/while/assert on a tensor-valued expression "
                   "inside a traced function (needs lax.cond / "
                   "lax.while_loop / jnp.where)")

    def check(self, ctx):
        for fi in ctx.traced.iter_traced():
            tainted = ctx.traced.tainted_names(fi.node)
            for n in iter_scope(fi.node):
                if isinstance(n, (ast.If, ast.While)):
                    test, kind = n.test, type(n).__name__.lower()
                    fix = ("lax.cond or jnp.where" if kind == "if"
                           else "lax.while_loop or a bounded lax.scan")
                elif isinstance(n, ast.Assert):
                    test, kind, fix = n.test, "assert", \
                        "checkify or a host-side precondition"
                elif isinstance(n, ast.IfExp):
                    test, kind = n.test, "conditional expression"
                    fix = "jnp.where or lax.cond"
                else:
                    continue
                if _is_none_check(test):
                    continue
                if ctx.traced.is_static(test, tainted):
                    continue
                yield self.violation(
                    ctx, n, f"Python {kind} branches on a tensor-valued "
                    f"expression inside traced `{fi.name}`; use {fix}")
