"""Traced-scope model: which functions run under a jax trace, and which
values inside them are tracers.

The whole ruleset keys off this model, so it encodes the repo's own
conventions rather than generic JAX ones:

- ``nn/module.py`` contract: ``Module.apply`` (and the legacy
  ``update_output``/``update_grad_input`` names) is the pure traced
  forward; ``forward`` is the *eager* convenience layer and is NOT
  traced.  A class counts as a Module if its base-name chain (resolved
  within the file) reaches one of ``MODULE_BASES`` — this keeps
  ``transform/vision.py``'s host-side ``FeatureTransformer.apply``
  (numpy image ops) out of the traced set.
- ``optim/optim_method.py`` contract: ``update(grads, params,
  opt_state, lr, step)`` on an ``OptimMethod`` subclass is traced.
- anything decorated with a jax transform (``jit``/``vmap``/``grad``/
  ``checkpoint``/``shard_map``/…), directly or via
  ``functools.partial(jax.jit, ...)``.
- functions *passed to* a transform or a ``lax`` control-flow combinator
  (``lax.cond``/``scan``/``while_loop``/…) at any call site.
- closure: functions defined inside a traced function, and functions
  reachable from a traced function through same-file calls (bare names
  and ``self.method``) — this is what makes "host-sync reachable from a
  jitted path" checkable.

Taint: per traced function, which local names are tensor-valued.
Parameters are tainted (minus ``self``/``cls``/``training``); static
accessors (``.shape``/``.ndim``/``.dtype``/``.size``, ``len()``,
``isinstance()``, ``self.*`` hyper-parameters) launder taint away, and
host-sync escapes (``.item()``/``.tolist()``/``float()``) produce
static values (they are GL101's problem, not GL102's).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

# jax transforms whose application makes a function traced
TRANSFORMS = {
    "jit", "pmap", "vmap", "grad", "value_and_grad", "checkpoint", "remat",
    "shard_map", "custom_vjp", "custom_jvp", "xmap",
}
# lax-style combinators whose callable arguments are traced
COMBINATORS = TRANSFORMS | {
    "cond", "while_loop", "fori_loop", "scan", "switch", "associative_scan",
    "map",
}

# class base names whose `apply` follows the traced Module/Criterion
# contract (textual match after in-file transitive resolution)
MODULE_BASES = {
    "Module", "Container", "Sequential", "Concat", "ConcatTable",
    "ParallelTable", "Criterion", "KerasLayer",
}
OPTIM_BASES = {"OptimMethod"}

TRACED_METHODS = {"apply", "update_output", "update_grad_input"}
OPTIM_TRACED_METHODS = {"update"}

# parameters that are never tracers under the repo's contracts
UNTAINTED_PARAMS = {"self", "cls", "training"}

# attributes that are static metadata even on a tracer.
# dense_shape/n_rows: COOBatch pytree AUX metadata (nn/sparse.py) —
# carried outside the leaves, so they are host ints on every trace
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "name", "aval",
                "weak_type", "dense_shape", "n_rows"}

# calls that return host/static values regardless of argument taint
STATIC_CALLS = {"len", "isinstance", "issubclass", "getattr", "hasattr",
                "range", "type", "str", "repr", "format", "id",
                # pytree STRUCTURE queries: emptiness/arity of a pytree is
                # static even when its leaves are tracers
                "tree_leaves", "tree_structure", "tree_flatten",
                # mesh topology is compile-time constant (axis_index is
                # NOT: it is a per-device traced value)
                "axis_size", "psum_scatter_count"}

# methods whose *result* is a host value even on a tracer (the sync
# itself is GL101's finding; the result no longer taints control flow)
SYNC_METHODS = {"item", "tolist"}
SYNC_CASTS = {"float", "int", "bool", "complex"}


def dotted(node: ast.AST) -> Optional[str]:
    """'jax.lax.cond' for nested Attributes, 'jit' for a Name; None
    otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_seg(node: ast.AST) -> Optional[str]:
    d = dotted(node)
    return d.rsplit(".", 1)[-1] if d else None


class FuncInfo:
    def __init__(self, node, name, class_name, parent):
        self.node = node
        self.name = name
        self.class_name = class_name      # nearest enclosing class, or None
        self.parent = parent              # enclosing FuncInfo, or None


def iter_scope(node: ast.AST):
    """Yield descendant nodes of a function body without descending into
    nested function/class definitions (they are scopes of their own)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def collect_functions(tree: ast.AST, on_class=None):
    """The ONE function indexer behind the traced/thread/resource
    models: ``(funcs by id(node), name -> [FuncInfo])`` with
    nearest-enclosing class and function attribution, nested defs
    included.  ``on_class(node)`` is called once per ClassDef (the
    traced model records base names there)."""
    funcs: Dict[int, FuncInfo] = {}
    by_name: Dict[str, List[FuncInfo]] = {}

    def walk(node, class_name, parent):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                if on_class is not None:
                    on_class(child)
                walk(child, child.name, parent)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                fi = FuncInfo(child, child.name, class_name, parent)
                funcs[id(child)] = fi
                by_name.setdefault(child.name, []).append(fi)
                walk(child, class_name, fi)
            else:
                walk(child, class_name, parent)

    walk(tree, None, None)
    return funcs, by_name


class TracedModel:
    def __init__(self, tree: ast.Module, path: str):
        self.tree = tree
        self.path = path.replace("\\", "/")
        self.class_bases: Dict[str, List[str]] = {}

        def _bases(child):
            self.class_bases[child.name] = [
                s for s in (last_seg(b) for b in child.bases) if s]

        self.funcs, self.by_name = collect_functions(tree,
                                                     on_class=_bases)
        self.traced_ids: Set[int] = set()
        self.root_ids: Set[int] = set()
        self._mark_roots()
        self._propagate()
        self._taint_cache: Dict[int, Set[str]] = {}
        # name → True when some same-file function of that name returns a
        # tensor-valued expression (name-based: scoping ignored on purpose,
        # it only has to be right often enough to seed the taint pass)
        self._ret_tainted: Dict[str, bool] = {}
        self._compute_taints()

    def _class_reaches(self, cls: Optional[str], targets: Set[str],
                       seen: Optional[Set[str]] = None) -> bool:
        """Follow in-file base-name edges; an imported (unresolvable) base
        matches textually against ``targets``."""
        if cls is None:
            return False
        seen = seen or set()
        if cls in seen:
            return False
        seen.add(cls)
        for b in self.class_bases.get(cls, []):
            if b in targets:
                return True
            if b in self.class_bases and self._class_reaches(b, targets,
                                                             seen):
                return True
        return False

    # ----------------------------------------------------------------- roots
    def _decorator_is_transform(self, dec: ast.AST) -> bool:
        if isinstance(dec, ast.Call):
            # @partial(jax.jit, ...) / @jax.jit(static_argnums=...)
            if last_seg(dec.func) == "partial":
                return any(last_seg(a) in TRANSFORMS for a in dec.args)
            return last_seg(dec.func) in TRANSFORMS
        return last_seg(dec) in TRANSFORMS

    def _mark_roots(self):
        for fi in self.funcs.values():
            node = fi.node
            if any(self._decorator_is_transform(d)
                   for d in node.decorator_list):
                self._add_root(id(node))
                continue
            if fi.class_name is not None:
                if (fi.name in TRACED_METHODS
                        and self._class_reaches(fi.class_name,
                                                MODULE_BASES)):
                    self._add_root(id(node))
                elif (fi.name in OPTIM_TRACED_METHODS
                      and self._class_reaches(fi.class_name, OPTIM_BASES)):
                    self._add_root(id(node))
        # functions passed to transforms / combinators at any call site
        for call in ast.walk(self.tree):
            if not isinstance(call, ast.Call):
                continue
            seg = last_seg(call.func)
            if seg not in COMBINATORS:
                continue
            if seg == "map":
                # builtin map(fn, xs) is host iteration — only
                # lax.map/jax.lax.map traces its callable
                d = dotted(call.func)
                if not (d and d.endswith("lax.map")):
                    continue
            cands = list(call.args) + [k.value for k in call.keywords]
            for a in cands:
                if isinstance(a, ast.Name):
                    for fi in self.by_name.get(a.id, []):
                        self._add_root(id(fi.node))
                elif (isinstance(a, ast.Call)
                      and last_seg(a.func) == "partial"):
                    for inner in a.args:
                        if isinstance(inner, ast.Name):
                            for fi in self.by_name.get(inner.id, []):
                                self._add_root(id(fi.node))

    def _add_root(self, nid: int):
        self.traced_ids.add(nid)
        self.root_ids.add(nid)

    # ----------------------------------------------------------- propagation
    def _ancestors(self, fi: FuncInfo) -> Set[int]:
        out = set()
        cur: Optional[FuncInfo] = fi
        while cur is not None:
            out.add(id(cur))
            cur = cur.parent
        return out

    def _resolve_call(self, fi: FuncInfo, call: ast.Call):
        """Same-file callee candidates for a Call made inside ``fi``:
        bare names resolve to module-level functions and closure-visible
        nested defs; ``self.m(...)`` resolves to same-file methods."""
        if isinstance(call.func, ast.Name):
            anc = self._ancestors(fi)
            cands = [c for c in self.by_name.get(call.func.id, [])
                     if (c.parent is None and c.class_name is None)
                     or (c.parent is not None and id(c.parent) in anc)]
            return call.func.id, cands
        if (isinstance(call.func, ast.Attribute)
                and isinstance(call.func.value, ast.Name)
                and call.func.value.id == "self"):
            cands = [c for c in self.by_name.get(call.func.attr, [])
                     if c.class_name is not None]
            return call.func.attr, cands
        return None, []

    def _propagate(self):
        """Fixpoint: nested defs of traced funcs are traced; same-file
        callees of traced funcs (bare name / self.method) are traced."""
        changed = True
        while changed:
            changed = False
            for fi in self.funcs.values():
                if id(fi.node) in self.traced_ids:
                    continue
                if fi.parent and id(fi.parent.node) in self.traced_ids:
                    self.traced_ids.add(id(fi.node))
                    changed = True
            for fi in list(self.funcs.values()):
                if id(fi.node) not in self.traced_ids:
                    continue
                for n in iter_scope(fi.node):
                    if not isinstance(n, ast.Call):
                        continue
                    callee, cands = self._resolve_call(fi, n)
                    if callee in (None, "__init__", "init", "initialize"):
                        continue  # eager setup paths, never traced
                    for c in cands:
                        if id(c.node) not in self.traced_ids:
                            self.traced_ids.add(id(c.node))
                            changed = True

    # ------------------------------------------------------------ public API
    def is_traced(self, node: ast.AST) -> bool:
        return id(node) in self.traced_ids

    def iter_traced(self):
        for fi in self.funcs.values():
            if id(fi.node) in self.traced_ids:
                yield fi

    # ---------------------------------------------------------------- taint
    def tainted_names(self, func: ast.AST) -> Set[str]:
        """Final local taint set for a traced function (computed by the
        fixpoint in _compute_taints).  Untraced functions fall back to
        the conservative all-params view."""
        if id(func) not in self._taint_cache:
            self._taint_cache[id(func)] = self._local_taint(
                func, set(_all_param_names(func)) - UNTAINTED_PARAMS)
        return self._taint_cache[id(func)]

    def _local_taint(self, func: ast.AST, init: Set[str]) -> Set[str]:
        """Propagate an initial tainted-name set through the function's
        own assignments (two passes so forward references settle)."""
        tainted = set(init)
        for _ in range(2):
            for n in iter_scope(func):
                if isinstance(n, ast.Assign):
                    static = self.is_static(n.value, tainted)
                    for t in n.targets:
                        for name in _target_names(t):
                            (tainted.discard if static
                             else tainted.add)(name)
                elif isinstance(n, ast.AnnAssign) and n.value is not None:
                    for name in _target_names(n.target):
                        (tainted.discard
                         if self.is_static(n.value, tainted)
                         else tainted.add)(name)
                elif isinstance(n, ast.AugAssign):
                    if not self.is_static(n.value, tainted):
                        for name in _target_names(n.target):
                            tainted.add(name)
                elif isinstance(n, ast.For):
                    self._bind_for_target(n, tainted)
                elif isinstance(n, (ast.With, ast.AsyncWith)):
                    for item in n.items:
                        if item.optional_vars is not None and \
                                not self.is_static(item.context_expr,
                                                   tainted):
                            for name in _target_names(item.optional_vars):
                                tainted.add(name)
        return tainted

    def _bind_for_target(self, n: ast.For, tainted: Set[str]) -> None:
        """Loop-target taint with container-structure awareness: dict
        KEYS are static metadata even when the values are tracers
        (``for name, v in input.items()`` — name is a feed name, v a
        tensor); same for enumerate indices and zip per-position."""
        def bind(target, static):
            for name in _target_names(target):
                (tainted.discard if static else tainted.add)(name)

        it, tgt = n.iter, n.target
        if isinstance(it, ast.Call):
            two = isinstance(tgt, ast.Tuple) and len(tgt.elts) == 2
            if isinstance(it.func, ast.Attribute):
                recv_static = self.is_static(it.func.value, tainted)
                if it.func.attr == "items" and two:
                    bind(tgt.elts[0], True)
                    bind(tgt.elts[1], recv_static)
                    return
                if it.func.attr == "keys":
                    bind(tgt, True)
                    return
            fn = last_seg(it.func)
            if fn == "enumerate" and two and it.args:
                bind(tgt.elts[0], True)
                bind(tgt.elts[1], self.is_static(it.args[0], tainted))
                return
            if fn == "zip" and isinstance(tgt, ast.Tuple) \
                    and len(tgt.elts) == len(it.args):
                for t_i, a_i in zip(tgt.elts, it.args):
                    bind(t_i, self.is_static(a_i, tainted))
                return
        bind(tgt, self.is_static(it, tainted))

    def _compute_taints(self):
        """Param-level taint, interprocedurally:

        - root-traced functions (jit-decorated, contract methods,
          combinator callbacks): every param is a tracer;
        - call-graph-propagated helpers: only params bound to a tainted
          argument at some same-file call site — so
          ``_conv_dims(self.format)`` style config helpers stay
          branchable even though they are reachable from jitted paths;
        - nested defs additionally inherit the enclosing scope's taint
          (closure capture), minus names shadowed by their own params.

        Monotone fixpoint: taints only grow, so it terminates.
        """
        pt: Dict[int, Set[str]] = {}
        for fi in self.iter_traced():
            nid = id(fi.node)
            if nid in self.root_ids:
                pt[nid] = (set(_all_param_names(fi.node))
                           - UNTAINTED_PARAMS
                           - _static_config_params(fi.node))
            else:
                pt[nid] = set()
        local: Dict[int, Set[str]] = {}
        for _ in range(12):  # files converge in 2-3 rounds
            changed = False
            local = {}
            # funcs dict preserves collection order: parents first
            for fi in self.iter_traced():
                inherited: Set[str] = set()
                if fi.parent is not None and id(fi.parent.node) in local:
                    inherited = (local[id(fi.parent.node)]
                                 - set(_all_param_names(fi.node)))
                local[id(fi.node)] = self._local_taint(
                    fi.node, pt[id(fi.node)] | inherited)
                lt = local[id(fi.node)]
                for n in iter_scope(fi.node):
                    if isinstance(n, ast.Return) and n.value is not None \
                            and not self.is_static(n.value, lt) \
                            and not self._ret_tainted.get(fi.name):
                        self._ret_tainted[fi.name] = True
                        changed = True
            for fi in self.iter_traced():
                lt = local[id(fi.node)]
                for call in iter_scope(fi.node):
                    if not isinstance(call, ast.Call):
                        continue
                    _, cands = self._resolve_call(fi, call)
                    for c in cands:
                        cid = id(c.node)
                        if cid not in pt or cid in self.root_ids:
                            continue
                        if self._bind_call_taint(call, c.node, lt,
                                                 pt[cid]):
                            changed = True
            if not changed:
                break
        self._taint_cache = dict(local)

    def _bind_call_taint(self, call: ast.Call, callee: ast.AST,
                         caller_taint: Set[str],
                         callee_pt: Set[str]) -> bool:
        """Bind tainted caller arguments to callee param names.  Returns
        True when callee_pt grew."""
        a = callee.args
        pos = [x.arg for x in list(getattr(a, "posonlyargs", [])) + a.args]
        if pos and pos[0] in ("self", "cls"):
            pos = pos[1:]
        allp = set(_all_param_names(callee))
        # a scalar type annotation is a declaration that the param is
        # host-side config — trust it over the call-site binding
        declared_static = _annotated_static_params(callee)
        before = len(callee_pt)
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                if not self.is_static(arg.value, caller_taint):
                    callee_pt.update(pos[i:])
                    if a.vararg is not None:
                        callee_pt.add(a.vararg.arg)
                break
            if self.is_static(arg, caller_taint):
                continue
            if i < len(pos):
                callee_pt.add(pos[i])
            elif a.vararg is not None:
                callee_pt.add(a.vararg.arg)
        for kw in call.keywords:
            if self.is_static(kw.value, caller_taint):
                continue
            if kw.arg is None or kw.arg not in allp:
                if a.kwarg is not None:
                    callee_pt.add(a.kwarg.arg)
            else:
                callee_pt.add(kw.arg)
        callee_pt -= UNTAINTED_PARAMS
        callee_pt -= declared_static
        return len(callee_pt) > before

    def is_static(self, node: ast.AST, tainted: Set[str]) -> bool:
        """True when the expression is host-computable (hyper-parameters,
        shapes, constants) — i.e. safe to branch on at trace time."""
        if node is None or isinstance(node, (ast.Constant, ast.JoinedStr,
                                             ast.Lambda)):
            return True
        if isinstance(node, ast.Name):
            return node.id not in tainted
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return True
            return self.is_static(node.value, tainted)
        if isinstance(node, ast.Subscript):
            return (self.is_static(node.value, tainted)
                    and self.is_static(node.slice, tainted))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return all(self.is_static(e, tainted) for e in node.elts)
        if isinstance(node, ast.Dict):
            return all(self.is_static(e, tainted)
                       for e in (node.keys + node.values) if e is not None)
        if isinstance(node, ast.Starred):
            return self.is_static(node.value, tainted)
        if isinstance(node, ast.Slice):
            return all(self.is_static(e, tainted)
                       for e in (node.lower, node.upper, node.step))
        if isinstance(node, ast.UnaryOp):
            return self.is_static(node.operand, tainted)
        if isinstance(node, ast.BinOp):
            return (self.is_static(node.left, tainted)
                    and self.is_static(node.right, tainted))
        if isinstance(node, ast.BoolOp):
            return all(self.is_static(v, tainted) for v in node.values)
        if isinstance(node, ast.Compare):
            # identity checks (`rng is None`) are resolved at trace
            # time regardless of what the operands hold
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in node.ops):
                return True
            # `"key" in params` / `nm in memo`: membership of a static
            # key in a dict/pytree is a static structure probe, even
            # when the container's leaves are tracers.  (Limitation:
            # `x in arr` elementwise membership on an *array* with a
            # static x is not caught — rare, and jnp.isin is the idiom.)
            if (all(isinstance(op, (ast.In, ast.NotIn))
                    for op in node.ops)
                    and self.is_static(node.left, tainted)):
                return True
            return (self.is_static(node.left, tainted)
                    and all(self.is_static(c, tainted)
                            for c in node.comparators))
        if isinstance(node, (ast.GeneratorExp, ast.ListComp, ast.SetComp,
                             ast.DictComp)):
            inner = set(tainted)
            for gen in node.generators:
                names = set(_target_names(gen.target))
                if self.is_static(gen.iter, inner):
                    inner -= names
                else:
                    inner |= names
                if not all(self.is_static(i, inner) for i in gen.ifs):
                    return False
            if isinstance(node, ast.DictComp):
                return (self.is_static(node.key, inner)
                        and self.is_static(node.value, inner))
            return self.is_static(node.elt, inner)
        if isinstance(node, ast.IfExp):
            return all(self.is_static(e, tainted)
                       for e in (node.test, node.body, node.orelse))
        if isinstance(node, ast.Call):
            # host-sync escapes: result is a python scalar (GL101 flags
            # the sync itself)
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in SYNC_METHODS):
                return True
            fn = last_seg(node.func)
            if fn in SYNC_CASTS or fn in STATIC_CALLS:
                return True
            # contract methods return tensors by definition; same-file
            # functions known to return tensor-valued expressions too
            if fn in TRACED_METHODS or fn in OPTIM_TRACED_METHODS \
                    or self._ret_tainted.get(fn):
                return False
            return (self.is_static(node.func, tainted)
                    and all(self.is_static(a, tainted) for a in node.args)
                    and all(self.is_static(k.value, tainted)
                            for k in node.keywords))
        return False  # unknown expression kinds: assume tensor-valued


def _annotated_static_params(func: ast.AST) -> Set[str]:
    """Params annotated with a Python scalar type (``causal: bool``,
    ``target: str``) — a declaration that the value is host-side config;
    traced values are arrays and annotated as such."""
    out: Set[str] = set()
    a = func.args
    for arg in (list(getattr(a, "posonlyargs", [])) + a.args
                + a.kwonlyargs):
        ann = arg.annotation
        if isinstance(ann, ast.Name) and ann.id in ("str", "bool", "int",
                                                    "float"):
            out.add(arg.arg)
    return out


def _static_config_params(func: ast.AST) -> Set[str]:
    """Params of a *root* traced function that are static config rather
    than tracers: scalar-annotated (see _annotated_static_params — under
    shard_map/partial these are bound statically), or carrying a Python
    scalar default (``eps=1e-6``)."""
    out = _annotated_static_params(func)
    a = func.args
    pos = list(getattr(a, "posonlyargs", [])) + a.args
    for arg, d in zip(reversed(pos), reversed(a.defaults)):
        if isinstance(d, ast.Constant) and isinstance(
                d.value, (bool, int, float, str)):
            out.add(arg.arg)
    for arg, d in zip(a.kwonlyargs, a.kw_defaults):
        if isinstance(d, ast.Constant) and isinstance(
                d.value, (bool, int, float, str)):
            out.add(arg.arg)
    return out


def _all_param_names(func: ast.AST) -> List[str]:
    a = func.args
    out = [x.arg for x in (list(getattr(a, "posonlyargs", [])) + a.args
                           + a.kwonlyargs)]
    for x in (a.vararg, a.kwarg):
        if x is not None:
            out.append(x.arg)
    return out


def _target_names(t: ast.AST):
    if isinstance(t, ast.Name):
        yield t.id
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            yield from _target_names(e)
    elif isinstance(t, ast.Starred):
        yield from _target_names(t.value)
    # attribute/subscript stores don't bind local names
