"""Resource model: which tracked resources exist, who acquires and
releases them, and which call sites own an acquisition.

The GL3xx rule family keys off this model the same way GL2xx keys off
``threads.ThreadModel`` — it encodes the repo's own exception-path
resource conventions rather than generic ones.  The bug class it
exists for is PR 14's review round 4: a wire-inflight pin acquired by
``_resolve_pinned`` leaked when a statement between the acquire and its
``try/finally`` raised — the pin wedged ``HotCutover`` until timeout.
Locks have ``with``; *counted* resources (inflight pins, probe slots,
queue-row counters) have nothing — so the contract becomes a
lightweight annotation the linter can check:

- **``# acquires: <resource>`` on a ``def`` line** — calling this
  function acquires the named resource and OWNERSHIP TRANSFERS TO THE
  CALLER (``_WireInflight.enter``, ``_resolve_pinned``).  GL301 checks
  every same-file call site: the acquisition must be covered by a
  ``try/finally`` that releases it (or the calling function must
  itself be ``# acquires:``-annotated, passing ownership further up).
  A *may-acquire* API (``ReplicaHealth.admit`` returns whether this
  request is the probe) uses the same annotation — the caller owns the
  release on the paths where the acquire happened.
- **``# releases: <resource>`` on a ``def`` line** — calling this
  function releases the resource (``_WireInflight.exit``,
  ``ReplicaHealth.cancel_probe``).  A ``finally`` body containing such
  a call is what protects an acquisition.
- **On a plain statement** (normally an attribute increment/decrement)
  the annotations mark the PRIMITIVE inc/dec sites of a paired counter
  (``self._q_rows += req.n_rows`` tagged ``# acquires: <resource>``
  in ``serving/batcher.py``).  GL303
  checks the pairing: a resource with acquire sites but no release
  site anywhere in the file is a one-way counter, and any *unannotated*
  mutation of a marked attribute (outside ``__init__``) is a new
  inc/dec added outside the discipline.

Placement follows the suppression/``guarded-by`` convention: a
trailing comment annotates that statement (a ``def`` line annotates the
function); a standalone comment line annotates the next statement.
Several resources comma-separate.

Resolution is NAME-based and same-file (the house model): a call whose
last segment matches an annotated ``def`` in this file carries that
def's resources.  Cross-module ownership (``replica_set`` calling
``health.admit``) is out of scope — per-file contracts are the unit,
exactly like the thread model; annotate the boundary def in its own
file and keep the cross-file contract in prose.

The model also carries the GL302 client-error declaration:
``# graftlint: client-error=Name[,Name]`` extends the wire error
taxonomy (the exception types allowed to map to HTTP 4xx) for one
file.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from tools.graftlint.tracing import (FuncInfo, collect_functions,
                                     iter_scope, last_seg)

_RES_RE = re.compile(
    r"#.*?\b(acquires|releases)\s*:\s*"
    r"([A-Za-z_][A-Za-z0-9_]*(?:\s*,\s*[A-Za-z_][A-Za-z0-9_]*)*)")

_CLIENT_DECL_RE = re.compile(
    r"#\s*graftlint:\s*client-errors?\s*=\s*"
    r"([A-Za-z_][A-Za-z0-9_]*(?:\s*,\s*[A-Za-z_][A-Za-z0-9_]*)*)")

ACQUIRES = "acquires"
RELEASES = "releases"


class ResourceModel:
    """Per-file acquire/release model (see module docstring)."""

    def __init__(self, tree: ast.Module, source: str, path: str):
        self.tree = tree
        self.path = path
        self.lines = source.splitlines()

        # function index (the shared tracing.collect_functions walker)
        self.funcs: Dict[int, FuncInfo]
        self.by_name: Dict[str, List[FuncInfo]]
        self.funcs, self.by_name = collect_functions(tree)

        # line -> (kind, {resources}) from the annotation comments
        self._ann_lines = self._annotation_lines()

        # id(def node) -> resources; and name -> resources for call
        # resolution (union over same-named defs — name-based model)
        self.def_acquires: Dict[int, Set[str]] = {}
        self.def_releases: Dict[int, Set[str]] = {}
        self.name_acquires: Dict[str, Set[str]] = {}
        self.name_releases: Dict[str, Set[str]] = {}
        # statement-level primitive sites:
        # line -> (kind, {resources}) for non-def statements
        self.stmt_sites: Dict[int, Tuple[str, Set[str]]] = {}
        self._bind()

        # GL303 bookkeeping: (class, attr) -> set of resources marked on
        # its mutation sites, and every mutation site of those attrs
        self.marked_attrs: Dict[Tuple[Optional[str], str], Set[str]] = {}
        self._mark_attrs()

        # GL302: file-extended client-error taxonomy
        self.client_errors: Set[str] = set()
        for line in self.lines:
            m = _CLIENT_DECL_RE.search(line)
            if m:
                self.client_errors |= {t.strip()
                                       for t in m.group(1).split(",")
                                       if t.strip()}

    def _annotation_lines(self) -> Dict[int, Tuple[str, Set[str]]]:
        """statement line -> (kind, resources), with the standalone-
        comment-annotates-next-statement placement rule."""
        out: Dict[int, Tuple[str, Set[str]]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _RES_RE.search(line)
            if not m:
                continue
            kind, names = m.groups()
            toks = {t.strip() for t in names.split(",") if t.strip()}
            if line.lstrip().startswith("#"):
                j = i
                while j < len(self.lines) and (
                        not self.lines[j].strip()
                        or self.lines[j].lstrip().startswith("#")):
                    j += 1
                out[j + 1] = (kind, toks)
            else:
                out[i] = (kind, toks)
        return out

    def _bind(self):
        if not self._ann_lines:
            return
        def_lines = {fi.node.lineno: fi for fi in self.funcs.values()}
        for line, (kind, toks) in self._ann_lines.items():
            fi = def_lines.get(line)
            if fi is not None:
                dst = (self.def_acquires if kind == ACQUIRES
                       else self.def_releases)
                dst.setdefault(id(fi.node), set()).update(toks)
                by = (self.name_acquires if kind == ACQUIRES
                      else self.name_releases)
                by.setdefault(fi.name, set()).update(toks)
            else:
                prev = self.stmt_sites.get(line)
                if prev is not None and prev[0] != kind:
                    # a statement can only be one kind; keep the first
                    continue
                if prev is not None:
                    prev[1].update(toks)
                else:
                    self.stmt_sites[line] = (kind, set(toks))

    # ----------------------------------------------------- GL303 attr marks
    @staticmethod
    def _mutated_attr(stmt: ast.AST) -> Optional[str]:
        """Attribute name when ``stmt`` stores to ``self.X`` or
        ``self.X[...]`` (Assign/AugAssign/AnnAssign/Delete), else
        None."""
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Delete):
            targets = list(stmt.targets)
        for t in targets:
            if isinstance(t, ast.Subscript):
                t = t.value
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                return t.attr
        return None

    def _mark_attrs(self):
        for fi in self.funcs.values():
            for stmt in iter_scope(fi.node):
                if not isinstance(stmt, (ast.Assign, ast.AugAssign,
                                         ast.AnnAssign, ast.Delete)):
                    continue
                site = self.stmt_sites.get(stmt.lineno)
                if site is None:
                    continue
                attr = self._mutated_attr(stmt)
                if attr is not None:
                    self.marked_attrs.setdefault(
                        (fi.class_name, attr), set()).update(site[1])

    # ------------------------------------------------------- call resolution
    def call_acquires(self, call: ast.Call) -> Set[str]:
        """Resources acquired by this call (name-based, same-file)."""
        seg = last_seg(call.func)
        if seg is None and isinstance(call.func, ast.Attribute):
            seg = call.func.attr
        return set(self.name_acquires.get(seg or "", set()))

    def call_releases(self, call: ast.Call) -> Set[str]:
        seg = last_seg(call.func)
        if seg is None and isinstance(call.func, ast.Attribute):
            seg = call.func.attr
        return set(self.name_releases.get(seg or "", set()))

    def releases_in(self, body: List[ast.stmt], resource: str) -> bool:
        """Whether ``body`` (e.g. a ``finally`` suite) releases the
        resource: a call to a release-annotated def, or a statement
        annotated ``# releases: <resource>``."""
        for stmt in body:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Call) \
                        and resource in self.call_releases(n):
                    return True
            site = self.stmt_sites.get(stmt.lineno)
            if site is not None and site[0] == RELEASES \
                    and resource in site[1]:
                return True
        return False

    # ------------------------------------------------------- site inventory
    def acquire_stmt_sites(self) -> List[Tuple[int, Set[str]]]:
        return sorted((line, toks) for line, (kind, toks)
                      in self.stmt_sites.items() if kind == ACQUIRES)

    def release_stmt_sites(self) -> List[Tuple[int, Set[str]]]:
        return sorted((line, toks) for line, (kind, toks)
                      in self.stmt_sites.items() if kind == RELEASES)

    def has_annotations(self) -> bool:
        return bool(self.def_acquires or self.def_releases
                    or self.stmt_sites)
