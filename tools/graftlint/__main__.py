"""CLI: ``python -m tools.graftlint bigdl_tpu``.

Exit code 0 when no error-severity findings survive suppressions,
1 otherwise, 2 on usage errors.  ``--json`` prints the machine schema
(tests/test_graftlint.py asserts it); ``--changed-only`` scopes the run
to git-changed files for fast local iteration.
"""

from __future__ import annotations

import argparse
import os
import sys

# allow running from a checkout without installing: the repo root is the
# parent of tools/
_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.graftlint import core  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="JAX-hazard static analysis (see "
                    "tools/graftlint/README.md for the rule catalog)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories to lint "
                         "(default: bigdl_tpu)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (schema version "
                         f"{core.JSON_SCHEMA_VERSION}); alias of "
                         "--format json")
    ap.add_argument("--format", default=None,
                    choices=("human", "json", "sarif"),
                    help="output format: human (default), json "
                         "(graftlint schema) or sarif (SARIF 2.1.0 — "
                         "CI inline PR annotations)")
    ap.add_argument("--stats", action="store_true",
                    help="print per-rule finding/suppression counts "
                         "(the suppression-debt dashboard) and exit 0")
    ap.add_argument("--write-baseline", nargs="?", metavar="PATH",
                    const="", default=None,
                    help="with --stats: write the per-file suppression "
                         "baseline JSON (default "
                         "tools/graftlint/suppressions_baseline.json) "
                         "— the reviewed act that admits net-new "
                         "suppression debt past the tier-1 gate")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids/names to run; an id "
                         "prefix selects a family (--select GL2 runs "
                         "GL201-GL206) (default: all)")
    ap.add_argument("--changed-only", action="store_true",
                    help="lint only files changed vs --base "
                         "(plus untracked)")
    ap.add_argument("--base", default="HEAD",
                    help="git ref for --changed-only (default HEAD)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in core.all_rules():
            print(f"{r.id}  {r.name:24s} [{r.severity}] {r.description}")
        return 0

    fmt = args.format or ("json" if args.json else "human")
    if args.json and args.format and args.format != "json":
        print("graftlint: --json conflicts with "
              f"--format {args.format}", file=sys.stderr)
        return 2

    # default gate paths: the library AND the tools/ tree (bench.py
    # helpers and tools/*.py threaded code are part of the product)
    paths = args.paths or [p for p in ("bigdl_tpu", "tools", "bench.py")
                           if os.path.exists(p)] or ["bigdl_tpu"]
    for p in paths:
        if not os.path.exists(p):
            print(f"graftlint: path not found: {p}", file=sys.stderr)
            return 2
    select = ([s.strip() for s in args.select.split(",") if s.strip()]
              if args.select else None)
    if args.write_baseline is not None and not args.stats:
        print("graftlint: --write-baseline requires --stats (the "
              "baseline is the debt table, frozen)", file=sys.stderr)
        return 2
    if args.stats:
        # --stats is a whole-tree dashboard: scoping or reformatting
        # flags it cannot honor are usage errors, not silent no-ops
        if args.changed_only:
            print("graftlint: --stats does not support --changed-only "
                  "(the debt table is whole-tree)", file=sys.stderr)
            return 2
        if fmt == "sarif":
            print("graftlint: --stats has no SARIF form; use --json",
                  file=sys.stderr)
            return 2
        stats = core.lint_paths_stats(paths, select=select)
        import json
        if args.write_baseline is not None:
            if select:
                print("graftlint: --write-baseline must cover the "
                      "full ruleset (drop --select)", file=sys.stderr)
                return 2
            out = args.write_baseline or core.BASELINE_DEFAULT_PATH
            with open(out, "w", encoding="utf-8") as fh:
                json.dump(core.baseline_document(stats, paths), fh,
                          indent=2, sort_keys=True)
                fh.write("\n")
            # stderr: stdout carries the (possibly JSON) stats payload
            print(f"graftlint: baseline written to {out}",
                  file=sys.stderr)
        if fmt == "json":
            print(json.dumps(stats, indent=2, sort_keys=True))
        else:
            print(core.stats_to_human(stats))
        return 0
    result = core.lint_paths(paths, select=select,
                             changed_only=args.changed_only,
                             base=args.base)
    if fmt == "json":
        print(core.to_json(result))
    elif fmt == "sarif":
        print(core.to_sarif(result))
    else:
        print(core.to_human(result))
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
