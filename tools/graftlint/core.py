"""graftlint rule framework.

The analyzer is pure-AST (never imports the code it lints, never imports
jax) so it runs in milliseconds and can gate every PR from tier-1.

Pieces:

- :class:`Violation` — one finding (rule id, severity, path:line:col, msg).
- :class:`Rule` — base class; subclasses register themselves via
  :func:`register` and implement ``check(ctx)``.
- :class:`FileContext` — parsed file + the traced-scope model
  (``tracing.TracedModel``) + path predicates rules use for scoping.
- suppressions — ``# graftlint: disable=GL101`` (trailing: that line;
  standalone comment line: the next statement line) and
  ``# graftlint: disable-file=GL101`` (whole file).  Rule ids, rule
  names, and ``all`` are accepted.
- :func:`lint_source` / :func:`lint_paths` — drivers; JSON schema in
  :func:`to_json`.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import subprocess
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

from tools.graftlint import resources, spmd, threads, tracing

SEVERITIES = ("error", "warning")

JSON_SCHEMA_VERSION = 1


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str            # rule id, e.g. "GL101"
    name: str            # rule slug, e.g. "host-sync"
    severity: str        # "error" | "warning"
    path: str
    line: int
    col: int
    message: str

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.severity}] {self.message} ({self.name})")


class Rule:
    """One check.  Subclasses set id/name/severity/description and yield
    Violations from ``check``."""

    id: str = ""
    name: str = ""
    severity: str = "error"
    description: str = ""

    def check(self, ctx: "FileContext") -> Iterator[Violation]:
        raise NotImplementedError

    # helper so rules don't repeat the dataclass plumbing
    def violation(self, ctx: "FileContext", node: ast.AST,
                  message: str) -> Violation:
        return Violation(self.id, self.name, self.severity, ctx.path,
                         getattr(node, "lineno", 1),
                         getattr(node, "col_offset", 0) + 1, message)


REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and index by rule id."""
    inst = cls()
    assert inst.id and inst.name, cls
    assert inst.severity in SEVERITIES, inst.severity
    assert inst.id not in REGISTRY, f"duplicate rule id {inst.id}"
    REGISTRY[inst.id] = inst
    return cls


def all_rules() -> List[Rule]:
    # import for side effect: rule modules self-register
    from tools.graftlint import rules  # noqa: F401
    return [REGISTRY[k] for k in sorted(REGISTRY)]


# --------------------------------------------------------------- suppressions

# the directive may follow justification text in the same comment:
# `# host-side precompute ... graftlint: disable=GL104`
_SUPPRESS_RE = re.compile(
    r"#.*?graftlint:\s*(disable-file|disable)\s*=\s*"
    r"([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)")


class Suppressions:
    """Which rules are silenced where.

    Scoping (tested in tests/test_graftlint.py):
    - trailing comment  → suppresses that physical line only;
    - a standalone comment line → suppresses the next statement line
      (blank lines and further comment lines are skipped, so the
      directive can sit above a multi-line justification block);
    - ``disable-file`` anywhere → suppresses the whole file.
    """

    def __init__(self, source: str):
        self.file_level: set = set()
        self.by_line: Dict[int, set] = {}
        lines = source.splitlines()
        for i, line in enumerate(lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            kind, names = m.groups()
            toks = {t.strip() for t in names.split(",") if t.strip()}
            if kind == "disable-file":
                self.file_level |= toks
            elif line.lstrip().startswith("#"):
                # standalone comment: applies to the next statement line
                j = i
                while j < len(lines) and (
                        not lines[j].strip()
                        or lines[j].lstrip().startswith("#")):
                    j += 1
                self.by_line.setdefault(j + 1, set()).update(toks)
            else:
                self.by_line.setdefault(i, set()).update(toks)

    def is_suppressed(self, v: Violation) -> bool:
        keys = {v.rule, v.name, "all"}
        if self.file_level & keys:
            return True
        return bool(self.by_line.get(v.line, set()) & keys)


def _selected(rule: "Rule", select: Sequence[str]) -> bool:
    """``--select`` matching: exact rule id, exact rule name, or an id
    PREFIX — ``--select GL2`` runs the whole GL2xx concurrency family."""
    return any(rule.name == s or rule.id.startswith(s)
               for s in select if s)


# --------------------------------------------------------------- file context

class FileContext:
    """Everything a rule needs about one file."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        self.suppressions = Suppressions(source)
        self.traced = tracing.TracedModel(self.tree, path)
        self.threads = threads.ThreadModel(self.tree, source, path)
        self.resources = resources.ResourceModel(self.tree, source, path)
        self.spmd = spmd.SpmdModel(self.tree, source, path)
        norm = path.replace(os.sep, "/")
        base = os.path.basename(norm)
        self.is_test = ("/tests/" in norm or norm.startswith("tests/")
                        or base.startswith("test_") or base == "conftest.py")
        self.is_dataset = "/dataset/" in norm or norm.startswith("dataset/")
        self.is_interop = "/interop/" in norm or norm.startswith("interop/")
        self.is_library = ("bigdl_tpu" in norm and not self.is_test
                           and not self.is_dataset)
        # the wire plane: modules where HTTP statuses mean something —
        # GL302's error-taxonomy scope
        self.is_wire = any(f"/{p}/" in norm or norm.startswith(f"{p}/")
                           for p in ("frontend", "serving"))


# -------------------------------------------------------------------- drivers

def lint_source(source: str, path: str = "<string>",
                select: Optional[Sequence[str]] = None,
                respect_suppressions: bool = True) -> List[Violation]:
    """Lint one source string.  ``select`` restricts to those rule ids."""
    kept, suppressed = _lint_source_full(source, path, select)
    if respect_suppressions:
        return kept
    out = sorted(kept + suppressed,
                 key=lambda v: (v.path, v.line, v.col, v.rule))
    return out


def _lint_source_full(source: str, path: str,
                      select: Optional[Sequence[str]] = None,
                      ) -> Tuple[List[Violation], List[Violation]]:
    """(kept, suppressed) violations for one source string — the
    suppressed list powers ``--stats``' suppression-debt view."""
    try:
        ctx = FileContext(path, source)
    except SyntaxError as e:
        return [Violation("GL000", "syntax-error", "error", path,
                          e.lineno or 1, (e.offset or 0) + 1,
                          f"file does not parse: {e.msg}")], []
    kept: List[Violation] = []
    suppressed: List[Violation] = []
    for rule in all_rules():
        if select and not _selected(rule, select):
            continue
        for v in rule.check(ctx):
            (suppressed if ctx.suppressions.is_suppressed(v)
             else kept).append(v)
    kept.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    suppressed.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return kept, suppressed


def iter_python_files(paths: Sequence[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def changed_files(base: str = "HEAD") -> set:
    """Absolute paths touched vs ``base`` (staged + unstaged +
    untracked) — the ``--changed-only`` fast path for local use.  git
    prints repo-relative paths, so they are re-anchored at the repo
    toplevel; lint targets given as absolute paths or from a
    subdirectory still intersect correctly."""
    try:
        r = subprocess.run(["git", "rev-parse", "--show-toplevel"],
                           capture_output=True, text=True, check=True)
        root = r.stdout.strip()
    except (OSError, subprocess.CalledProcessError):
        return set()
    out: set = set()
    for args in (["git", "diff", "--name-only", base, "--"],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            r = subprocess.run(args, capture_output=True, text=True,
                               check=True, cwd=root)
        except (OSError, subprocess.CalledProcessError):
            continue
        out |= {os.path.join(root, l.strip())
                for l in r.stdout.splitlines() if l.strip()}
    return out


def filter_changed(files: Iterable[str], changed: Iterable[str]) -> List[str]:
    """Intersect lint targets with a changed-path set (both sides
    resolved to absolute paths)."""
    norm = {os.path.abspath(c) for c in changed}
    return [f for f in files if os.path.abspath(f) in norm]


# ------------------------------------------------- changed-import closure

def module_name_of(path: str, root: str) -> Optional[str]:
    """Dotted module name of a .py file relative to the import root
    (``bigdl_tpu/serving/batcher.py`` -> ``bigdl_tpu.serving.batcher``;
    a package ``__init__.py`` names the package itself)."""
    rel = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
    if rel.startswith("..") or not rel.endswith(".py"):
        return None
    rel = rel[:-3]
    parts = rel.split(os.sep)
    if parts[-1] == "__init__":
        parts = parts[:-1]
    if not parts or not all(p.isidentifier() for p in parts):
        return None
    return ".".join(parts)


def imported_modules(source: str, pkg: str = "") -> set:
    """DIRECTLY imported dotted module names in one source file.
    ``pkg`` is the file's own package (for resolving relative
    imports).  ``from a.b import c`` contributes ``a.b`` (and ``a.b.c``
    — the name may be a submodule); ``import a.b`` contributes
    ``a.b``."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return set()
    out: set = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Import):
            for a in n.names:
                # `import a.b.c` executes a/__init__ and a/b/__init__
                # on the way down — ancestor packages are imports too
                parts = a.name.split(".")
                for k in range(1, len(parts) + 1):
                    out.add(".".join(parts[:k]))
        elif isinstance(n, ast.ImportFrom):
            base = n.module or ""
            if n.level:
                # relative import: climb `level` packages from pkg
                parts = pkg.split(".") if pkg else []
                parts = parts[:len(parts) - (n.level - 1)] \
                    if n.level <= len(parts) + 1 else []
                base = ".".join(parts + ([n.module] if n.module else []))
            if base:
                out.add(base)
                for a in n.names:
                    out.add(f"{base}.{a.name}")
    return out


def expand_changed_with_importers(files: Sequence[str],
                                  changed: Iterable[str],
                                  root: Optional[str] = None) -> List[str]:
    """The ``--changed-only`` closure: changed files PLUS lint targets
    that directly import a changed module.  The GL2xx model is
    cross-attribute within a file (a lock rename in one method
    re-checks the whole file), and within-repo contracts cross file
    boundaries through imports — so a change to ``batcher.py`` must
    re-lint ``service.py`` too.  Direct imports only (the transitive
    closure is the full run)."""
    if root is None:
        try:
            r = subprocess.run(["git", "rev-parse", "--show-toplevel"],
                               capture_output=True, text=True, check=True)
            root = r.stdout.strip()
        except (OSError, subprocess.CalledProcessError):
            root = os.getcwd()
    changed_abs = {os.path.abspath(c) for c in changed}
    changed_mods = {m for c in changed_abs
                    for m in [module_name_of(c, root)] if m}
    out: List[str] = []
    for f in files:
        fa = os.path.abspath(f)
        if fa in changed_abs:
            out.append(f)
            continue
        if not changed_mods:
            continue
        try:
            with open(f, "r", encoding="utf-8") as fh:
                src = fh.read()
        except OSError:
            continue
        mod = module_name_of(fa, root) or ""
        pkg = mod.rsplit(".", 1)[0] if "." in mod else ""
        if imported_modules(src, pkg) & changed_mods:
            out.append(f)
    return out


# ------------------------------------------------- mechanism ledger (GL401)

def _mechanism_ledger_full(files: Sequence[str],
                           select: Optional[Sequence[str]] = None,
                           ) -> Tuple[List[Violation], List[Violation]]:
    """The repo-level half of GL401's ``*-mirror`` contract: every
    ``# replicated-by: <x>-mirror`` use must have a ``# replicates:
    <x>-mirror`` provider write SOMEWHERE in the scanned set.  Per-file
    analysis cannot see this (the consumer and the mirror write live in
    different files — optimizer.py relies on the write in
    distri_optimizer.py), so the ledger runs once over the whole file
    list in :func:`lint_paths`.  Deleting the mirror write (the PR-7
    revert) fails here.  Returns (kept, suppressed)."""
    rule = REGISTRY.get("GL401") if REGISTRY else None
    if rule is None:
        rule = next((r for r in all_rules() if r.id == "GL401"), None)
    if rule is None or (select and not _selected(rule, select)):
        return [], []
    models: List[spmd.SpmdModel] = []
    sups: Dict[str, Suppressions] = {}
    for f in files:
        try:
            with open(f, "r", encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src, filename=f)
        except (OSError, SyntaxError):
            continue
        m = spmd.SpmdModel(tree, src, f)
        models.append(m)
        sups[m.path] = Suppressions(src)
    kept: List[Violation] = []
    suppressed: List[Violation] = []
    for path, line, mech in spmd.mechanism_ledger(models):
        v = Violation(
            rule.id, rule.name, rule.severity, path, line, 1,
            f"`# replicated-by: {mech}` relies on a mirror write no "
            f"scanned file provides (`# replicates: {mech}`): without "
            "the mirror the predicate is per-host and the collective "
            "below this branch goes one-sided")
        (suppressed if path in sups and sups[path].is_suppressed(v)
         else kept).append(v)
    return kept, suppressed


@dataclasses.dataclass
class LintResult:
    violations: List[Violation]
    files_scanned: int

    @property
    def errors(self) -> List[Violation]:
        return [v for v in self.violations if v.severity == "error"]

    @property
    def exit_code(self) -> int:
        return 1 if self.errors else 0


def lint_paths(paths: Sequence[str],
               select: Optional[Sequence[str]] = None,
               changed_only: bool = False,
               base: str = "HEAD") -> LintResult:
    files = list(iter_python_files(paths))
    if changed_only:
        # changed files PLUS files that directly import a changed
        # module — a lock/contract change in one module re-lints its
        # in-repo importers (see expand_changed_with_importers)
        files = expand_changed_with_importers(files, changed_files(base))
    violations: List[Violation] = []
    for f in files:
        with open(f, "r", encoding="utf-8") as fh:
            violations.extend(lint_source(fh.read(), path=f, select=select))
    ledger_kept, _ = _mechanism_ledger_full(files, select)
    violations.extend(ledger_kept)
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return LintResult(violations, len(files))


def lint_paths_with_stats(
        paths: Sequence[str],
        select: Optional[Sequence[str]] = None) -> "Tuple[LintResult, dict]":
    """One scan, both artifacts: the gate's :class:`LintResult` AND
    the suppression-debt stats dict (same schema as
    :func:`lint_paths_stats`).  Whole-tree callers — the CI gate, the
    real-tree test suite — need both views and shouldn't pay the
    parse twice."""
    rules = {r.id: {"name": r.name, "findings": 0, "suppressed": 0}
             for r in all_rules()
             if not select or _selected(r, select)}
    by_file: Dict[str, Dict[str, int]] = {}
    violations: List[Violation] = []
    files = list(iter_python_files(paths))
    for f in files:
        with open(f, "r", encoding="utf-8") as fh:
            kept, suppressed = _lint_source_full(fh.read(), path=f,
                                                 select=select)
        violations.extend(kept)
        for v in kept:
            rules.setdefault(v.rule, {"name": v.name, "findings": 0,
                                      "suppressed": 0})["findings"] += 1
        for v in suppressed:
            rules[v.rule]["suppressed"] += 1
            row = by_file.setdefault(_relpath(f), {})
            row[v.rule] = row.get(v.rule, 0) + 1
    # the cross-file mirror ledger is a whole-run pass (see
    # _mechanism_ledger_full) — its findings are GL401 debt like any
    # other, so the dashboard and the gate must agree on them
    ledger_kept, ledger_sup = _mechanism_ledger_full(files, select)
    violations.extend(ledger_kept)
    for v in ledger_kept:
        rules.setdefault(v.rule, {"name": v.name, "findings": 0,
                                  "suppressed": 0})["findings"] += 1
    for v in ledger_sup:
        rules[v.rule]["suppressed"] += 1
        row = by_file.setdefault(_relpath(v.path), {})
        row[v.rule] = row.get(v.rule, 0) + 1
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    stats = {"files_scanned": len(files), "rules": rules,
             "suppressions_by_file": {p: dict(sorted(r.items()))
                                      for p, r in sorted(by_file.items())}}
    return LintResult(violations, len(files)), stats


def lint_paths_stats(paths: Sequence[str],
                     select: Optional[Sequence[str]] = None) -> dict:
    """Per-rule finding/suppression counts across the tree — the
    suppression-debt dashboard behind ``--stats``.  Returns
    ``{"files_scanned": n, "rules": {id: {"name", "findings",
    "suppressed"}}}`` with a row for every registered rule (zeros
    included: debt you don't have is part of the dashboard)."""
    return lint_paths_with_stats(paths, select=select)[1]


_RELPATH_ROOT: List[Optional[str]] = [None]  # memo: one git call per run


def _relpath(path: str) -> str:
    """Repo-relative, /-separated path for baseline keys (falls back to
    the path as given when it is outside the repo root)."""
    if _RELPATH_ROOT[0] is None:
        try:
            r = subprocess.run(["git", "rev-parse", "--show-toplevel"],
                               capture_output=True, text=True,
                               check=True)
            _RELPATH_ROOT[0] = r.stdout.strip() or os.getcwd()
        except (OSError, subprocess.CalledProcessError):
            _RELPATH_ROOT[0] = os.getcwd()
    rel = os.path.relpath(os.path.abspath(path), _RELPATH_ROOT[0])
    if rel.startswith(".."):
        rel = path
    return rel.replace(os.sep, "/")


BASELINE_SCHEMA_VERSION = 1

#: checked-in suppression-debt ledger (see suppression_debt_delta)
BASELINE_DEFAULT_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "suppressions_baseline.json")


def baseline_document(stats: dict, paths: Sequence[str]) -> dict:
    """The ``--write-baseline`` payload: per-file per-rule suppression
    counts, sorted for stable diffs.  Checked in at
    ``tools/graftlint/suppressions_baseline.json`` and enforced by the
    tier-1 gate in ``tests/test_graftlint.py``: counts may SHRINK
    silently (debt paid down) but growing one requires regenerating
    this file — a reviewed act — plus a triage-table row in
    ``tools/graftlint/README.md``."""
    return {
        "schema_version": BASELINE_SCHEMA_VERSION,
        "tool": "graftlint",
        "generated_by": "python -m tools.graftlint --stats "
                        "--write-baseline " + " ".join(paths),
        "suppressions": stats.get("suppressions_by_file", {}),
    }


def load_baseline(path: str = BASELINE_DEFAULT_PATH) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if doc.get("schema_version") != BASELINE_SCHEMA_VERSION \
            or not isinstance(doc.get("suppressions"), dict):
        raise ValueError(
            f"unreadable suppression baseline {path}: regenerate with "
            "`python -m tools.graftlint --stats --write-baseline`")
    return doc


def suppression_debt_delta(stats: dict, baseline: dict) -> List[str]:
    """Human-readable list of (file, rule) whose CURRENT suppression
    count exceeds the checked-in baseline — net-new suppression debt.
    Empty when debt only shrank or held."""
    out: List[str] = []
    base = baseline.get("suppressions", {})
    for path, row in sorted(stats.get("suppressions_by_file",
                                      {}).items()):
        for rule, n in sorted(row.items()):
            allowed = base.get(path, {}).get(rule, 0)
            if n > allowed:
                out.append(f"{path}: {rule} suppressions {n} > "
                           f"baseline {allowed}")
    return out


def stats_to_human(stats: dict) -> str:
    lines = [f"{'rule':8s}{'name':30s}{'findings':>9s}{'suppressed':>11s}"]
    tot_f = tot_s = 0
    for rid in sorted(stats["rules"]):
        row = stats["rules"][rid]
        tot_f += row["findings"]
        tot_s += row["suppressed"]
        lines.append(f"{rid:8s}{row['name']:30s}{row['findings']:>9d}"
                     f"{row['suppressed']:>11d}")
    lines.append(f"{'total':38s}{tot_f:>9d}{tot_s:>11d}")
    # the per-file debt table, ordered by (rule, path): diffable across
    # runs, so a baseline regen shows up as clean line deltas in review
    debt = sorted((rule, path, n)
                  for path, row in stats.get("suppressions_by_file",
                                             {}).items()
                  for rule, n in row.items())
    if debt:
        lines.append("suppression debt by file (rule, path, count):")
        for rule, path, n in debt:
            lines.append(f"  {rule:8s}{path:44s}{n:>3d}")
    lines.append(f"graftlint --stats: {stats['files_scanned']} file(s)")
    return "\n".join(lines)


# --------------------------------------------------------------------- output

def to_json(result: LintResult) -> str:
    counts = {"error": 0, "warning": 0}
    for v in result.violations:
        counts[v.severity] += 1
    return json.dumps({
        "version": JSON_SCHEMA_VERSION,
        "tool": "graftlint",
        "files_scanned": result.files_scanned,
        "counts": counts,
        "violations": [dataclasses.asdict(v) for v in result.violations],
    }, indent=2)


SARIF_SCHEMA_URI = ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def to_sarif(result: LintResult) -> str:
    """SARIF 2.1.0 — the format CI uses to annotate findings inline on
    PRs.  One run, the full rule catalog as ``tool.driver.rules``
    (results reference rules by index), one result per violation with
    a physical location.  Paths are emitted as given (repo-relative
    when the lint was invoked repo-relative, which is how CI runs it)."""
    rules = all_rules()
    index = {r.id: i for i, r in enumerate(rules)}
    results = []
    for v in result.violations:
        res = {
            "ruleId": v.rule,
            "level": "error" if v.severity == "error" else "warning",
            "message": {"text": f"{v.message} ({v.name})"},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": v.path.replace(os.sep, "/")},
                    "region": {"startLine": v.line,
                               "startColumn": v.col},
                },
            }],
        }
        if v.rule in index:
            res["ruleIndex"] = index[v.rule]
        results.append(res)
    doc = {
        "$schema": SARIF_SCHEMA_URI,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri":
                    "tools/graftlint/README.md",
                "rules": [{
                    "id": r.id,
                    "name": r.name,
                    "shortDescription": {"text": r.description},
                    "defaultConfiguration": {
                        "level": "error" if r.severity == "error"
                        else "warning"},
                } for r in rules],
            }},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2)


def to_human(result: LintResult) -> str:
    lines = [v.render() for v in result.violations]
    lines.append(f"graftlint: {len(result.violations)} finding(s) "
                 f"({len(result.errors)} error(s)) in "
                 f"{result.files_scanned} file(s)")
    return "\n".join(lines)
