"""graftlint — JAX-hazard static analysis for the bigdl_tpu tree.

The failure modes that sink a production JAX stack (silent recompiles,
host↔device syncs in the step loop, Python control flow on tracers,
dtype promotion leaks, nondeterministic library RNG) are invisible to
numeric unit tests — they show up later as throughput cliffs.  This
pass catches them at PR time; tests/test_graftlint.py wires it into
tier-1 so it gates every PR.

CLI:   python -m tools.graftlint bigdl_tpu [--json] [--changed-only]
API:   lint_source / lint_paths / all_rules (see core.py)
Rules: tools/graftlint/README.md is the catalog.
"""

from tools.graftlint.core import (  # noqa: F401
    JSON_SCHEMA_VERSION,
    LintResult,
    REGISTRY,
    Rule,
    Violation,
    all_rules,
    filter_changed,
    lint_paths,
    lint_source,
    to_human,
    to_json,
)

__all__ = [
    "JSON_SCHEMA_VERSION", "LintResult", "REGISTRY", "Rule", "Violation",
    "all_rules", "filter_changed", "lint_paths", "lint_source",
    "to_human", "to_json",
]
