"""Thread/lock model: which locks exist, what they guard, which code
runs on which thread, and which locks are held where.

The GL2xx rule family keys off this model the same way GL101-GL103 key
off ``tracing.TracedModel`` — it encodes the repo's own threading
conventions rather than generic ones:

- **Lock discovery.**  ``self.X = threading.Lock()`` (and ``RLock`` /
  ``Condition`` / ``Semaphore``) attributes per class, module-level
  ``_LOCK = threading.Lock()`` globals, and lock *families*
  (``self._death_locks = [threading.Lock() ...]``).  Reentrancy is
  tracked per lock: ``Lock()`` is non-reentrant, ``RLock()`` and a
  default ``Condition()`` (which wraps an RLock) are reentrant, and
  ``Condition(self.X)`` ALIASES ``self.X`` — holding the condition is
  holding the lock (the ``ReplicaSet._wake``/``_lock`` shape).
- **``# guarded-by:`` annotations** (the lightweight convention the
  GL201 contract rides on):

  - on an attribute assignment (normally in ``__init__``):
    ``self._q = deque()  # guarded-by: _cond`` declares every access of
    ``self._q`` must hold ``self._cond``;
  - ``# write-guarded-by: _lock`` declares WRITES must hold the lock
    while reads are deliberately lock-free (single-writer counters,
    CPython-atomic reference reads — the ``Tracer._dropped`` shape);
  - on a ``def`` line it declares the lock is held ON ENTRY (the
    caller-must-hold contract of ``ModelRegistry._resolve`` /
    ``*_locked`` helpers) — the body is checked as if inside the lock,
    and GL202 treats a lock acquisition inside it as a re-take.

  Standalone-comment placement follows the suppression convention: a
  comment line annotates the next statement.  Annotations attach to the
  statement's FIRST physical line.
- **Thread entries.**  Functions handed to ``threading.Thread(target=
  ...)`` / ``Timer``, executor ``submit``/``map`` callbacks, and
  ``add_done_callback`` hooks, transitively closed over same-file calls
  (bare names and ``self.method``) — "runs off the constructing thread"
  is this closure.
- **Held regions.**  Per function, the set of canonical locks held at
  every AST node, from lexical ``with self.lock:`` nesting (plus the
  held-on-entry annotation).  ``lock.acquire()``/``release()`` pairs
  are NOT modeled (the repo idiom is ``with``; the one
  ``_profile_lock.acquire(blocking=False)`` try-lock is invisible to
  the model, documented limitation).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from tools.graftlint.tracing import (FuncInfo, collect_functions, dotted,
                                     iter_scope, last_seg)

# lock constructors, by reentrancy.  A default Condition() wraps an
# RLock; Condition(lock) takes the wrapped lock's kind (and aliases it).
NONREENTRANT_CTORS = {"Lock", "Semaphore", "BoundedSemaphore"}
REENTRANT_CTORS = {"RLock"}
CONDITION_CTOR = "Condition"
LOCK_CTORS = NONREENTRANT_CTORS | REENTRANT_CTORS | {CONDITION_CTOR}

_GUARD_RE = re.compile(
    r"#.*?\b(write-guarded-by|guarded-by)\s*:\s*([A-Za-z_][A-Za-z0-9_]*)")

#: annotation modes
GUARD_ALL = "all"      # guarded-by: reads and writes need the lock
GUARD_WRITE = "write"  # write-guarded-by: writes need it, reads are free


class LockInfo:
    """One discovered lock: attribute of a class, or module global."""

    __slots__ = ("name", "reentrant", "alias_of", "family", "condition")

    def __init__(self, name: str, reentrant: bool,
                 alias_of: Optional[str] = None, family: bool = False,
                 condition: bool = False):
        self.name = name
        self.reentrant = reentrant
        self.alias_of = alias_of    # peer attr name (Condition(self.X))
        self.family = family        # list/dict of locks: self.X[i]
        self.condition = condition  # supports .wait()/.notify()


class ThreadModel:
    """Per-file lock/guard/thread model (see module docstring)."""

    def __init__(self, tree: ast.Module, source: str, path: str):
        self.tree = tree
        self.path = path
        self.lines = source.splitlines()

        # class name -> {attr name -> LockInfo}; module-level locks
        self.class_locks: Dict[str, Dict[str, LockInfo]] = {}
        self.module_locks: Dict[str, LockInfo] = {}
        # class name -> attrs assigned threading.Thread(...) somewhere
        self.class_threads: Dict[str, Set[str]] = {}

        # function index (the shared tracing.collect_functions walker)
        self.funcs: Dict[int, FuncInfo]
        self.by_name: Dict[str, List[FuncInfo]]
        self.funcs, self.by_name = collect_functions(tree)

        # annotations
        # (class name|None, attr/global name) -> (lock key, mode)
        self.guards: Dict[Tuple[Optional[str], str], Tuple[str, str]] = {}
        # id(func node) -> set of lock keys held on entry
        self.entry_held: Dict[int, Set[str]] = {}
        self._guard_lines = self._annotation_lines()
        self._discover_locks()
        self._bind_annotations()

        # thread-entry closure
        self.thread_entry_ids: Set[int] = set()
        self._mark_thread_entries()
        self._propagate_entries()

        self._held_cache: Dict[int, Dict[int, frozenset]] = {}

    # ------------------------------------------------------- lock discovery
    @staticmethod
    def _lock_ctor(call: ast.AST) -> Optional[str]:
        """'Lock'/'RLock'/'Condition'/... when ``call`` constructs a
        threading lock, else None."""
        if not isinstance(call, ast.Call):
            return None
        seg = last_seg(call.func)
        if seg not in LOCK_CTORS:
            return None
        d = dotted(call.func)
        # accept bare names (from threading import Lock) and any dotted
        # path ending in the ctor (threading.Lock, mp.Lock)
        return seg if d else None

    def _lock_info_from_call(self, call: ast.Call, name: str) -> LockInfo:
        ctor = self._lock_ctor(call)
        if ctor == CONDITION_CTOR:
            # Condition(self.X) aliases X; Condition() wraps an RLock
            if call.args and isinstance(call.args[0], ast.Attribute) \
                    and isinstance(call.args[0].value, ast.Name) \
                    and call.args[0].value.id == "self":
                return LockInfo(name, reentrant=False,
                                alias_of=call.args[0].attr, condition=True)
            return LockInfo(name, reentrant=True, condition=True)
        return LockInfo(name, reentrant=ctor in REENTRANT_CTORS)

    def _discover_locks(self):
        # module-level locks
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and self._lock_ctor(node.value):
                nm = node.targets[0].id
                self.module_locks[nm] = self._lock_info_from_call(
                    node.value, nm)
        # class attribute locks and thread attrs, from any method body
        for fi in self.funcs.values():
            if fi.class_name is None:
                continue
            for n in iter_scope(fi.node):
                if not isinstance(n, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = (n.targets if isinstance(n, ast.Assign)
                           else [n.target])
                value = n.value
                if value is None:
                    continue
                for t in targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    cls = fi.class_name
                    if self._lock_ctor(value):
                        self.class_locks.setdefault(cls, {})[t.attr] = \
                            self._lock_info_from_call(value, t.attr)
                    elif self._is_lock_family(value):
                        self.class_locks.setdefault(cls, {})[t.attr] = \
                            LockInfo(t.attr, reentrant=False, family=True)
                    elif isinstance(value, ast.Call) \
                            and last_seg(value.func) == "Thread":
                        self.class_threads.setdefault(cls, set()).add(
                            t.attr)

    def _is_lock_family(self, value: ast.AST) -> bool:
        """``[threading.Lock() for ...]`` / ``[Lock(), Lock()]`` — a
        collection of locks indexed at use sites (``self.X[i]``)."""
        if isinstance(value, ast.ListComp):
            return self._lock_ctor(value.elt) is not None
        if isinstance(value, (ast.List, ast.Tuple)):
            return bool(value.elts) and all(
                self._lock_ctor(e) for e in value.elts)
        return False

    # -------------------------------------------------------- annotations
    def _annotation_lines(self) -> Dict[int, Tuple[str, str]]:
        """statement line -> (lock name, mode) from ``# guarded-by:`` /
        ``# write-guarded-by:`` comments (trailing = that line,
        standalone comment = next statement line)."""
        out: Dict[int, Tuple[str, str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _GUARD_RE.search(line)
            if not m:
                continue
            kind, lock = m.groups()
            mode = GUARD_WRITE if kind.startswith("write") else GUARD_ALL
            if line.lstrip().startswith("#"):
                j = i
                while j < len(self.lines) and (
                        not self.lines[j].strip()
                        or self.lines[j].lstrip().startswith("#")):
                    j += 1
                out[j + 1] = (lock, mode)
            else:
                out[i] = (lock, mode)
        return out

    def _lock_key(self, lock_name: str,
                  class_name: Optional[str]) -> Optional[str]:
        """Canonical key for a lock referenced by bare name in an
        annotation: ``self.X`` when the class owns it, the global name
        for module locks."""
        if class_name is not None:
            info = self.class_locks.get(class_name, {}).get(lock_name)
            if info is not None:
                return self._canon_attr(class_name, lock_name)
        if lock_name in self.module_locks:
            return lock_name
        return None

    def _bind_annotations(self):
        lines = self._guard_lines
        if not lines:
            return
        # attribute / global guard declarations
        for fi in self.funcs.values():
            for n in iter_scope(fi.node):
                if not isinstance(n, (ast.Assign, ast.AnnAssign)):
                    continue
                if n.lineno not in lines:
                    continue
                lock, mode = lines[n.lineno]
                targets = (n.targets if isinstance(n, ast.Assign)
                           else [n.target])
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self" \
                            and fi.class_name is not None:
                        key = self._lock_key(lock, fi.class_name)
                        if key:
                            self.guards[(fi.class_name, t.attr)] = (key,
                                                                    mode)
        for n in self.tree.body:
            if isinstance(n, (ast.Assign, ast.AnnAssign)) \
                    and n.lineno in lines:
                lock, mode = lines[n.lineno]
                targets = (n.targets if isinstance(n, ast.Assign)
                           else [n.target])
                for t in targets:
                    if isinstance(t, ast.Name):
                        key = self._lock_key(lock, None)
                        if key:
                            self.guards[(None, t.id)] = (key, mode)
        # held-on-entry declarations on def lines
        for fi in self.funcs.values():
            if fi.node.lineno in lines:
                lock, _mode = lines[fi.node.lineno]
                key = self._lock_key(lock, fi.class_name)
                if key:
                    self.entry_held.setdefault(id(fi.node), set()).add(key)

    def guards_for(self, class_name: Optional[str]) -> Dict[str,
                                                            Tuple[str, str]]:
        """attr/global name -> (lock key, mode) for one class (or the
        module globals with ``class_name=None``)."""
        return {attr: g for (cls, attr), g in self.guards.items()
                if cls == class_name}

    # ---------------------------------------------------- canonicalization
    def _canon_attr(self, class_name: str, attr: str,
                    seen: Optional[Set[str]] = None) -> str:
        info = self.class_locks.get(class_name, {}).get(attr)
        seen = seen or set()
        if info is not None and info.alias_of and attr not in seen:
            seen.add(attr)
            target = info.alias_of
            if target in self.class_locks.get(class_name, {}):
                return self._canon_attr(class_name, target, seen)
        return f"self.{attr}"

    def lock_info(self, class_name: Optional[str],
                  key: str) -> Optional[LockInfo]:
        """LockInfo for a canonical key (post-alias)."""
        if key.startswith("self."):
            attr = key[5:].rstrip("[*]")
            return self.class_locks.get(class_name or "", {}).get(attr)
        return self.module_locks.get(key)

    def canon_lock(self, class_name: Optional[str],
                   node: ast.AST) -> Optional[str]:
        """Canonical lock key of an expression, or None when it isn't a
        known lock: ``self.X`` attrs (aliases resolved), ``self.X[i]``
        family members (``self.X[*]``), module-global names."""
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self" and class_name is not None:
            info = self.class_locks.get(class_name, {}).get(node.attr)
            if info is not None and not info.family:
                return self._canon_attr(class_name, node.attr)
            return None
        if isinstance(node, ast.Subscript) \
                and isinstance(node.value, ast.Attribute) \
                and isinstance(node.value.value, ast.Name) \
                and node.value.value.id == "self" \
                and class_name is not None:
            info = self.class_locks.get(class_name, {}).get(
                node.value.attr)
            if info is not None and info.family:
                return f"self.{node.value.attr}[*]"
            return None
        if isinstance(node, ast.Name) and node.id in self.module_locks:
            return node.id
        return None

    def condition_keys(self, class_name: Optional[str]) -> Set[str]:
        """Canonical keys of Condition-valued attrs/globals reachable
        from ``class_name`` (pre-alias attr names map to their canonical
        lock so held-checks line up)."""
        out: Set[str] = set()
        for attr, info in self.class_locks.get(class_name or "",
                                               {}).items():
            if info.condition:
                out.add(self._canon_attr(class_name, attr))
        for nm, info in self.module_locks.items():
            if info.condition:
                out.add(nm)
        return out

    # ------------------------------------------------------- thread entries
    def _add_entry_target(self, node: ast.AST):
        if isinstance(node, ast.Name):
            for fi in self.by_name.get(node.id, []):
                self.thread_entry_ids.add(id(fi.node))
        elif isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            for fi in self.by_name.get(node.attr, []):
                if fi.class_name is not None:
                    self.thread_entry_ids.add(id(fi.node))

    def _mark_thread_entries(self):
        for call in ast.walk(self.tree):
            if not isinstance(call, ast.Call):
                continue
            seg = last_seg(call.func)
            if seg in ("Thread", "Timer"):
                for kw in call.keywords:
                    if kw.arg == "target":
                        self._add_entry_target(kw.value)
                if seg == "Timer" and len(call.args) >= 2:
                    self._add_entry_target(call.args[1])
            elif seg in ("submit", "map") \
                    and isinstance(call.func, ast.Attribute):
                recv = last_seg(call.func.value) or ""
                if re.search(r"pool|executor|^ex$", recv) and call.args:
                    self._add_entry_target(call.args[0])
            elif seg == "add_done_callback" and call.args:
                self._add_entry_target(call.args[0])

    def _propagate_entries(self):
        """Same-file closure: a function called (bare name /
        ``self.m``) from a thread entry also runs on that thread."""
        changed = True
        while changed:
            changed = False
            for fi in self.funcs.values():
                if id(fi.node) in self.thread_entry_ids:
                    continue
                if fi.parent and id(fi.parent.node) in self.thread_entry_ids:
                    self.thread_entry_ids.add(id(fi.node))
                    changed = True
            for fi in list(self.funcs.values()):
                if id(fi.node) not in self.thread_entry_ids:
                    continue
                for n in iter_scope(fi.node):
                    if not isinstance(n, ast.Call):
                        continue
                    cands: List[FuncInfo] = []
                    if isinstance(n.func, ast.Name):
                        cands = self.by_name.get(n.func.id, [])
                    elif isinstance(n.func, ast.Attribute) \
                            and isinstance(n.func.value, ast.Name) \
                            and n.func.value.id == "self":
                        cands = [c for c in
                                 self.by_name.get(n.func.attr, [])
                                 if c.class_name == fi.class_name]
                    for c in cands:
                        if id(c.node) not in self.thread_entry_ids:
                            self.thread_entry_ids.add(id(c.node))
                            changed = True

    def on_thread(self, func: ast.AST) -> bool:
        return id(func) in self.thread_entry_ids

    # --------------------------------------------------------- held regions
    def held_map(self, func: ast.AST,
                 class_name: Optional[str]) -> Dict[int, frozenset]:
        """id(node) -> frozenset of canonical lock keys held there, from
        lexical ``with`` nesting plus the held-on-entry annotation.
        Nested function/class definitions are NOT entered (their bodies
        run later, under whatever locks their caller holds)."""
        if id(func) in self._held_cache:
            return self._held_cache[id(func)]
        out: Dict[int, frozenset] = {}
        entry = frozenset(self.entry_held.get(id(func), set()))

        def visit(node, held):
            out[id(node)] = held
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired = set()
                for item in node.items:
                    visit(item.context_expr, held)
                    if item.optional_vars is not None:
                        visit(item.optional_vars, held)
                    lk = self.canon_lock(class_name, item.context_expr)
                    if lk is not None:
                        acquired.add(lk)
                inner = held | frozenset(acquired)
                for b in node.body:
                    visit(b, inner)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in getattr(func, "body", []):
            visit(stmt, entry)
        self._held_cache[id(func)] = out
        return out

    def acquires(self, func: ast.AST,
                 class_name: Optional[str]) -> Set[str]:
        """Canonical locks this function acquires via ``with`` anywhere
        in its own body (nested defs excluded)."""
        out: Set[str] = set()
        for n in iter_scope(func):
            if isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    lk = self.canon_lock(class_name, item.context_expr)
                    if lk is not None:
                        out.add(lk)
        return out

    def methods_of(self, class_name: str) -> List[FuncInfo]:
        return [fi for fi in self.funcs.values()
                if fi.class_name == class_name]

    def class_names(self) -> Set[str]:
        return {fi.class_name for fi in self.funcs.values()
                if fi.class_name is not None}
