#!/usr/bin/env sh
# graftlint CI entry point: one invocation produces both artifacts CI
# consumes — the SARIF report (inline PR annotations) and the
# suppression-debt dashboard (--stats, printed to the job log).
#
# Usage:  tools/lint_ci.sh [paths...]        (default: bigdl_tpu tools bench.py)
#   GRAFTLINT_SARIF_OUT=path  where to write the SARIF file
#                             (default: graftlint.sarif in the repo root)
#   PYTHON=interpreter        defaults to `python`
#
# Exit status is the lint gate's: 0 clean, 1 findings, 2 usage error.
set -u

cd "$(dirname "$0")/.."
PY="${PYTHON:-python}"
OUT="${GRAFTLINT_SARIF_OUT:-graftlint.sarif}"

"$PY" -m tools.graftlint --format sarif "$@" > "$OUT"
rc=$?
echo "graftlint: SARIF report written to $OUT" >&2

# the debt dashboard is informational — it never changes the exit
# status, and a usage error above skips it (same bad args would recur)
if [ "$rc" -ne 2 ]; then
    "$PY" -m tools.graftlint --stats "$@"
fi
exit $rc
