"""Developer tooling for the bigdl_tpu repo (not shipped with the library).

- ``tools.byte_audit``  — HLO byte-traffic attribution (run as a script).
- ``tools.graftlint``   — JAX-hazard static analysis (``python -m
  tools.graftlint bigdl_tpu``); gates tier-1 via tests/test_graftlint.py.
"""
