"""trace_report — summarize a bigdl_tpu telemetry Chrome trace.

Reads the Chrome-trace JSON the telemetry tracer emits
(``Tracer.dump`` / ``Config.telemetry_trace_path``) and prints the
driver-pipeline picture the raw timeline buries:

- **per-phase time share** — self-time per span category (stage /
  dispatch / device_wait / replay / trigger) over the trace wall clock,
  plus ``other`` for unaccounted time, summing to ~1.  Self-time:
  nested spans (a validation span inside a replay span) are charged to
  the child, never double-counted;
- **top spans** — by total duration, with call counts and mean;
- **stall picture** — device-wait fraction (host blocked on device —
  healthy when the device is the bottleneck) vs host-stage fraction
  (device starved by the input pipeline), plus the DISRUPTION count:
  resilience instants (failover, quarantine, replica death, shed,
  breaker trip, rollback) folded in, because a stall picture that
  ignores the failovers that caused the stalls is half a picture;
- **watchdog events** — recompiles, stager starvations, host-sync
  stalls (instant events the watchdogs injected);
- **instant events by category** — EVERY ``ph:"i"`` event grouped by
  its ``cat`` (watchdog / resilience / anything a future subsystem
  emits), so no category is silently ignored; ``--events`` prints the
  chronological listing with args (the incident timeline).

Usage::

    python -m tools.trace_report trace.json
    python -m tools.trace_report trace.json --json
    python -m tools.trace_report trace.json --top 20
    python -m tools.trace_report trace.json --events

Virtual tracks (the ``device`` track carrying in-flight block spans,
category ``pipeline``) overlap the host timeline by design and are
excluded from phase-share accounting — they answer "what was the device
doing", not "where did host time go".
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Dict, List

# categories counted as host pipeline phases; spans on virtual tracks
# (cat "pipeline") overlap the host timeline and are excluded
PHASE_CATS = ("stage", "dispatch", "device_wait", "replay", "trigger")
_EXCLUDED_CATS = {"pipeline"}


def load_trace(path: str) -> dict:
    """Load a Chrome-trace JSON file; accepts both the object form
    (``{"traceEvents": [...]}``) and a bare event list."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, list):
        data = {"traceEvents": data}
    if not isinstance(data, dict) or "traceEvents" not in data:
        raise ValueError(
            f"{path}: not a Chrome trace (no traceEvents key)")
    return data


def _self_times(spans: List[dict]) -> Dict[int, float]:
    """Self time (dur minus nested-child dur) per span index, computed
    per tid with a nesting stack.  Spans from ``with`` blocks on one
    thread nest properly; partial overlap (malformed input) is treated
    as nested-by-start-order, which only redistributes time between the
    overlapping pair."""
    self_us = {i: float(s.get("dur", 0.0)) for i, s in enumerate(spans)}
    by_tid = defaultdict(list)
    for i, s in enumerate(spans):
        by_tid[s.get("tid", 0)].append(i)
    for tid, idxs in by_tid.items():
        idxs.sort(key=lambda i: (spans[i]["ts"], -spans[i].get("dur", 0.0)))
        stack: List[int] = []  # indices of currently-open spans
        for i in idxs:
            ts = spans[i]["ts"]
            while stack and spans[stack[-1]]["ts"] \
                    + spans[stack[-1]].get("dur", 0.0) <= ts:
                stack.pop()
            if stack:  # nested: charge my duration against the parent
                self_us[stack[-1]] -= spans[i].get("dur", 0.0)
            stack.append(i)
    return self_us


def summarize(trace: dict, top: int = 10) -> dict:
    """Aggregate a loaded trace into the report dict (the schema the
    fixture test gates)."""
    events = trace["traceEvents"]
    spans = [e for e in events if e.get("ph") == "X"]
    instants = [e for e in events if e.get("ph") == "i"]
    host_spans = [s for s in spans
                  if s.get("cat") not in _EXCLUDED_CATS]
    if not spans:
        raise ValueError("trace contains no complete ('X') spans")
    t0 = min(s["ts"] for s in spans)
    t1 = max(s["ts"] + s.get("dur", 0.0) for s in spans)
    wall_us = max(t1 - t0, 1e-9)

    self_us = _self_times(host_spans)
    cat_us: Dict[str, float] = defaultdict(float)
    name_rows: Dict[str, dict] = {}
    for i, s in enumerate(host_spans):
        cat = s.get("cat") or "uncategorized"
        cat_us[cat] += self_us[i]
        row = name_rows.setdefault(
            s["name"], {"name": s["name"], "cat": cat, "count": 0,
                        "total_us": 0.0})
        row["count"] += 1
        row["total_us"] += s.get("dur", 0.0)

    share = {c: round(cat_us.get(c, 0.0) / wall_us, 4)
             for c in sorted(cat_us)}
    accounted = sum(share.values())
    share["other"] = round(max(0.0, 1.0 - accounted), 4)

    top_spans = sorted(name_rows.values(),
                       key=lambda r: -r["total_us"])[:top]
    for r in top_spans:
        r["total_ms"] = round(r.pop("total_us") / 1e3, 3)
        r["mean_ms"] = round(r["total_ms"] / r["count"], 4)

    # instants: EVERY category is accounted (a resilience failover or a
    # category some future subsystem invents must not vanish from the
    # report just because this tool predates it)
    watchdog = defaultdict(int)
    resilience = defaultdict(int)
    by_category: Dict[str, Dict[str, int]] = defaultdict(
        lambda: defaultdict(int))
    recompiles = []
    timeline = []
    for e in instants:
        cat = e.get("cat") or "uncategorized"
        by_category[cat][e["name"]] += 1
        if cat == "resilience":
            resilience[e["name"]] += 1
        elif cat in ("watchdog", "uncategorized"):
            watchdog[e["name"]] += 1
        if e["name"] == "recompile":
            recompiles.append(e.get("args", {}))
        timeline.append({"t_ms": round((e["ts"] - t0) / 1e3, 3),
                         "cat": cat, "name": e["name"],
                         "args": e.get("args", {})})
    timeline.sort(key=lambda r: r["t_ms"])

    other = trace.get("otherData", {})
    return {
        "wall_s": round(wall_us / 1e6, 6),
        "span_count": len(spans),
        "dropped_events": other.get("dropped_events", 0),
        "phase_share": share,
        "phase_seconds": {c: round(v / 1e6, 6)
                          for c, v in sorted(cat_us.items())},
        "stall": {
            "device_wait_fraction": share.get("device_wait", 0.0),
            "host_stage_fraction": share.get("stage", 0.0),
            "dispatch_fraction": share.get("dispatch", 0.0),
            # the disruption fold (satellite of the admin-plane PR): a
            # wait spike with failovers behind it reads differently
            # from one without
            "disruption_events": int(sum(resilience.values())),
        },
        "recompile_events": recompiles,
        "watchdog_events": dict(watchdog),
        "resilience_events": dict(resilience),
        "events_by_category": {c: dict(n)
                               for c, n in sorted(by_category.items())},
        "event_timeline": timeline,
        "top_spans": top_spans,
    }


def _render(report: dict, events: bool = False) -> str:
    lines = [f"wall {report['wall_s'] * 1e3:.1f} ms, "
             f"{report['span_count']} spans"
             + (f" ({report['dropped_events']} dropped)"
                if report["dropped_events"] else "")]
    lines.append("phase share (self-time / wall):")
    for cat, frac in sorted(report["phase_share"].items(),
                            key=lambda kv: -kv[1]):
        lines.append(f"  {cat:<14} {frac * 100:6.2f}%")
    st = report["stall"]
    lines.append(
        f"stall picture: device_wait {st['device_wait_fraction']:.3f} "
        f"(host blocked on device), host_stage "
        f"{st['host_stage_fraction']:.3f} (device starved by input), "
        f"{st['disruption_events']} disruption event(s)")
    if report["watchdog_events"]:
        lines.append("watchdog events: " + ", ".join(
            f"{k}×{v}" for k, v in sorted(
                report["watchdog_events"].items())))
        for r in report["recompile_events"]:
            lines.append(f"  recompile: {r}")
    else:
        lines.append("watchdog events: none")
    if report["resilience_events"]:
        lines.append("resilience events: " + ", ".join(
            f"{k}×{v}" for k, v in sorted(
                report["resilience_events"].items())))
    if events:
        lines.append("instant-event timeline (t from first span):")
        rows = report["event_timeline"]
        for r in rows[:200]:
            args = (" " + json.dumps(r["args"], sort_keys=True)
                    if r["args"] else "")
            lines.append(f"  {r['t_ms']:>10.3f} ms  [{r['cat']}] "
                         f"{r['name']}{args}")
        if len(rows) > 200:
            lines.append(f"  ... {len(rows) - 200} more (use --json)")
    lines.append(f"top spans:")
    w = max((len(r["name"]) for r in report["top_spans"]), default=8)
    lines.append(f"  {'span':<{w}}  {'count':>6}  {'total(ms)':>10}  "
                 f"{'mean(ms)':>9}")
    for r in report["top_spans"]:
        lines.append(f"  {r['name']:<{w}}  {r['count']:>6}  "
                     f"{r['total_ms']:>10.3f}  {r['mean_ms']:>9.4f}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tools.trace_report",
        description="Summarize a bigdl_tpu telemetry Chrome trace")
    p.add_argument("trace", help="Chrome-trace JSON file (Tracer.dump)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the report as JSON")
    p.add_argument("--top", type=int, default=10,
                   help="how many top spans to show")
    p.add_argument("--events", action="store_true",
                   help="print the chronological instant-event "
                        "timeline (watchdog + resilience)")
    args = p.parse_args(argv)
    try:
        report = summarize(load_trace(args.trace), top=args.top)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"trace_report: {e}", file=sys.stderr)
        return 2
    print(json.dumps(report) if args.as_json
          else _render(report, events=args.events))
    return 0


if __name__ == "__main__":
    sys.exit(main())
