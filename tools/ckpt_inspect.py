"""ckpt_inspect — print and verify bigdl_tpu snapshot manifests.

Reads the ``__manifest__`` member of one snapshot (or every
``model.<N>`` in a checkpoint directory) and reports step, schema hash,
grad_sync configuration, array count/bytes, and integrity — WITHOUT
deserializing a single array: verification streams each member through
CRC32c in chunks, so inspecting a multi-GB checkpoint needs constant
memory and can never execute anything (the data-only policy).

Usage::

    python -m tools.ckpt_inspect ckpt_dir/            # whole directory
    python -m tools.ckpt_inspect ckpt_dir/model.120   # one snapshot
    python -m tools.ckpt_inspect ckpt_dir --json
    python -m tools.ckpt_inspect ckpt_dir --no-verify # manifest only
    python -m tools.ckpt_inspect ckpt_dir --schema    # elastic audit

``--schema`` is the elastic-training audit: per snapshot it prints the
recorded world size, the ZeRO-1 bucket layout (padded sizes and the
world-size-invariant unpadded content), and the wire dtype, then
renders each snapshot's ELASTIC verdict against the newest
schema-bearing one — would a resume that tolerates world-size drift
(``schema.elastic_compatible``) accept it?  Exit 0 when every snapshot
is elastic-resumable, 1 when any is incompatible (or corrupt).

Exit codes: 0 = every inspected snapshot is intact (and, under
``--schema``, elastic-resumable), 1 = at least one is corrupt/torn or
elastic-incompatible (the latest VALID one is still named so an
operator knows what a resume would pick), 2 = nothing inspectable at
the given path.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from bigdl_tpu.checkpoint.snapshot import (SnapshotError, read_manifest,
                                           verify_snapshot)


def inspect_snapshot(path: str, verify: bool = True,
                     with_schema: bool = False) -> dict:
    """One snapshot → report row (never raises for a corrupt file —
    the corruption IS the finding).  ``with_schema`` embeds the full
    recorded schema dict for the ``--schema`` elastic audit."""
    row: dict = {"path": path, "size_bytes": None, "status": "ok"}
    try:
        row["size_bytes"] = os.path.getsize(path)
    except OSError as e:
        return {**row, "status": "unreadable", "detail": str(e)}
    try:
        manifest = read_manifest(path)
    except SnapshotError as e:
        return {**row, "status": "corrupt", "detail": str(e)}
    if manifest is None:
        row.update(status="legacy", format="v2 (no manifest)",
                   detail="pre-manifest checkpoint — integrity "
                          "unverifiable without loading")
        if with_schema:
            row["schema"] = None
        return row
    schema = manifest.get("schema") or {}
    if with_schema:
        row["schema"] = manifest.get("schema")
    gs = schema.get("grad_sync") or {}
    row.update(
        format=f"{manifest.get('format')} v{manifest.get('version')}",
        step=manifest.get("step"), epoch=manifest.get("epoch"),
        schema_hash=manifest.get("schema_hash"),
        arrays=len(manifest.get("arrays", [])),
        total_bytes=manifest.get("total_bytes"),
        param_leaves=len(schema.get("params") or {}),
        optim_method=schema.get("optim_method"),
        grad_sync=bool(gs.get("enabled")),
    )
    if gs.get("enabled"):
        row["grad_sync_plan"] = {
            "buckets": len(gs.get("bucket_sizes", [])),
            "wire_dtype": gs.get("wire_dtype"),
            "n_shard": gs.get("n_shard")}
    if verify:
        ok, detail = verify_snapshot(path)
        row["checksum"] = "ok" if ok else "FAILED"
        if not ok:
            row.update(status="corrupt", detail=detail)
    else:
        row["checksum"] = "unverified"
    return row


def _candidate_paths(target: str) -> List[str]:
    """Same discovery a real resume performs — reuse the manager, don't
    re-derive the model.<N> convention here."""
    if os.path.isdir(target):
        from bigdl_tpu.checkpoint.manager import CheckpointManager
        mgr = CheckpointManager(target)
        return [mgr.path_for(s) for s in mgr.steps()]
    if os.path.exists(target):
        return [target]
    return []


def _resume_pick(target: str) -> Optional[str]:
    """What an actual resume would select: CheckpointManager.
    latest_valid for a directory (always deep-verified, even under
    --no-verify — the operator-facing 'latest valid' line must not
    claim a snapshot resume would CRC-skip), the single file's own
    verdict otherwise."""
    if os.path.isdir(target):
        from bigdl_tpu.checkpoint.manager import CheckpointManager
        return CheckpointManager(target).latest_valid()
    ok, _ = verify_snapshot(target)
    return target if ok else None


def _render(rows: List[dict], latest_valid: Optional[str]) -> str:
    lines = []
    for r in rows:
        head = f"{r['path']}  [{r['status']}]"
        lines.append(head)
        if r["status"] in ("corrupt", "unreadable", "legacy"):
            lines.append(f"  {r.get('detail', '')}")
            continue
        lines.append(
            f"  step {r.get('step')}  epoch {r.get('epoch')}  "
            f"schema {r.get('schema_hash')}  checksum {r.get('checksum')}")
        gs = (f"grad_sync on ({r['grad_sync_plan']['buckets']} buckets, "
              f"wire {r['grad_sync_plan']['wire_dtype']}, "
              f"{r['grad_sync_plan']['n_shard']} shards)"
              if r.get("grad_sync") else "grad_sync off")
        lines.append(
            f"  {r.get('arrays')} arrays / {r.get('total_bytes')} bytes "
            f"({r.get('param_leaves')} param leaves), "
            f"{r.get('optim_method')}, {gs}")
    lines.append(f"latest valid: {latest_valid or 'NONE'}")
    return "\n".join(lines)


def schema_audit(rows: List[dict]) -> dict:
    """The ``--schema`` elastic verdicts: every snapshot's recorded
    schema against the NEWEST schema-bearing intact one (what a resume
    would continue with).  ``compatible`` is the overall exit-0/1
    verdict — True only when every intact snapshot is acceptable to an
    elastic resume (``schema.elastic_compatible``: world-size/padding
    drift tolerated, logical model identity strict)."""
    from bigdl_tpu.checkpoint.schema import elastic_compatible, schema_hash
    bearing = [r for r in rows
               if r["status"] == "ok" and r.get("schema") is not None]
    ref = bearing[-1] if bearing else None
    verdicts = []
    compatible = True
    for r in rows:
        if r["status"] in ("corrupt", "unreadable"):
            verdicts.append({"path": r["path"], "verdict": "corrupt",
                             "lines": [r.get("detail", "")]})
            compatible = False
            continue
        if ref is None:
            verdicts.append({"path": r["path"], "verdict": "no-reference",
                             "lines": ["(no intact schema-bearing "
                                       "snapshot to compare against)"]})
            continue
        if r is ref:
            verdicts.append({"path": r["path"], "verdict": "reference",
                             "lines": []})
            continue
        ok, lines = elastic_compatible(r.get("schema"), ref["schema"])
        if not ok:
            verdict = "INCOMPATIBLE"
            compatible = False
        elif r.get("schema") is not None and schema_hash(r["schema"]) \
                == schema_hash(ref["schema"]):
            verdict = "identical"
        else:
            verdict = "elastic-resumable"
        verdicts.append({"path": r["path"], "verdict": verdict,
                         "lines": lines})
    return {"reference": ref["path"] if ref else None,
            "verdicts": verdicts, "compatible": compatible}


def _schema_line(r: dict) -> str:
    schema = r.get("schema") or {}
    gs = schema.get("grad_sync") or {}
    if not gs.get("enabled"):
        return (f"  step {r.get('step')}  world -  grad_sync off  "
                f"({r.get('param_leaves')} param leaves, "
                f"{schema.get('optim_method')})")
    sizes = gs.get("bucket_sizes", [])
    content = gs.get("bucket_content")
    layout = f"buckets {sizes}" + (f" (content {content} unpadded)"
                                   if content is not None else "")
    return (f"  step {r.get('step')}  world {gs.get('n_shard')}  "
            f"wire {gs.get('wire_dtype')}  {layout}")


def _render_schema(rows: List[dict], audit: dict,
                   latest_valid: Optional[str]) -> str:
    by_path = {v["path"]: v for v in audit["verdicts"]}
    lines = []
    for r in rows:
        lines.append(f"{r['path']}  [{r['status']}]")
        if r["status"] in ("corrupt", "unreadable"):
            lines.append(f"  {r.get('detail', '')}")
            continue
        if r["status"] == "legacy":
            lines.append(f"  {r.get('detail', '')}")
        else:
            lines.append(_schema_line(r))
        v = by_path[r["path"]]
        lines.append(f"  elastic: {v['verdict']}")
        lines.extend(f"  {ln}" for ln in v["lines"])
    lines.append(f"latest valid: {latest_valid or 'NONE'}")
    lines.append("elastic verdict: "
                 + ("RESUMABLE" if audit["compatible"] else "INCOMPATIBLE"))
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tools.ckpt_inspect",
        description="Print/verify bigdl_tpu snapshot manifests without "
                    "loading arrays")
    p.add_argument("target", help="snapshot file or checkpoint directory")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the report as JSON")
    p.add_argument("--no-verify", action="store_false", dest="verify",
                   help="manifest only — skip the streamed CRC check")
    p.add_argument("--schema", action="store_true", dest="schema",
                   help="elastic audit: world size, ZeRO bucket layout, "
                        "and per-snapshot elastic-resume verdicts "
                        "(exit 1 on any incompatibility)")
    args = p.parse_args(argv)

    paths = _candidate_paths(args.target)
    if not paths:
        print(f"ckpt_inspect: no snapshot at {args.target} "
              "(expected a model.<N> file or a directory of them)",
              file=sys.stderr)
        return 2
    rows = [inspect_snapshot(path, verify=args.verify,
                             with_schema=args.schema) for path in paths]
    latest_valid = _resume_pick(args.target)
    report = {"snapshots": rows, "latest_valid": latest_valid,
              "corrupt": sum(r["status"] in ("corrupt", "unreadable")
                             for r in rows)}
    if args.schema:
        audit = report["elastic"] = schema_audit(rows)
        print(json.dumps(report) if args.as_json
              else _render_schema(rows, audit, latest_valid))
        return 0 if audit["compatible"] and not report["corrupt"] else 1
    print(json.dumps(report) if args.as_json
          else _render(rows, latest_valid))
    return 1 if report["corrupt"] else 0


if __name__ == "__main__":
    sys.exit(main())
