"""Bench-driven autotuner — successive-halving search over the
exposed config space (ROADMAP item 3; in the spirit of TVM
arXiv:1802.04799 and Learning to Optimize Tensor Programs
arXiv:1805.08166).

Eight PRs grew a measured knob space — ``steps_per_dispatch`` K,
``grad_bucket_bytes``, ``grad_wire_dtype``, ``kernel_impl``,
activation-memory policy, serving bucket sets /
``serving_batch_timeout_ms`` — whose defaults were hand-recorded
(``bench.PRODUCTION_K``, tuning notes in bench.py docstrings).  This
driver makes them self-tuning: a declarative per-workload discrete
grid (``WORKLOADS``) is searched by successive halving, every trial
measured through the EXISTING measurement substrate —
``bench._measure``'s warmup-discarded windows for training workloads,
a closed-loop offered-load burst (the ``bench.py --serving`` harness
shape) for serving — with the PR 6 steady-state discipline applied to
the window samples (windows outside ±15% of the trimmed median are
excluded from the score, exclusions counted, never silent).  Winners
are written to a schema-versioned, checked-in ``tuned_configs.json``
(per-workload best config + measurement provenance) that the runtime
consumes as defaults through ``bigdl_tpu.utils.tuned`` (resolution:
explicit setter > ``BIGDL_TPU_*`` env > tuned entry for
``workload@backend`` > dataclass default).

Search contract (gated in tests/test_autotune.py):

- **Budget is hard**: total MEASURED windows across all rungs ≤
  ``--budget``; the rung plan (trial count + windows per trial per
  rung) is logged in the output JSON — no silent caps.  Warmup
  windows are discarded by ``bench._measure`` before samples exist
  and are not budgeted, same as every bench entry.
- **Deterministic given the same measurements**: trials enter in
  canonical-key order and every ranking sorts on
  ``(-score, config_key)`` where ``config_key`` is the trial's
  ``json.dumps(config, sort_keys=True)`` — an exact score tie goes to
  the lexicographically smallest canonical key.
- **Early rungs short, survivors confirmed**: every rung starts at one
  window per trial and leftover budget is spent from the LAST rung
  backwards (up to ``--full-windows``), so the final survivor always
  gets the longest confirmation run the budget allows.  Samples
  accumulate across rungs — a survivor's score at rung r uses all its
  windows so far.
- **Grid axes that cannot be measured here are pruned LOUDLY**: axes
  marked TPU-only (``kernel_impl`` — interpret-mode pallas on a CPU
  host is correctness emulation, not a perf signal) or
  multi-device-only (the grad-sync wire knobs) are dropped with the
  reason recorded in the output JSON; the knob then simply keeps its
  config-chain default at runtime.

CLI::

    python -m tools.autotune --workload ptb_lstm [--budget 40]
        [--out tuned_configs.json] [--full-windows 4] [--eta 2]
        [--smoke] [--dry-run]
    python -m tools.autotune --list

Prints ONE JSON line (the bench discipline) with the search result;
``--dry-run`` searches without writing the tuned file.
"""

from __future__ import annotations

import argparse
import dataclasses
import itertools
import json
import logging
import math
import os
import sys
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python tools/autotune.py` and -m both work
    sys.path.insert(0, REPO)

logger = logging.getLogger("bigdl_tpu.autotune")

SCORE_METRIC = "units_per_sec_trimmed_median_steady"


# ---------------------------------------------------------------- grid
@dataclasses.dataclass(frozen=True)
class Axis:
    """One tunable knob: a ``Config`` field name plus its candidate
    values.  ``requires`` gates measurability ("" always, "tpu" real
    Mosaic hardware, "multidevice" a >1-chip mesh); ``why`` is the
    prune reason recorded when the gate fails."""
    knob: str
    values: tuple
    requires: str = ""
    why: str = ""


@dataclasses.dataclass(frozen=True)
class Workload:
    """A named tuning target: its grid and its trial runner factory.
    ``runner(smoke)`` returns ``measure(trial, windows, rung) ->
    [units/sec per window]``."""
    name: str
    kind: str  # "training" | "serving"
    axes: Tuple[Axis, ...]
    smoke_axes: Tuple[Axis, ...]
    runner: Callable


def prune_axes(axes: Sequence[Axis], backend: str,
               n_devices: int) -> Tuple[List[Axis], Dict[str, str]]:
    """Drop grid axes the current host cannot produce a real perf
    signal for; the returned reasons are logged in the output JSON
    (never silently)."""
    kept, pruned = [], {}
    for ax in axes:
        if ax.requires == "tpu" and backend != "tpu":
            pruned[ax.knob] = ax.why
        elif ax.requires == "multidevice" and n_devices < 2:
            pruned[ax.knob] = ax.why
        else:
            kept.append(ax)
    return kept, pruned


def build_grid(axes: Sequence[Axis]) -> List[dict]:
    """Cartesian product of the axes, in declared axis/value order
    (deterministic)."""
    if not axes:
        return [{}]
    names = [ax.knob for ax in axes]
    return [dict(zip(names, combo))
            for combo in itertools.product(*(ax.values for ax in axes))]


def config_key(cfg: dict) -> str:
    """Canonical trial identity — also the documented tie-break key."""
    return json.dumps(cfg, sort_keys=True)


# ------------------------------------------------------ scoring
def steady_filter(samples: Sequence[float]) -> Tuple[List[float], int]:
    """The PR 6 steady-state discipline — ``bench.steady_windows``,
    the SAME implementation ``bench.scaling_child`` reads, so the two
    exclusion accountings stay comparable.  ``min_samples=4`` here
    (vs the bench default 3) because early rungs accumulate one window
    at a time and 1-3 windows carry no spread to filter on.  A
    uniformly-unsteady trial scores on the reference rate with EVERY
    window counted excluded — never a silent fall-back to the raw
    set."""
    import bench
    kept, excluded, ref = bench.steady_windows(samples, min_samples=4)
    if not kept:
        return [ref], excluded
    return kept, excluded


def score_samples(samples: Sequence[float]) -> Tuple[float, int]:
    """(score, excluded_windows): trimmed-median units/sec over the
    steady windows — the same ``bench._stats`` summary every bench
    entry reports, so rankings are made on the numbers the captures
    already audit."""
    import bench
    steady, excluded = steady_filter(samples)
    _, stats = bench._stats(steady)
    return stats.get("trimmed_median", stats["median"]), excluded


# ------------------------------------------------ successive halving
def plan_rungs(n_configs: int, budget: int, eta: int = 2,
               full_windows: int = 4) -> List[Tuple[int, int]]:
    """Deterministic rung schedule under a HARD window budget.

    Survivor ladder: ``n, ceil(n/eta), …, 1``.  Every rung starts at
    one window per trial (the minimum that ranks anything); leftover
    budget is then spent from the last rung backwards, up to
    ``full_windows`` per trial — survivors earn confirmation windows
    first.  Raises when the budget cannot give every config even one
    window per rung (an unmeasured config must never be silently
    dropped)."""
    if n_configs < 1:
        raise ValueError("empty grid — nothing to tune")
    ladder = [n_configs]
    while ladder[-1] > 1:
        ladder.append(math.ceil(ladder[-1] / eta))
    windows = [1] * len(ladder)
    minimal = sum(ladder)
    if budget < minimal:
        raise ValueError(
            f"budget {budget} windows cannot rank {n_configs} configs "
            f"— the minimal successive-halving schedule (1 window per "
            f"trial per rung, survivor ladder {ladder}) needs "
            f"{minimal}; raise --budget or shrink the grid")
    spent = minimal
    for r in range(len(ladder) - 1, -1, -1):
        while windows[r] < full_windows and spent + ladder[r] <= budget:
            windows[r] += 1
            spent += ladder[r]
    return list(zip(ladder, windows))


def successive_halving(trials: Sequence[dict], measure: Callable,
                       budget: int, eta: int = 2,
                       full_windows: int = 4) -> dict:
    """Run the search; returns the result document (best config,
    per-rung log, leaderboard, window accounting).

    ``measure(trial, windows, rung)`` returns one units/sec sample per
    window.  Determinism: trials are processed in canonical-key order
    and all rankings tie-break on that key (see module docstring)."""
    plan = plan_rungs(len(trials), budget, eta, full_windows)
    state = sorted(
        ({"config": dict(t), "key": config_key(t), "samples": []}
         for t in trials), key=lambda s: s["key"])
    if len({s["key"] for s in state}) != len(state):
        raise ValueError("duplicate configs in grid")
    windows_total = 0
    rung_log = []
    alive = list(state)
    for rung, (n_r, w_r) in enumerate(plan):
        alive = alive[:n_r]
        for t in alive:
            samples = [float(s) for s in measure(t["config"], w_r, rung)]
            t["samples"].extend(samples)
            windows_total += len(samples)
        for t in alive:
            t["score"], t["excluded"] = score_samples(t["samples"])
        alive.sort(key=lambda t: (-t["score"], t["key"]))
        survivors = plan[rung + 1][0] if rung + 1 < len(plan) else 1
        rung_log.append({
            "rung": rung, "trials": n_r, "windows_per_trial": w_r,
            "windows_used": n_r * w_r,
            "survivors": min(survivors, n_r),
            "best": alive[0]["config"],
            "best_score": alive[0]["score"],
        })
        logger.info("rung %d: %d trials x %d windows -> best %s @ %.1f",
                    rung, n_r, w_r, alive[0]["key"], alive[0]["score"])
    if windows_total > budget:
        raise RuntimeError(  # a runner returned more samples than asked
            f"measured {windows_total} windows > budget {budget}")
    best = alive[0]
    return {
        "best_config": best["config"],
        "score": best["score"],
        "score_metric": SCORE_METRIC,
        "n_configs": len(trials),
        "rungs": rung_log,
        "windows_total": windows_total,
        "budget": budget,
        "excluded_windows": sum(t.get("excluded", 0) for t in state),
        "leaderboard": [{"config": t["config"],
                         "score": t["score"],
                         "windows": len(t["samples"])}
                        for t in alive],
    }


# ------------------------------------------------------ trial runners
def _ptb_runner(smoke: bool) -> Callable:
    """PTB word-LM training trials through ``bench._measure`` (the PTB
    bench entry's exact recipe, shortened)."""
    import jax.numpy as jnp
    import numpy as np

    import bench
    from bigdl_tpu import nn
    from bigdl_tpu.models.rnn import ptb_model

    if smoke:
        vocab, hidden, layers, batch, seq, iters, unroll = \
            64, 16, 1, 4, 8, 2, 1
    else:
        vocab, hidden, layers, batch, seq, iters, unroll = \
            10000, 650, 2, 20, 35, 8, 5
    rng = np.random.default_rng(0)  # same data every trial: the only
    px = jnp.asarray(rng.integers(  # variance across trials is timing
        0, vocab, (batch, seq)).astype(np.int32))
    py = jnp.asarray(rng.integers(
        0, vocab, (batch, seq)).astype(np.int32))

    def measure(trial, windows, rung):
        model = ptb_model(vocab, hidden, hidden, layers,
                          scan_unroll=unroll,
                          kernel_impl=trial.get("kernel_impl"))
        samples, _ca, _path = bench._measure(
            model, batch, windows, iters, x=px, y=py,
            criterion=nn.TimeDistributedCriterion(nn.ClassNLLCriterion()),
            units_per_step=batch * seq,
            fuse_k=trial.get("steps_per_dispatch", 1),
            warmup_windows=1,
            activation_memory=trial.get("activation_memory"))
        return samples

    return measure


def _wide_deep_runner(smoke: bool) -> Callable:
    """Census-dims Wide&Deep training trials (the bench entry's
    recipe: COO wide path + embedding bags + MLP, f32)."""
    import jax.numpy as jnp
    import numpy as np

    import bench
    from bigdl_tpu import nn
    from bigdl_tpu.models.recommender import WideAndDeep
    from bigdl_tpu.nn.sparse import COOBatch

    if smoke:
        batch, nnz_per, wide_dim, fields = 8, 2, 200, [20, 10]
        dense_dim, embed_dim, hidden, iters = 4, 4, (8,), 2
    else:
        batch, nnz_per, wide_dim = 8192, 8, 100_000
        fields = [10_000, 1_000, 100, 100, 50]
        dense_dim, embed_dim, hidden, iters = 13, 16, (100, 50), 8
    r = np.random.default_rng(3)
    nnz = batch * nnz_per
    coo = COOBatch(
        jnp.asarray(np.repeat(np.arange(batch, dtype=np.int32), nnz_per)),
        jnp.asarray(r.integers(0, wide_dim, nnz).astype(np.int32)),
        jnp.asarray(np.ones(nnz, np.float32)),
        (batch, wide_dim))
    deep_ids = jnp.asarray(np.stack(
        [r.integers(0, c, batch) for c in fields], axis=1).astype(np.int32))
    dense = jnp.asarray(r.normal(0, 1, (batch, dense_dim))
                        .astype(np.float32))
    yb = jnp.asarray(r.integers(0, 2, batch).astype(np.float32))

    class _SqueezeBCE:  # model emits (N, 1) logits->sigmoid
        def __init__(self):
            self.bce = nn.BCECriterion()

        def apply(self, out, y):
            return self.bce.apply(out[:, 0], y)

    def measure(trial, windows, rung):
        model = WideAndDeep(wide_dim, fields, dense_dim=dense_dim,
                            embed_dim=embed_dim, hidden=hidden,
                            kernel_impl=trial.get("kernel_impl"))
        samples, _ca, _path = bench._measure(
            model, batch, windows, iters,
            x=(coo, deep_ids, dense), y=yb, criterion=_SqueezeBCE(),
            compute_dtype=jnp.float32,
            fuse_k=trial.get("steps_per_dispatch", 1),
            warmup_windows=1,
            activation_memory=trial.get("activation_memory"))
        return samples

    return measure


def _serving_runner(smoke: bool) -> Callable:
    """Serving trials: the ``bench.py --serving`` closed-loop
    offered-load shape (T caller threads, single-row blocking predicts
    — occupancy earned purely by the batcher), one burst per window,
    rows/sec per burst as the sample."""
    import threading

    import numpy as np

    from bigdl_tpu import nn

    if smoke:
        din, n_threads, per_thread = 16, 4, 6
        model = nn.Sequential(nn.Linear(din, 32), nn.ReLU(),
                              nn.Linear(32, 8), nn.SoftMax())
    else:
        din, n_threads, per_thread = 64, 16, 100
        model = nn.Sequential(  # the bench --serving MLP
            nn.Linear(din, 256), nn.ReLU(), nn.Linear(256, 256),
            nn.ReLU(), nn.Linear(256, 8), nn.SoftMax())
    model.initialize(rng=0)
    spec = ((din,), np.float32)
    rng = np.random.default_rng(0)
    xs = [rng.normal(0, 1, (1, din)).astype(np.float32)
          for _ in range(n_threads)]

    def measure(trial, windows, rung):
        from bigdl_tpu.serving import InferenceService
        svc = InferenceService(
            model, input_spec=spec,
            max_batch_size=trial["serving_max_batch_size"],
            batch_timeout_ms=trial["serving_batch_timeout_ms"],
            buckets=trial.get("serving_row_buckets", ""),
            queue_capacity=4096,
            name=f"autotune-r{rung}")
        samples = []
        try:
            for _ in range(windows):
                barrier = threading.Barrier(n_threads + 1)
                errs: list = []

                def worker(x):
                    barrier.wait()
                    try:
                        for _ in range(per_thread):
                            svc.predict(x, timeout=120)
                    except Exception as e:  # recorded, never dropped
                        errs.append(f"{type(e).__name__}: {e}")

                threads = [threading.Thread(target=worker, args=(x,))
                           for x in xs]
                for t in threads:
                    t.start()
                barrier.wait()
                t0 = time.perf_counter()
                for t in threads:
                    t.join()
                wall = time.perf_counter() - t0
                if errs:
                    raise RuntimeError(
                        f"serving trial {trial} failed: {errs[:3]}")
                samples.append(n_threads * per_thread / wall)
        finally:
            svc.stop()
        return samples

    return measure


def _int8_gemm_runner(smoke: bool) -> Callable:
    """Quantized-GEMM trials: raw ``ops.pallas_int8_gemm.int8_matmul``
    throughput on a serving-shaped panel (small batch, square
    128-multiple K/O so the kernel's ``supported()`` gate passes).
    The activation-mode knob is measurable on any backend — both modes
    lower to real XLA compute through the bitwise fallback (f32 MXU
    dot vs int8 quantize + int32 dot); the tile/impl knobs only change
    Mosaic behaviour and are tpu-gated below."""
    import jax
    import numpy as np

    from bigdl_tpu.ops.pallas_int8_gemm import int8_matmul

    if smoke:
        batch, k, o, iters = 8, 128, 128, 4
    else:
        batch, k, o, iters = 32, 512, 512, 50
    rng = np.random.default_rng(0)  # same data every trial
    x = np.asarray(rng.normal(0, 1, (batch, k)), np.float32)
    wq = rng.integers(-127, 128, (o, k)).astype(np.int8)
    ws = (rng.uniform(0.001, 0.02, (o, 1))).astype(np.float32)
    b = rng.normal(0, 1, (o,)).astype(np.float32)

    def measure(trial, windows, rung):
        mode = trial.get("int8_activation_mode", "weight_only")
        impl = trial.get("kernel_impl")
        block_rows = trial.get("int8_block_rows")

        @jax.jit
        def step(xin):
            return int8_matmul(xin, wq, ws, b, mode=mode, impl=impl,
                               block_rows=block_rows)

        step(x).block_until_ready()  # compile outside the window
        samples = []
        for _ in range(windows):
            t0 = time.perf_counter()
            for _ in range(iters):
                y = step(x)
            y.block_until_ready()
            samples.append(iters * batch / (time.perf_counter() - t0))
        return samples

    return measure


# ----------------------------------------------------------- registry
_TRAINING_AXES = (
    Axis("steps_per_dispatch", (1, 2, 4, 8, 16)),
    Axis("activation_memory", ("none", "dots", "full")),
    Axis("kernel_impl", ("xla", "pallas"), requires="tpu",
         why="interpret-mode pallas on a non-TPU host is correctness "
             "emulation, not a perf signal (ops/PALLAS_NOTES.md); the "
             "knob keeps its config-chain default"),
    Axis("grad_wire_dtype", ("f32", "bf16"), requires="multidevice",
         why="wire compression only exists on a >1-chip data mesh; the "
             "single-chip bench harness cannot rank it"),
    Axis("grad_bucket_bytes", (1 << 20, 4 << 20, 16 << 20),
         requires="multidevice",
         why="bucketing only exists on a >1-chip data mesh; the "
             "single-chip bench harness cannot rank it"),
)
_TRAINING_SMOKE_AXES = (
    Axis("steps_per_dispatch", (1, 2)),
    Axis("activation_memory", ("none",)),
)

_SERVING_AXES = (
    Axis("serving_max_batch_size", (16, 32, 64)),
    Axis("serving_batch_timeout_ms", (0.0, 1.0, 2.0, 5.0)),
    Axis("serving_row_buckets", ("pow2", "top")),
)
_SERVING_SMOKE_AXES = (
    Axis("serving_max_batch_size", (8,)),
    Axis("serving_batch_timeout_ms", (0.0, 2.0)),
    Axis("serving_row_buckets", ("pow2",)),
)

_INT8_GEMM_AXES = (
    # measurable anywhere: both modes are real XLA compute through the
    # bitwise fallback (weight_only = f32 MXU dot against the int8
    # panel; dynamic = on-the-fly activation quantization + int32 dot)
    Axis("int8_activation_mode", ("weight_only", "dynamic")),
    Axis("kernel_impl", ("xla", "pallas"), requires="tpu",
         why="interpret-mode pallas on a non-TPU host is correctness "
             "emulation, not a perf signal (ops/PALLAS_NOTES.md); the "
             "knob keeps its config-chain default"),
    Axis("int8_block_rows", (0, 64, 128, 256), requires="tpu",
         why="the row-block tile only exists inside the Mosaic kernel; "
             "interpret-mode tiling on a non-TPU host times the "
             "emulator, not the MXU"),
)
_INT8_GEMM_SMOKE_AXES = (
    Axis("int8_activation_mode", ("weight_only", "dynamic")),
)

WORKLOADS: Dict[str, Workload] = {
    "ptb_lstm": Workload("ptb_lstm", "training", _TRAINING_AXES,
                         _TRAINING_SMOKE_AXES, _ptb_runner),
    "wide_deep": Workload("wide_deep", "training", _TRAINING_AXES,
                          _TRAINING_SMOKE_AXES, _wide_deep_runner),
    "serving_mlp": Workload("serving_mlp", "serving", _SERVING_AXES,
                            _SERVING_SMOKE_AXES, _serving_runner),
    "int8_gemm": Workload("int8_gemm", "kernel", _INT8_GEMM_AXES,
                          _INT8_GEMM_SMOKE_AXES, _int8_gemm_runner),
}


# ------------------------------------------------------------- output
def write_tuned(path: str, workload: str, backend: str, result: dict,
                provenance: dict) -> dict:
    """Merge one workload's winner into the tuned-configs file
    (atomic replace; other entries preserved).  An existing file that
    fails validation ABORTS the write — fix or delete it first; a
    damaged file must never be silently clobbered or extended."""
    from bigdl_tpu.utils import tuned
    entries: dict = {}
    if os.path.exists(path):
        with open(path, "r", encoding="utf-8") as fh:
            text = fh.read()
        if text.strip():
            entries = tuned.validate_document(json.loads(text))
    entries[f"{workload}@{backend}"] = {
        "workload": workload,
        "backend": backend,
        "best": result["best_config"],
        "provenance": provenance,
    }
    doc = {"schema_version": tuned.SCHEMA_VERSION, "entries": entries}
    tuned.validate_document(doc)  # never write what load() would reject
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return doc


def tune(workload: str, budget: int = 40, eta: int = 2,
         full_windows: int = 4, smoke: bool = False,
         out: Optional[str] = None, dry_run: bool = False,
         measure: Optional[Callable] = None) -> dict:
    """Search one workload's grid and (unless ``dry_run``) merge the
    winner into the tuned-configs file.  ``measure`` overrides the
    workload's runner (tests inject deterministic measurements)."""
    if workload not in WORKLOADS:
        raise SystemExit(
            f"unknown workload {workload!r}; available: "
            f"{sorted(WORKLOADS)}")
    if smoke and not dry_run and out is None:
        # a smoke winner comes from tiny models over a tiny grid —
        # merging it into the checked-in file would silently replace a
        # production-tuned entry under the same workload@backend key
        # (resolve_default never re-checks provenance.smoke).  Refused
        # BEFORE the search so no budget is spent on a doomed run.
        raise SystemExit(
            "--smoke results must not overwrite the default "
            "tuned_configs.json; pass an explicit --out (or --dry-run)")
    import jax

    import bench
    wl = WORKLOADS[workload]
    backend = jax.default_backend()
    axes = wl.smoke_axes if smoke else wl.axes
    axes, pruned = prune_axes(axes, backend, jax.device_count())
    for knob, why in pruned.items():
        logger.warning("axis %s pruned on %s: %s", knob, backend, why)
    grid = build_grid(axes)
    result = successive_halving(
        grid, measure or wl.runner(smoke), budget,
        eta=eta, full_windows=full_windows)
    result["workload"] = workload
    result["backend"] = backend
    result["pruned_axes"] = pruned
    result["smoke"] = smoke
    provenance = {
        "tool": "tools/autotune.py",
        "toolchain": bench._toolchain(),
        "score": result["score"],
        "score_metric": SCORE_METRIC,
        "n_configs": result["n_configs"],
        "windows_total": result["windows_total"],
        "budget": budget,
        "rungs": [{k: r[k] for k in
                   ("rung", "trials", "windows_per_trial", "survivors")}
                  for r in result["rungs"]],
        "excluded_windows": result["excluded_windows"],
        "pruned_axes": pruned,
        "smoke": smoke,
        "captured_at": time.strftime("%Y-%m-%d %H:%M:%S UTC",
                                     time.gmtime()),
    }
    if not dry_run:
        from bigdl_tpu.utils import tuned
        path = out or tuned.default_path()
        write_tuned(path, workload, backend, result, provenance)
        result["out"] = path
        # the process that just re-tuned must also SEE the new file
        tuned.reset_cache()
    return result


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.autotune",
        description="successive-halving autotuner over the declared "
                    "per-workload config grids; writes "
                    "tuned_configs.json (consumed by Engine/Config as "
                    "below-env defaults)")
    ap.add_argument("--workload", help="workload tag to tune")
    ap.add_argument("--budget", type=int, default=40,
                    help="HARD cap on total measured windows across "
                         "all rungs (default 40)")
    ap.add_argument("--eta", type=int, default=2,
                    help="halving factor (default 2)")
    ap.add_argument("--full-windows", type=int, default=4,
                    help="max windows per trial per rung — the "
                         "confirmation-run length (default 4)")
    ap.add_argument("--out", default=None,
                    help="tuned-configs path (default: "
                         "$BIGDL_TPU_TUNED_CONFIGS or the repo-root "
                         "tuned_configs.json)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny models + tiny grids (CI / tests); "
                         "requires --out or --dry-run — smoke winners "
                         "never overwrite the checked-in file")
    ap.add_argument("--dry-run", action="store_true",
                    help="search but do not write the tuned file")
    ap.add_argument("--list", action="store_true",
                    help="list workloads and their grids, then exit")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO,
                        format="%(name)s %(levelname)s %(message)s")
    if args.list:
        listing = {
            name: {"kind": wl.kind,
                   "axes": {ax.knob: list(ax.values) for ax in wl.axes},
                   "gated_axes": {ax.knob: ax.requires
                                  for ax in wl.axes if ax.requires}}
            for name, wl in sorted(WORKLOADS.items())}
        print(json.dumps(listing, indent=2))
        return 0
    if not args.workload:
        ap.error("--workload is required (or --list)")
    result = tune(args.workload, budget=args.budget, eta=args.eta,
                  full_windows=args.full_windows, smoke=args.smoke,
                  out=args.out, dry_run=args.dry_run)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
