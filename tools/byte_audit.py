"""Byte-level audit of the compiled ResNet-50 train step.

VERDICT r4 item 1e: jax 0.8→0.9 recompiled the identical bench source
from 78.7 to 85.09 GB/step (cost-analysis "bytes accessed"), moving the
HBM floor 96.1→103.9 ms and ResNet throughput 2505→~2370.  This tool
attributes the compiled program's traffic so the +6.4 GB is accounted
for instruction-by-instruction instead of asserted.

Usage:
    python tools/byte_audit.py [--format NHWC|NCHW] [--batch N]
        [--remat none|tails|full] [--top N] [--cpu]
    python tools/byte_audit.py --diff a.hlo b.hlo [--top N]
    python tools/byte_audit.py --audit-copies prog.hlo [--min-bytes N]

``--diff`` (round-10, the fused-kernel PR): side-by-side bytes
comparison of two HLO dumps — per-op-kind delta table plus totals and
collective wire payloads — so a kernel/layout win is provable from two
``compiled.as_text()`` files instead of asserted (the canned
PTB-LSTM / Wide&Deep step fixtures in tests/fixtures gate the fused
kernels' strictly-lower-bytes claim this way).

``--audit-copies`` (round-10, donation/aliasing audit): entry-
computation ``copy``/``copy-start`` instructions at or above a size
threshold, with shapes and source lines — the fingerprint of a
donation or aliasing gap.  Findings from running it over the fused
K-step dispatch (K=4, CPU host): every large copy is either (a) a
donated-carry copy the CPU backend inserts because BUFFER DONATION IS
NOT IMPLEMENTED ON CPU (on TPU the donated params/mstate/ostate alias
in place), or (b) a layout copy around the scan-major transpose of the
hoisted input projections — intrinsic to hoisting (one small copy per
block vs T small matmuls), not an aliasing gap.  No unintended
full-tensor copies on the donated path; re-run on-chip per toolchain
bump (the CPU-host caveat makes host findings advisory).

Prints:
- cost_analysis totals (flops, bytes) + roofline floors;
- per-opcode aggregate of bytes ACCESSED (output write + operand
  reads) over the ENTRY computation of the optimized HLO — fusion
  bodies' internal values never materialize and are excluded, which is
  exactly what makes the entry-visible buffers the interesting set.
  Tuple-typed operands are parsed paren-balanced, GTE consumers are
  charged element (not tuple) sizes, and async *-done ops charge the
  aliased result buffer only (regression-tested on canned HLO in
  tests/test_byte_audit.py).  It still parses untiled logical shapes
  and cannot see every aliasing (donated buffers, tuple pass-through),
  so totals will NOT equal the cost model's; use it for RELATIVE
  attribution between two runs, with cost_analysis as ground truth;
- the top-N largest single instructions with their opcodes/shapes.

Comparing two runs of this tool (different jax versions, layouts,
batch sizes) shows WHICH buffer class grew.
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from collections import defaultdict

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2, "f16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}

# e.g. "f32[256,56,56,64]{3,2,1,0}" or "bf16[64]"  (layout braces optional)
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def shape_bytes(shape_str: str) -> int:
    total = 0
    for dt, dims in _SHAPE_RE.findall(shape_str):
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dt]
    return total


# shape part may be a single shape OR a tuple with internal spaces
# ("(bf16[...]{...}, f32[...]{...})") — lazy-match up to the opcode
# token, which may be hyphenated (get-tuple-element, custom-call,
# dynamic-update-slice, all-reduce)
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(.+?)\s+([\w-]+)\(")


# '%' is optional: some as_text() formats print operands without the
# sigil (mirrors _INSTR_RE's optional '%' on definitions); resolution
# against out_bytes keys filters non-operand tokens either way
_OPERAND_RE = re.compile(r"%?([\w.\-]+)")


def _operand_text(line: str, start: int) -> str:
    """Operand-list text from ``start`` (just past the opcode's opening
    paren) to its MATCHING close paren.  Operands printed with a
    tuple-typed shape — ``while((s32[], f32[...]{1,0}) %tuple)`` —
    contain internal parens, so a naive split(")")[0] cuts inside the
    printed type and silently drops every %ref after it."""
    depth = 1
    for i in range(start, len(line)):
        c = line[i]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return line[start:i]
    return line[start:]


def audit(hlo_text: str, top: int):
    """Aggregate bytes ACCESSED (output write + operand reads) by opcode
    over the optimized HLO's ENTRY computation only — nested
    computations (fusion bodies, reduce bodies) describe values that
    never materialize in HBM and would wildly overcount if parsed.
    This mirrors XLA cost analysis' accounting, which sums operand +
    output sizes per top-level instruction.

    Tuple handling: a get-tuple-element's consumers are charged the
    ELEMENT size (the GTE's own declared shape), never the producing
    tuple's total; async ``*-done`` ops, whose tuple-shaped operand
    merely aliases the in-flight buffers, are charged their own result
    size instead of the start op's whole (operand, result) tuple."""
    # pass 1: entry instruction shapes (for operand lookups)
    entry_lines = []
    in_entry = False
    for line in hlo_text.splitlines():
        if line.startswith("ENTRY "):
            in_entry = True
            continue
        if in_entry and line.startswith("}"):
            in_entry = False
        if in_entry:
            entry_lines.append(line)
    out_bytes = {}
    tuple_shaped = set()
    parsed = []
    for line in entry_lines:
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, shape_str, opcode = m.groups()
        out_bytes[name] = shape_bytes(shape_str)
        if shape_str.lstrip().startswith("("):
            tuple_shaped.add(name)
        parsed.append((line, m.end(), name, shape_str, opcode))

    # aliasing/bookkeeping ops move no bytes themselves but must stay
    # resolvable as operands of real consumers
    no_traffic = {"get-tuple-element", "tuple", "bitcast", "parameter"}
    by_op = defaultdict(int)
    instrs = []
    for line, argstart, name, shape_str, opcode in parsed:
        if opcode in no_traffic:
            continue
        b = out_bytes[name]
        # operand reads: %refs in the argument list that name entry
        # instructions.  Paren-balanced cut — attributes after the list
        # (control-predecessors={...}, calls=%fused...) also hold %refs
        # but are not reads
        args = _operand_text(line, argstart)
        for ref in _OPERAND_RE.findall(args):
            rb = out_bytes.get(ref, 0)
            if rb and ref in tuple_shaped and opcode.endswith("-done"):
                # the done op consumes the start's aliased result
                # buffer, not the whole (operand, result) tuple
                rb = out_bytes[name]
            b += rb
        if b == 0:
            continue
        # fusion kinds matter more than the generic "fusion" opcode
        if opcode == "fusion":
            km = re.search(r'kind=(\w+)', line)
            opcode = f"fusion.{km.group(1)}" if km else opcode
        by_op[opcode] += b
        instrs.append((b, opcode, name, shape_str[:80]))
    instrs.sort(reverse=True)
    return by_op, instrs[:top]


# ---------------------------------------------- collective wire bytes
# (round-7, grad_sync wire-format audit): attribute the bytes each
# collective puts on the wire, by op kind — the observable that the
# grad_wire_dtype knob halves.
_COLLECTIVE_KINDS = ("all-reduce", "reduce-scatter", "all-gather",
                     "collective-permute", "all-to-all")


def collective_wire_bytes(hlo_text: str) -> dict:
    """Per-collective-kind wire-payload bytes over the WHOLE module
    (collectives inside while/scan bodies — where the fused K-step
    driver puts them — would be invisible to an entry-only walk; as
    with XLA cost analysis, a loop body is counted ONCE, not per trip).

    Payload model, deliberately simple and dtype-proportional (this
    exists to compare wire dtypes, not to model ring hops):
    - ``all-reduce`` / ``all-gather`` / ``collective-permute`` /
      ``all-to-all``: result bytes;
    - ``reduce-scatter``: operand bytes (the full pre-scatter vector —
      its result is 1/N of what crossed the wire);
    - async ``*-start``: largest element of the in-flight
      (operand, result) tuple (the payload buffer); ``*-done`` ops are
      skipped — their start was already charged.
    Returns ``{kind: bytes, ..., "total": sum}`` (only kinds present).
    """
    by_kind: dict = defaultdict(int)
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if not m:
            continue
        _, shape_str, opcode = m.groups()
        if opcode.endswith("-done"):
            continue
        base = opcode[:-6] if opcode.endswith("-start") else opcode
        if base not in _COLLECTIVE_KINDS:
            continue
        if base == "reduce-scatter":
            b = shape_bytes(_operand_text(line, m.end())) \
                or shape_bytes(shape_str)
        elif opcode.endswith("-start"):
            elems = [_DTYPE_BYTES[dt]
                     * int(np.prod([int(d) for d in dims.split(",") if d],
                                   dtype=np.int64))
                     for dt, dims in _SHAPE_RE.findall(shape_str)
                     if dt in _DTYPE_BYTES]
            b = max(elems, default=0)
        else:
            b = shape_bytes(shape_str)
        if b:
            by_kind[base] += b
    out = dict(by_kind)
    out["total"] = sum(by_kind.values())
    return out


# ------------------------------------------------- two-dump comparison
def diff_audit(hlo_a: str, hlo_b: str, top: int = 20) -> dict:
    """Per-op-kind bytes-accessed delta between two HLO dumps (A = the
    baseline, B = the candidate).  Returns::

        {"per_op": [(kind, bytes_a, bytes_b, bytes_b - bytes_a), ...],
         "total_a": ..., "total_b": ..., "total_delta": ...,
         "wire_a": {...}, "wire_b": {...}}

    ``per_op`` is sorted by |delta| descending and includes kinds
    present in either dump.  Totals are the summed per-op attributions
    (RELATIVE comparison semantics — see :func:`audit`: use deltas
    between dumps, not absolutes vs the cost model).  Collective wire
    payloads ride along so wire-dtype comparisons read from the same
    table."""
    by_a, _ = audit(hlo_a, top)
    by_b, _ = audit(hlo_b, top)
    kinds = sorted(set(by_a) | set(by_b),
                   key=lambda k: -abs(by_b.get(k, 0) - by_a.get(k, 0)))
    per_op = [(k, by_a.get(k, 0), by_b.get(k, 0),
               by_b.get(k, 0) - by_a.get(k, 0)) for k in kinds]
    ta, tb = sum(by_a.values()), sum(by_b.values())
    return {"per_op": per_op, "total_a": ta, "total_b": tb,
            "total_delta": tb - ta,
            "wire_a": collective_wire_bytes(hlo_a),
            "wire_b": collective_wire_bytes(hlo_b)}


def print_diff(d: dict) -> None:
    print(f"{'op kind':28s} {'A (MB)':>12s} {'B (MB)':>12s} "
          f"{'delta (MB)':>12s}")
    for kind, a, b, delta in d["per_op"]:
        print(f"{kind:28s} {a / 1e6:12.3f} {b / 1e6:12.3f} "
              f"{delta / 1e6:+12.3f}")
    print(f"{'TOTAL':28s} {d['total_a'] / 1e6:12.3f} "
          f"{d['total_b'] / 1e6:12.3f} {d['total_delta'] / 1e6:+12.3f}")
    if d["wire_a"]["total"] or d["wire_b"]["total"]:
        print(f"{'collective wire total':28s} "
              f"{d['wire_a']['total'] / 1e6:12.3f} "
              f"{d['wire_b']['total'] / 1e6:12.3f} "
              f"{(d['wire_b']['total'] - d['wire_a']['total']) / 1e6:+12.3f}")


# --------------------------------------------- donation/aliasing audit
def copy_audit(hlo_text: str, min_bytes: int = 1 << 20) -> list:
    """Entry-computation ``copy``/``copy-start`` instructions moving at
    least ``min_bytes`` (result size), as ``(bytes, name, line)``
    tuples sorted largest first — the donation/aliasing-gap
    fingerprint.  Interpretation guidance (and the findings from the
    fused K-step dispatch) in the module docstring: on CPU hosts
    donated carries are ALWAYS copied (donation unimplemented there),
    so treat host results as advisory and re-audit on-chip."""
    in_entry = False
    found = []
    for line in hlo_text.splitlines():
        if line.startswith("ENTRY "):
            in_entry = True
            continue
        if in_entry and line.startswith("}"):
            in_entry = False
        if not in_entry:
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, shape_str, opcode = m.groups()
        if opcode not in ("copy", "copy-start"):
            continue
        b = shape_bytes(shape_str)
        if b >= min_bytes:
            found.append((b, name, line.strip()))
    found.sort(reverse=True)
    return found


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--format", default="NHWC", choices=["NHWC", "NCHW"])
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--remat", default="none",
                    choices=["none", "tails", "full"])
    ap.add_argument("--top", type=int, default=20)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--diff", nargs=2, metavar=("A.hlo", "B.hlo"),
                    help="per-op-kind bytes delta between two HLO dumps")
    ap.add_argument("--audit-copies", metavar="PROG.hlo",
                    help="entry copy/copy-start instructions >= "
                         "--min-bytes (donation/aliasing audit)")
    ap.add_argument("--min-bytes", type=int, default=1 << 20)
    args = ap.parse_args()

    if args.diff:
        with open(args.diff[0]) as fh:
            a = fh.read()
        with open(args.diff[1]) as fh:
            b = fh.read()
        print_diff(diff_audit(a, b, args.top))
        return

    if args.audit_copies:
        with open(args.audit_copies) as fh:
            text = fh.read()
        found = copy_audit(text, args.min_bytes)
        if not found:
            print(f"no entry copies >= {args.min_bytes} bytes")
        for b, name, line in found:
            print(f"  {b / 1e6:9.3f}MB  {name:32s} {line[:110]}")
        return

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp
    from functools import partial
    from bigdl_tpu import nn, optim
    from bigdl_tpu.models.resnet import resnet50
    from bigdl_tpu.utils.precision import mixed_precision_loss_fn

    remat = {"none": False, "tails": "tails", "full": True}[args.remat]
    model = resnet50(format=args.format, remat=remat)
    criterion = nn.ClassNLLCriterion()
    method = optim.SGD(learning_rate=0.1, momentum=0.9, weight_decay=1e-4)
    params, mstate = model.init(jax.random.PRNGKey(0))
    ostate = method.init_state(params)
    shape = ((args.batch, 224, 224, 3) if args.format == "NHWC"
             else (args.batch, 3, 224, 224))
    x = jnp.asarray(np.random.default_rng(0).normal(0, 1, shape)
                    .astype(np.float32))
    y = jnp.asarray(np.random.default_rng(1).integers(
        0, 1000, (args.batch,)).astype(np.int32))
    base_loss = mixed_precision_loss_fn(model, criterion, jnp.bfloat16)
    grad_fn = jax.value_and_grad(base_loss, has_aux=True)
    rng0 = jax.random.PRNGKey(42)

    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(p, ms, os_, x, y, lr, it, rng):
        (loss, ms), g = grad_fn(p, ms, x, y, rng)
        p, os_ = method.update(g, p, os_, lr, it)
        return p, ms, os_, loss

    compiled = step.lower(params, mstate, ostate, x, y, 0.1, 0,
                          rng0).compile()
    c = compiled.cost_analysis()
    if isinstance(c, list):
        c = c[0]
    flops = float(c.get("flops", 0.0))
    bts = float(c.get("bytes accessed", 0.0))
    print(f"jax={jax.__version__} platform={jax.devices()[0].platform} "
          f"format={args.format} batch={args.batch} remat={args.remat}")
    print(f"cost_analysis: flops={flops/1e9:.1f}G bytes={bts/1e9:.2f}GB "
          f"t_mxu={flops/197e12*1e3:.2f}ms t_hbm={bts/819e9*1e3:.2f}ms")
    try:
        ma = compiled.memory_analysis()
        print(f"memory_analysis: {ma}")
    except Exception as e:
        print(f"memory_analysis unavailable: {e}")

    hlo = compiled.as_text()
    by_op, top_instrs = audit(hlo, args.top)
    print("\n-- entry bytes accessed (write + operand reads, untiled) "
          "by opcode (GB) --")
    for op, b in sorted(by_op.items(), key=lambda kv: -kv[1]):
        if b > 50e6:
            print(f"  {op:28s} {b/1e9:8.3f}")
    print(f"\n-- top {args.top} instructions --")
    for b, opcode, name, shape_str in top_instrs:
        print(f"  {b/1e6:9.1f}MB  {opcode:22s} {name:40s} {shape_str}")
    cw = collective_wire_bytes(hlo)
    if cw["total"]:
        print("\n-- collective wire bytes by op kind (payload model) --")
        for kind, b in sorted(cw.items(), key=lambda kv: -kv[1]):
            print(f"  {kind:22s} {b/1e6:10.2f}MB")


if __name__ == "__main__":
    main()
