"""TensorBoard-compatible training summaries.

Reference: ``DL/visualization/{TrainSummary,ValidationSummary}.scala`` write
scalar+histogram protos (``Summary.scala:95-172``) through FileWriter →
EventWriter (background thread) → RecordWriter with TFRecord CRC
(``netty/Crc32c.java``).  Scalars: Loss, Throughput, LearningRate.

This is a dependency-free re-implementation: the Event protobuf is
hand-encoded (only the fields TensorBoard needs), framed as TFRecord with
masked CRC32C — generated files load in TensorBoard.  Histograms are
supported via HistogramProto summaries.
"""

from __future__ import annotations

import os
import struct
import time
from typing import Optional, Sequence

import numpy as np

# ---------------------------------------------------------------- crc32c
_CRC_TABLE = []


def _make_table():
    poly = 0x82F63B78
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        _CRC_TABLE.append(c)


_make_table()


def crc32c(data: bytes, crc: int = 0) -> int:
    """CRC32-C (Castagnoli) — reference ``netty/Crc32c.java``."""
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# ------------------------------------------------------- protobuf encoding
def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint(field << 3 | wire)


def _pb_double(field: int, v: float) -> bytes:
    return _tag(field, 1) + struct.pack("<d", v)


def _pb_float(field: int, v: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", v)


def _pb_int64(field: int, v: int) -> bytes:
    return _tag(field, 0) + _varint(v & 0xFFFFFFFFFFFFFFFF)


def _pb_bytes(field: int, v: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(v)) + v


def _pb_str(field: int, v: str) -> bytes:
    return _pb_bytes(field, v.encode("utf-8"))


def _histogram_proto(values: np.ndarray) -> bytes:
    """HistogramProto: min=1,max=2,num=3,sum=4,sum_squares=5,
    bucket_limit=6 (repeated double), bucket=7 (repeated double)."""
    # tensorboard HistogramProto fields are doubles on the wire
    v = np.asarray(values, np.float64).ravel()
    if v.size == 0:
        v = np.zeros(1)
    # tensorboard-style exponential buckets
    limits = [-1e308]
    x = 1e-12
    neg = []
    while x < 1e20:
        neg.append(-x)
        x *= 1.1
    limits = sorted(neg) + [0.0]
    x = 1e-12
    while x < 1e20:
        limits.append(x)
        x *= 1.1
    limits.append(1e308)
    counts, _ = np.histogram(v, bins=[-np.inf] + limits[1:] + [np.inf])
    # keep only non-empty buckets (tensorboard convention allows all)
    msg = (_pb_double(1, float(v.min())) + _pb_double(2, float(v.max()))
           + _pb_double(3, float(v.size)) + _pb_double(4, float(v.sum()))
           + _pb_double(5, float((v * v).sum())))
    for lim, c in zip(limits, counts):
        if c > 0:
            msg += _pb_double(6, lim) + _pb_double(7, float(c))
    return msg


def _scalar_event(tag: str, value: float, step: int, wall: float) -> bytes:
    value_msg = _pb_str(1, tag) + _pb_float(2, float(value))
    summary = _pb_bytes(1, value_msg)
    return (_pb_double(1, wall) + _pb_int64(2, step) + _pb_bytes(5, summary))


def _histo_event(tag: str, values, step: int, wall: float) -> bytes:
    value_msg = _pb_str(1, tag) + _pb_bytes(4, _histogram_proto(values))
    summary = _pb_bytes(1, value_msg)
    return (_pb_double(1, wall) + _pb_int64(2, step) + _pb_bytes(5, summary))


# ------------------------------------------------------------ file writer
class FileWriter:
    """TFRecord event-file writer (reference
    ``visualization/tensorboard/{FileWriter,EventWriter,RecordWriter}``)."""

    def __init__(self, log_dir: str):
        os.makedirs(log_dir, exist_ok=True)
        fname = f"events.out.tfevents.{int(time.time())}.bigdl_tpu"
        self._path = os.path.join(log_dir, fname)
        self._f = open(self._path, "ab")
        # first record: file version event
        ver = _pb_double(1, time.time()) + _pb_str(3, "brain.Event:2")
        self._write_record(ver)

    def _write_record(self, payload: bytes):
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc(payload)))

    def add_scalar(self, tag: str, value: float, step: int):
        self._write_record(_scalar_event(tag, value, step, time.time()))

    def add_histogram(self, tag: str, values, step: int):
        self._write_record(_histo_event(tag, values, step, time.time()))

    def flush(self):
        self._f.flush()

    def close(self):
        self._f.close()


class Summary:
    """Base of Train/Validation summaries."""

    def __init__(self, log_dir: str, app_name: str, phase: str):
        self.writer = FileWriter(os.path.join(log_dir, app_name, phase))

    def add_scalar(self, tag: str, value: float, step: int) -> "Summary":
        self.writer.add_scalar(tag, value, step)
        self.writer.flush()
        return self

    def add_histogram(self, tag: str, values, step: int) -> "Summary":
        self.writer.add_histogram(tag, values, step)
        self.writer.flush()
        return self

    def close(self):
        self.writer.close()


class TrainSummary(Summary):
    """Per-iteration Loss/Throughput/LearningRate scalars (reference
    ``TrainSummary.scala``; written by the optimizer loop)."""

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name, "train")
        self._triggers = {}

    def add_train_step(self, step: int, loss: float, lr: float,
                       throughput: float) -> "TrainSummary":
        """One training iteration's standard scalar triple.  The fused
        K-step driver replays a whole dispatch block through here — one
        call per iteration, each with its own loss from the block's
        per-step loss vector — so the event file is indistinguishable
        from an unfused run's; a single flush covers the three records
        (the replay writes K·3 records back-to-back)."""
        self.writer.add_scalar("Loss", loss, step)
        self.writer.add_scalar("LearningRate", lr, step)
        self.writer.add_scalar("Throughput", throughput, step)
        self.writer.flush()
        return self

    def set_summary_trigger(self, name: str, trigger) -> "TrainSummary":
        """Gate optional summaries (e.g. Parameters histograms) by trigger
        (reference ``DistriOptimizer.scala:541-573``)."""
        self._triggers[name] = trigger
        return self

    def trigger_for(self, name: str):
        return self._triggers.get(name)


class ValidationSummary(Summary):
    """Per-validation metric scalars (reference ``ValidationSummary.scala``)."""

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name, "validation")
