"""Minimal protobuf wire-format codec (pure Python, no generated code).

The reference ships ~187k LoC of *generated* Java protobuf (Caffe protos,
TF framework protos, BigDL's own ``bigdl.proto`` — SURVEY §2.8).  The TPU
build needs to speak those wire formats for interop (TFRecord ``Example``
parsing, BigDL checkpoint import, TF GraphDef import) but none of the
generated-code machinery: protobuf wire format is five primitive wire
types, decodable generically.  This module provides:

- :func:`decode_message` — bytes → ``{field_number: [raw values]}``
  (varints as int, fixed32/64 as int, length-delimited as bytes).
  Callers interpret fields against the schema's field numbers.
- small typed encode helpers (the mirror of the writers in
  ``utils/summary.py``) for building messages on export.

Wire types: 0=varint, 1=64-bit, 2=length-delimited, 5=32-bit
(groups 3/4 are legacy and unsupported — none of the target schemas
use them).
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Tuple


# ------------------------------------------------------------------ decode
def read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    """Return (value, new_pos)."""
    result = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def iter_fields(data: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, raw_value) for each field."""
    pos = 0
    n = len(data)
    while pos < n:
        key, pos = read_varint(data, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = read_varint(data, pos)
        elif wire == 1:
            val = struct.unpack_from("<Q", data, pos)[0]
            pos += 8
        elif wire == 2:
            ln, pos = read_varint(data, pos)
            val = data[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = struct.unpack_from("<I", data, pos)[0]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire} at {pos}")
        yield field, wire, val


def decode_message(data: bytes) -> Dict[int, List]:
    """Decode one message level into {field_number: [values]}."""
    out: Dict[int, List] = {}
    for field, _, val in iter_fields(data):
        out.setdefault(field, []).append(val)
    return out


# --------------------------------------------------- typed value accessors
def as_int(v) -> int:
    return int(v)


def as_sint(v: int) -> int:
    """Two's-complement reinterpretation of a varint as a signed int64
    (proto int32/int64 negative values are encoded as 10-byte varints)."""
    v = int(v)
    return v - (1 << 64) if v >= (1 << 63) else v


def as_zigzag(v: int) -> int:
    """sint32/sint64 zigzag decode."""
    v = int(v)
    return (v >> 1) ^ -(v & 1)


def as_float(v: int) -> float:
    """fixed32 bits -> float."""
    return struct.unpack("<f", struct.pack("<I", v))[0]


def as_double(v: int) -> float:
    """fixed64 bits -> double."""
    return struct.unpack("<d", struct.pack("<Q", v))[0]


def as_str(v: bytes) -> str:
    return v.decode("utf-8")


def unpack_packed(v: bytes, kind: str) -> List:
    """Decode a packed repeated scalar field (wire type 2 payload)."""
    out: List = []
    pos = 0
    if kind in ("varint", "int"):
        while pos < len(v):
            x, pos = read_varint(v, pos)
            out.append(x)
    elif kind == "float":
        out = list(struct.unpack(f"<{len(v) // 4}f", v))
    elif kind == "double":
        out = list(struct.unpack(f"<{len(v) // 8}d", v))
    elif kind == "fixed64":
        out = list(struct.unpack(f"<{len(v) // 8}Q", v))
    elif kind == "fixed32":
        out = list(struct.unpack(f"<{len(v) // 4}I", v))
    else:
        raise ValueError(kind)
    return out


def ints(msg: Dict[int, List], field: int, kind: str = "varint") -> List[int]:
    """Repeated int field that may be packed or unpacked."""
    out: List[int] = []
    for v in msg.get(field, []):
        if isinstance(v, bytes):
            out.extend(unpack_packed(v, kind))
        else:
            out.append(v)
    return out


# ------------------------------------------------------------------ encode
def varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def tag(field: int, wire: int) -> bytes:
    return varint((field << 3) | wire)


def enc_varint(field: int, v: int) -> bytes:
    return tag(field, 0) + varint(v)


def enc_bytes(field: int, v: bytes) -> bytes:
    return tag(field, 2) + varint(len(v)) + v


def enc_str(field: int, v: str) -> bytes:
    return enc_bytes(field, v.encode("utf-8"))


def enc_float(field: int, v: float) -> bytes:
    return tag(field, 5) + struct.pack("<f", v)


def enc_double(field: int, v: float) -> bytes:
    return tag(field, 1) + struct.pack("<d", v)


def enc_packed_floats(field: int, vs) -> bytes:
    payload = struct.pack(f"<{len(vs)}f", *vs)
    return enc_bytes(field, payload)


def enc_packed_ints(field: int, vs) -> bytes:
    payload = b"".join(varint(int(v)) for v in vs)
    return enc_bytes(field, payload)
