"""Tuned-config layer — the autotuner's output consumed as defaults.

``tools/autotune.py`` (ROADMAP item 3, in the spirit of TVM
arXiv:1802.04799 / Learning to Optimize Tensor Programs
arXiv:1805.08166) writes per-workload best configs plus measurement
provenance to a checked-in, schema-versioned ``tuned_configs.json``.
This module is the CONSUMPTION side: ``Engine``/``Optimizer``/
``InferenceService`` resolve knob defaults through
:func:`resolve_default`, which implements the documented precedence

    explicit setter (``configure()`` / ``Engine.set_*`` / per-run
    builder) > ``BIGDL_TPU_*`` env var > ``tuned_configs.json`` entry
    (keyed by ``workload@backend``) > dataclass default

so a tuned value only ever fills a slot the user left at its dataclass
default — it can never override an explicit choice or an env var.

Failure contract (gated in tests/test_autotune.py):

- **Absent or empty file is provably inert**: no entries, no warning —
  every lookup returns None and the chain falls through to the
  dataclass default (bitwise-identical training, the established
  inertness-gate pattern).
- **Malformed / stale-schema files are rejected LOUDLY**: one
  ``logging.error`` naming the file and the reason, then the ENTIRE
  tuned layer is skipped (never a partial read — a file wrong in one
  place is not trusted anywhere else).
- Entries may only reference knobs that exist on
  :class:`~bigdl_tpu.utils.config.Config` (same-typed values); the
  checked-in file is additionally round-trip-gated in tier-1.

The parsed file is cached process-wide; ``Engine.reset()`` clears the
cache (so tests and multi-run processes cannot leak a prior workload's
tuned defaults — see :func:`reset_cache`).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Optional, Tuple

from bigdl_tpu.utils.config import Config, get_config

logger = logging.getLogger("bigdl_tpu.tuned")

SCHEMA_VERSION = 1
ENV_PATH = "BIGDL_TPU_TUNED_CONFIGS"

# cache states: None = not loaded yet; dict = validated entries (empty
# when the file is absent, empty, or was rejected)
_entries: Optional[dict] = None


class TunedConfigError(ValueError):
    """A tuned_configs.json that cannot be trusted (wrong schema
    version, unknown knobs, type drift, structural damage)."""


def default_path() -> str:
    """``$BIGDL_TPU_TUNED_CONFIGS`` when set, else the checked-in
    ``tuned_configs.json`` at the repository root (the directory that
    holds the ``bigdl_tpu`` package)."""
    env = os.environ.get(ENV_PATH)
    if env:
        return env
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(pkg_root, "tuned_configs.json")


def _knob_types() -> dict:
    """Config field name -> dataclass default (the type authority)."""
    return {f.name: getattr(Config(), f.name)
            for f in dataclasses.fields(Config)
            if not f.name.startswith("_")}


def _type_ok(default, value) -> bool:
    """Same-typed as the Config default.  bool is NOT an int here
    (bool subclasses int in Python — a tuned ``true`` must not slip
    into an int knob), and ints are acceptable floats."""
    if isinstance(default, bool) or isinstance(value, bool):
        return isinstance(default, bool) and isinstance(value, bool)
    if isinstance(default, float):
        return isinstance(value, (int, float))
    return isinstance(value, type(default))


def validate_document(doc) -> dict:
    """Validate a parsed tuned-configs document; returns its entries
    dict or raises :class:`TunedConfigError` listing what is wrong.
    The whole file is rejected on the first problem — a partially
    trusted tuning file is worse than none."""
    if not isinstance(doc, dict):
        raise TunedConfigError(
            f"top level must be an object, got {type(doc).__name__}")
    version = doc.get("schema_version")
    if version != SCHEMA_VERSION:
        raise TunedConfigError(
            f"schema_version {version!r} != supported {SCHEMA_VERSION} "
            f"— stale or future file; re-run tools/autotune.py")
    entries = doc.get("entries")
    if not isinstance(entries, dict):
        raise TunedConfigError("'entries' must be an object")
    knobs = _knob_types()
    for key, entry in entries.items():
        if not isinstance(entry, dict):
            raise TunedConfigError(f"entry {key!r} must be an object")
        workload = entry.get("workload")
        backend = entry.get("backend")
        if (not isinstance(workload, str) or not isinstance(backend, str)
                or key != f"{workload}@{backend}"):
            raise TunedConfigError(
                f"entry key {key!r} must equal '<workload>@<backend>' "
                f"and match its workload={workload!r} backend="
                f"{backend!r} fields")
        best = entry.get("best")
        if not isinstance(best, dict) or not best:
            raise TunedConfigError(
                f"entry {key!r}: 'best' must be a non-empty object")
        for knob, value in best.items():
            if knob not in knobs:
                raise TunedConfigError(
                    f"entry {key!r}: unknown knob {knob!r} — tuned "
                    f"knobs must exist on Config")
            if not _type_ok(knobs[knob], value):
                raise TunedConfigError(
                    f"entry {key!r}: knob {knob!r} value {value!r} "
                    f"({type(value).__name__}) does not match the "
                    f"Config field type "
                    f"({type(knobs[knob]).__name__})")
        if not isinstance(entry.get("provenance"), dict):
            raise TunedConfigError(
                f"entry {key!r}: 'provenance' (toolchain stamp, "
                f"windows, score) is required — unattributed tuning "
                f"numbers are not trusted")
    return entries


def load(path: Optional[str] = None, force: bool = False) -> dict:
    """Entries of the tuned-config file, validated and cached.
    Absent/empty file → ``{}`` silently (inert); damaged file → ONE
    loud ``logging.error`` and ``{}`` (tuned layer skipped)."""
    global _entries
    if _entries is not None and not force and path is None:
        return _entries
    p = path or default_path()
    entries: dict = {}
    if os.path.exists(p):
        try:
            with open(p, "r", encoding="utf-8") as fh:
                text = fh.read()
            if text.strip():
                entries = validate_document(json.loads(text))
        except (OSError, json.JSONDecodeError, TunedConfigError) as e:
            logger.error(
                "tuned_configs.json REJECTED — tuned-default layer "
                "disabled for this process (%s: %s: %s).  Fix or "
                "delete the file, or point %s elsewhere, then "
                "Engine.reset() to reload.",
                p, type(e).__name__, e, ENV_PATH)
            entries = {}
    if path is None:
        _entries = entries
    return entries


def reset_cache() -> None:
    """Drop the cached file so the next lookup re-reads (and
    re-validates) it.  Called by ``Engine.reset()`` — the regression
    gate for "a prior workload's tuned defaults cannot leak across
    runs" lives in tests/test_autotune.py."""
    global _entries
    _entries = None


def lookup(workload: str, knob: str,
           backend: Optional[str] = None):
    """Tuned value for ``knob`` under ``workload@backend``, or None.
    ``backend`` defaults to the live ``jax.default_backend()`` — tuned
    numbers are a property of the hardware they were measured on, so a
    cpu-tuned entry never leaks onto a TPU run (and vice versa)."""
    if not workload:
        return None
    entries = load()
    if not entries:
        return None
    if backend is None:
        import jax
        backend = jax.default_backend()
    entry = entries.get(f"{workload}@{backend}")
    if entry is None:
        return None
    return entry["best"].get(knob)


def resolve_default(knob: str, workload: Optional[str] = None,
                    backend: Optional[str] = None) -> Tuple[object, str]:
    """Resolve a knob through the documented default chain; returns
    ``(value, source)`` with source one of ``"explicit"`` (a
    ``configure()`` call), ``"env"`` (``BIGDL_TPU_*``), ``"tuned"``
    (tuned_configs.json hit for ``workload@backend``) or
    ``"default"`` (dataclass default).  Engine-level and per-run
    setters sit ABOVE this function — their call sites short-circuit
    before asking for a default."""
    cfg = get_config()
    src = cfg.source(knob)
    if src != "default":
        return getattr(cfg, knob), src
    if workload:
        v = lookup(workload, knob, backend=backend)
        if v is not None:
            return v, "tuned"
    return getattr(cfg, knob), "default"
