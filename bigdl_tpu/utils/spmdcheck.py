"""spmdcheck — a collective-schedule sanitizer for multi-host divergence.

The static half of the divergence story is graftlint GL401-GL404: a
branch whose predicate is process-local sitting above a collective.
What static analysis cannot see is the DYNAMIC schedule — the actual
sequence of collectives each process issues once real data, real
preemptions and real membership epochs drive the branches.  This module
validates the SPMD invariant at runtime the way lockdep validates lock
ordering: record the schedule every (emulated) process issues and fail
the session on the FIRST divergence, with both schedules and both
stacks, instead of letting a one-sided allgather hang a pod.

How it works
------------

The driver's collective boundaries carry ``note(kind, axis, payload)``
calls (block dispatch, the replay fetch, checkpoint capture, the
multihost allgather helpers, membership adoption).  When the sanitizer
is off, ``note`` reads ONE module global and returns — the inertness
contract (gated bitwise in ``tests/test_spmdcheck.py``).  When on, the
note appends a :class:`ScheduleEntry` — ``(kind, axis, payload
fingerprint)`` plus a cheap stack — to the current participant's
schedule.

Multi-host is EMULATED: tests wrap per-process work in ``with
participant(pid):`` and run the same workload once per pid (the
``local[1]``-style trick the virtual-mesh conftest already plays).
Outside a ``participant`` block the pid defaults to
``jax.process_index()`` so the same note sites keep working on a real
pod.  Entry ``i`` of participant ``p`` is compared against entry ``i``
of the LOWEST-pid participant as soon as both exist; the first mismatch
records a :class:`DivergenceReport` carrying both entries, both stacks
and both full schedules.  Reporting is once per participant pair — a
schedule that slid out of phase would otherwise flood every subsequent
entry.

Fingerprints cover what the collective contract actually requires to
agree: op kind, mesh axis, and the payload's treedef + leaf
dtypes/shapes (values are allowed to differ — that is the point of a
collective).

Inertness contract (house discipline, the lockdep/FaultInjector
shape): with ``Config.spmdcheck`` off nothing is allocated, ``note``
is a single ``is None`` test, and driver behavior is byte-identical.

Opt-in: ``BIGDL_TPU_SPMDCHECK=1 python -m pytest tests/ ...`` — the
conftest installs the recorder and fails the session if any divergence
was recorded, so the multihost/membership/grad_sync suites double as a
divergence hunt.  Composes with ``BIGDL_TPU_LOCKDEP=1``; the two
sanitizers share no state.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

_THIS_FILE = os.path.abspath(__file__)

_MAX_REPORTS = 100     # bound the report list; a broken suite floods
_STACK_DEPTH = 10

FrameTup = Tuple[str, int, str]  # (filename, lineno, funcname)


def _cheap_stack(skip: int = 2) -> List[FrameTup]:
    """A few frames of (file, line, func) without touching linecache —
    cheap enough to capture on every note (source lines resolve lazily,
    only when a report renders)."""
    out: List[FrameTup] = []
    try:
        f = sys._getframe(skip)
    except ValueError:
        return out
    while f is not None and len(out) < _STACK_DEPTH:
        fn = f.f_code.co_filename
        if fn != _THIS_FILE:
            out.append((fn, f.f_lineno, f.f_code.co_name))
        f = f.f_back
    return out


def _fmt_stack(frames: List[FrameTup], indent: str = "    ") -> str:
    if not frames:
        return indent + "<no frames>"
    return "\n".join(
        f"{indent}{os.path.relpath(fn) if fn.startswith(os.sep) else fn}"
        f":{ln} in {fun}" for fn, ln, fun in frames)


def _fingerprint(payload) -> str:
    """Treedef + leaf dtype/shape digest — the structural identity a
    collective needs every process to agree on.  Only called when the
    sanitizer is ON (jax imports stay off the inert path)."""
    if payload is None:
        return "-"
    try:
        import jax
        import numpy as np
        leaves, treedef = jax.tree_util.tree_flatten(payload)
        leaf_s = ",".join(
            f"{getattr(l, 'dtype', np.asarray(l).dtype)!s}"
            f"{tuple(getattr(l, 'shape', np.shape(l)))!r}"
            for l in leaves)
        return f"{treedef}|{leaf_s}"
    except Exception:  # exotic payloads still fingerprint by repr-type
        return f"<{type(payload).__name__}>"


@dataclasses.dataclass
class ScheduleEntry:
    """One recorded collective boundary."""

    kind: str                 # e.g. "dispatch", "allgather", "checkpoint"
    axis: Optional[str]       # mesh axis, when the op names one
    fingerprint: str          # payload treedef/dtype/shape digest
    stack: List[FrameTup]

    def brief(self) -> str:
        fp = self.fingerprint
        if len(fp) > 60:
            fp = fp[:57] + "..."
        return f"{self.kind}(axis={self.axis or '-'}, {fp})"


@dataclasses.dataclass
class DivergenceReport:
    """Two participants disagree on schedule position ``index``."""

    pid_a: int
    pid_b: int
    index: int
    entry_a: Optional[ScheduleEntry]   # None: participant a ended early
    entry_b: Optional[ScheduleEntry]
    schedule_a: List[ScheduleEntry]
    schedule_b: List[ScheduleEntry]

    def render(self) -> str:
        def side(pid, entry, sched):
            lines = [f"  process {pid} at #{self.index}: "
                     + (entry.brief() if entry else "<schedule ended>")]
            if entry is not None:
                lines.append(_fmt_stack(entry.stack, indent="      "))
            lines.append(f"   schedule of process {pid} "
                         f"({len(sched)} entries):")
            lines += [f"      #{i} {e.brief()}"
                      for i, e in enumerate(sched)]
            return lines

        out = ["spmdcheck: collective schedules diverge"]
        out += side(self.pid_a, self.entry_a, self.schedule_a)
        out += side(self.pid_b, self.entry_b, self.schedule_b)
        out.append("  one process will enter a collective the other "
                   "never issues — on a real pod this deadlocks")
        return "\n".join(out)


class SpmdDivergenceError(RuntimeError):
    """Raised by :func:`check_clean` when divergences were recorded."""


class _Recorder:
    """The one global schedule table.  Guarded by a raw ``threading``
    lock allocated at install time (under lockdep this is a proxy; the
    sanitizers compose — spmdcheck never patches anything)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.schedules: Dict[int, List[ScheduleEntry]] = {}
        self.divergences: List[DivergenceReport] = []
        self.reported_pairs: set = set()
        self.notes = 0

    def reset(self):
        with self.lock:
            self.schedules.clear()
            self.divergences.clear()
            self.reported_pairs.clear()
            self.notes = 0

    def record(self, pid: int, entry: ScheduleEntry) -> None:
        with self.lock:
            self.notes += 1
            sched = self.schedules.setdefault(pid, [])
            sched.append(entry)
            self._compare_locked(pid, len(sched) - 1)

    def _compare_locked(self, pid: int, index: int) -> None:
        """Compare the fresh entry against the reference participant
        (lowest pid) at the same position, as soon as both exist."""
        ref = min(self.schedules)
        if pid == ref:
            # the reference grew: re-check any laggard already past us
            for other, osched in self.schedules.items():
                if other != ref and len(osched) > index:
                    self._diverge_locked(ref, other, index)
            return
        if len(self.schedules[ref]) > index:
            self._diverge_locked(ref, pid, index)

    def _diverge_locked(self, ref: int, pid: int, index: int) -> None:
        a = self.schedules[ref][index]
        b = self.schedules[pid][index]
        if (a.kind, a.axis, a.fingerprint) == (b.kind, b.axis,
                                               b.fingerprint):
            return
        pair = frozenset((ref, pid))
        if pair in self.reported_pairs:
            return  # one slid schedule reports once, not per entry
        self.reported_pairs.add(pair)
        if len(self.divergences) < _MAX_REPORTS:
            self.divergences.append(DivergenceReport(
                pid_a=ref, pid_b=pid, index=index, entry_a=a, entry_b=b,
                schedule_a=list(self.schedules[ref]),
                schedule_b=list(self.schedules[pid])))

    def finalize_locked_lengths(self) -> None:
        """Length mismatches (one participant simply stopped noting) —
        checked at :func:`divergences` read time, not per note, because
        schedules legitimately grow at different rates mid-run."""
        with self.lock:
            if len(self.schedules) < 2:
                return
            ref = min(self.schedules)
            rs = self.schedules[ref]
            for pid, sched in self.schedules.items():
                if pid == ref or len(sched) == len(rs):
                    continue
                pair = frozenset((ref, pid))
                if pair in self.reported_pairs:
                    continue
                self.reported_pairs.add(pair)
                n = min(len(rs), len(sched))
                if len(self.divergences) < _MAX_REPORTS:
                    self.divergences.append(DivergenceReport(
                        pid_a=ref, pid_b=pid, index=n,
                        entry_a=rs[n] if len(rs) > n else None,
                        entry_b=sched[n] if len(sched) > n else None,
                        schedule_a=list(rs), schedule_b=list(sched)))


#: None when off — the single global ``note`` reads (inertness contract)
_RECORDER: Optional[_Recorder] = None

_tls = threading.local()

_DEFAULT_PID: Optional[int] = None


def _current_pid() -> int:
    pid = getattr(_tls, "pid", None)
    if pid is not None:
        return pid
    global _DEFAULT_PID
    if _DEFAULT_PID is None:
        try:
            import jax
            _DEFAULT_PID = int(jax.process_index())
        except Exception:
            _DEFAULT_PID = 0
    return _DEFAULT_PID


@contextlib.contextmanager
def participant(pid: int):
    """Attribute notes on this thread to emulated process ``pid`` —
    the test-side K-process emulation.  Nestable; restores the previous
    pid on exit."""
    prev = getattr(_tls, "pid", None)
    _tls.pid = int(pid)
    try:
        yield
    finally:
        _tls.pid = prev


def note(kind: str, axis: Optional[str] = None, payload=None) -> None:
    """Record one collective boundary for the current participant.

    THE hot-path contract: when the sanitizer is off this is one global
    read and a return — no allocation, no jax, no fingerprinting."""
    rec = _RECORDER
    if rec is None:
        return
    rec.record(_current_pid(), ScheduleEntry(
        kind=kind, axis=axis, fingerprint=_fingerprint(payload),
        stack=_cheap_stack(skip=2)))


# ------------------------------------------------------------------ API
def install() -> None:
    """Start recording; idempotent.  Nothing is patched — the note
    sites are compiled into the driver and gate on the recorder."""
    global _RECORDER
    if _RECORDER is None:
        _RECORDER = _Recorder()


def uninstall() -> None:
    """Stop recording and drop the recorder (reports are discarded —
    read :func:`divergences` first)."""
    global _RECORDER
    _RECORDER = None


def maybe_install() -> bool:
    """The config/env gate: install iff ``Config.spmdcheck`` (or
    ``BIGDL_TPU_SPMDCHECK=1``) — the off path allocates NOTHING."""
    from bigdl_tpu.utils.config import get_config
    if not get_config().spmdcheck:
        return False
    install()
    return True


def installed() -> bool:
    return _RECORDER is not None


def reset() -> None:
    """Clear schedules and reports (between independent suites)."""
    rec = _RECORDER
    if rec is not None:
        rec.reset()


def notes_recorded() -> int:
    rec = _RECORDER
    return 0 if rec is None else rec.notes


def schedules() -> Dict[int, List[ScheduleEntry]]:
    rec = _RECORDER
    if rec is None:
        return {}
    with rec.lock:
        return {p: list(s) for p, s in rec.schedules.items()}


def divergences(final: bool = False) -> List[DivergenceReport]:
    """All recorded divergences.  ``final=True`` additionally compares
    schedule LENGTHS (a participant that stopped noting early), which
    only makes sense once the emulated processes have finished."""
    rec = _RECORDER
    if rec is None:
        return []
    if final:
        rec.finalize_locked_lengths()
    with rec.lock:
        return list(rec.divergences)


def report() -> str:
    """Human summary of everything recorded so far."""
    rec = _RECORDER
    if rec is None:
        return "spmdcheck: not installed"
    ds = divergences()
    with rec.lock:
        n_sched = len(rec.schedules)
        n_notes = rec.notes
    lines = [f"spmdcheck: {n_notes} note(s) across {n_sched} "
             f"participant(s), {len(ds)} divergence(s)"]
    lines += [d.render() for d in ds]
    return "\n".join(lines)


def check_clean(final: bool = True) -> None:
    """Raise :class:`SpmdDivergenceError` naming every divergence (the
    conftest session gate)."""
    ds = divergences(final=final)
    if ds:
        raise SpmdDivergenceError(
            f"{len(ds)} collective-schedule divergence(s) detected:\n"
            + "\n".join(d.render() for d in ds))
