"""Mixed precision for TPU.

No reference analog (the reference's only precision trick is the FP16 wire
compression of ``parameters/FP16CompressedTensor.scala``, which ICI makes
unnecessary) — but bf16 compute is how the MXU reaches peak throughput, so
the training stack treats it as first-class: **params, optimizer state and
the update stay f32; forward/backward compute in bf16** (classic mixed
precision; loss and criterion math in f32 for stable softmax/log).

bf16 needs no loss scaling (same exponent range as f32), unlike fp16.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

tmap = jax.tree_util.tree_map


def cast_floating(tree, dtype):
    """Cast only floating leaves of a pytree (ints/bools pass through)."""
    return tmap(
        lambda a: a.astype(dtype)
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
        else a,
        tree)


def mixed_precision_loss_fn(model, criterion, compute_dtype=jnp.bfloat16):
    """Build a loss fn computing fwd/bwd in ``compute_dtype`` with f32
    master params and f32 criterion math.  Grads come back f32 (the
    transpose of the downcast is an upcast)."""

    def loss_fn(params, mstate, x, y, rng):
        p_c = cast_floating(params, compute_dtype)
        x_c = cast_floating(x, compute_dtype)
        out, new_mstate = model.apply(p_c, mstate, x_c, training=True,
                                      rng=rng)
        out = cast_floating(out, jnp.float32)
        new_mstate = cast_floating(new_mstate, jnp.float32)
        return criterion.apply(out, y), new_mstate

    return loss_fn
