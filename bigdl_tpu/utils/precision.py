"""Mixed precision for TPU.

The reference's precision trick is the FP16 wire compression of
``parameters/FP16CompressedTensor.scala``; its TPU analog is the
``grad_wire_dtype`` knob of ``parallel/grad_sync.py`` (BENCH r05 measured
a 0.32 collective-overhead fraction at 8 chips — software wire compression
earns its keep even over ICI).  Additionally, bf16 compute is how the MXU
reaches peak throughput, so the training stack treats it as first-class:
**params, optimizer state and the update stay f32; forward/backward
compute in bf16** (classic mixed precision; loss and criterion math in
f32 for stable softmax/log).

bf16 needs no loss scaling (same exponent range as f32), unlike fp16.

:func:`stochastic_round` is the ONE shared downcast helper — SGD's
reduced-precision momentum state and grad_sync's wire downcast both use
it, so the unbiasedness analysis lives in exactly one place.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

tmap = jax.tree_util.tree_map


def stochastic_round(x, dtype, key):
    """Unbiased f32→bf16 rounding: add uniform random low-16 bits, then
    truncate (bf16 is exactly the top 16 bits of f32).  Plain
    round-to-nearest would systematically drop updates smaller than half
    a bf16 ulp (momentum accumulation, gradient wire downcast); the
    expectation of this rounding is ``x``.  Non-(f32→bf16) pairs fall
    back to round-to-nearest ``astype`` — f16 has 10 mantissa bits, so
    its ulp is 64× finer and RTN bias is negligible at wire precision.
    """
    if x.dtype == dtype:
        return x
    if dtype != jnp.bfloat16 or x.dtype != jnp.float32:
        return x.astype(dtype)
    bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
    noise = jax.random.bits(key, x.shape, jnp.uint32) & jnp.uint32(0xFFFF)
    bits = (bits + noise) & jnp.uint32(0xFFFF0000)
    return jax.lax.bitcast_convert_type(bits, jnp.float32).astype(
        jnp.bfloat16)


def cast_floating(tree, dtype):
    """Cast only floating leaves of a pytree (ints/bools pass through)."""
    return tmap(
        lambda a: a.astype(dtype)
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
        else a,
        tree)


def mixed_precision_loss_fn(model, criterion, compute_dtype=jnp.bfloat16):
    """Build a loss fn computing fwd/bwd in ``compute_dtype`` with f32
    master params and f32 criterion math.  Grads come back f32 (the
    transpose of the downcast is an upcast)."""

    def loss_fn(params, mstate, x, y, rng):
        p_c = cast_floating(params, compute_dtype)
        x_c = cast_floating(x, compute_dtype)
        out, new_mstate = model.apply(p_c, mstate, x_c, training=True,
                                      rng=rng)
        out = cast_floating(out, jnp.float32)
        new_mstate = cast_floating(new_mstate, jnp.float32)
        return criterion.apply(out, y), new_mstate

    return loss_fn
