"""Shared host-side image math + thread-safe RNG.

Single home for the numeric kernels used by BOTH augmentation stacks —
the Sample-based transformers (``dataset/image.py``, reference
``DL/dataset/image/``) and the ImageFeature pipeline
(``transform/vision.py``, reference ``DL/transform/vision/image/``) — so
constants and fixes cannot drift between them.

``ThreadRng`` exists because these transforms run under the multi-worker
batch assembler (``dataset/prefetch.py``): numpy ``Generator`` is not
thread-safe, so each worker thread gets its own child generator spawned
deterministically from the seed.

Per-thread streams alone are NOT run-to-run deterministic under the
multi-worker assembler: which sample lands on which thread is
scheduler-dependent.  So the assembler brackets each transform call in
:func:`sample_key`, and ``ThreadRng`` then derives every draw from
``(seed, instance_salt, sample_index)`` — a pure function of the data
stream, independent of thread scheduling (same counter-based-RNG idea
as ``jax.random.fold_in``).
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import zlib

import numpy as np

_sample_key = threading.local()


@contextlib.contextmanager
def sample_key(key: int):
    """Pin the active per-sample RNG key for the current thread (set by
    the batch assembler around each per-sample transform call)."""
    prev = getattr(_sample_key, "key", None)
    _sample_key.key = key
    try:
        yield
    finally:
        _sample_key.key = prev

# eigen decomposition of ImageNet RGB covariance (AlexNet lighting noise;
# reference ``Lighting.scala`` constants)
LIGHTING_EIGVAL = np.array([0.2175, 0.0188, 0.0045], np.float32)
LIGHTING_EIGVEC = np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]], np.float32)


class ThreadRng:
    """Per-thread numpy Generators, deterministically derived from one
    seed.  Same interface subset as ``np.random.Generator``.

    Under an active :func:`sample_key`, draws come from a generator
    seeded by ``(seed, salt, key)`` instead — scheduling-independent AND
    stable across construction order/processes.  ``salt`` (a string,
    conventionally the owning transform's class name) keeps two
    transforms built with the same seed (e.g. ``RandomCropper`` +
    ``HFlip``, both default seed 0) from replaying identical streams per
    sample; two instances of the SAME class in one pipeline should be
    given distinct seeds."""

    def __init__(self, seed: int = 0, salt: str = ""):
        self._seed = seed
        self._salt = zlib.crc32(salt.encode())
        self._seed_seq = np.random.SeedSequence(seed)
        self._counter = itertools.count()
        self._lock = threading.Lock()
        self._local = threading.local()

    def _gen(self) -> np.random.Generator:
        key = getattr(_sample_key, "key", None)
        if key is not None:
            cached = getattr(self._local, "keyed", None)
            if cached is None or cached[0] != key:
                g = np.random.default_rng(
                    np.random.SeedSequence((self._seed, self._salt, key)))
                self._local.keyed = (key, g)
            return self._local.keyed[1]
        g = getattr(self._local, "gen", None)
        if g is None:
            with self._lock:
                child = self._seed_seq.spawn(1)[0]
            g = np.random.default_rng(child)
            self._local.gen = g
        return g

    def random(self):
        return self._gen().random()

    def uniform(self, low=0.0, high=1.0, size=None):
        return self._gen().uniform(low, high, size)

    def normal(self, loc=0.0, scale=1.0, size=None):
        return self._gen().normal(loc, scale, size)

    def integers(self, low, high=None, size=None):
        return self._gen().integers(low, high, size)

    def permutation(self, n):
        return self._gen().permutation(n)

    def choice(self, a, size=None, p=None):
        return self._gen().choice(a, size=size, p=p)


def lighting_delta(rng, alphastd: float) -> np.ndarray:
    """Per-image RGB offset of AlexNet PCA lighting noise."""
    alpha = np.asarray(rng.normal(0, alphastd, 3), np.float32)
    return (LIGHTING_EIGVEC * alpha * LIGHTING_EIGVAL).sum(axis=1)


def color_jitter(img: np.ndarray, rng, brightness: float, contrast: float,
                 saturation: float) -> np.ndarray:
    """Random brightness/contrast/saturation in random order (reference
    ``ColorJitter.scala`` semantics on float images)."""
    for op in rng.permutation(3):
        if op == 0 and brightness:
            img = img * (1 + rng.uniform(-brightness, brightness))
        elif op == 1 and contrast:
            m = img.mean()
            img = (img - m) * (1 + rng.uniform(-contrast, contrast)) + m
        elif op == 2 and saturation and img.ndim == 3:
            grey = img.mean(-1, keepdims=True)
            img = grey + (img - grey) * (1 + rng.uniform(-saturation,
                                                         saturation))
    return np.asarray(img, np.float32)


def rgb_to_hsv(img: np.ndarray) -> np.ndarray:
    """Vectorized RGB[0,255]→HSV (H in degrees [0,360))."""
    x = img / 255.0
    mx = x.max(-1)
    mn = x.min(-1)
    diff = mx - mn + 1e-12
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    h = np.where(mx == r, (g - b) / diff % 6,
                 np.where(mx == g, (b - r) / diff + 2, (r - g) / diff + 4))
    h = h * 60.0
    s = np.where(mx > 0, diff / (mx + 1e-12), 0.0)
    return np.stack([h, s, mx], -1)


def hsv_to_rgb(hsv: np.ndarray) -> np.ndarray:
    h, s, v = hsv[..., 0] / 60.0, hsv[..., 1], hsv[..., 2]
    c = v * s
    xm = c * (1 - np.abs(h % 2 - 1))
    m = v - c
    z = np.zeros_like(c)
    i = (h.astype(np.int32) % 6)[..., None]
    rgb = np.select(
        [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
        [np.stack([c, xm, z], -1), np.stack([xm, c, z], -1),
         np.stack([z, c, xm], -1), np.stack([z, xm, c], -1),
         np.stack([xm, z, c], -1), np.stack([c, z, xm], -1)])
    return (rgb + m[..., None]) * 255.0


def resize_bilinear(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Pure-numpy bilinear resize, align_corners=False convention."""
    h, w = img.shape[:2]
    if h == out_h and w == out_w:
        return img.copy()
    ys = (np.arange(out_h) + 0.5) * h / out_h - 0.5
    xs = (np.arange(out_w) + 0.5) * w / out_w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None]
    wx = np.clip(xs - x0, 0, 1)[None, :]
    if img.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    a = img[y0][:, x0]
    b = img[y0][:, x1]
    c = img[y1][:, x0]
    d = img[y1][:, x1]
    top = a * (1 - wx) + b * wx
    bot = c * (1 - wx) + d * wx
    return (top * (1 - wy) + bot * wy).astype(img.dtype)
