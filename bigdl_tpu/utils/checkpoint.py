"""Checkpoint save/load — thin back-compat shim over
:mod:`bigdl_tpu.checkpoint`.

The real machinery (atomic commit, CRC32c manifests, async writer,
retention, latest-VALID discovery, schema validation, preemption) lives
in ``bigdl_tpu/checkpoint/``; this module keeps the original
``save_checkpoint`` / ``load_checkpoint`` / ``latest_checkpoint``
signatures and the same safe data-only ``.npz`` wire, so every existing
call site and on-disk checkpoint keeps working.  Files written here are
v3 snapshots (they now carry a ``__manifest__`` member); v2 files load
unchanged.

Reference lineage: ``Optimizer.setCheckpoint(path, trigger)`` saving
``model.<neval>`` via ``File.save`` (``DistriOptimizer.scala:505-531``);
the format is deliberately NOT pickle so loading a checkpoint from an
untrusted directory cannot execute code.
"""

from __future__ import annotations

import os
from typing import Optional

from bigdl_tpu.checkpoint.snapshot import (SnapshotError, decode_tree,
                                           encode_tree, load_snapshot,
                                           to_device, to_host,
                                           write_snapshot)

# historical private names, kept for back-compat importers
_encode = encode_tree
_decode = decode_tree
_to_host = to_host
_to_device = to_device


def save_checkpoint(path: str, params, model_state=None, opt_state=None,
                    driver_state: Optional[dict] = None,
                    neval: Optional[int] = None,
                    overwrite: bool = True) -> str:
    """Write a checkpoint.  With ``neval``, the file is ``model.<neval>``
    inside ``path`` (reference naming); else ``path`` itself.
    ``overwrite=False`` raises ``FileExistsError`` on an existing file —
    the reference's unset ``overWriteCheckpoint``, now a real path."""
    if neval is not None:
        os.makedirs(path, exist_ok=True)
        fname = os.path.join(path, f"model.{neval}")
    else:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        fname = path
    return write_snapshot(fname, params=to_host(params),
                          model_state=to_host(model_state)
                          if model_state is not None else None,
                          opt_state=to_host(opt_state)
                          if opt_state is not None else None,
                          driver_state=driver_state, step=neval,
                          overwrite=overwrite)


def load_checkpoint(path: str):
    """Load a checkpoint written by :func:`save_checkpoint` (or any
    snapshot the new subsystem wrote).  Returns a dict with
    params/model_state/opt_state/driver_state (device arrays).
    Integrity-verified first: a torn or bit-flipped file raises instead
    of deserializing garbage.  ``allow_pickle`` stays False: data-only
    by construction."""
    try:
        blob = load_snapshot(path)
    except SnapshotError as e:
        raise ValueError(str(e)) from e
    return {k: blob[k]
            for k in ("params", "model_state", "opt_state", "driver_state")}


def latest_checkpoint(folder: str) -> Optional[str]:
    """Find the newest VALID ``model.N`` file (reference
    retry-from-latest, ``DistriOptimizer.scala:981-1061``) — corrupt or
    torn snapshots are skipped, never returned."""
    if not os.path.isdir(folder):
        return None
    from bigdl_tpu.checkpoint.manager import CheckpointManager
    return CheckpointManager(folder).latest_valid()
