"""Checkpoint save/load.

Reference: ``Optimizer.setCheckpoint(path, trigger)`` saves
``model.<neval>`` + ``optimMethod-<name>.<neval>`` via ``File.save``
(``DistriOptimizer.scala:505-531``, ``utils/File.scala``); resume =
``Module.load`` + ``OptimMethod.load``; epoch-position state lives in the
OptimMethod state table so training resumes mid-epoch
(``DistriOptimizer.scala:124-134,442-450``).

Here a checkpoint is one file holding (params, model_state, opt_state,
driver_state) as numpy pytrees — device arrays are pulled to host on save
and restored with ``jnp.asarray`` on load.  Local filesystem only (the
reference's HDFS/S3 paths have no analog in this environment).
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


def _to_host(tree):
    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


def _to_device(tree):
    return jax.tree_util.tree_map(
        lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x, tree)


def save_checkpoint(path: str, params, model_state=None, opt_state=None,
                    driver_state: Optional[dict] = None,
                    neval: Optional[int] = None,
                    overwrite: bool = True) -> str:
    """Write a checkpoint.  With ``neval``, the file is ``model.<neval>``
    inside ``path`` (reference naming); else ``path`` itself."""
    if neval is not None:
        os.makedirs(path, exist_ok=True)
        fname = os.path.join(path, f"model.{neval}")
    else:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        fname = path
    if os.path.exists(fname) and not overwrite:
        raise FileExistsError(
            f"{fname} exists (reference: overWriteCheckpoint not set)")
    blob = {
        "version": 1,
        "params": _to_host(params),
        "model_state": _to_host(model_state) if model_state is not None else None,
        "opt_state": _to_host(opt_state) if opt_state is not None else None,
        "driver_state": dict(driver_state) if driver_state else None,
    }
    tmp = fname + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(blob, f, protocol=pickle.HIGHEST_PROTOCOL)
    os.replace(tmp, fname)  # atomic: a crash never leaves a torn checkpoint
    return fname


def load_checkpoint(path: str):
    """Load a checkpoint written by :func:`save_checkpoint`.  Returns a dict
    with params/model_state/opt_state/driver_state (device arrays)."""
    with open(path, "rb") as f:
        blob = pickle.load(f)
    return {
        "params": _to_device(blob["params"]),
        "model_state": _to_device(blob["model_state"])
        if blob["model_state"] is not None else None,
        "opt_state": _to_device(blob["opt_state"])
        if blob["opt_state"] is not None else None,
        "driver_state": blob["driver_state"],
    }


def latest_checkpoint(folder: str) -> Optional[str]:
    """Find the highest-neval ``model.N`` file (reference retry-from-latest,
    ``DistriOptimizer.scala:981-1061``)."""
    if not os.path.isdir(folder):
        return None
    best, best_n = None, -1
    for f in os.listdir(folder):
        if f.startswith("model."):
            try:
                n = int(f.split(".", 1)[1])
            except ValueError:
                continue
            if n > best_n:
                best, best_n = os.path.join(folder, f), n
    return best
