"""Checkpoint save/load.

Reference: ``Optimizer.setCheckpoint(path, trigger)`` saves
``model.<neval>`` + ``optimMethod-<name>.<neval>`` via ``File.save``
(``DistriOptimizer.scala:505-531``, ``utils/File.scala``); resume =
``Module.load`` + ``OptimMethod.load``; epoch-position state lives in the
OptimMethod state table so training resumes mid-epoch
(``DistriOptimizer.scala:124-134,442-450``).

Here a checkpoint is one file holding (params, model_state, opt_state,
driver_state) as numpy pytrees — device arrays are pulled to host on save
and restored with ``jnp.asarray`` on load.  Local filesystem only (the
reference's HDFS/S3 paths have no analog in this environment).

Format: a **data-only** ``.npz`` archive (arrays + a JSON skeleton
describing the pytree structure) — deliberately NOT pickle, so loading a
checkpoint from an untrusted directory cannot execute code (the reference
inherits exactly that risk from Java serialization in ``File.load``; the
retry path auto-loads whatever ``model.N`` file is present, so the format
must be safe by construction).
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


def _to_host(tree):
    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


def _to_device(tree):
    return jax.tree_util.tree_map(
        lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x, tree)


def _encode(tree, arrays: list):
    """Pytree → JSON-able skeleton; array leaves appended to ``arrays``
    and referenced by index."""
    if isinstance(tree, dict):
        return {"t": "dict",
                "k": list(tree.keys()),
                "v": [_encode(tree[k], arrays) for k in tree.keys()]}
    if isinstance(tree, (list, tuple)):
        return {"t": "list" if isinstance(tree, list) else "tuple",
                "v": [_encode(x, arrays) for x in tree]}
    if tree is None or isinstance(tree, (bool, int, float, str)):
        return {"t": "py", "v": tree}
    arr = np.asarray(tree)
    if arr.dtype.name == "bfloat16":
        # npz can't store ml_dtypes without pickle; round-trip via uint16
        arrays.append(arr.view(np.uint16))
        return {"t": "arr", "i": len(arrays) - 1, "d": "bfloat16"}
    arrays.append(arr)
    return {"t": "arr", "i": len(arrays) - 1}


def _decode(node, arrays):
    t = node["t"]
    if t == "dict":
        return {k: _decode(v, arrays) for k, v in zip(node["k"], node["v"])}
    if t == "list":
        return [_decode(v, arrays) for v in node["v"]]
    if t == "tuple":
        return tuple(_decode(v, arrays) for v in node["v"])
    if t == "py":
        return node["v"]
    arr = arrays[f"a{node['i']}"]
    if node.get("d") == "bfloat16":
        import ml_dtypes
        arr = arr.view(ml_dtypes.bfloat16)
    return arr


def save_checkpoint(path: str, params, model_state=None, opt_state=None,
                    driver_state: Optional[dict] = None,
                    neval: Optional[int] = None,
                    overwrite: bool = True) -> str:
    """Write a checkpoint.  With ``neval``, the file is ``model.<neval>``
    inside ``path`` (reference naming); else ``path`` itself."""
    if neval is not None:
        os.makedirs(path, exist_ok=True)
        fname = os.path.join(path, f"model.{neval}")
    else:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        fname = path
    if os.path.exists(fname) and not overwrite:
        raise FileExistsError(
            f"{fname} exists (reference: overWriteCheckpoint not set)")
    arrays: list = []
    skeleton = {
        "version": 2,
        "params": _encode(_to_host(params), arrays),
        "model_state": _encode(_to_host(model_state), arrays)
        if model_state is not None else None,
        "opt_state": _encode(_to_host(opt_state), arrays)
        if opt_state is not None else None,
        "driver_state": dict(driver_state) if driver_state else None,
    }
    tmp = fname + ".tmp"
    with open(tmp, "wb") as f:
        # stream straight to the file: no in-memory copy of the archive
        np.savez(f, __meta__=np.frombuffer(
            json.dumps(skeleton).encode(), dtype=np.uint8),
            **{f"a{i}": a for i, a in enumerate(arrays)})
    os.replace(tmp, fname)  # atomic: a crash never leaves a torn checkpoint
    return fname


def load_checkpoint(path: str):
    """Load a checkpoint written by :func:`save_checkpoint`.  Returns a dict
    with params/model_state/opt_state/driver_state (device arrays).
    ``allow_pickle`` stays False: data-only by construction."""
    try:
        with np.load(path, allow_pickle=False) as z:
            arrays = {k: z[k] for k in z.files}
    except (ValueError, OSError) as e:
        raise ValueError(
            f"{path} is not a bigdl_tpu v2 (npz) checkpoint — legacy or "
            "foreign formats are not auto-loaded (data-only policy); "
            f"original error: {e}") from e
    skeleton = json.loads(bytes(arrays.pop("__meta__")).decode())
    return {
        "params": _to_device(_decode(skeleton["params"], arrays)),
        "model_state": _to_device(_decode(skeleton["model_state"], arrays))
        if skeleton["model_state"] is not None else None,
        "opt_state": _to_device(_decode(skeleton["opt_state"], arrays))
        if skeleton["opt_state"] is not None else None,
        "driver_state": skeleton["driver_state"],
    }


def latest_checkpoint(folder: str) -> Optional[str]:
    """Find the highest-neval ``model.N`` file (reference retry-from-latest,
    ``DistriOptimizer.scala:981-1061``)."""
    if not os.path.isdir(folder):
        return None
    best, best_n = None, -1
    for f in os.listdir(folder):
        if f.startswith("model."):
            try:
                n = int(f.split(".", 1)[1])
            except ValueError:
                continue
            if n > best_n:
                best, best_n = os.path.join(folder, f), n
    return best
