"""Per-layer timing + jax-profiler integration.

Reference: ``AbstractModule.scala:254-287`` — every module self-times
``forwardTime``/``backwardTime``; ``getTimes()`` aggregates per layer and
conv layers break out im2col time.

TPU redesign: under jit the layers FUSE — per-layer wall-time inside the
compiled step doesn't exist as an observable (that's the point of XLA).
So profiling splits into the two things that are actually measurable:

- :func:`get_times` — eager per-layer forward/backward timing of a module
  tree on real inputs (the ``getTimes()`` analog, for finding the slow
  layer before jit);
- :func:`profile_step` — wraps a jit'd step with ``jax.profiler`` traces
  (view in TensorBoard / xprof, where XLA attributes time per fused op);
  ``named_scope`` annotations give HLO ops layer-derived names.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional, Tuple

import jax

from bigdl_tpu.nn.module import Container, Module


class LayerTime:
    __slots__ = ("name", "forward_s", "backward_s")

    def __init__(self, name: str, forward_s: float, backward_s: float):
        self.name = name
        self.forward_s = forward_s
        self.backward_s = backward_s

    def __repr__(self):
        return (f"{self.name}: fwd {self.forward_s * 1e3:.3f}ms "
                f"bwd {self.backward_s * 1e3:.3f}ms")


def _block(x):
    return jax.block_until_ready(x)


def get_times(model: Module, input, *, repeats: int = 3,
              rng: Optional[jax.Array] = None) -> List[LayerTime]:
    """Per-layer eager forward+backward timings (reference
    ``AbstractModule.getTimes``).  Walks a Container tree, timing each
    leaf's apply and its vjp on the activations produced by the previous
    layers.  Returns leaves in execution order plus a TOTAL row."""
    model._ensure_init()
    times: List[LayerTime] = []

    def leaf_time(m: Module, params, state, x) -> Tuple[Any, float, float]:
        # forward
        fwd = lambda p, xx: m.apply(p, state, xx, training=False, rng=rng)[0]
        _block(fwd(params, x))  # compile/warm
        t0 = time.perf_counter()
        for _ in range(repeats):
            y = _block(fwd(params, x))
        f_s = (time.perf_counter() - t0) / repeats
        # backward (vjp wrt params+input, like updateGradInput+accGrad)
        y0, vjp = jax.vjp(fwd, params, x)
        ct = jax.tree_util.tree_map(lambda a: a, y0)
        _block(vjp(ct))
        t0 = time.perf_counter()
        for _ in range(repeats):
            _block(vjp(ct))
        b_s = (time.perf_counter() - t0) / repeats
        return y0, f_s, b_s

    def walk(m: Module, params, state, x, prefix=""):
        label = f"{prefix}{m.name}"
        if isinstance(m, Container) and m.modules:
            from bigdl_tpu.nn.module import Sequential
            if isinstance(m, Sequential):
                out = x
                for i, c in enumerate(m.modules):
                    out = walk(c, params[str(i)], state[str(i)], out,
                               prefix=label + "/")
                return out
            # non-sequential containers: time as one unit
        y, f_s, b_s = leaf_time(m, params, state, x)
        times.append(LayerTime(label, f_s, b_s))
        return y

    t0 = time.perf_counter()
    walk(model, model._params, model._state, input)
    total = time.perf_counter() - t0
    times.append(LayerTime("TOTAL(walk)", total, 0.0))
    return times


def format_times(times: List[LayerTime]) -> str:
    """Pretty table, slowest forward first (reference ``getTimes`` print
    style)."""
    body = sorted((t for t in times if not t.name.startswith("TOTAL")),
                  key=lambda t: -(t.forward_s + t.backward_s))
    width = max((len(t.name) for t in times), default=10)
    lines = [f"{'layer':<{width}}  {'fwd(ms)':>9}  {'bwd(ms)':>9}"]
    for t in body:
        lines.append(f"{t.name:<{width}}  {t.forward_s * 1e3:>9.3f}  "
                     f"{t.backward_s * 1e3:>9.3f}")
    return "\n".join(lines)


def profile_window(seconds: float, log_dir: Optional[str] = None,
                   tracer=None) -> str:
    """Wall-clock ``jax.profiler`` capture: whatever the process is
    doing for the next ``seconds`` lands in the xplane trace (open with
    TensorBoard).  The admin plane's ``/profile?seconds=N`` endpoint is
    a thin shim over this — the on-demand deep dive for a live serving
    process, where there is no single ``step_fn`` to hand to
    :func:`profile_step`.  Returns the log dir.

    Same divergence note as :func:`profile_step`: this is the opt-in,
    off-the-hot-path tool — never the always-on path (the always-on
    surfaces are the tracer and /metrics, which never sync)."""
    import tempfile
    import time as _time
    from contextlib import nullcontext

    if log_dir is None:
        log_dir = tempfile.mkdtemp(prefix="bigdl_tpu_profile_")
    span = (tracer.span("jax_profiler_window", cat="profiler",
                        log_dir=log_dir, seconds=seconds)
            if tracer is not None else nullcontext())
    with span:
        with jax.profiler.trace(log_dir):
            _time.sleep(float(seconds))
    return log_dir


def profile_step(step_fn, *args, log_dir: str, steps: int = 3,
                 tracer=None):
    """Run ``step_fn(*args)`` under the jax profiler (xplane trace in
    ``log_dir``; open with TensorBoard).  The jit'd step's per-op times
    carry the layer names annotated by jit tracing.

    ``tracer``: optional :class:`bigdl_tpu.telemetry.Tracer` bridge —
    the profiled region and each profiled step also land as spans in
    the telemetry Chrome trace, so the step timeline links to the
    xplane capture (the span's ``log_dir`` arg is the pointer).  The
    deliberate divergence from the driver's inertness rule: this
    function exists to sync (``block_until_ready`` per step) — it is
    the opt-in, off-the-hot-path deep dive, never the always-on path.
    """
    from contextlib import nullcontext

    def span(name, **kw):
        return tracer.span(name, cat="profiler", **kw) if tracer \
            else nullcontext()

    # warmup/compile outside the trace
    _block(step_fn(*args))
    with span("jax_profiler_trace", log_dir=log_dir, steps=steps):
        with jax.profiler.trace(log_dir):
            out = None
            for i in range(steps):
                with span("profiled_step", i=i):
                    out = _block(step_fn(*args))
    return out
