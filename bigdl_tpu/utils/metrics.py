"""Metrics — named training-loop phase accumulators.

Reference: ``DL/optim/Metrics.scala:31`` — named counters backed by Spark
accumulators, printed by ``summary()``; the built-in profiling of the
training loop.

Since the telemetry PR this is a thin veneer over
:class:`bigdl_tpu.telemetry.registry.MetricRegistry` — the driver's
phase accumulators, the serving engine's counters, and the runtime
watchdogs share ONE metrics implementation (each named accumulator is a
registry :class:`~bigdl_tpu.telemetry.registry.Histogram`, so the same
data also carries p50/p95/p99 for free).  The public surface —
``add``/``time``/``value``/``mean``/``summary``/``reset`` — and the
``summary()`` string format are unchanged (back-compat gated in
``tests/test_telemetry.py``).
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Optional

from bigdl_tpu.telemetry.registry import MetricRegistry


class Metrics:
    def __init__(self, registry: Optional[MetricRegistry] = None):
        # shared registry (the driver hands its telemetry registry in)
        # or a private one — either way the veneer below is identical
        self.registry = registry if registry is not None else MetricRegistry()
        self._owned: set = set()  # names this instance created

    def add(self, name: str, value: float) -> None:
        self._owned.add(name)
        self.registry.histogram(name).observe(value)

    @contextmanager
    def time(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def _hist(self, name: str):
        m = self.registry.get(name)
        from bigdl_tpu.telemetry.registry import Histogram
        return m if isinstance(m, Histogram) else None

    def value(self, name: str) -> float:
        h = self._hist(name)
        return h.sum if h is not None else 0.0

    def mean(self, name: str) -> float:
        h = self._hist(name)
        return h.mean if h is not None else 0.0

    def summary(self) -> str:
        """(reference ``Metrics.summary`` printed at
        ``DistriOptimizer.scala:393``)"""
        from bigdl_tpu.telemetry.registry import Histogram
        rows = [(name, m) for name in self.registry.names()
                for m in [self.registry.get(name)]
                if isinstance(m, Histogram)]
        parts = [f"{k}: sum={h.sum:.4f} mean={h.mean:.4f} n={h.count}"
                 for k, h in rows]
        return "\n".join(parts)

    def snapshot(self) -> dict:
        """JSON-able registry snapshot (superset of ``summary()``)."""
        return self.registry.snapshot()

    def reset(self) -> None:
        """Clear THIS instance's accumulators only.  The registry may be
        shared with the telemetry watchdogs (gauges + cached counter
        objects); a blanket ``registry.reset()`` would orphan those —
        their later increments would update objects no snapshot can see
        — so only the names this Metrics created are discarded."""
        for name in self._owned:
            self.registry.discard(name)
        self._owned.clear()
