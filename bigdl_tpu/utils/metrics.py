"""Metrics — named training-loop phase counters.

Reference: ``DL/optim/Metrics.scala:31`` — named counters backed by Spark
accumulators, printed by ``summary()``; the built-in profiling of the
training loop.  Here: plain host-side aggregation (one process per host;
cross-host aggregation would ride jax collectives if ever needed).
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager


class Metrics:
    def __init__(self):
        self._sums = defaultdict(float)
        self._counts = defaultdict(int)

    def add(self, name: str, value: float) -> None:
        self._sums[name] += value
        self._counts[name] += 1

    @contextmanager
    def time(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def value(self, name: str) -> float:
        return self._sums[name]

    def mean(self, name: str) -> float:
        c = self._counts[name]
        return self._sums[name] / c if c else 0.0

    def summary(self) -> str:
        """(reference ``Metrics.summary`` printed at
        ``DistriOptimizer.scala:393``)"""
        parts = [f"{k}: sum={self._sums[k]:.4f} mean={self.mean(k):.4f} "
                 f"n={self._counts[k]}" for k in sorted(self._sums)]
        return "\n".join(parts)

    def reset(self) -> None:
        self._sums.clear()
        self._counts.clear()
