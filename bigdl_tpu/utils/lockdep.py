"""lockdep — a TSan-lite lock-order sanitizer for the threaded plane.

The static half of the deadlock story is graftlint GL202: per-file
lexical lock nesting plus one level of call expansion.  What it cannot
see is the DYNAMIC order — lock A of one module taken under lock B of
another, through callbacks, supervisors and executor threads.  This
module validates the static model at runtime, the way kernel lockdep
does: run the real test suites with every lock instrumented and let
the acquisition-order graph prove (or break) the ordering claims.

How it works
------------

``install()`` replaces ``threading.Lock`` / ``threading.RLock`` with
factories returning thin proxies around the real primitives.  A
default ``threading.Condition()`` (and everything built on it —
``Event``, ``Semaphore``, ``queue.Queue``, ``concurrent.futures``)
rides the patched factories automatically, and ``Condition(lock)``
aliasing shares the wrapped lock object, so the graph sees through the
``ReplicaSet._wake`` shape for free.

Each proxy is keyed by its ALLOCATION SITE (``file:line`` of the
constructor call) — the lockdep notion of a lock *class*: every
``RequestBatcher._cond`` across every test shares one node, so an
ordering observed between two instances generalizes the way the static
rules assume.  Per thread, a stack of held locks is kept; acquiring B
while holding A adds the edge ``A → B`` (with both acquisition stacks)
to one global graph.  At acquire time, if a path ``B →* A`` already
exists, a :class:`CycleReport` is recorded naming BOTH sides: the
current stack (holding A, acquiring B) and the recorded stacks of
every edge on the conflicting path.  The graph is kept acyclic (the
offending edge is not inserted), so one bad ordering reports once per
site pair instead of cascading.

Same-site pairs (two instances of the same lock class nested) are NOT
edges — with site-keyed classes the direction is ambiguous, and the
same-object re-take is GL202's static domain (a non-reentrant re-take
deadlocks immediately anyway).

A wall-clock **held-too-long** check rides the same accounting: a hold
longer than ``Config.lockdep_hold_ms`` (default 200 ms; 0 disables) is
recorded with its acquire stack — GL206 blocking-under-lock, observed
rather than inferred.  Slow holds are advisory (warmup compiles
legitimately serialize under the warm lock); cycles are the errors.

Inertness contract (house discipline, the ``FaultInjector`` empty-plan
shape): with ``Config.lockdep`` off nothing is allocated and nothing
is patched — ``threading.Lock is _ORIG_LOCK`` stays bitwise true,
``proxies_allocated() == 0``, and the driver/serving paths are
byte-identical (gated in ``tests/test_lockdep.py``).

Opt-in: ``BIGDL_TPU_LOCKDEP=1 python -m pytest tests/ ...`` — the
conftest installs the sanitizer before any product lock exists and
fails the session if any cycle was recorded, so every tier-1 run
doubles as a deadlock hunt.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

import _thread

#: the real factories, captured at import — the off-state identity the
#: inertness gate asserts on
_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock

_THIS_FILE = os.path.abspath(__file__)
# frames from these files are plumbing, not the caller's story
_SKIP_FILES = (_THIS_FILE, threading.__file__)

_MAX_REPORTS = 100     # bound the report lists; a broken suite floods
_STACK_DEPTH = 10

FrameTup = Tuple[str, int, str]  # (filename, lineno, funcname)


def _cheap_stack(skip: int = 2) -> List[FrameTup]:
    """A few frames of (file, line, func) without touching linecache —
    cheap enough to capture on EVERY acquire (formatting resolves
    source lines lazily, only when a report renders)."""
    out: List[FrameTup] = []
    try:
        f = sys._getframe(skip)
    except ValueError:
        return out
    while f is not None and len(out) < _STACK_DEPTH:
        fn = f.f_code.co_filename
        if fn not in _SKIP_FILES:
            out.append((fn, f.f_lineno, f.f_code.co_name))
        f = f.f_back
    return out


def _fmt_stack(frames: List[FrameTup], indent: str = "    ") -> str:
    if not frames:
        return indent + "<no frames>"
    return "\n".join(f"{indent}{os.path.relpath(fn) if fn.startswith(os.sep) else fn}"
                     f":{ln} in {fun}" for fn, ln, fun in frames)


def _site(skip: int = 2) -> str:
    """Allocation site of a lock: first frame outside lockdep/threading
    — the lock's *class* in the kernel-lockdep sense."""
    try:
        f = sys._getframe(skip)
    except ValueError:
        return "<unknown>"
    while f is not None:
        fn = f.f_code.co_filename
        if fn != _THIS_FILE and fn != _SKIP_FILES[1]:
            rel = os.path.relpath(fn) if fn.startswith(os.sep) else fn
            return f"{rel}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


@dataclasses.dataclass
class _Edge:
    """Observed order: ``a`` held while ``b`` acquired."""

    a: str
    b: str
    thread: str
    a_stack: List[FrameTup]
    b_stack: List[FrameTup]
    count: int = 1


@dataclasses.dataclass
class CycleReport:
    """One detected lock-order inversion, with both sides' stacks."""

    thread: str
    holding: str          # site of the lock currently held
    acquiring: str        # site of the lock being acquired
    path: List[str]       # acquiring ->* holding through recorded edges
    this_stack: List[FrameTup]
    conflict_edges: List[_Edge]

    def render(self) -> str:
        lines = [
            "lockdep: lock-order cycle",
            f"  thread {self.thread!r} acquiring {self.acquiring} "
            f"while holding {self.holding}:",
            _fmt_stack(self.this_stack),
            f"  but the order {' -> '.join(self.path)} was already "
            "established:",
        ]
        for e in self.conflict_edges:
            lines.append(f"  edge {e.a} -> {e.b} "
                         f"(thread {e.thread!r}, seen {e.count}x):")
            lines.append("   held at:")
            lines.append(_fmt_stack(e.a_stack, indent="      "))
            lines.append("   acquired at:")
            lines.append(_fmt_stack(e.b_stack, indent="      "))
        return "\n".join(lines)


@dataclasses.dataclass
class SlowHold:
    """A lock held past the wall-clock threshold (advisory)."""

    site: str
    held_s: float
    thread: str
    acquire_stack: List[FrameTup]

    def render(self) -> str:
        return (f"lockdep: {self.site} held {self.held_s * 1e3:.1f} ms "
                f"on thread {self.thread!r}\n"
                f"{_fmt_stack(self.acquire_stack)}")


class LockOrderError(RuntimeError):
    """Raised by :func:`check_clean` when cycles were recorded."""


class _State:
    """The one global graph.  Its own lock is a RAW ``_thread`` lock so
    the sanitizer never traces itself."""

    def __init__(self):
        self.lock = _thread.allocate_lock()
        self.installed = False
        self.hold_threshold_s = 0.0
        self.edges: Dict[Tuple[str, str], _Edge] = {}
        self.adj: Dict[str, Set[str]] = {}
        self.cycles: List[CycleReport] = []
        self.slow_holds: List[SlowHold] = []
        self.reported_pairs: Set[frozenset] = set()
        self.proxies = 0
        self.acquires = 0

    def reset_graph(self):
        self.edges.clear()
        self.adj.clear()
        self.cycles.clear()
        self.slow_holds.clear()
        self.reported_pairs.clear()


_STATE = _State()

_tls = threading.local()


class _Held:
    __slots__ = ("obj", "site", "t0", "frames")

    def __init__(self, obj, site, t0, frames):
        self.obj = obj
        self.site = site
        self.t0 = t0
        self.frames = frames


def _held_list() -> list:
    lst = getattr(_tls, "held", None)
    if lst is None:
        lst = _tls.held = []
    return lst


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """BFS over the order graph; path [src, ..., dst] or None.
    Caller holds the state lock."""
    if src == dst:
        return [src]
    prev: Dict[str, str] = {}
    frontier = [src]
    seen = {src}
    while frontier:
        nxt: List[str] = []
        for u in frontier:
            for v in _STATE.adj.get(u, ()):  # deterministic enough
                if v in seen:
                    continue
                prev[v] = u
                if v == dst:
                    path = [v]
                    while path[-1] != src:
                        path.append(prev[path[-1]])
                    return list(reversed(path))
                seen.add(v)
                nxt.append(v)
        frontier = nxt
    return None


def _note_acquire(proxy) -> None:
    held = _held_list()
    frames = _cheap_stack(skip=3)
    entry = _Held(proxy, proxy._ld_site, time.monotonic(), frames)
    first_hold = all(h.obj is not proxy for h in held)
    if held and first_hold:
        tname = threading.current_thread().name
        with _STATE.lock:
            _STATE.acquires += 1
            for h in held:
                if h.site == proxy._ld_site:
                    continue  # same lock class: direction ambiguous
                _add_edge_locked(h, entry, tname)
    else:
        with _STATE.lock:
            _STATE.acquires += 1
    held.append(entry)


def _add_edge_locked(a: _Held, b: _Held, thread_name: str) -> None:
    key = (a.site, b.site)
    edge = _STATE.edges.get(key)
    if edge is not None:
        edge.count += 1
        return
    # new order a -> b: does b already reach a?  Then two threads can
    # interleave the two orders and deadlock.
    path = _find_path(b.site, a.site)
    if path is not None:
        pair = frozenset((a.site, b.site))
        if pair not in _STATE.reported_pairs:
            _STATE.reported_pairs.add(pair)
            conflict = [_STATE.edges[(path[i], path[i + 1])]
                        for i in range(len(path) - 1)
                        if (path[i], path[i + 1]) in _STATE.edges]
            if len(_STATE.cycles) < _MAX_REPORTS:
                _STATE.cycles.append(CycleReport(
                    thread=thread_name, holding=a.site,
                    acquiring=b.site, path=path,
                    this_stack=b.frames, conflict_edges=conflict))
        return  # keep the graph acyclic: report once, don't cascade
    _STATE.edges[key] = _Edge(a.site, b.site, thread_name,
                              a.frames, b.frames)
    _STATE.adj.setdefault(a.site, set()).add(b.site)


def _note_release(proxy) -> None:
    held = _held_list()
    for i in range(len(held) - 1, -1, -1):
        if held[i].obj is proxy:
            entry = held.pop(i)
            thr = _STATE.hold_threshold_s
            if thr > 0:
                dt = time.monotonic() - entry.t0
                if dt > thr:
                    with _STATE.lock:
                        if len(_STATE.slow_holds) < _MAX_REPORTS:
                            _STATE.slow_holds.append(SlowHold(
                                entry.site, dt,
                                threading.current_thread().name,
                                entry.frames))
            return
    # release of a lock this thread never tracked (e.g. acquired
    # before install, or handed across threads) — nothing to pop


class _LockProxy:
    """Wraps a non-reentrant lock.  Deliberately does NOT define
    ``_release_save``/``_acquire_restore``/``_is_owned`` so a
    ``Condition`` built on it falls back to ``self.release()`` /
    ``self.acquire()`` — every wait/notify round-trip flows through the
    proxy and the accounting stays truthful."""

    __slots__ = ("_ld_inner", "_ld_site")

    def __init__(self, inner, site):
        self._ld_inner = inner
        self._ld_site = site

    def acquire(self, blocking=True, timeout=-1):
        got = self._ld_inner.acquire(blocking, timeout)
        if got:
            _note_acquire(self)
        return got

    def release(self):
        self._ld_inner.release()
        _note_release(self)

    def locked(self):
        return self._ld_inner.locked()

    def __getattr__(self, name):
        # delegate everything else (e.g. ``_at_fork_reinit``, which
        # concurrent.futures registers as an at-fork hook) to the real
        # lock.  A plain Lock has no ``_release_save`` family, so a
        # Condition built on a _LockProxy still falls back to the
        # proxy's acquire/release — accounting stays truthful.
        return getattr(object.__getattribute__(self, "_ld_inner"), name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<lockdep Lock {self._ld_site} of {self._ld_inner!r}>"


class _RLockProxy(_LockProxy):
    """Wraps an RLock.  Forwards the Condition fast-path hooks to the
    inner lock WITH held-stack save/restore, because the default
    ``Condition._release_save`` (one ``release()``) is wrong for a
    recursively-held RLock."""

    __slots__ = ()

    def _release_save(self):
        held = _held_list()
        mine = [h for h in held if h.obj is self]
        for h in mine:
            held.remove(h)
        return (self._ld_inner._release_save(), mine)

    def _acquire_restore(self, state):
        inner_state, mine = state
        self._ld_inner._acquire_restore(inner_state)
        _held_list().extend(mine)

    def _is_owned(self):
        return self._ld_inner._is_owned()


def _lock_factory():
    with _STATE.lock:
        _STATE.proxies += 1
    return _LockProxy(_ORIG_LOCK(), _site())


def _rlock_factory(*args, **kwargs):
    with _STATE.lock:
        _STATE.proxies += 1
    return _RLockProxy(_ORIG_RLOCK(*args, **kwargs), _site())


# ------------------------------------------------------------------ API
def install(hold_ms: Optional[float] = None) -> None:
    """Patch the lock factories; idempotent.  Call BEFORE the threaded
    modules construct their locks (locks created earlier stay raw and
    invisible — harmless, just unobserved)."""
    if _STATE.installed:
        return
    if hold_ms is None:
        from bigdl_tpu.utils.config import get_config
        hold_ms = float(get_config().lockdep_hold_ms)
    _STATE.hold_threshold_s = max(0.0, hold_ms) / 1e3
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    _STATE.installed = True


def uninstall() -> None:
    """Restore the stdlib factories.  Existing proxies keep working
    (they wrap real locks); the graph and reports are kept for
    inspection until :func:`reset`."""
    if not _STATE.installed:
        return
    threading.Lock = _ORIG_LOCK
    threading.RLock = _ORIG_RLOCK
    _STATE.installed = False


def maybe_install() -> bool:
    """The config/env gate: install iff ``Config.lockdep`` (or
    ``BIGDL_TPU_LOCKDEP=1``) — the off path allocates NOTHING."""
    from bigdl_tpu.utils.config import get_config
    if not get_config().lockdep:
        return False
    install()
    return True


def installed() -> bool:
    return _STATE.installed


def reset() -> None:
    """Clear the graph and all reports (between independent suites)."""
    with _STATE.lock:
        _STATE.reset_graph()


def cycles() -> List[CycleReport]:
    with _STATE.lock:
        return list(_STATE.cycles)


def slow_holds() -> List[SlowHold]:
    with _STATE.lock:
        return list(_STATE.slow_holds)


def proxies_allocated() -> int:
    return _STATE.proxies


def acquire_count() -> int:
    return _STATE.acquires


def graph_edges() -> Dict[Tuple[str, str], int]:
    """(a, b) -> times observed; dashboards/tests."""
    with _STATE.lock:
        return {k: e.count for k, e in _STATE.edges.items()}


def report() -> str:
    """Human summary of everything recorded so far."""
    cs, sh = cycles(), slow_holds()
    lines = [f"lockdep: {len(_STATE.edges)} edge(s), {len(cs)} "
             f"cycle(s), {len(sh)} slow hold(s), "
             f"{_STATE.proxies} lock(s) instrumented"]
    for c in cs:
        lines.append(c.render())
    for s in sh:
        lines.append(s.render())
    return "\n".join(lines)


def check_clean() -> None:
    """Raise :class:`LockOrderError` naming every cycle (the conftest
    session gate).  Slow holds never fail — they are advisory."""
    cs = cycles()
    if cs:
        raise LockOrderError(
            f"{len(cs)} lock-order cycle(s) detected:\n"
            + "\n".join(c.render() for c in cs))
