"""Unified typed configuration.

Reference: the ``bigdl.*`` Java system properties scattered across
``Engine.scala:45-47,190-235`` / ``AllReduceParameter.scala:36-47``
(``bigdl.engineType``, ``bigdl.coreNumber``, ``bigdl.failure.retryTimes``,
``bigdl.check.singleton``, …) + the required ``spark-bigdl.conf`` overlay
+ per-example scopt parsers.  SURVEY §5 flags the lack of one typed
config object as a thing for the new build to centralize — this is it.

Resolution order (later wins): dataclass defaults → ``BIGDL_TPU_*``
environment variables → explicit ``configure(**kw)`` calls.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

_ENV_PREFIX = "BIGDL_TPU_"


@dataclasses.dataclass
class Config:
    # failure handling (reference bigdl.failure.retryTimes, default 5)
    failure_retry_times: int = 5
    # data pipeline
    prefetch_batches: int = 2          # MTSampleToMiniBatch default queue
    loader_workers: int = 4            # per-host preprocessing threads
    # numerics
    compute_dtype: str = "float32"     # "bfloat16" flips matmul precision
    matmul_precision: str = "default"  # jax "default"|"high"|"highest"
    # logging / observability
    log_every_n_iterations: int = 1
    summary_flush_secs: float = 10.0
    # mesh defaults (dryrun/tests override explicitly)
    mesh_data: int = -1
    mesh_model: int = 1
    mesh_seq: int = 1
    mesh_pipe: int = 1

    @staticmethod
    def _coerce(value: str, typ):
        if typ is bool:
            return value.lower() in ("1", "true", "yes", "on")
        return typ(value)

    @classmethod
    def from_env(cls) -> "Config":
        cfg = cls()
        for f in dataclasses.fields(cls):
            env = _ENV_PREFIX + f.name.upper()
            if env in os.environ:
                setattr(cfg, f.name,
                        cls._coerce(os.environ[env], type(getattr(cfg,
                                                                  f.name))))
        return cfg


_config: Optional[Config] = None


def get_config() -> Config:
    global _config
    if _config is None:
        _config = Config.from_env()
    return _config


def configure(**kw) -> Config:
    """Override config fields programmatically (highest precedence)."""
    cfg = get_config()
    for k, v in kw.items():
        if not hasattr(cfg, k):
            raise AttributeError(f"unknown config field {k!r}; fields: "
                                 f"{[f.name for f in dataclasses.fields(Config)]}")
        setattr(cfg, k, v)
    return cfg


def reset_config() -> None:
    """Drop overrides; next get_config() re-reads the environment."""
    global _config
    _config = None
