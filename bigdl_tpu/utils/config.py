"""Unified typed configuration.

Reference: the ``bigdl.*`` Java system properties scattered across
``Engine.scala:45-47,190-235`` / ``AllReduceParameter.scala:36-47``
(``bigdl.engineType``, ``bigdl.coreNumber``, ``bigdl.failure.retryTimes``,
``bigdl.check.singleton``, …) + the required ``spark-bigdl.conf`` overlay
+ per-example scopt parsers.  SURVEY §5 flags the lack of one typed
config object as a thing for the new build to centralize — this is it.

Resolution order (later wins): dataclass defaults → per-workload
``tuned_configs.json`` entries (autotuner output, consumed through
``utils/tuned.resolve_default`` — only where a call site supplies a
workload tag) → ``BIGDL_TPU_*`` environment variables → explicit
``configure(**kw)`` calls.  The config records WHERE each field's value
came from (``Config.source``: "default" | "env" | "explicit") so the
tuned layer can slot in below env without guessing — a field that still
carries its dataclass default is the only place a tuned value may apply.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

_ENV_PREFIX = "BIGDL_TPU_"


@dataclasses.dataclass
class Config:
    # failure handling (reference bigdl.failure.retryTimes, default 5)
    failure_retry_times: int = 5
    # data pipeline
    prefetch_batches: int = 2          # MTSampleToMiniBatch default queue
    loader_workers: int = 4            # per-host preprocessing threads
    # driver loop: K consecutive train steps fused into ONE jit dispatch
    # (lax.scan over stacked microbatches).  1 = classic step-per-dispatch;
    # raise for dispatch-bound workloads (small-step LSTMs, sparse recs).
    # Blocks are auto-flushed at epoch/trigger boundaries, so semantics
    # are K-invariant; see README "stepping & input pipeline".
    steps_per_dispatch: int = 1
    # gradient sync (parallel/grad_sync.py — the AllReduceParameter
    # analog): grads are flattened into buckets of at most
    # grad_bucket_bytes (f32 accounting) so per-bucket reduce-scatters
    # overlap backward compute, and the wire dtype controls the
    # on-the-wire compression (reference FP16CompressedTensor; BENCH
    # r05 measured collective_overhead_fraction=0.32 at 8 chips, so
    # compression matters even over ICI).  "f32" | "bf16" | "f16";
    # the bf16 wire downcasts with unbiased stochastic rounding; f16
    # uses round-to-nearest (64x finer ulp) with SATURATION at ±65504
    # — gradient spikes clamp instead of going inf on the wire (see
    # utils/precision.stochastic_round, parallel/grad_sync.wire_cast).
    # The optimizer update always accumulates in f32 master slices.
    grad_bucket_bytes: int = 4 << 20
    grad_wire_dtype: str = "f32"
    # checkpointing (bigdl_tpu/checkpoint — async fault-tolerant
    # snapshots): retention keeps the newest checkpoint_keep_last
    # snapshots plus (with checkpoint_keep_every=N) every N-th step
    # forever; checkpoint_async=True commits snapshots on a bounded
    # background writer thread so the driver pays only the device→host
    # capture (checkpoint/stall_fraction gauge proves it) — False
    # restores the synchronous inline write (debugging / tiny runs).
    checkpoint_keep_last: int = 5
    checkpoint_keep_every: int = 0
    checkpoint_async: bool = True
    # serving (bigdl_tpu/serving — dynamic-batching inference engine):
    # a coalesced batch dispatches when it reaches serving_max_batch_size
    # rows or serving_batch_timeout_ms after its first request; the
    # request queue holds at most serving_queue_capacity requests before
    # submit() raises ServiceOverloaded (explicit backpressure).  The
    # timeout is the latency/occupancy dial: ~1-5 ms suits interactive
    # traffic, tens of ms squeezes occupancy out of sparse traffic, 0
    # is adaptive mode (dispatch whatever is already queued — the
    # previous dispatch's latency is the coalescing window; the
    # PredictionService shim runs this way).
    serving_max_batch_size: int = 32
    serving_batch_timeout_ms: float = 2.0
    serving_queue_capacity: int = 256
    # resilience (bigdl_tpu/resilience — designed-in failure handling):
    # serving_deadline_ms is the default per-request deadline a
    # ReplicaSet stamps on submissions (0 = none; the deadline travels
    # with the request — expired work is refused before the device
    # call, and the supervisor fails work stuck on a dead replica so
    # the router can retry it elsewhere).  numeric_guard is the
    # training driver's non-finite loss/grad policy: "off" (default —
    # provably inert) | "skip" (jnp.where-gate the update on device,
    # count, continue) | "rollback" (restore the latest VALID
    # checkpoint, bounded by failure_retry_times) | "abort" (fail
    # loudly at the exact iteration).  fault_plan names a deterministic
    # fault-injection plan (grammar in resilience/faults.py; "" = no
    # injector object even exists — the bitwise-inert state) seeded by
    # fault_seed, so every degradation path is gated by a test instead
    # of hand-checked during incidents.
    serving_deadline_ms: float = 0.0
    numeric_guard: str = "off"
    fault_plan: str = ""
    fault_seed: int = 0
    # custom-kernel selection (bigdl_tpu/ops/pallas_*.py — the fused
    # LSTM cell and COO embedding-bag):  "xla" = always the baseline
    # lowering; "pallas" = fused kernel wherever its measured
    # supported() gate passes (silent XLA fallback otherwise; interpret
    # mode off-TPU); "auto" = pallas-if-supported on a TPU backend, xla
    # elsewhere (interpret-mode kernels are correctness-emulation, not
    # a speedup, so auto never engages them on CPU hosts).  Resolved
    # through Engine.kernel_impl() so the autotuner (ROADMAP item 3)
    # inherits kernel choice as one more measured knob.  Env:
    # BIGDL_TPU_KERNEL_IMPL.  Per-layer ``impl=`` constructor args win.
    kernel_impl: str = "auto"
    # int8 quantized inference (nn/quantized.py over
    # ops/pallas_int8_gemm.py).  int8_activation_mode is the default
    # per-layer mode quantize(model) stamps on converted layers:
    # "weight_only" (int8 weights, f32/bf16 activations, f32 MXU
    # accumulation — no activation quantization error, the serving
    # default) or "dynamic" (BigQuant-style on-the-fly int8
    # activations, int32 accumulate).  int8_block_rows is the GEMM
    # row-block size, 0 = auto (<=128 whole-batch, else 128-row
    # blocks) — an autotuner knob like kernel_impl.  Env:
    # BIGDL_TPU_INT8_ACTIVATION_MODE / BIGDL_TPU_INT8_BLOCK_ROWS.
    int8_activation_mode: str = "weight_only"
    int8_block_rows: int = 0
    # activation-memory policy default (Optimizer.set_activation_memory
    # overrides per run): "none" | "dots" | "full" | "bf16" |
    # "bf16+dots" | "bf16+full" — remat / bf16 activation storage for
    # HBM-bound workloads (see optim/optimizer.py for the semantics).
    # One more autotuner knob: tuned_configs.json can set it per
    # workload.  Env: BIGDL_TPU_ACTIVATION_MEMORY.
    activation_memory: str = "none"
    # serving row-bucket set: "" or "pow2" = power-of-two buckets up to
    # serving_max_batch_size (serving.row_buckets — the default);
    # "top" = one bucket at max_batch_size (max executable sharing, max
    # padding); "8,16,32" = explicit ascending list whose top must be
    # >= serving_max_batch_size.  Parsed by serving.parse_row_buckets.
    serving_row_buckets: str = ""
    # numerics
    compute_dtype: str = "float32"     # "bfloat16" flips matmul precision
    matmul_precision: str = "default"  # jax "default"|"high"|"highest"
    # NaN sanitizer (SURVEY §5: lean on jax.debug_nans instead of the
    # reference's per-layer checks): opt in via BIGDL_TPU_DEBUG_NANS=1
    # or configure(debug_nans=True), then call apply_debug_config()
    debug_nans: bool = False
    # logging / observability
    log_every_n_iterations: int = 1
    summary_flush_secs: float = 10.0
    # telemetry (bigdl_tpu/telemetry): step-timeline tracer + metric
    # registry + runtime watchdogs wired through the training driver.
    # Provably inert — enabling adds no dispatch and no host sync; the
    # loss sequence is bitwise identical (tests/test_telemetry.py).
    # BIGDL_TPU_TELEMETRY=1 is the short env alias for
    # BIGDL_TPU_TELEMETRY_ENABLED=1.  telemetry_trace_path: write the
    # Chrome-trace JSON there when training ends ("" = keep in memory;
    # summarize with `python -m tools.trace_report <path>`).
    telemetry_enabled: bool = False
    telemetry_trace_path: str = ""
    telemetry_trace_capacity: int = 200_000  # retained spans, then drop+count
    # admin plane (telemetry/admin.py): a stdlib http.server thread
    # serving /metrics (Prometheus text), /healthz (JSON), /trace
    # (Chrome-trace dump), /flight (flight-recorder ring) and
    # /profile?seconds=N (on-demand jax.profiler capture).  0 (default)
    # = OFF — no socket, no thread, provably inert.  Binds 127.0.0.1
    # only (no auth on this surface — see README "Admin plane").
    # Env: BIGDL_TPU_ADMIN_PORT.
    admin_port: int = 0
    # request-scoped tracing (telemetry/context.py): mint a
    # RequestContext (trace_id, tenant, hop history, Chrome flow
    # events) per serving submit and propagate it through coalescing,
    # dispatch and ReplicaSet failover.  Off (default) = no context
    # object is ever allocated — the serving path is byte-identical.
    # Env: BIGDL_TPU_REQUEST_TRACING.
    request_tracing: bool = False
    # flight recorder (telemetry/flight.py): append-and-flush JSONL
    # stream of structured events (health transitions, breaker trips,
    # failovers, sheds, rollbacks, recompiles, checkpoint commits,
    # preemption) with trace_id correlation — survives SIGKILL, joined
    # with a trace by `python -m tools.obs_report`.  "" (default) =
    # OFF — nothing allocated, nothing opened.  Env:
    # BIGDL_TPU_FLIGHT_RECORDER_PATH / _CAPACITY.
    flight_recorder_path: str = ""
    flight_recorder_capacity: int = 4096  # in-memory ring bound
    # wire frontend (frontend/server.py): the port
    # FrontendServer(port=None) binds the HTTP serving endpoint on.
    # 0 (default) = the frontend refuses config-driven construction —
    # unlike the admin plane nothing auto-starts either way; the wire
    # surface only exists when a FrontendServer is explicitly built.
    # Binds 127.0.0.1 only (X-Tenant is a tag, not a credential).
    # Env: BIGDL_TPU_FRONTEND_PORT.
    frontend_port: int = 0
    # wire-frontend auth (frontend/server.py): when set, every request
    # must carry `Authorization: Bearer <token>` or is refused 401 —
    # and a FrontendServer REFUSES to bind a non-loopback host unless
    # a token is configured (X-Tenant stays a QoS tag, never a
    # credential).  "" (default) keeps the historical loopback-open
    # behavior.  Env: BIGDL_TPU_FRONTEND_AUTH_TOKEN.
    frontend_auth_token: str = ""
    # wire-frontend connection core (frontend/server.py +
    # frontend/eventloop.py): "eventloop" (default) serves every
    # connection from a small set of selector loop threads with
    # incremental HTTP/1.1 parsing and callback-driven writes — no
    # thread per connection; "threaded" keeps the PR-14
    # thread-per-connection stdlib core.  Both speak the identical
    # wire surface (one shared test suite).  Env: BIGDL_TPU_FRONTEND_CORE.
    frontend_core: str = "eventloop"
    # event-loop shard count: number of loop threads, each binding its
    # own SO_REUSEPORT listener on the same port so the kernel spreads
    # accepts (multi-core fan-in).  Platforms without SO_REUSEPORT fall
    # back to one shared listener round-robined across the loops.
    # Env: BIGDL_TPU_FRONTEND_SHARDS.
    frontend_shards: int = 1
    # hard cap on concurrently-open wire connections (both cores):
    # past it, fresh accepts are refused with a bare close before any
    # parser/thread exists — counted frontend/conns_refused.  0 =
    # uncapped.  Env: BIGDL_TPU_FRONTEND_MAX_CONNECTIONS.
    frontend_max_connections: int = 10000
    # idle keep-alive reap timeout (seconds): connections with no
    # in-flight exchange and no traffic for this long are closed
    # (frontend/conns_reaped), so idle floods cannot starve active
    # clients of fds.  0 = never reap.  Env:
    # BIGDL_TPU_FRONTEND_IDLE_TIMEOUT_S.
    frontend_idle_timeout_s: float = 120.0
    # pin each event-loop shard thread to one CPU
    # (os.sched_setaffinity, loop i → available cpu i mod count) so
    # shards stop migrating across cores under load (cache/IRQ
    # locality).  Silently inert on platforms without sched_setaffinity
    # (macOS, Windows).  Env: BIGDL_TPU_FRONTEND_PIN_CPUS.
    frontend_pin_cpus: bool = False
    # lockdep (utils/lockdep.py): TSan-lite lock-order sanitizer for
    # the threaded host plane.  False (default) = provably inert — no
    # wrapper object is ever allocated, threading.Lock/RLock stay the
    # stdlib factories (the FaultInjector empty-plan discipline).
    # True (or BIGDL_TPU_LOCKDEP=1) wraps lock CONSTRUCTION so every
    # tier-1 run doubles as a deadlock hunt: per-thread held-lock
    # stacks accrete a global acquisition-order graph and a cycle is
    # reported AT ACQUIRE TIME with both conflicting stacks.
    # lockdep_hold_ms additionally records holds longer than the
    # threshold (blocking-under-lock, GL206's runtime twin); 0
    # disables the wall-clock check.
    lockdep: bool = False
    lockdep_hold_ms: float = 200.0
    # spmdcheck (utils/spmdcheck.py): collective-schedule sanitizer for
    # multi-host SPMD divergence — the runtime twin of graftlint
    # GL401-GL404.  False (default) = provably inert: the driver's
    # note sites read one module global and return; nothing is
    # allocated.  True (or BIGDL_TPU_SPMDCHECK=1) records the sequence
    # of (op kind, axis, payload treedef/dtype) each emulated process
    # issues and the first cross-process mismatch is reported with
    # both schedules + both stacks.
    spmdcheck: bool = False
    # mesh defaults (dryrun/tests override explicitly)
    mesh_data: int = -1
    mesh_model: int = 1
    mesh_seq: int = 1
    mesh_pipe: int = 1
    # provenance: field name -> "env" | "explicit" for every field that
    # was overridden; absent = still the dataclass default (the one
    # state where a tuned_configs.json value may apply — see
    # utils/tuned.resolve_default).  Private: not an env-settable knob.
    _sources: dict = dataclasses.field(default_factory=dict, repr=False,
                                       compare=False)

    def source(self, name: str) -> str:
        """Where ``name``'s current value came from: ``"default"`` |
        ``"env"`` | ``"explicit"``."""
        return self._sources.get(name, "default")

    @staticmethod
    def _coerce(value: str, typ):
        if typ is bool:
            return value.lower() in ("1", "true", "yes", "on")
        return typ(value)

    @classmethod
    def from_env(cls) -> "Config":
        cfg = cls()
        for f in dataclasses.fields(cls):
            if f.name.startswith("_"):
                continue  # bookkeeping, not a knob
            env = _ENV_PREFIX + f.name.upper()
            if env in os.environ:
                setattr(cfg, f.name,
                        cls._coerce(os.environ[env], type(getattr(cfg,
                                                                  f.name))))
                cfg._sources[f.name] = "env"
        # short alias: BIGDL_TPU_TELEMETRY=1 ⇔ BIGDL_TPU_TELEMETRY_ENABLED=1
        # (the explicit long form wins when both are set)
        alias = _ENV_PREFIX + "TELEMETRY"
        if alias in os.environ and \
                _ENV_PREFIX + "TELEMETRY_ENABLED" not in os.environ:
            cfg.telemetry_enabled = cls._coerce(os.environ[alias], bool)
            cfg._sources["telemetry_enabled"] = "env"
        return cfg


_config: Optional[Config] = None


def get_config() -> Config:
    global _config
    if _config is None:
        _config = Config.from_env()
        if _config.debug_nans:
            # BIGDL_TPU_DEBUG_NANS=1 alone must be enough: push the
            # toggle into jax as soon as the config is first read
            apply_debug_config(_config)
    return _config


def configure(**kw) -> Config:
    """Override config fields programmatically (highest precedence)."""
    cfg = get_config()
    for k, v in kw.items():
        if k.startswith("_") or not hasattr(cfg, k):
            names = [f.name for f in dataclasses.fields(Config)
                     if not f.name.startswith("_")]
            raise AttributeError(
                f"unknown config field {k!r}; fields: {names}")
        setattr(cfg, k, v)
        cfg._sources[k] = "explicit"
    if "debug_nans" in kw:
        apply_debug_config(cfg)
    return cfg


def reset_config() -> None:
    """Drop overrides; next get_config() re-reads the environment."""
    global _config
    _config = None


def apply_debug_config(cfg: Optional[Config] = None) -> None:
    """Push debug toggles into the jax runtime (the ``debug_nans``
    sanitizer makes every jit'd computation fail LOUDLY at the first
    NaN instead of training garbage — the reference's NaN checks are
    scattered per-layer asserts)."""
    import jax
    cfg = cfg or get_config()
    jax.config.update("jax_debug_nans", bool(cfg.debug_nans))
