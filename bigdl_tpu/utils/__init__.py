"""bigdl_tpu.utils — checkpointing, metrics, TensorBoard summaries."""

from bigdl_tpu.utils.checkpoint import (
    save_checkpoint, load_checkpoint, latest_checkpoint,
)
from bigdl_tpu.utils.metrics import Metrics
from bigdl_tpu.utils.summary import (
    FileWriter, TrainSummary, ValidationSummary, crc32c,
)
