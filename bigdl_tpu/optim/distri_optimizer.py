"""DistriOptimizer — synchronous data-parallel training over a TPU mesh.

Reference: ``DL/optim/DistriOptimizer.scala`` (1,106 LoC) +
``DL/parameters/AllReduceParameter.scala``: each Spark iteration is 2 jobs —
(A) per-executor forward/backward with BlockManager weight fetch and FP16
gradient put, (B) per-node aggregation of its 1/N gradient slice, optimizer
update of its 1/N weight slice, weight re-publish.  That is literally a
reduce-scatter + all-gather with a sharded optimizer update (ZeRO-1).

TPU redesign: ONE jit'd SPMD step-block over a ``jax.sharding.Mesh``,
driven by the shared fused/pipelined loop in ``Optimizer._train_driver``
(K-step ``lax.scan`` fusion + double-buffered device prefetch — the
analog of BigDL 2.0 hiding the per-iteration Spark job dispatch cost).

- The global batch rides the ``data`` mesh axis (the analog of one data
  partition per executor); a staged K-step block is sharded
  ``P(None, "data")`` — step axis replicated, batch axis sharded.
- With ``parameter_sharding=True`` (default, pure DP), gradient sync is
  the EXPLICIT bucketed protocol of ``parallel/grad_sync.py`` — the
  TPU-native ``AllReduceParameter`` + ``FP16CompressedTensor``:
  size-capped grad buckets reduce-scatter over ``data`` in a
  configurable wire dtype (``Config.grad_wire_dtype``: f32|bf16|f16,
  unbiased stochastic-rounded downcast), each chip runs the optimizer
  on its owned f32 master slice (ZeRO-1, ``AllReduceParameter.scala:
  73-76``; arXiv:2004.13336), and updated params all-gather back in the
  wire dtype — all inside ``shard_map`` within the fused K-step jit so
  XLA's latency-hiding scheduler overlaps per-bucket collectives with
  backward compute.  An early revision left gradient aggregation to
  GSPMD's implicit f32 all-reduce on the assumption that ICI makes
  software compression unnecessary — BENCH r05 measured that
  assumption WRONG: ``collective_overhead_fraction = 0.32`` at 8 chips
  (531 ms/step ablated vs 782 ms with collectives), so the wire format
  earns its keep exactly as it did for the reference over Ethernet.
- ``parameter_sharding=False`` (or ``grad_sync=False``) keeps the
  implicit path: params replicated, XLA inserts the f32 gradient
  AllReduce — the baseline the grad_sync numerics tests gate against.
- Straggler gradient-dropping (``DistriOptimizer.scala:398-425``) is
  intentionally absent: SPMD collectives are lock-step; XLA's synchronous
  model replaces it (documented divergence, SURVEY.md §7 stage 4).
- Failure retry-from-checkpoint (``:981-1061``) wraps the driver loop.

Multi-host: each process feeds its local shard of the global batch via
``jax.make_array_from_process_local_data``; ``jax.distributed.initialize``
is the analog of Spark executor registration.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.checkpoint import build_schema
from bigdl_tpu.engine import Engine
from bigdl_tpu.optim.optimizer import (Optimizer, select_step,
                                       step_finite)
from bigdl_tpu.parallel import grad_sync
from bigdl_tpu.resilience.membership import (ClusterMembership,
                                             MembershipChanged)
from bigdl_tpu.resilience.numeric import NonFiniteStepError
from bigdl_tpu.utils import spmdcheck

logger = logging.getLogger("bigdl_tpu.optim")

tmap = jax.tree_util.tree_map


def batch_axis_spec(leaf, mesh: Mesh, axis: str = "data") -> P:
    """Shard dim 0 over the mesh axis when divisible, else replicate —
    used for ZeRO-1-style optimizer-state sharding."""
    n = mesh.shape[axis]
    if leaf.ndim > 0 and leaf.shape[0] % n == 0 and leaf.shape[0] >= n:
        return P(axis)
    return P()


class DistriOptimizer(Optimizer):
    """Data-parallel SPMD trainer.  See module docstring."""

    def __init__(self, model, dataset, criterion, batch_size=None,
                 mesh: Optional[Mesh] = None,
                 parameter_sharding: bool = True,
                 param_specs=None,
                 grad_sync: Optional[bool] = None,
                 grad_wire_dtype: Optional[str] = None,
                 grad_bucket_bytes: Optional[int] = None):
        """``param_specs``: optional pytree of PartitionSpec matching the
        model params — enables tensor parallelism (build with
        ``parallel.tensor_parallel.build_param_specs``).  ``None`` keeps
        params replicated (pure DP).

        ``grad_sync``: force the explicit bucketed gradient-sync path
        (parallel/grad_sync.py) on/off; ``None`` (default) enables it
        whenever ``parameter_sharding`` is on and the run is pure DP
        (no ``param_specs``, non-``data`` mesh axes all size 1).
        ``grad_wire_dtype`` ("f32"|"bf16"|"f16") and
        ``grad_bucket_bytes`` override the ``Config`` defaults."""
        super().__init__(model, dataset, criterion, batch_size)
        self.mesh = mesh or Engine.get_mesh()
        self.parameter_sharding = parameter_sharding
        self.param_specs = param_specs
        self.grad_sync = grad_sync
        self.grad_wire_dtype = grad_wire_dtype
        self.grad_bucket_bytes = grad_bucket_bytes
        self.failure_retry_times = Engine._state.failure_retry_times
        self._param_sh = None
        self._ostate_sh = None
        self._block_sh = None  # P(None, "data"): step axis × batch axis
        self._n_dev = 1
        self._use_grad_sync = False
        self._gs_plan = None
        self._gs_wire = None

    # -------------------------------------------------------- shardings
    def _shardings(self, params, ostate):
        mesh = self.mesh
        repl = NamedSharding(mesh, P())
        param_sh = tmap(lambda _: repl, params) if self.param_specs is None \
            else tmap(lambda sp: NamedSharding(mesh, sp), self.param_specs,
                      is_leaf=lambda x: isinstance(x, P))
        if self._use_grad_sync or (self.parameter_sharding
                                   and self.param_specs is None):
            # ZeRO-1: shard optimizer state over the data axis (only when
            # params are replicated — TP already shards the state with
            # them).  grad_sync state (flat master/optimizer buckets,
            # padded to the data-axis size) lands on the same rule: each
            # chip holds exactly the slice it owns.
            ostate_sh = tmap(
                lambda l: NamedSharding(mesh, batch_axis_spec(l, mesh)),
                ostate)
        elif self.param_specs is not None:
            # optimizer-state subtrees (velocity/m/v/...) are tmaps over the
            # params, so a subtree with the params' structure inherits the
            # param shardings leaf-for-leaf; anything else is replicated
            pstruct = jax.tree_util.tree_structure(params)
            ostate_sh = {}
            for key, sub in ostate.items():
                if jax.tree_util.tree_structure(sub) == pstruct:
                    ostate_sh[key] = param_sh
                else:
                    ostate_sh[key] = tmap(lambda _: repl, sub)
        else:
            ostate_sh = tmap(lambda _: repl, ostate)
        return repl, param_sh, ostate_sh

    # ---------------------------------------------- explicit grad sync
    def _resolve_grad_sync(self, mesh: Mesh, params) -> None:
        """Decide whether this run takes the explicit grad_sync path and
        build its static bucket plan.  Pure-DP only: tensor parallelism
        shards the params themselves, so the flat-bucket ZeRO-1 protocol
        does not apply (those runs keep the constraint-driven path)."""
        pure_dp = (self.param_specs is None and "data" in mesh.axis_names
                   and all(mesh.shape[a] == 1 for a in mesh.axis_names
                           if a != "data"))
        if self.grad_sync is None:
            use = self.parameter_sharding and pure_dp
        else:
            use = bool(self.grad_sync)
            if use and not pure_dp:
                raise ValueError(
                    "grad_sync=True requires a pure data-parallel run "
                    "(no param_specs, non-data mesh axes of size 1); "
                    f"mesh={dict(zip(mesh.axis_names, mesh.devices.shape))}")
        self._use_grad_sync = use
        if not use:
            return
        if self.grad_clip is not None and self.grad_clip_spec is None:
            raise ValueError(
                "grad_sync clips owned slices of the reduced gradient and "
                "needs a structured clip spec — use "
                "set_gradient_clipping_by_value/_by_l2_norm (or "
                "grad_sync=False for a custom grad_clip callable)")
        # constructor args win; otherwise the default chain
        # (configure()/env > tuned_configs.json for this run's workload
        # tag > dataclass default — utils/tuned.resolve_default)
        from bigdl_tpu.utils.tuned import resolve_default
        wl = self.workload or Engine.workload()
        wire = self.grad_wire_dtype if self.grad_wire_dtype is not None \
            else resolve_default("grad_wire_dtype", workload=wl)[0]
        bucket = self.grad_bucket_bytes \
            if self.grad_bucket_bytes is not None \
            else resolve_default("grad_bucket_bytes", workload=wl)[0]
        self._gs_wire = grad_sync.resolve_wire_dtype(wire)
        self._gs_plan = grad_sync.build_plan(
            params, mesh.shape["data"], int(bucket))

    def _check_resumed_opt_state(self, ostate) -> None:
        """Fail LOUDLY when a retry/resume checkpoint's opt_state was
        written by the other sync path — the formats differ (grad_sync:
        ``{"master": [flat buckets], "opt": ...}`` vs per-leaf pytree)
        and letting the mismatch reach jit tracing produces an opaque
        KeyError/structure error instead of this message."""
        is_gs = (isinstance(ostate, dict) and set(ostate) ==
                 {"master", "opt"} and isinstance(ostate.get("master"),
                                                  list))
        if self._use_grad_sync and not is_gs:
            raise ValueError(
                "resumed opt_state is not grad_sync-format (expected "
                "{'master': [...], 'opt': ...}) — the checkpoint was "
                "written by a non-grad_sync run; resume with the "
                "matching setting (grad_sync=False / "
                "parameter_sharding=False) or clear the checkpoint dir")
        if not self._use_grad_sync and is_gs:
            raise ValueError(
                "resumed opt_state is grad_sync-format but this run has "
                "grad_sync disabled — re-enable it or clear the "
                "checkpoint dir")
        if is_gs:
            want = [(s,) for s in self._gs_plan.bucket_sizes]
            got = [tuple(m.shape) for m in ostate["master"]]
            if want != got:
                raise ValueError(
                    f"resumed grad_sync masters {got} do not match this "
                    f"run's bucket plan {want} — mesh size or "
                    f"grad_bucket_bytes changed since the checkpoint "
                    f"was written")

    # ------------------------------------------------- elastic membership
    def set_elastic(self,
                    membership: Optional[ClusterMembership] = None
                    ) -> "DistriOptimizer":
        """Arm elastic training: membership epochs over THIS mesh's
        device pool.  A ``resize``/``host_loss``/``device_loss`` fault
        clause (or an explicit ``request_resize`` on the returned
        membership) opens a new epoch; the driver detects it at the
        replay boundary, snapshots, and ``optimize()`` resumes on the
        new roster with the ZeRO-1 state re-sharded.  Built ONCE per
        optimizer — epochs stay monotonic across every shrink/regrow
        cycle of one run (4 → 2 → 4 ends at epoch 3, not 1)."""
        if self._membership is None:
            self._membership = membership if membership is not None \
                else ClusterMembership(
                    tuple(self.mesh.devices.flat),
                    registry=self.metrics.registry,
                    recorder=getattr(self, "_flight", None))
        return self

    def _arm_membership_from_plan(self, faults) -> None:
        if faults is None or not faults.has_membership_kinds():
            return
        self.set_elastic()

    # replay-boundary: runs before any block is staged on this epoch
    def _adopt_membership_roster(self) -> None:
        """An epoch opened BETWEEN runs (operator ``request_resize``
        before ``optimize()``): nothing is in flight, so adopt the
        roster up front — no snapshot restore, no steps lost.  Must run
        BEFORE any placement/sharding derives from ``self.mesh``;
        without it the run would dispatch on the stale mesh while the
        membership ledger says otherwise."""
        m = self._membership
        if m is None:
            return
        cur = m.current()
        # replicated-by: membership-epoch-ledger
        if tuple(cur.devices) == tuple(self.mesh.devices.flat):
            return
        # spmdcheck: roster adoption re-keys every later collective (new
        # mesh) — all processes must adopt the same epoch here
        spmdcheck.note("membership_adopt", axis=f"epoch{cur.epoch}")
        self.mesh = Mesh(np.asarray(cur.devices), ("data",))
        if self.model._params is not None:
            # params may still be committed to the old roster's devices
            # — pull them to host so this run's dispatch commits them
            # to the adopted mesh (the restore path gets host arrays
            # from the snapshot for free)
            self.model._params = jax.device_get(self.model._params)
            self.model._state = jax.device_get(self.model._state)
        logger.warning(
            "membership epoch %d (%s): adopting world=%d roster "
            "at run start", cur.epoch, cur.reason, cur.world)
        self._flight_event("resize_adopt", epoch=cur.epoch,
                           world=cur.world, reason=cur.reason)

    # replay-boundary: the driver replayed/abandoned the in-flight block
    # before raising MembershipChanged — restore lands on a block edge
    def _resume_after_resize(self, e: MembershipChanged) -> None:
        """Rebuild the mesh on the new epoch's roster and restore the
        latest valid snapshot so the next ``_optimize_impl`` resumes on
        it (the grad_sync state is re-sharded there, where the new
        bucket plan exists).  Called from ``optimize()``'s
        :class:`MembershipChanged` handler — a resize is a measured
        event, not a failure, so it never burns the retry budget."""
        ep = e.epoch
        self.mesh = Mesh(np.asarray(ep.devices), ("data",))
        logger.warning(
            "membership epoch %d (%s, graceful=%s): resuming on "
            "world=%d", ep.epoch, ep.reason, ep.graceful, ep.world)
        mgr = self._checkpoint_manager()
        mgr.wait()
        ckpt = mgr.latest_valid()
        if ckpt is None:
            raise RuntimeError(
                f"membership epoch {ep.epoch} ({ep.reason}) but no "
                f"valid snapshot under {self.checkpoint_path} to "
                f"resume from — elastic training needs one committed "
                f"snapshot before an abrupt device loss") from e
        mgr.restore_into(self, ckpt, verified=True)
        lost = max(0, e.detected_neval - int(self.state["neval"]))
        self.metrics.registry.counter(
            "resilience/steps_lost_to_resize").inc(lost)
        self._flight_event("resize_restore", epoch=ep.epoch,
                           world=ep.world, reason=ep.reason,
                           steps_lost=lost,
                           iteration=int(self.state["neval"]))
        # downtime clock keeps running until the resumed driver stages
        # its first block (observed there as resilience/resize_downtime_s)
        self._resize_t0 = e.t0

    def _maybe_reshard_resumed(self, ostate):
        """Elastic resume of a grad_sync state written at a DIFFERENT
        world size: strip the old per-shard padding, re-pad each flat
        bucket to this run's plan (``grad_sync.reshard_state`` —
        padding is zeros and elementwise optimizers map zeros to zeros,
        so the re-bucketing is information-preserving).  Non-elastic
        runs fall through to ``_check_resumed_opt_state``'s hard
        refusal unchanged."""
        if self._membership is None or not self._use_grad_sync:
            return ostate
        is_gs = (isinstance(ostate, dict) and set(ostate) ==
                 {"master", "opt"} and isinstance(ostate.get("master"),
                                                  list))
        if not is_gs:
            return ostate
        want = [(s,) for s in self._gs_plan.bucket_sizes]
        got = [tuple(np.shape(m)) for m in ostate["master"]]
        # plan shapes derive from config + model; the restored state is
        # the same snapshot on every host
        # replicated-by: snapshot-schema
        if want == got:
            return ostate
        logger.info(
            "elastic resume: re-sharding grad_sync state %s -> %s "
            "(n_shard=%d)", got, want, self._gs_plan.n_shard)
        return grad_sync.reshard_state(self._gs_plan, ostate)

    def _build_block_fn(self, grad_fn, k: int):
        """grad_sync runs: ONE donated jit whose body is a ``shard_map``
        over the mesh — per-chip forward/backward on the local batch
        shard, then the explicit reduce-scatter → owned-slice update →
        all-gather of ``parallel/grad_sync.py`` (K-step ``lax.scan``
        INSIDE the shard_map, so per-bucket collectives of step j can
        overlap compute of step j+1 under XLA's latency-hiding
        scheduler).  Non-grad_sync runs keep the base GSPMD block."""
        if not self._use_grad_sync:
            return super()._build_block_fn(grad_fn, k)
        from functools import partial

        mesh, axis = self.mesh, "data"
        n = mesh.shape[axis]
        plan, wire = self._gs_plan, self._gs_wire
        optim = self.optim_method
        clip_spec = self.grad_clip_spec if self.grad_clip is not None \
            else None

        guard = self._resolved_numeric_guard()

        def one_step(params, mstate, ostate, x, y, lr, step, rng):
            (loss, new_mstate), grads = grad_fn(params, mstate, x, y, rng)
            if guard != "off":
                # mesh-global finite verdict: every chip must agree so
                # the jnp.where gate below selects identically on every
                # owned ZeRO-1 slice (pmin of the local flags — one
                # poisoned chip vetoes the whole step)
                finite = jax.lax.pmin(
                    step_finite(loss, grads).astype(jnp.int32),
                    axis).astype(bool)
            new_params, new_ostate = grad_sync.sync_and_update(
                plan, grads, ostate, optim, lr, step,
                wire_dtype=wire, axis_name=axis, clip_spec=clip_spec)
            synced_mstate = grad_sync.sync_model_state(new_mstate, axis)
            loss_out = jax.lax.pmean(loss, axis)
            if guard == "off":
                return new_params, synced_mstate, new_ostate, loss_out
            if guard == "skip":
                return (select_step(finite, new_params, params),
                        select_step(finite, synced_mstate, mstate),
                        select_step(finite, new_ostate, ostate),
                        (loss_out, finite))
            return new_params, synced_mstate, new_ostate, \
                (loss_out, finite)

        body = self._block_body(one_step, k)

        def ostate_spec(l):
            # flat bucket leaves (masters + mirrored optimizer state)
            # shard over `data` — the SAME ownership predicate the host
            # placement uses (batch_axis_spec), so in_specs can never
            # disagree with where _optimize_impl put the state
            return batch_axis_spec(l, mesh, axis)

        @partial(jax.jit, donate_argnums=(0, 1, 2))
        def block_fn(params, mstate, ostate, xs, ys, lrs, steps, rngs):
            for leaf in jax.tree_util.tree_leaves(xs):
                if leaf.shape[1] % n:
                    raise ValueError(
                        f"grad_sync needs the batch divisible by the "
                        f"data axis: got {leaf.shape[1]} rows over "
                        f"{n} chips — pad/drop the remainder or pass "
                        f"grad_sync=False")
            os_spec = tmap(ostate_spec, ostate)
            in_specs = (tmap(lambda _: P(), params),
                        tmap(lambda _: P(), mstate),
                        os_spec,
                        tmap(lambda _: P(None, axis), xs),
                        None if ys is None
                        else tmap(lambda _: P(None, axis), ys),
                        P(), P(), P())
            out_specs = (tmap(lambda _: P(), params),
                         tmap(lambda _: P(), mstate),
                         os_spec, P())
            fn = grad_sync.shard_map_compat(body, mesh, in_specs,
                                            out_specs)
            return fn(params, mstate, ostate, xs, ys, lrs, steps, rngs)

        return block_fn

    def _make_global(self, arr: np.ndarray, sharding: NamedSharding):
        """Per-host local shard → global device array (multi-host safe)."""
        # spmdcheck: assembling a global array is a rendezvous — noted
        # even on the single-process path so emulated schedules match
        # what a real pod would run
        spmdcheck.note("make_global", payload=arr)
        if jax.process_count() == 1:
            return jax.device_put(arr, sharding)
        return jax.make_array_from_process_local_data(sharding, arr)

    # ----------------------------------------------- train-driver hooks
    def _place_train_block(self, xs, ys):
        """Staged (K, local_batch, ...) host trees → global arrays with
        the step axis replicated and the batch axis sharded over `data`
        (the per-microbatch analog of one data partition per executor).
        The ``device_put`` underneath is asynchronous — the driver
        stages block i+1 while block i computes, so this is where the
        double-buffered host→HBM transfer actually happens."""
        place = lambda a: self._make_global(np.asarray(a), self._block_sh)
        xs = tmap(place, xs)
        ys = None if ys is None else tmap(place, ys)
        return xs, ys

    def _records_scale(self) -> int:
        # batch.size() is the PER-HOST local batch; under multi-host the
        # assembled global array is process_count× larger, and epoch
        # accounting compares against the GLOBAL dataset.size()
        return jax.process_count()

    def _constrain_step_outputs(self, params, ostate):
        # pin output layouts so the pattern stays reduce-scatter+gather
        # (ZeRO-1) / TP-sharded across every step of the scanned block
        params = jax.lax.with_sharding_constraint(params, self._param_sh)
        ostate = jax.lax.with_sharding_constraint(ostate, self._ostate_sh)
        return params, ostate

    def _log_train_iteration(self, lr: float) -> None:
        s = self.state
        logger.info(
            "epoch %d iter %d loss %.4f lr %.5g throughput %.1f rec/s "
            "(%.1f rec/s/dev)",
            s["epoch"], s["neval"], s["loss"], lr, s["throughput"],
            s["throughput"] / self._n_dev)

    def _log_parameter_histograms(self, params) -> None:
        # trigger-gated per-parameter histograms (reference
        # DistriOptimizer.scala:541-573 "Parameters" summary)
        ptrig = getattr(self.train_summary, "trigger_for",
                        lambda _n: None)("Parameters")
        if ptrig is not None and ptrig(self.state):
            flat = jax.tree_util.tree_flatten_with_path(params)[0]
            for path, leaf in flat:
                tag = "Parameters/" + "/".join(
                    str(getattr(k, "key", k)) for k in path)
                self.train_summary.add_histogram(
                    tag, np.asarray(leaf), self.state["neval"])

    # ------------------------------------------- multi-host-safe val/ckpt
    # Eval placement hooks: batches go through the same ``_make_global``
    # path as training inputs, so validation is correct on real multi-host
    # jobs (the base hooks feed host-local arrays into a jit against
    # global params — single-process only).
    #
    # Multi-host contract: every process must see the SAME number of
    # validation batches and identical batch shapes (the framework's own
    # per-host dataset sharding guarantees this); the hooks issue one
    # collective per batch, so unequal counts would deadlock.
    def _place_eval_input(self, x):
        n_data = self.mesh.shape["data"]
        data_sh = NamedSharding(self.mesh, P("data"))
        repl = NamedSharding(self.mesh, P())

        def place(a):
            a = np.asarray(a)
            # the dataset layer shards per host from the same global
            # source: batch shapes (and the ragged tail, if any) are
            # identical on every process, so the fallback choice —
            # and the collective in _make_global — stays uniform
            # replicated-by: global-batch-layout
            if a.shape[0] % n_data == 0:
                return self._make_global(a, data_sh)
            # ragged last eval batch: single-process can fall back to a
            # replicated (unsharded but correct) forward; multi-host has
            # no safe fallback — per-process rows differ, so a
            # "replicated" global array would be undefined
            if jax.process_count() > 1:
                raise ValueError(
                    f"multi-host validation batch of {a.shape[0]} rows is "
                    f"not divisible by the data axis ({n_data}); use a "
                    "divisible validation batch size (drop_remainder or "
                    "pad)")
            return jax.device_put(a, repl)

        return tmap(place, x)

    def _place_eval_target(self, t):
        return tmap(lambda a: self._host_global(np.asarray(a)), t)

    def _gather_eval_output(self, out):
        return self._host_global(out)

    def _host_global(self, arr):
        """Globally-sharded device array → host array every process sees
        fully (process_allgather under multi-host)."""
        # spmdcheck: noted before the single-process early return so the
        # emulated schedule records the allgather a real pod would issue
        spmdcheck.note("allgather", payload=arr)
        if jax.process_count() == 1:
            return arr
        from jax.experimental import multihost_utils
        return multihost_utils.process_allgather(arr, tiled=True)

    def _do_checkpoint(self, params, mstate, ostate,
                       sync: bool = False) -> None:
        if jax.process_count() > 1:
            # sharded leaves are not fully addressable on one process:
            # allgather to host, then only process 0 writes
            params = tmap(self._host_global, params)
            mstate = tmap(self._host_global, mstate)
            ostate = tmap(self._host_global, ostate)
            if jax.process_index() != 0:
                # record the step on EVERY process: the preemption
                # branch's already-saved dedup reads last_saved_step,
                # and a process-0-only update would make that predicate
                # diverge — non-zero hosts would enter the allgather
                # above while process 0 skips it (collective deadlock)
                # replicates: checkpoint-step-mirror
                self._checkpoint_manager().last_saved_step = \
                    int(self.state["neval"])
                return
        super()._do_checkpoint(params, mstate, ostate, sync=sync)

    def _checkpoint_schema(self, params) -> dict:
        if not self._use_grad_sync:
            return super()._checkpoint_schema(params)
        return build_schema(
            params, grad_sync=True,
            bucket_sizes=self._gs_plan.bucket_sizes,
            wire_dtype=jnp.dtype(self._gs_wire).name,
            n_shard=self._gs_plan.n_shard,
            optim_method=type(self.optim_method).__name__,
            bucket_content=grad_sync.bucket_content_sizes(self._gs_plan))

    # ------------------------------------------------------------- train
    # replay-boundary: restores happen only between _optimize_impl runs,
    # after the failed run's blocks are torn down
    def optimize(self):
        attempts = 0
        while True:
            try:
                return self._optimize_impl()
            except MembershipChanged as e:
                # elastic resize: the driver already replayed/abandoned
                # the in-flight block and secured a boundary snapshot —
                # rebuild the mesh on the new roster, restore, and go
                # again.  A measured event, not a failure: the retry
                # budget is untouched.
                self._resume_after_resize(e)
            except NonFiniteStepError as e:
                # numeric_guard: "abort" must surface at the exact
                # iteration — the one failure class the reference-style
                # retry loop below must NOT swallow; "rollback" runs
                # the shared restore-latest-valid recovery.  The budget
                # is read LIVE from config (like LocalOptimizer and the
                # dispatch-retry loop), not from the Engine-init
                # snapshot the legacy loop below still uses.
                attempts += 1
                from bigdl_tpu.utils.config import get_config
                self._rollback_nonfinite(
                    e, attempts, get_config().failure_retry_times)
            except Exception:
                # reference retry-from-checkpoint loop
                # (DistriOptimizer.scala:981-1061), now on the manager:
                # discovery returns the latest VALID snapshot (a torn/
                # corrupt file from the crash window is skipped, never
                # loaded) and restore_into brings back the FULL state —
                # params, model state, optimizer state (Adam moments /
                # grad_sync masters; schema-validated in the next
                # _optimize_impl), driver counters, RNG seed and the
                # dataset shuffle position, so the retried run replays
                # the interrupted one exactly
                attempts += 1
                if attempts > self.failure_retry_times \
                        or not self.checkpoint_path:
                    raise
                mgr = self._checkpoint_manager()
                ckpt = mgr.latest_valid()
                if ckpt is None:
                    raise
                logger.exception(
                    "training failed; retry %d/%d from %s",
                    attempts, self.failure_retry_times, ckpt)
                mgr.restore_into(self, ckpt, verified=True)

    def _optimize_impl(self):
        self._adopt_membership_roster()
        mesh = self.mesh
        self._n_dev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
        rng = jax.random.PRNGKey(self.seed)
        rng, init_rng = jax.random.split(rng)
        if self.model._params is not None:
            # copy: the block fn donates its inputs; without this the
            # caller-owned model arrays would be deleted by donation
            # (device_put below is a no-op for already-placed arrays)
            params = jax.tree_util.tree_map(jnp.array, self.model._params)
            mstate = jax.tree_util.tree_map(jnp.array, self.model._state)
        else:
            params, mstate = self.model.init(init_rng)
        self._resolve_grad_sync(mesh, params)
        self._validate_resume_schema(params)
        if self._resume_opt_state is not None:
            ostate = self._resume_opt_state
            self._resume_opt_state = None
            ostate = self._maybe_reshard_resumed(ostate)
            self._check_resumed_opt_state(ostate)
        elif self._use_grad_sync:
            ostate = grad_sync.init_state(self._gs_plan, params,
                                          self.optim_method)
        else:
            ostate = self.optim_method.init_state(params)
        repl, param_sh, ostate_sh = self._shardings(params, ostate)
        self._param_sh, self._ostate_sh = param_sh, ostate_sh
        self._block_sh = NamedSharding(mesh, P(None, "data"))

        # place initial values
        params = tmap(lambda x, s: jax.device_put(x, s), params, param_sh)
        ostate = tmap(lambda x, s: jax.device_put(x, s), ostate, ostate_sh)
        mstate = tmap(lambda x: jax.device_put(x, repl), mstate)

        grad_fn = self._loss_and_grad_fn()
        logger.info(
            "DistriOptimizer: %d samples/epoch, mesh=%s, grad_sync=%s%s",
            self.dataset.size(),
            dict(zip(mesh.axis_names, mesh.devices.shape)),
            self._use_grad_sync,
            f" (wire={jnp.dtype(self._gs_wire).name}, "
            f"buckets={self._gs_plan.num_buckets})"
            if self._use_grad_sync else
            f" (zero1={self.parameter_sharding})")

        params, mstate, ostate = self._train_driver(params, mstate, ostate,
                                                    grad_fn, rng)

        self.model._params = params
        self.model._state = mstate
        self._final_opt_state = ostate
        return self.model
