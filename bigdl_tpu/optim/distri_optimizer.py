"""DistriOptimizer — synchronous data-parallel training over a TPU mesh.

Reference: ``DL/optim/DistriOptimizer.scala`` (1,106 LoC) +
``DL/parameters/AllReduceParameter.scala``: each Spark iteration is 2 jobs —
(A) per-executor forward/backward with BlockManager weight fetch and FP16
gradient put, (B) per-node aggregation of its 1/N gradient slice, optimizer
update of its 1/N weight slice, weight re-publish.  That is literally a
reduce-scatter + all-gather with a sharded optimizer update (ZeRO-1).

TPU redesign: ONE jit'd SPMD step-block over a ``jax.sharding.Mesh``,
driven by the shared fused/pipelined loop in ``Optimizer._train_driver``
(K-step ``lax.scan`` fusion + double-buffered device prefetch — the
analog of BigDL 2.0 hiding the per-iteration Spark job dispatch cost).

- The global batch rides the ``data`` mesh axis (the analog of one data
  partition per executor); a staged K-step block is sharded
  ``P(None, "data")`` — step axis replicated, batch axis sharded.
- Params are replicated; XLA inserts the gradient AllReduce over ICI when
  it sees sharded-batch grads meet replicated params — replacing
  ``putGradients``/``aggregateGradientPartition`` (+ its FP16 wire format:
  ICI needs no software compression).
- With ``parameter_sharding=True`` (default), optimizer state is sharded
  over the mesh via sharding annotations, so XLA emits reduce-scatter +
  sharded update + all-gather — the exact ZeRO-1 pattern of
  ``AllReduceParameter`` (each node owns 1/N of the flat vector and runs
  the optimizer on its slice only, ``AllReduceParameter.scala:73-76``).
  (See also "Automatic Cross-Replica Sharding of Weight Update in
  Data-Parallel Training", arXiv:2004.13336 — the same design.)
- Straggler gradient-dropping (``DistriOptimizer.scala:398-425``) is
  intentionally absent: SPMD collectives are lock-step; XLA's synchronous
  model replaces it (documented divergence, SURVEY.md §7 stage 4).
- Failure retry-from-checkpoint (``:981-1061``) wraps the driver loop.

Multi-host: each process feeds its local shard of the global batch via
``jax.make_array_from_process_local_data``; ``jax.distributed.initialize``
is the analog of Spark executor registration.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.engine import Engine
from bigdl_tpu.optim.optimizer import Optimizer
from bigdl_tpu.utils.checkpoint import latest_checkpoint, load_checkpoint

logger = logging.getLogger("bigdl_tpu.optim")

tmap = jax.tree_util.tree_map


def batch_axis_spec(leaf, mesh: Mesh, axis: str = "data") -> P:
    """Shard dim 0 over the mesh axis when divisible, else replicate —
    used for ZeRO-1-style optimizer-state sharding."""
    n = mesh.shape[axis]
    if leaf.ndim > 0 and leaf.shape[0] % n == 0 and leaf.shape[0] >= n:
        return P(axis)
    return P()


class DistriOptimizer(Optimizer):
    """Data-parallel SPMD trainer.  See module docstring."""

    def __init__(self, model, dataset, criterion, batch_size=None,
                 mesh: Optional[Mesh] = None,
                 parameter_sharding: bool = True,
                 param_specs=None):
        """``param_specs``: optional pytree of PartitionSpec matching the
        model params — enables tensor parallelism (build with
        ``parallel.tensor_parallel.build_param_specs``).  ``None`` keeps
        params replicated (pure DP)."""
        super().__init__(model, dataset, criterion, batch_size)
        self.mesh = mesh or Engine.get_mesh()
        self.parameter_sharding = parameter_sharding
        self.param_specs = param_specs
        self.failure_retry_times = Engine._state.failure_retry_times
        self._param_sh = None
        self._ostate_sh = None
        self._block_sh = None  # P(None, "data"): step axis × batch axis
        self._n_dev = 1

    # -------------------------------------------------------- shardings
    def _shardings(self, params, ostate):
        mesh = self.mesh
        repl = NamedSharding(mesh, P())
        param_sh = tmap(lambda _: repl, params) if self.param_specs is None \
            else tmap(lambda sp: NamedSharding(mesh, sp), self.param_specs,
                      is_leaf=lambda x: isinstance(x, P))
        if self.parameter_sharding and self.param_specs is None:
            # ZeRO-1: shard optimizer state over the data axis (only when
            # params are replicated — TP already shards the state with them)
            ostate_sh = tmap(
                lambda l: NamedSharding(mesh, batch_axis_spec(l, mesh)),
                ostate)
        elif self.param_specs is not None:
            # optimizer-state subtrees (velocity/m/v/...) are tmaps over the
            # params, so a subtree with the params' structure inherits the
            # param shardings leaf-for-leaf; anything else is replicated
            pstruct = jax.tree_util.tree_structure(params)
            ostate_sh = {}
            for key, sub in ostate.items():
                if jax.tree_util.tree_structure(sub) == pstruct:
                    ostate_sh[key] = param_sh
                else:
                    ostate_sh[key] = tmap(lambda _: repl, sub)
        else:
            ostate_sh = tmap(lambda _: repl, ostate)
        return repl, param_sh, ostate_sh

    def _make_global(self, arr: np.ndarray, sharding: NamedSharding):
        """Per-host local shard → global device array (multi-host safe)."""
        if jax.process_count() == 1:
            return jax.device_put(arr, sharding)
        return jax.make_array_from_process_local_data(sharding, arr)

    # ----------------------------------------------- train-driver hooks
    def _place_train_block(self, xs, ys):
        """Staged (K, local_batch, ...) host trees → global arrays with
        the step axis replicated and the batch axis sharded over `data`
        (the per-microbatch analog of one data partition per executor).
        The ``device_put`` underneath is asynchronous — the driver
        stages block i+1 while block i computes, so this is where the
        double-buffered host→HBM transfer actually happens."""
        place = lambda a: self._make_global(np.asarray(a), self._block_sh)
        xs = tmap(place, xs)
        ys = None if ys is None else tmap(place, ys)
        return xs, ys

    def _records_scale(self) -> int:
        # batch.size() is the PER-HOST local batch; under multi-host the
        # assembled global array is process_count× larger, and epoch
        # accounting compares against the GLOBAL dataset.size()
        return jax.process_count()

    def _constrain_step_outputs(self, params, ostate):
        # pin output layouts so the pattern stays reduce-scatter+gather
        # (ZeRO-1) / TP-sharded across every step of the scanned block
        params = jax.lax.with_sharding_constraint(params, self._param_sh)
        ostate = jax.lax.with_sharding_constraint(ostate, self._ostate_sh)
        return params, ostate

    def _log_train_iteration(self, lr: float) -> None:
        s = self.state
        logger.info(
            "epoch %d iter %d loss %.4f lr %.5g throughput %.1f rec/s "
            "(%.1f rec/s/dev)",
            s["epoch"], s["neval"], s["loss"], lr, s["throughput"],
            s["throughput"] / self._n_dev)

    def _log_parameter_histograms(self, params) -> None:
        # trigger-gated per-parameter histograms (reference
        # DistriOptimizer.scala:541-573 "Parameters" summary)
        ptrig = getattr(self.train_summary, "trigger_for",
                        lambda _n: None)("Parameters")
        if ptrig is not None and ptrig(self.state):
            flat = jax.tree_util.tree_flatten_with_path(params)[0]
            for path, leaf in flat:
                tag = "Parameters/" + "/".join(
                    str(getattr(k, "key", k)) for k in path)
                self.train_summary.add_histogram(
                    tag, np.asarray(leaf), self.state["neval"])

    # ------------------------------------------- multi-host-safe val/ckpt
    # Eval placement hooks: batches go through the same ``_make_global``
    # path as training inputs, so validation is correct on real multi-host
    # jobs (the base hooks feed host-local arrays into a jit against
    # global params — single-process only).
    #
    # Multi-host contract: every process must see the SAME number of
    # validation batches and identical batch shapes (the framework's own
    # per-host dataset sharding guarantees this); the hooks issue one
    # collective per batch, so unequal counts would deadlock.
    def _place_eval_input(self, x):
        n_data = self.mesh.shape["data"]
        data_sh = NamedSharding(self.mesh, P("data"))
        repl = NamedSharding(self.mesh, P())

        def place(a):
            a = np.asarray(a)
            if a.shape[0] % n_data == 0:
                return self._make_global(a, data_sh)
            # ragged last eval batch: single-process can fall back to a
            # replicated (unsharded but correct) forward; multi-host has
            # no safe fallback — per-process rows differ, so a
            # "replicated" global array would be undefined
            if jax.process_count() > 1:
                raise ValueError(
                    f"multi-host validation batch of {a.shape[0]} rows is "
                    f"not divisible by the data axis ({n_data}); use a "
                    "divisible validation batch size (drop_remainder or "
                    "pad)")
            return jax.device_put(a, repl)

        return tmap(place, x)

    def _place_eval_target(self, t):
        return tmap(lambda a: self._host_global(np.asarray(a)), t)

    def _gather_eval_output(self, out):
        return self._host_global(out)

    def _host_global(self, arr):
        """Globally-sharded device array → host array every process sees
        fully (process_allgather under multi-host)."""
        if jax.process_count() == 1:
            return arr
        from jax.experimental import multihost_utils
        return multihost_utils.process_allgather(arr, tiled=True)

    def _maybe_checkpoint(self, params, mstate, ostate):
        if not (self.checkpoint_trigger and self.checkpoint_path
                and self.checkpoint_trigger(self.state)):
            return
        if jax.process_count() > 1:
            # sharded leaves are not fully addressable on one process:
            # allgather to host, then only process 0 writes
            params = tmap(self._host_global, params)
            mstate = tmap(self._host_global, mstate)
            ostate = tmap(self._host_global, ostate)
            if jax.process_index() != 0:
                return
        super()._maybe_checkpoint(params, mstate, ostate)

    # ------------------------------------------------------------- train
    def optimize(self):
        attempts = 0
        while True:
            try:
                return self._optimize_impl()
            except Exception:
                # reference retry-from-checkpoint loop
                # (DistriOptimizer.scala:981-1061)
                attempts += 1
                if attempts > self.failure_retry_times \
                        or not self.checkpoint_path:
                    raise
                ckpt = latest_checkpoint(self.checkpoint_path)
                if ckpt is None:
                    raise
                logger.exception(
                    "training failed; retry %d/%d from %s",
                    attempts, self.failure_retry_times, ckpt)
                blob = load_checkpoint(ckpt)
                self.model._params = blob["params"]
                self.model._state = blob["model_state"]
                # restore optimizer state too (reference reloads the
                # OptimMethod state table) — else Adam moments/SGD velocity
                # reset to zero and the resumed step spikes
                self._resume_opt_state = blob["opt_state"]
                if blob["driver_state"]:
                    self.state.update(blob["driver_state"])

    def _optimize_impl(self):
        mesh = self.mesh
        self._n_dev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
        rng = jax.random.PRNGKey(self.seed)
        rng, init_rng = jax.random.split(rng)
        if self.model._params is not None:
            # copy: the block fn donates its inputs; without this the
            # caller-owned model arrays would be deleted by donation
            # (device_put below is a no-op for already-placed arrays)
            params = jax.tree_util.tree_map(jnp.array, self.model._params)
            mstate = jax.tree_util.tree_map(jnp.array, self.model._state)
        else:
            params, mstate = self.model.init(init_rng)
        if self._resume_opt_state is not None:
            ostate = self._resume_opt_state
            self._resume_opt_state = None
        else:
            ostate = self.optim_method.init_state(params)
        repl, param_sh, ostate_sh = self._shardings(params, ostate)
        self._param_sh, self._ostate_sh = param_sh, ostate_sh
        self._block_sh = NamedSharding(mesh, P(None, "data"))

        # place initial values
        params = tmap(lambda x, s: jax.device_put(x, s), params, param_sh)
        ostate = tmap(lambda x, s: jax.device_put(x, s), ostate, ostate_sh)
        mstate = tmap(lambda x: jax.device_put(x, repl), mstate)

        grad_fn = self._loss_and_grad_fn()
        logger.info(
            "DistriOptimizer: %d samples/epoch, mesh=%s, zero1=%s",
            self.dataset.size(),
            dict(zip(mesh.axis_names, mesh.devices.shape)),
            self.parameter_sharding)

        params, mstate, ostate = self._train_driver(params, mstate, ostate,
                                                    grad_fn, rng)

        self.model._params = params
        self.model._state = mstate
        self._final_opt_state = ostate
        return self.model
