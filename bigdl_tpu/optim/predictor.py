"""Inference path: Predictor, Evaluator, PredictionService.

Reference: ``DL/optim/Predictor.scala:197`` (RDD predict via broadcast model
+ per-partition local batching), ``Evaluator.scala:37``,
``PredictionService.scala`` (353 LoC — thread-safe concurrent inference with
an instance pool), ``LocalPredictor.scala``.

TPU redesign: the broadcast/mapPartitions machinery collapses into one
jit'd forward — the "broadcast" is params living in HBM, "partition-local
batching" is plain batching.  ``PredictionService`` is now a back-compat
shim over :class:`bigdl_tpu.serving.InferenceService` — the dynamic
batching engine that coalesces concurrent callers into one bucket-padded
AOT-compiled dispatch (see the ``serving`` package / README "serving").

Padding invariant (shared with the serving engine): partial batches are
padded with ZERO rows up to the compiled shape and the pad outputs are
sliced off.  This is sound because the forward runs in eval mode
(``training=False``): BatchNorm reads running statistics and dropout is
off, so rows are computed independently and a pad row cannot perturb a
real row.  Zero rows (rather than copies of a real row) keep the H2D
bytes compressible and make a violation of the invariant *visible* —
copied rows would mask cross-row leakage bit-exactly.
"""

from __future__ import annotations

import threading
import time
from typing import Iterable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.sample import MiniBatch, Sample, batch_samples
from bigdl_tpu.nn.module import Module
from bigdl_tpu.optim.validation import ValidationMethod, ValidationResult


def _resolve(model: Module, params, state):
    if params is None:
        model._ensure_init()
        params, state = model._params, model._state
    return params, state if state is not None else {}


# the zero-pad/leading-rows helpers are the serving engine's — one
# implementation of the padding invariant, not two drifting copies
from bigdl_tpu.serving.service import leading_rows, pad_rows


class Predictor:
    """Batched forward inference (reference ``Predictor.scala``).

    ``input_spec`` (optional): per-row ``jax.ShapeDtypeStruct`` (or
    ``(shape, dtype)``) of one sample — lets :meth:`predict` return a
    correctly-shaped empty array for an empty dataset via
    ``jax.eval_shape`` instead of a rank-less ``(0,)``.
    """

    def __init__(self, model: Module, params=None, state=None,
                 batch_size: int = 128, input_spec=None):
        self.model = model
        self.params, self.state = _resolve(model, params, state)
        self.batch_size = batch_size
        self.input_spec = input_spec
        self._rows_track: Optional[bool] = None  # lazily probed

        @jax.jit
        def fwd(params, state, x):
            out, _ = model.apply(params, state, x, training=False)
            return out

        self._fwd = fwd

    def _rows_track_input(self, x) -> bool:
        """Two-point ``jax.eval_shape`` probe (tracing only — no
        compile): does the output leading dim FOLLOW the input leading
        dim?  False for COO-style inputs whose output rows come from
        static metadata (so a single-point ``out_rows == in_rows`` check
        would be fooled whenever nnz happens to equal the sample
        count)."""

        def with_rows(k):
            return jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct((k,) + a.shape[1:],
                                               a.dtype), x)

        try:
            for k in (2, 3):
                out = jax.eval_shape(self._fwd, self.params, self.state,
                                     with_rows(k))
                if any(leaf.shape[:1] != (k,)
                       for leaf in jax.tree_util.tree_leaves(out)):
                    return False
            return True
        except Exception:
            return False  # probe shapes unsupported — be conservative

    def _iter_batches(self, data):
        if isinstance(data, AbstractDataSet):
            for b in data.data(train=False):
                if isinstance(b, MiniBatch):
                    yield b
                else:  # dataset of raw Samples
                    raise TypeError(
                        "DataSet must yield MiniBatch for predict; attach "
                        "SampleToMiniBatch or pass a list of Samples")
        else:
            buf = []
            for s in data:
                buf.append(s if isinstance(s, Sample) else Sample(np.asarray(s)))
                if len(buf) == self.batch_size:
                    yield batch_samples(buf)
                    buf = []
            if buf:
                yield batch_samples(buf)

    def _empty_result(self) -> np.ndarray:
        """Empty input → empty output with the model's true trailing
        dims, recovered abstractly (no device work, no compile) when the
        caller declared an ``input_spec``."""
        if self.input_spec is None:
            return np.empty((0,))
        from bigdl_tpu.serving.service import InferenceService
        row = InferenceService._normalize_row_spec(self.input_spec)
        spec1 = jax.tree_util.tree_map(
            lambda s: jax.ShapeDtypeStruct((1,) + tuple(s.shape), s.dtype),
            row)
        out = jax.eval_shape(self._fwd, self.params, self.state, spec1)
        return np.empty((0,) + tuple(out.shape[1:]),
                        dtype=np.dtype(out.dtype))

    def predict(self, data) -> np.ndarray:
        """data: AbstractDataSet (yielding MiniBatch) or iterable of
        Samples/arrays.  Returns stacked outputs (reference
        ``model.predict(rdd)`` → RDD[Activity]).

        The trailing partial batch is zero-padded up to the steady-state
        batch shape and the pad rows sliced off, so a whole-dataset
        predict compiles exactly ONE executable (the unbucketed tail
        shape was a second silent compile — graftlint GL106's hazard
        class; regression-gated in ``tests/test_serving.py``)."""
        outs = []
        steady = None  # rows of the first (steady-state) batch
        for batch in self._iter_batches(data):
            x = jax.tree_util.tree_map(jnp.asarray, batch.input)
            try:
                n = leading_rows(x)
            except ValueError:
                # heterogeneous leading dims — e.g. SparseMiniBatch's
                # (coo(nnz), dense(N)) inputs: no row accounting is
                # possible, dispatch as-is (the historical behavior)
                outs.append(np.asarray(
                    self._fwd(self.params, self.state, x)))
                continue
            if steady is None:
                steady = n
            if n < steady:
                # tail batch: pad-to-steady-and-slice saves the second
                # compile, but ONLY when output rows provably follow
                # input rows (eval_shape probe — a COO-only input whose
                # nnz bucket coincides with the sample count would fool
                # any single-point check and lose real rows); otherwise
                # dispatch the odd shape as-is: one extra compile,
                # never a wrong answer
                if self._rows_track is None:
                    self._rows_track = self._rows_track_input(x)
                if self._rows_track:
                    x = jax.tree_util.tree_map(jnp.asarray,
                                               pad_rows(x, steady))
                    out = np.asarray(self._fwd(self.params, self.state,
                                               x))
                    outs.append(out[:n])
                    continue
            outs.append(np.asarray(
                self._fwd(self.params, self.state, x)))
        if not outs:
            return self._empty_result()
        return np.concatenate(outs, axis=0)

    def predict_class(self, data) -> np.ndarray:
        """(reference ``predictClass``) — argmax over the last dim,
        0-based classes."""
        return np.argmax(self.predict(data), axis=-1)


class Evaluator:
    """Metric evaluation over a dataset (reference ``Evaluator.scala:37``;
    results reduce associatively exactly like the reference's
    ValidationResults across partitions)."""

    def __init__(self, model: Module, params=None, state=None):
        self.model = model
        self.params, self.state = _resolve(model, params, state)

        @jax.jit
        def fwd(params, state, x):
            out, _ = model.apply(params, state, x, training=False)
            return out

        self._fwd = fwd

    def evaluate(self, dataset: AbstractDataSet,
                 methods: Sequence[ValidationMethod]) -> dict:
        acc: dict[str, ValidationResult] = {}
        for batch in dataset.data(train=False):
            x = jax.tree_util.tree_map(jnp.asarray, batch.input)
            y = jax.tree_util.tree_map(jnp.asarray, batch.target)
            out = self._fwd(self.params, self.state, x)
            for m in methods:
                r = m(out, y)
                acc[m.name] = acc[m.name] + r if m.name in acc else r
        return acc


class PredictionService:
    """Thread-safe always-on inference endpoint (reference
    ``PredictionService.scala``) — back-compat shim over
    :class:`bigdl_tpu.serving.InferenceService`.

    The old implementation ran one padded batch-32 dispatch *per caller
    thread*: 8 concurrent single-row requests burned 8 full forwards.
    The serving engine coalesces concurrent callers into one bucketed
    dispatch, adds bounded-queue backpressure
    (:class:`bigdl_tpu.serving.ServiceOverloaded`), AOT bucket warmup and
    per-model stats; this shim keeps the historical constructor and the
    blocking ``predict`` + ``request_count`` surface.  New code should
    use :class:`~bigdl_tpu.serving.InferenceService` directly (futures,
    ``stats()``, ``stop()``)."""

    def __init__(self, model: Module, params=None, state=None,
                 batch_size: int = 32, **service_kw):
        from bigdl_tpu.serving import InferenceService
        self.model = model
        self.params, self.state = _resolve(model, params, state)
        self.batch_size = batch_size
        self._stats_lock = threading.Lock()
        self.request_count = 0  # guarded-by: _stats_lock
        # timeout 0 = adaptive batching: the historical service
        # dispatched immediately, so the shim must not tax lone
        # sequential callers with a coalescing wait — concurrent load
        # still coalesces (whatever queued during the previous dispatch
        # forms the next group); override via batch_timeout_ms=...
        service_kw.setdefault("batch_timeout_ms", 0.0)
        self.service = InferenceService(
            model, self.params, self.state, max_batch_size=batch_size,
            name="PredictionService", **service_kw)

    def predict(self, features) -> np.ndarray:
        """features: (n, ...) with any n ≥ 1 (chunked over the engine's
        coalesced bucket dispatches).  Coerced via ``np.asarray`` like
        the historical implementation, so list-of-lists inputs keep
        working (the engine itself would read a nested list as a
        pytree of scalars).

        Historical callers predate backpressure, so a transient
        :class:`~bigdl_tpu.serving.ServiceOverloaded` gets ONE bounded
        internal retry after the exception's own ``retry_after_ms``
        drain estimate — sustained overload still surfaces (the second
        rejection propagates; shedding exists to be felt upstream)."""
        from bigdl_tpu.serving import ServiceOverloaded
        x = np.asarray(features)
        try:
            out = self.service.predict(x)
        except ServiceOverloaded as e:
            wait_ms = e.retry_after_ms if e.retry_after_ms is not None \
                else 10.0
            time.sleep(min(wait_ms, 1000.0) / 1e3)
            out = self.service.predict(x)  # second rejection propagates
        with self._stats_lock:
            self.request_count += 1
        return out

    def stats(self) -> dict:
        return self.service.stats()

    def stop(self, drain: bool = True) -> None:
        self.service.stop(drain=drain)
