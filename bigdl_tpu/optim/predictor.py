"""Inference path: Predictor, Evaluator, PredictionService.

Reference: ``DL/optim/Predictor.scala:197`` (RDD predict via broadcast model
+ per-partition local batching), ``Evaluator.scala:37``,
``PredictionService.scala`` (353 LoC — thread-safe concurrent inference with
an instance pool), ``LocalPredictor.scala``.

TPU redesign: the broadcast/mapPartitions machinery collapses into one
jit'd forward — the "broadcast" is params living in HBM, "partition-local
batching" is plain batching.  ``PredictionService``'s instance pool is
unnecessary: a jit'd function is pure and reentrant, so concurrent callers
share one compiled executable; the service adds fixed-size batch padding so
odd request sizes never trigger a recompile.
"""

from __future__ import annotations

import threading
from typing import Iterable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.sample import MiniBatch, Sample, batch_samples
from bigdl_tpu.nn.module import Module
from bigdl_tpu.optim.validation import ValidationMethod, ValidationResult


def _resolve(model: Module, params, state):
    if params is None:
        model._ensure_init()
        params, state = model._params, model._state
    return params, state if state is not None else {}


class Predictor:
    """Batched forward inference (reference ``Predictor.scala``)."""

    def __init__(self, model: Module, params=None, state=None,
                 batch_size: int = 128):
        self.model = model
        self.params, self.state = _resolve(model, params, state)
        self.batch_size = batch_size

        @jax.jit
        def fwd(params, state, x):
            out, _ = model.apply(params, state, x, training=False)
            return out

        self._fwd = fwd

    def _iter_batches(self, data):
        if isinstance(data, AbstractDataSet):
            for b in data.data(train=False):
                if isinstance(b, MiniBatch):
                    yield b
                else:  # dataset of raw Samples
                    raise TypeError(
                        "DataSet must yield MiniBatch for predict; attach "
                        "SampleToMiniBatch or pass a list of Samples")
        else:
            buf = []
            for s in data:
                buf.append(s if isinstance(s, Sample) else Sample(np.asarray(s)))
                if len(buf) == self.batch_size:
                    yield batch_samples(buf)
                    buf = []
            if buf:
                yield batch_samples(buf)

    def predict(self, data) -> np.ndarray:
        """data: AbstractDataSet (yielding MiniBatch) or iterable of
        Samples/arrays.  Returns stacked outputs (reference
        ``model.predict(rdd)`` → RDD[Activity])."""
        outs = []
        for batch in self._iter_batches(data):
            x = jax.tree_util.tree_map(jnp.asarray, batch.input)
            outs.append(np.asarray(self._fwd(self.params, self.state, x)))
        if not outs:
            return np.empty((0,))
        return np.concatenate(outs, axis=0)

    def predict_class(self, data) -> np.ndarray:
        """(reference ``predictClass``) — argmax over the last dim,
        0-based classes."""
        return np.argmax(self.predict(data), axis=-1)


class Evaluator:
    """Metric evaluation over a dataset (reference ``Evaluator.scala:37``;
    results reduce associatively exactly like the reference's
    ValidationResults across partitions)."""

    def __init__(self, model: Module, params=None, state=None):
        self.model = model
        self.params, self.state = _resolve(model, params, state)

        @jax.jit
        def fwd(params, state, x):
            out, _ = model.apply(params, state, x, training=False)
            return out

        self._fwd = fwd

    def evaluate(self, dataset: AbstractDataSet,
                 methods: Sequence[ValidationMethod]) -> dict:
        acc: dict[str, ValidationResult] = {}
        for batch in dataset.data(train=False):
            x = jax.tree_util.tree_map(jnp.asarray, batch.input)
            y = jax.tree_util.tree_map(jnp.asarray, batch.target)
            out = self._fwd(self.params, self.state, x)
            for m in methods:
                r = m(out, y)
                acc[m.name] = acc[m.name] + r if m.name in acc else r
        return acc


class PredictionService:
    """Thread-safe always-on inference endpoint (reference
    ``PredictionService.scala``).  Requests of any size ≤ batch_size are
    padded to the fixed compiled shape (no recompilation storms); larger
    requests are chunked.  Safe for concurrent callers — jit'd executables
    are reentrant, so unlike the reference no instance pool is needed."""

    def __init__(self, model: Module, params=None, state=None,
                 batch_size: int = 32):
        self.model = model
        self.params, self.state = _resolve(model, params, state)
        self.batch_size = batch_size
        self._stats_lock = threading.Lock()
        self.request_count = 0

        @jax.jit
        def fwd(params, state, x):
            out, _ = model.apply(params, state, x, training=False)
            return out

        self._fwd = fwd

    def predict(self, features: np.ndarray) -> np.ndarray:
        """features: (n, ...) with any n ≥ 1."""
        features = np.asarray(features)
        n = features.shape[0]
        outs = []
        for off in range(0, n, self.batch_size):
            chunk = features[off:off + self.batch_size]
            pad = self.batch_size - chunk.shape[0]
            if pad:
                chunk = np.concatenate(
                    [chunk, np.repeat(chunk[-1:], pad, axis=0)], axis=0)
            out = np.asarray(self._fwd(self.params, self.state,
                                       jnp.asarray(chunk)))
            outs.append(out[:self.batch_size - pad] if pad else out)
        with self._stats_lock:
            self.request_count += 1
        return np.concatenate(outs, axis=0)
