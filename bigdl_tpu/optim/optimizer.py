"""Optimizer — the training front door.

Reference: ``DL/optim/Optimizer.scala:47`` builder API (``setValidation``,
``setCheckpoint:198``, ``overWriteCheckpoint:233``, ``setOptimMethod:366``,
``setEndWhen:389``, gradient clipping ``:423+``) whose factory dispatches
``LocalOptimizer`` (single JVM) vs ``DistriOptimizer`` (Spark).

Here: :class:`Optimizer` holds the builder surface + the shared driver loop
machinery; :class:`LocalOptimizer` jit-compiles the train step for the
local device (1 TPU chip); ``DistriOptimizer`` (bigdl_tpu.optim.
distri_optimizer) shard_maps it over the mesh.  The factory
``Optimizer.create`` mirrors the reference's dispatch.

Gradient clipping maps the reference's ``ConstantClippingProcessor`` /
``L2NormClippingProcessor`` (``parameters/ParameterOperations.scala:71,89``)
to pure pytree ops inside the jit'd step — the cross-partition sqsum
aggregation becomes a global norm over the (already full) gradient pytree,
and under data parallelism the psum'd gradient is identical on every
device, so clipping semantics match the reference exactly.
"""

from __future__ import annotations

import logging
import time
from functools import partial
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.nn.criterion import Criterion
from bigdl_tpu.nn.module import Module
from bigdl_tpu.optim.optim_method import OptimMethod, SGD
from bigdl_tpu.optim.trigger import Trigger, max_epoch
from bigdl_tpu.optim.validation import ValidationMethod, ValidationResult
from bigdl_tpu.utils.checkpoint import save_checkpoint
from bigdl_tpu.utils.metrics import Metrics

logger = logging.getLogger("bigdl_tpu.optim")

tmap = jax.tree_util.tree_map


def device_tree(x):
    """Move a (possibly nested tuple/list/dict) batch onto device —
    MiniBatch inputs may be pytrees (multi-input models), so a blind
    ``jnp.asarray`` would mis-stack them into one array."""
    return tmap(jnp.asarray, x)


def clip_by_value(grads, min_v: float, max_v: float):
    """(reference ConstantClippingProcessor)"""
    return tmap(lambda g: jnp.clip(g, min_v, max_v), grads)


def global_norm(grads) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(grads)
    return jnp.sqrt(sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in leaves))


def clip_by_global_norm(grads, max_norm: float):
    """(reference L2NormClippingProcessor — global norm across all slices)"""
    norm = global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return tmap(lambda g: g * scale, grads)


class Optimizer:
    """Builder + driver-loop base."""

    def __init__(self, model: Module, dataset: AbstractDataSet,
                 criterion: Criterion, batch_size: Optional[int] = None):
        self.model = model
        self.dataset = dataset
        self.criterion = criterion
        self.batch_size = batch_size

        self.optim_method: OptimMethod = SGD()
        self.end_when: Trigger = max_epoch(1)
        self.validation_trigger: Optional[Trigger] = None
        self.validation_dataset: Optional[AbstractDataSet] = None
        self.validation_methods: Sequence[ValidationMethod] = ()
        self.checkpoint_trigger: Optional[Trigger] = None
        self.checkpoint_path: Optional[str] = None
        self.overwrite_checkpoint = True
        self.grad_clip: Optional[Callable] = None
        self.train_summary = None
        self.validation_summary = None
        self.metrics = Metrics()
        self.seed = 1

        # driver state (reference: the state Table inside OptimMethod —
        # epoch/neval survive checkpoint/resume)
        self.state: dict = {"epoch": 0, "neval": 0,
                            "records_processed_this_epoch": 0}
        self._eval_fwd = None  # cached jit'd eval forward
        self._resume_opt_state = None  # optimizer state restored on retry
        self.compute_dtype = None  # None = full f32; jnp.bfloat16 for MXU

    # ------------------------------------------------------------- builder
    def set_optim_method(self, method: OptimMethod) -> "Optimizer":
        self.optim_method = method
        return self

    def set_end_when(self, trigger: Trigger) -> "Optimizer":
        self.end_when = trigger
        return self

    def set_validation(self, trigger: Trigger, dataset: AbstractDataSet,
                       methods: Sequence[ValidationMethod],
                       batch_size: Optional[int] = None) -> "Optimizer":
        self.validation_trigger = trigger
        self.validation_methods = list(methods)
        if batch_size is not None:
            # re-batch: reference scripts pass a validation batch size
            # (Optimizer.setValidation(batchSize) overload)
            from bigdl_tpu.dataset.transformer import SampleToMiniBatch
            dataset = dataset >> SampleToMiniBatch(
                batch_size, drop_remainder=False)
        self.validation_dataset = dataset
        return self

    def set_checkpoint(self, path: str, trigger: Trigger) -> "Optimizer":
        self.checkpoint_path = path
        self.checkpoint_trigger = trigger
        return self

    def over_write_checkpoint(self) -> "Optimizer":
        self.overwrite_checkpoint = True
        return self

    def set_gradient_clipping_by_value(self, min_v: float,
                                       max_v: float) -> "Optimizer":
        self.grad_clip = lambda g: clip_by_value(g, min_v, max_v)
        return self

    def set_gradient_clipping_by_l2_norm(self, max_norm: float) -> "Optimizer":
        self.grad_clip = lambda g: clip_by_global_norm(g, max_norm)
        return self

    def disable_gradient_clipping(self) -> "Optimizer":
        self.grad_clip = None
        return self

    def set_train_summary(self, summary) -> "Optimizer":
        self.train_summary = summary
        return self

    def set_val_summary(self, summary) -> "Optimizer":
        self.validation_summary = summary
        return self

    def set_seed(self, seed: int) -> "Optimizer":
        self.seed = seed
        return self

    def set_compute_dtype(self, dtype) -> "Optimizer":
        """Mixed precision: fwd/bwd in ``dtype`` (bf16 for the MXU), master
        params + optimizer update stay f32.  See utils/precision.py."""
        self.compute_dtype = dtype
        return self

    def set_state(self, state: dict) -> "Optimizer":
        """Resume driver state (epoch/neval) from a checkpoint."""
        self.state.update(state)
        return self

    # ------------------------------------------------------------ factory
    @staticmethod
    def create(model: Module, dataset: AbstractDataSet, criterion: Criterion,
               distributed: Optional[bool] = None, **kw):
        """(reference ``Optimizer.apply`` factories, ``Optimizer.scala:597+``)"""
        from bigdl_tpu.optim.distri_optimizer import DistriOptimizer
        if distributed is None:
            distributed = jax.device_count() > 1
        cls = DistriOptimizer if distributed else LocalOptimizer
        return cls(model, dataset, criterion, **kw)

    def optimize(self) -> Module:
        raise NotImplementedError

    # ------------------------------------------------------------- shared
    def _loss_and_grad_fn(self):
        model, criterion = self.model, self.criterion
        if self.compute_dtype is not None:
            from bigdl_tpu.utils.precision import mixed_precision_loss_fn
            loss_fn = mixed_precision_loss_fn(model, criterion,
                                              self.compute_dtype)
        else:
            def loss_fn(params, mstate, x, y, rng):
                out, new_mstate = model.apply(params, mstate, x,
                                              training=True, rng=rng)
                return criterion.apply(out, y), new_mstate

        # per-layer L1/L2 penalties (reference Regularizer.scala applies
        # them inside accGradParameters; here they enter the loss so
        # jax.grad produces the identical gradient contribution)
        from bigdl_tpu.nn.regularizers import (has_regularizers,
                                               regularization_loss)
        if has_regularizers(model):
            base = loss_fn

            def loss_fn(params, mstate, x, y, rng, _base=base):
                loss, new_mstate = _base(params, mstate, x, y, rng)
                return loss + regularization_loss(model, params), \
                    new_mstate

        return jax.value_and_grad(loss_fn, has_aux=True)

    def _fast_forward(self, data_iter, state):
        """Mid-epoch resume: skip the samples already processed this epoch
        so the epoch boundary (and shuffle cadence) stays correct
        (reference: recordsProcessedThisEpoch in the OptimMethod state
        table, ``DistriOptimizer.scala:124-134``)."""
        skip = state.get("records_processed_this_epoch", 0)
        skipped = 0
        while skipped < skip:
            skipped += next(data_iter).size()
        if skipped:
            logger.info("resume: skipped %d already-processed records",
                        skipped)

    def _maybe_checkpoint(self, params, mstate, ostate):
        if self.checkpoint_trigger and self.checkpoint_path \
                and self.checkpoint_trigger(self.state):
            f = save_checkpoint(self.checkpoint_path, params, mstate, ostate,
                                driver_state=self.state,
                                neval=self.state["neval"],
                                overwrite=self.overwrite_checkpoint)
            logger.info("checkpoint saved to %s", f)

    def _run_validation(self, params, mstate) -> Optional[dict]:
        if not (self.validation_trigger and self.validation_methods
                and self.validation_dataset is not None
                and self.validation_trigger(self.state)):
            return None
        results = self.evaluate_with(params, mstate)
        for name, res in results.items():
            logger.info("validation %s = %s", name, res)
            if self.validation_summary is not None:
                self.validation_summary.add_scalar(name, res.result,
                                                   self.state["neval"])
        # expose primary score to triggers; feed metric-driven schedules
        # (Plateau) exactly once per validation — NOT once per iteration
        first = next(iter(results.values()))
        self.state["score"] = first.result
        sched = self.optim_method.learning_rate_schedule
        if sched is not None and hasattr(sched, "record"):
            sched.record(first.result)
        return results

    # placement hooks — DistriOptimizer overrides these for sharded /
    # multi-host evaluation; the loop itself lives only here
    def _place_eval_input(self, x):
        return device_tree(x)

    def _place_eval_target(self, t):
        return device_tree(t)

    def _gather_eval_output(self, out):
        return out

    def evaluate_with(self, params, mstate) -> dict:
        """Forward the validation set through the model in eval mode."""
        if self._eval_fwd is None:
            model = self.model

            @jax.jit
            def fwd(params, mstate, x):
                out, _ = model.apply(params, mstate, x, training=False)
                return out

            self._eval_fwd = fwd

        acc: dict[str, ValidationResult] = {}
        for batch in self.validation_dataset.data(train=False):
            if not isinstance(batch, MiniBatch):
                raise TypeError("validation dataset must yield MiniBatch "
                                "(attach SampleToMiniBatch)")
            out = self._eval_fwd(params, mstate,
                                 self._place_eval_input(batch.input))
            out = self._gather_eval_output(out)
            tgt = self._place_eval_target(batch.target)
            for m in self.validation_methods:
                r = m(out, tgt)
                acc[m.name] = acc[m.name] + r if m.name in acc else r
        if not acc:
            raise ValueError(
                "validation dataset yielded no batches — its size is smaller "
                "than the batch size and SampleToMiniBatch dropped the "
                "remainder; use SampleToMiniBatch(n, drop_remainder=False) "
                "for validation or shrink the batch")
        return acc


class LocalOptimizer(Optimizer):
    """Single-host training loop (reference ``LocalOptimizer.scala:45``).

    The reference clones the model per core and sums gradients across
    thread replicas; under XLA one jit'd step uses the whole chip, so the
    loop is: next batch → jit'd (loss, grad, update) → triggers.
    """

    def optimize(self) -> Module:
        rng = jax.random.PRNGKey(self.seed)
        rng, init_rng = jax.random.split(rng)
        if self.model._params is not None:
            # copy: train_step donates its inputs, and these arrays are
            # owned by the caller's model — donation would delete them,
            # corrupting the model on a failed/interrupted run
            params = jax.tree_util.tree_map(jnp.array, self.model._params)
            mstate = jax.tree_util.tree_map(jnp.array, self.model._state)
        else:
            params, mstate = self.model.init(init_rng)
        if self._resume_opt_state is not None:
            ostate = self._resume_opt_state
            self._resume_opt_state = None
        else:
            ostate = self.optim_method.init_state(params)

        grad_fn = self._loss_and_grad_fn()
        grad_clip = self.grad_clip
        optim = self.optim_method

        # donate params/mstate/ostate: they are rebound to the outputs each
        # iteration, so XLA can update in place instead of copying ~2x the
        # model + optimizer state through HBM every step
        @partial(jax.jit, donate_argnums=(0, 1, 2))
        def train_step(params, mstate, ostate, x, y, lr, step, rng):
            (loss, new_mstate), grads = grad_fn(params, mstate, x, y, rng)
            if grad_clip is not None:
                grads = grad_clip(grads)
            params, ostate = optim.update(grads, params, ostate, lr, step)
            return params, new_mstate, ostate, loss

        data_iter = self.dataset.data(train=True)
        epoch_size = self.dataset.size()
        state = self.state
        self._fast_forward(data_iter, state)
        logger.info("LocalOptimizer: %d samples/epoch, device=%s",
                    epoch_size, jax.devices()[0])

        while not self.end_when(state):
            t0 = time.perf_counter()
            with self.metrics.time("data"):
                batch = next(data_iter)
            n_records = batch.size()
            lr = self.optim_method.current_lr(state["neval"], state["epoch"])
            rng, step_rng = jax.random.split(rng)
            with self.metrics.time("computing"):
                params, mstate, ostate, loss = train_step(
                    params, mstate, ostate,
                    device_tree(batch.input), device_tree(batch.target),
                    lr, state["neval"], step_rng)
                loss = float(loss)
            dt = time.perf_counter() - t0

            state["neval"] += 1
            state["records_processed_this_epoch"] += n_records
            state["loss"] = loss
            state["throughput"] = n_records / dt
            # reference per-iteration log line (DistriOptimizer.scala:388-394)
            logger.info(
                "epoch %d iter %d loss %.4f lr %.5g throughput %.1f rec/s",
                state["epoch"], state["neval"], loss, lr, state["throughput"])
            if self.train_summary is not None:
                self.train_summary.add_scalar("Loss", loss, state["neval"])
                self.train_summary.add_scalar("LearningRate", lr,
                                              state["neval"])
                self.train_summary.add_scalar("Throughput",
                                              state["throughput"],
                                              state["neval"])

            state["epoch_finished"] = \
                state["records_processed_this_epoch"] >= epoch_size
            if state["epoch_finished"]:
                state["epoch"] += 1
                state["records_processed_this_epoch"] = 0
                self.dataset.shuffle()
                data_iter = self.dataset.data(train=True)

            self._run_validation(params, mstate)
            self._maybe_checkpoint(params, mstate, ostate)
            state["epoch_finished"] = False

        # write trained weights back into the user's model object
        # (reference: final getModel copy, DistriOptimizer.scala:1063)
        self.model._params = params
        self.model._state = mstate
        self._final_opt_state = ostate
        return self.model
