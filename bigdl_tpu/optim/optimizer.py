"""Optimizer — the training front door.

Reference: ``DL/optim/Optimizer.scala:47`` builder API (``setValidation``,
``setCheckpoint:198``, ``overWriteCheckpoint:233``, ``setOptimMethod:366``,
``setEndWhen:389``, gradient clipping ``:423+``) whose factory dispatches
``LocalOptimizer`` (single JVM) vs ``DistriOptimizer`` (Spark).

Here: :class:`Optimizer` holds the builder surface + the ONE driver loop
both trainers share; :class:`LocalOptimizer` jit-compiles the train step
for the local device (1 TPU chip); ``DistriOptimizer`` (bigdl_tpu.optim.
distri_optimizer) shard_maps it over the mesh via the placement /
sharding-constraint hooks.  The factory ``Optimizer.create`` mirrors the
reference's dispatch.

Driver-loop design (the analog of hiding the reference's per-iteration
2-Spark-job orchestration cost, ``DistriOptimizer.scala``'s step):

- **K-step dispatch fusion**: ``steps_per_dispatch = K`` stacks K
  microbatches and runs the (loss, grad, update) step under ``lax.scan``
  inside ONE jit with donated params/mstate/ostate — one host dispatch
  per K iterations instead of per iteration.  The per-step loss vector
  comes back so triggers/summaries still observe every iteration.
- **Exact trigger/epoch semantics**: blocks are planned with
  ``trigger.probe_fire_step`` so a validation/checkpoint/end iteration
  is always a block's LAST step, and epoch boundaries flush partial
  blocks (the stager's records budget) — iteration numbers, shuffle
  cadence, and mid-epoch resume behave identically for every K.
- **Pipelined host work**: the next block is staged (host-stacked and
  asynchronously ``device_put``) right after a dispatch, so the
  host→HBM transfer of block i+1 overlaps the compute of block i; the
  blocking loss fetch runs ONE BLOCK BEHIND the dispatch, so the device
  queue is never drained by a ``float(loss)`` — not even at K=1.

Documented divergence: triggers keyed on ``loss``/``score`` (min_loss,
max_score) are probed with their last known values, so under pipelining
they stop/validate at the correct *iteration number* but the device may
already have run up to one extra block (the final params then include
those extra steps).  Iteration- and epoch-count triggers are exact.

Gradient clipping maps the reference's ``ConstantClippingProcessor`` /
``L2NormClippingProcessor`` (``parameters/ParameterOperations.scala:71,89``)
to pure pytree ops inside the jit'd step — the cross-partition sqsum
aggregation becomes a global norm over the (already full) gradient pytree,
and under data parallelism the psum'd gradient is identical on every
device, so clipping semantics match the reference exactly.
"""

from __future__ import annotations

import logging
import sys
import time
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.prefetch import DeviceBlockStager
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.engine import Engine
from bigdl_tpu.nn.criterion import Criterion
from bigdl_tpu.nn.module import Module
from bigdl_tpu.optim.optim_method import OptimMethod, SGD
from bigdl_tpu.optim.trigger import Trigger, max_epoch, probe_fire_step
from bigdl_tpu.optim.validation import ValidationMethod, ValidationResult
from bigdl_tpu.checkpoint import (CheckpointManager, PreemptionHandler,
                                  build_schema, validate_schema)
from bigdl_tpu.resilience.faults import FaultInjector, InjectedFault
from bigdl_tpu.resilience.membership import (ClusterMembership,
                                             MembershipChanged)
from bigdl_tpu.resilience.numeric import (NonFiniteStepError,
                                          validate_policy)
from bigdl_tpu.telemetry import DriverTelemetry, NULL_SPAN, jit_cache_size
from bigdl_tpu.utils import spmdcheck
from bigdl_tpu.utils.metrics import Metrics

logger = logging.getLogger("bigdl_tpu.optim")

tmap = jax.tree_util.tree_map


def device_tree(x):
    """Move a (possibly nested tuple/list/dict) batch onto device —
    MiniBatch inputs may be pytrees (multi-input models), so a blind
    ``jnp.asarray`` would mis-stack them into one array."""
    return tmap(jnp.asarray, x)


def clip_by_value(grads, min_v: float, max_v: float):
    """(reference ConstantClippingProcessor)"""
    return tmap(lambda g: jnp.clip(g, min_v, max_v), grads)


def global_norm(grads) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(grads)
    return jnp.sqrt(sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in leaves))


def clip_by_global_norm(grads, max_norm: float):
    """(reference L2NormClippingProcessor — global norm across all slices)"""
    norm = global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return tmap(lambda g: g * scale, grads)


def step_finite(loss, grads):
    """Scalar bool: this step's loss AND every (inexact) gradient leaf
    are finite.  Computed INSIDE the jit'd step so the flag rides the
    one-block-behind loss fetch — the numeric guard never adds a host
    sync (graftlint catalog: "the numeric guard rides the replay
    boundary")."""
    finite = jnp.isfinite(loss)
    for g in jax.tree_util.tree_leaves(grads):
        if jnp.issubdtype(g.dtype, jnp.inexact):
            finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(g)))
    return finite


def select_step(finite, new, old):
    """``jnp.where``-select a whole pytree: the updated binding where
    the step was finite, the pre-step binding otherwise (the dynamic
    loss-scaling skip idiom — a skipped step leaves params, model state
    AND optimizer state exactly as if the step never ran)."""
    return tmap(lambda a, b: jnp.where(finite, a, b), new, old)


class _Staged:
    """A planned, device-placed K'-step block awaiting dispatch."""

    __slots__ = ("xs", "ys", "sizes", "lrs", "lrs_dev", "steps_dev",
                 "rngs_dev", "sync", "stage_s")

    def __init__(self, xs, ys, sizes, lrs, lrs_dev, steps_dev, rngs_dev,
                 sync, stage_s=0.0):
        self.xs, self.ys, self.sizes = xs, ys, sizes
        self.lrs, self.lrs_dev = lrs, lrs_dev
        self.steps_dev, self.rngs_dev = steps_dev, rngs_dev
        self.sync = sync  # a trigger/epoch/end boundary ends this block
        self.stage_s = stage_s  # host time spent planning+staging (telemetry)


class _InFlight:
    """A dispatched block whose per-step losses are still on device."""

    __slots__ = ("losses", "sizes", "lrs", "t0", "stage_s", "dispatch_s",
                 "first_compile")

    def __init__(self, losses, sizes, lrs, t0, stage_s=0.0,
                 dispatch_s=0.0, first_compile=False):
        self.losses, self.sizes, self.lrs, self.t0 = losses, sizes, lrs, t0
        self.stage_s = stage_s        # staging host time (telemetry)
        self.dispatch_s = dispatch_s  # jit enqueue host time (telemetry)
        self.first_compile = first_compile  # dispatch included a compile


class Optimizer:
    """Builder + the shared fused/pipelined driver loop."""

    def __init__(self, model: Module, dataset: AbstractDataSet,
                 criterion: Criterion, batch_size: Optional[int] = None):
        self.model = model
        self.dataset = dataset
        self.criterion = criterion
        self.batch_size = batch_size

        self.optim_method: OptimMethod = SGD()
        self.end_when: Trigger = max_epoch(1)
        self.validation_trigger: Optional[Trigger] = None
        self.validation_dataset: Optional[AbstractDataSet] = None
        self.validation_methods: Sequence[ValidationMethod] = ()
        self.checkpoint_trigger: Optional[Trigger] = None
        self.checkpoint_path: Optional[str] = None
        self.overwrite_checkpoint = True
        # retention/async knobs (None = Config defaults); the manager is
        # built lazily so builder calls in any order all take effect
        self.checkpoint_keep_last: Optional[int] = None
        self.checkpoint_keep_every: Optional[int] = None
        self.checkpoint_async: Optional[bool] = None
        self.preemption_handling = False
        self._ckpt_manager: Optional[CheckpointManager] = None
        self._preemption: Optional[PreemptionHandler] = None
        self._resume_schema: Optional[dict] = None
        self.grad_clip: Optional[Callable] = None
        self.grad_clip_spec: Optional[tuple] = None
        self.train_summary = None
        self.validation_summary = None
        self.metrics = Metrics()
        self.seed = 1
        # K-step dispatch fusion; None = Engine/config default
        self.steps_per_dispatch: Optional[int] = None
        # workload tag (set_workload): the tuned_configs.json key this
        # run's knob defaults resolve under; None = only the
        # process-wide Engine.set_workload tag (if any) applies
        self.workload: Optional[str] = None

        # driver state (reference: the state Table inside OptimMethod —
        # epoch/neval survive checkpoint/resume)
        self.state: dict = {"epoch": 0, "neval": 0,
                            "records_processed_this_epoch": 0}
        # telemetry (bigdl_tpu/telemetry): None = resolve from Config at
        # optimize(); set_telemetry overrides per run.  When enabled the
        # driver carries a DriverTelemetry in self._telemetry — tracer
        # spans per pipeline phase, recompile/stall/memory watchdogs —
        # all host-side and provably inert (no dispatch, no sync).
        self.telemetry_enabled: Optional[bool] = None
        self.telemetry_trace_path: Optional[str] = None
        self._telemetry: Optional[DriverTelemetry] = None
        # flight recorder (bigdl_tpu/telemetry/flight): None — the
        # provably-inert state — unless Config.flight_recorder_path is
        # set; resolved per run by _train_driver.  Driver events
        # (checkpoint commits, rollbacks, numeric-guard hits,
        # preemption, crashes) land there with the run's trace_id.
        self._flight = None
        # admin-plane source name, minted once per optimizer (stable
        # across this optimizer's runs, unique across optimizers)
        self._admin_name: Optional[str] = None
        self._eval_fwd = None  # cached jit'd eval forward
        self._resume_opt_state = None  # optimizer state restored on retry
        self.compute_dtype = None  # None = full f32; jnp.bfloat16 for MXU
        # activation-memory policy (set_activation_memory): "none" =
        # inert (bitwise-identical driver), else remat and/or bf16
        # activation storage for HBM-bound workloads.  None = setter
        # never called — resolved through the default chain (env/tuned
        # entry may apply; _resolved_activation_memory)
        self.activation_memory: Optional[str] = None
        # numeric-failure policy (set_numeric_guard): "off" | "skip" |
        # "rollback" | "abort" — see bigdl_tpu/resilience/numeric.py.
        # None = setter never called; Config.numeric_guard /
        # BIGDL_TPU_NUMERIC_GUARD applies.
        self.numeric_guard: Optional[str] = None
        # fault injection (bigdl_tpu/resilience/faults): None unless a
        # Config.fault_plan is live — EVERY driver fault site below
        # guards on that, so the disabled path is byte-identical
        self._fault_injector: Optional[FaultInjector] = None
        self._guard_policy = "off"  # resolved per run by _train_driver
        self._dispatch_count = 0  # jit dispatches issued (observability)
        self._stager: Optional[DeviceBlockStager] = None
        self._epoch_size = 0
        # elastic training (bigdl_tpu/resilience/membership): None —
        # the provably-inert state — unless a membership fault clause
        # or DistriOptimizer.set_elastic() arms one.  Every membership
        # site below guards on that, so a plan-free run builds no
        # membership object and no roster check.
        self._membership: Optional[ClusterMembership] = None
        # monotonic() timestamp of the last MembershipChanged detection
        # — the resumed run observes resilience/resize_downtime_s from
        # it once the driver is staging again
        self._resize_t0: Optional[float] = None

    # ------------------------------------------------------------- builder
    def set_optim_method(self, method: OptimMethod) -> "Optimizer":
        self.optim_method = method
        return self

    def set_end_when(self, trigger: Trigger) -> "Optimizer":
        self.end_when = trigger
        return self

    def set_validation(self, trigger: Trigger, dataset: AbstractDataSet,
                       methods: Sequence[ValidationMethod],
                       batch_size: Optional[int] = None) -> "Optimizer":
        self.validation_trigger = trigger
        self.validation_methods = list(methods)
        if batch_size is not None:
            # re-batch: reference scripts pass a validation batch size
            # (Optimizer.setValidation(batchSize) overload)
            from bigdl_tpu.dataset.transformer import SampleToMiniBatch
            dataset = dataset >> SampleToMiniBatch(
                batch_size, drop_remainder=False)
        self.validation_dataset = dataset
        return self

    def set_checkpoint(self, path: str, trigger: Trigger,
                       keep_last: Optional[int] = None,
                       keep_every: Optional[int] = None,
                       async_save: Optional[bool] = None) -> "Optimizer":
        """Snapshot the FULL training state to ``path/model.<neval>``
        whenever ``trigger`` fires (reference ``setCheckpoint``, now
        backed by :mod:`bigdl_tpu.checkpoint`): atomic + checksummed,
        committed on a background writer (``async_save``, default
        ``Config.checkpoint_async``), retained per ``keep_last`` /
        ``keep_every`` (defaults ``Config.checkpoint_keep_last/
        _keep_every``)."""
        self.checkpoint_path = path
        self.checkpoint_trigger = trigger
        self.checkpoint_keep_last = keep_last
        self.checkpoint_keep_every = keep_every
        self.checkpoint_async = async_save
        if self._ckpt_manager is not None:
            # stop the old manager's writer thread — reconfiguring must
            # not strand a parked daemon per call
            self._ckpt_manager.close(raise_errors=False)
        self._ckpt_manager = None  # rebuilt with the new settings
        return self

    def over_write_checkpoint(self, enabled: bool = True) -> "Optimizer":
        """Allow (default) or forbid overwriting an existing
        ``model.<neval>`` file — the reference's ``overWriteCheckpoint``
        flag, both directions now real: with ``enabled=False`` a
        colliding save raises ``FileExistsError`` instead of silently
        replacing the older run's snapshot."""
        self.overwrite_checkpoint = bool(enabled)
        if self._ckpt_manager is not None:
            self._ckpt_manager.overwrite = self.overwrite_checkpoint
        return self

    def set_preemption_handling(self, enabled: bool = True) -> "Optimizer":
        """Install a SIGTERM/SIGINT handler for the duration of
        ``optimize()``: on signal the driver finishes the in-flight
        block, writes one final synchronous snapshot to the checkpoint
        path, and returns cleanly with ``state["preempted"] = True``
        (requires ``set_checkpoint``).  Resume with :meth:`resume`."""
        self.preemption_handling = bool(enabled)
        return self

    # replay-boundary: run start — nothing is in flight before optimize()
    def resume(self, path: Optional[str] = None) -> bool:
        """Restore the latest VALID snapshot (corrupt/torn ones are
        skipped, never loaded) from the configured checkpoint directory
        into this optimizer: model params/state, optimizer state
        (schema-validated at ``optimize()``), driver counters, RNG seed
        and dataset shuffle position — the next ``optimize()`` continues
        mid-epoch exactly.  Returns False when no snapshot exists."""
        if not self.checkpoint_path:
            raise ValueError("resume() needs set_checkpoint(path, ...) "
                             "so there is a directory to resume from")
        mgr = self._checkpoint_manager()
        verified = path is None
        ckpt = path if path is not None else mgr.latest_valid()
        if ckpt is None:
            return False
        mgr.restore_into(self, ckpt, verified=verified)
        logger.info("resumed from %s (iteration %d)", ckpt,
                    self.state.get("neval", 0))
        return True

    def set_gradient_clipping_by_value(self, min_v: float,
                                       max_v: float) -> "Optimizer":
        self.grad_clip = lambda g: clip_by_value(g, min_v, max_v)
        # structured mirror of the closure: the grad_sync step clips
        # OWNED SLICES of the reduced gradient, so it needs the clip
        # kind/bounds, not an opaque pytree callable
        self.grad_clip_spec = ("value", min_v, max_v)
        return self

    def set_gradient_clipping_by_l2_norm(self, max_norm: float) -> "Optimizer":
        self.grad_clip = lambda g: clip_by_global_norm(g, max_norm)
        self.grad_clip_spec = ("norm", max_norm)
        return self

    def disable_gradient_clipping(self) -> "Optimizer":
        self.grad_clip = None
        self.grad_clip_spec = None
        return self

    def set_train_summary(self, summary) -> "Optimizer":
        self.train_summary = summary
        return self

    def set_val_summary(self, summary) -> "Optimizer":
        self.validation_summary = summary
        return self

    def set_seed(self, seed: int) -> "Optimizer":
        self.seed = seed
        return self

    def set_compute_dtype(self, dtype) -> "Optimizer":
        """Mixed precision: fwd/bwd in ``dtype`` (bf16 for the MXU), master
        params + optimizer update stay f32.  See utils/precision.py."""
        self.compute_dtype = dtype
        return self

    _ACTIVATION_POLICIES = ("none", "bf16", "dots", "full", "bf16+dots",
                            "bf16+full")

    def set_activation_memory(self, policy: Optional[str]) -> "Optimizer":
        """Trade MXU headroom for HBM traffic on workloads pinned to
        the memory wall (BENCH: hbm_floor_fraction > 0.9).

        ``policy``:

        - ``None`` / ``"none"`` — inert: the step function is built
          exactly as before (bitwise-identical loss sequence, same
          dispatch count).
        - ``"dots"`` — selective rematerialization via
          ``jax.checkpoint(policy=checkpoint_dots)``: matmul outputs
          are saved, everything elementwise is recomputed in the
          backward instead of round-tripping through HBM.
        - ``"full"`` — full rematerialization
          (``nothing_saveable``): only the step inputs are saved; the
          whole forward is recomputed during the backward.  Exact math
          — remat changes WHAT is stored, never what is computed, so
          the loss trajectory is unchanged to float rounding (XLA may
          fuse the recomputed chain differently).
        - ``"bf16"`` — bf16 activation storage: forward/backward
          compute (and therefore every stored activation) in bf16 via
          the mixed-precision loss path; master params, gradients as
          applied, and the optimizer update stay f32.  A no-op when
          ``set_compute_dtype(bf16)`` is already active.
        - ``"bf16+dots"`` / ``"bf16+full"`` — both.

        Only activation dtypes/remat change — never params or update
        math (gated in tests/test_pallas_kernels.py)."""
        if policy is not None and policy not in self._ACTIVATION_POLICIES:
            raise ValueError(
                f"activation memory policy must be one of "
                f"{self._ACTIVATION_POLICIES} or None, got {policy!r}")
        # an explicit None IS the inert policy, not "unset": it must
        # override an env/tuned default the same way "none" does
        # (self.activation_memory stays None only when this setter was
        # never called — the one state the default chain may fill)
        self.activation_memory = "none" if policy is None else policy
        return self

    def set_numeric_guard(self, policy: Optional[str]) -> "Optimizer":
        """Non-finite loss/gradient policy for this run (overrides
        ``Config.numeric_guard`` / ``BIGDL_TPU_NUMERIC_GUARD``):

        - ``None`` / ``"off"`` — inert: the step function and the
          replay fetch are built exactly as before (bitwise loss
          sequence, equal dispatch count; gated in
          tests/test_resilience.py).
        - ``"skip"`` — the jit'd step gates its own update: on a
          non-finite loss or gradient the params / model-state /
          optimizer-state updates are ``jnp.where``-selected away ON
          DEVICE (the dynamic-loss-scaling skip idiom), the step is
          counted in ``resilience/steps_skipped``, training continues.
        - ``"rollback"`` — the replay raises
          :class:`~bigdl_tpu.resilience.NonFiniteStepError`; the
          optimizer restores the latest VALID snapshot
          (``CheckpointManager.latest_valid``) and re-runs, bounded by
          ``Config.failure_retry_times`` — automatic loss-spike
          recovery (requires ``set_checkpoint``; refused loudly at
          ``optimize()`` otherwise).
        - ``"abort"`` — the run fails loudly at the exact iteration.

        The per-step finite flags ride the SAME one-block-behind fetch
        as the loss vector — no policy adds a host sync."""
        # explicit None IS the inert policy, not "unset" (the
        # set_activation_memory contract): it must override an
        # env-provided policy the same way "off" does
        self.numeric_guard = "off" if policy is None \
            else validate_policy(policy)
        return self

    def set_steps_per_dispatch(self, k: int) -> "Optimizer":
        """Fuse ``k`` consecutive train steps into one jit dispatch
        (``lax.scan`` over stacked microbatches).  Loss trajectory and
        trigger cadence are K-invariant; raise it when the per-step
        compute is small enough that host dispatch shows up in the step
        time (BENCH: PTB-LSTM, Wide&Deep)."""
        if int(k) < 1:
            raise ValueError(f"steps_per_dispatch must be >= 1, got {k}")
        self.steps_per_dispatch = int(k)
        return self

    def set_workload(self, tag: Optional[str]) -> "Optimizer":
        """Tag this run's workload (``"ptb_lstm"``, ``"wide_deep"``, …)
        so autotuned defaults from ``tuned_configs.json`` apply to any
        knob still at its dataclass default: ``steps_per_dispatch``,
        ``activation_memory`` and (DistriOptimizer) the grad-sync
        wire/bucket knobs resolve through

            explicit setter > ``BIGDL_TPU_*`` env >
            tuned_configs.json[``tag@backend``] > dataclass default

        (``utils/tuned.resolve_default``).  With no tuned entry for the
        tag — or no tuned file at all — tagging is provably inert
        (bitwise loss sequence, equal dispatch count; gated in
        tests/test_autotune.py).  ``kernel_impl`` is resolved at MODEL
        construction, before an optimizer exists — use
        ``Engine.set_workload`` for that knob."""
        self.workload = tag
        return self

    def set_telemetry(self, enabled: bool = True,
                      trace_path: Optional[str] = None) -> "Optimizer":
        """Enable/disable the telemetry subsystem for this run
        (overrides ``Config.telemetry_enabled`` / ``BIGDL_TPU_TELEMETRY``).
        ``trace_path``: write the Chrome-trace JSON there when training
        ends (summarize with ``python -m tools.trace_report``)."""
        self.telemetry_enabled = bool(enabled)
        if trace_path is not None:
            self.telemetry_trace_path = trace_path
        return self

    def telemetry_snapshot(self) -> Optional[dict]:
        """Registry + watchdog snapshot of the (last) telemetry-enabled
        run; None when telemetry was off."""
        return self._telemetry.snapshot() if self._telemetry else None

    def set_state(self, state: dict) -> "Optimizer":
        """Resume driver state (epoch/neval) from a checkpoint."""
        self.state.update(state)
        return self

    # ------------------------------------------------------------ factory
    @staticmethod
    def create(model: Module, dataset: AbstractDataSet, criterion: Criterion,
               distributed: Optional[bool] = None, **kw):
        """(reference ``Optimizer.apply`` factories, ``Optimizer.scala:597+``)"""
        from bigdl_tpu.optim.distri_optimizer import DistriOptimizer
        if distributed is None:
            distributed = jax.device_count() > 1
        cls = DistriOptimizer if distributed else LocalOptimizer
        return cls(model, dataset, criterion, **kw)

    def optimize(self) -> Module:
        raise NotImplementedError

    # ------------------------------------------------------------- shared
    def _resolved_activation_memory(self) -> str:
        """Per-run ``set_activation_memory`` wins; otherwise the
        default chain (``configure()``/``BIGDL_TPU_ACTIVATION_MEMORY``
        > tuned entry for this run's workload tag > ``"none"``).  A
        garbage value arriving through env or a tuned file fails
        loudly here, same as the setter would."""
        if self.activation_memory is not None:
            return self.activation_memory
        from bigdl_tpu.utils.tuned import resolve_default
        policy, src = resolve_default(
            "activation_memory",
            workload=self.workload or Engine.workload())
        if policy not in self._ACTIVATION_POLICIES:
            raise ValueError(
                f"activation_memory {policy!r} (from {src}) must be "
                f"one of {self._ACTIVATION_POLICIES}")
        return policy

    def _resolved_numeric_guard(self) -> str:
        """Per-run ``set_numeric_guard`` wins; otherwise
        ``Config.numeric_guard`` (a garbage env value fails loudly
        here, same as the setter would)."""
        if self.numeric_guard is not None:
            return self.numeric_guard
        from bigdl_tpu.utils.config import get_config
        return validate_policy(get_config().numeric_guard,
                               source="Config.numeric_guard")

    def _loss_and_grad_fn(self):
        model, criterion = self.model, self.criterion
        policy = self._resolved_activation_memory()
        compute_dtype = self.compute_dtype
        if policy.startswith("bf16"):
            if compute_dtype is not None and compute_dtype != jnp.bfloat16:
                # refusing beats silently dropping the requested
                # storage downcast: an explicit non-bf16 compute dtype
                # contradicts a bf16 activation policy
                raise ValueError(
                    f"activation memory policy {policy!r} "
                    f"conflicts with set_compute_dtype({compute_dtype}) "
                    f"— bf16 activation storage IS bf16 compute; drop "
                    f"one of the two settings")
            # bf16 activation storage: stored residuals are bf16 because
            # the fwd/bwd compute is — params/update stay f32 by the
            # mixed-precision contract (utils/precision.py)
            compute_dtype = jnp.bfloat16
        if compute_dtype is not None:
            from bigdl_tpu.utils.precision import mixed_precision_loss_fn
            loss_fn = mixed_precision_loss_fn(model, criterion,
                                              compute_dtype)
        else:
            def loss_fn(params, mstate, x, y, rng):
                out, new_mstate = model.apply(params, mstate, x,
                                              training=True, rng=rng)
                return criterion.apply(out, y), new_mstate

        # per-layer L1/L2 penalties (reference Regularizer.scala applies
        # them inside accGradParameters; here they enter the loss so
        # jax.grad produces the identical gradient contribution)
        from bigdl_tpu.nn.regularizers import (has_regularizers,
                                               regularization_loss)
        if has_regularizers(model):
            base = loss_fn

            def loss_fn(params, mstate, x, y, rng, _base=base):
                loss, new_mstate = _base(params, mstate, x, y, rng)
                return loss + regularization_loss(model, params), \
                    new_mstate

        if policy.endswith("dots") or policy.endswith("full"):
            # selective remat over the whole loss computation: "dots"
            # saves matmul outputs and recomputes the elementwise chain
            # in the backward; "full" saves only the step inputs.
            # Exact math either way — only the residual set changes.
            remat_policy = (jax.checkpoint_policies.dots_saveable
                            if policy.endswith("dots") else
                            jax.checkpoint_policies.nothing_saveable)
            loss_fn = jax.checkpoint(loss_fn, policy=remat_policy)

        return jax.value_and_grad(loss_fn, has_aux=True)

    def _fast_forward(self, data_iter, state):
        """Mid-epoch resume: skip the samples already processed this epoch
        so the epoch boundary (and shuffle cadence) stays correct
        (reference: recordsProcessedThisEpoch in the OptimMethod state
        table, ``DistriOptimizer.scala:124-134``).

        ``records_processed_this_epoch`` counts GLOBAL records (the
        replay adds ``n_local * scale``); the iterator here yields this
        host's LOCAL batches, so the skip budget is the global count
        divided back by the records scale (process_count under
        multi-host SPMD — every host skips its own 1/P share).  The
        counter must divide EVENLY: under an elastic resume P may have
        changed since the snapshot, and a remainder means this host's
        share is not expressible in whole records — silently flooring
        would mis-position the dataset (the PR-7 fix assumed a
        constant P)."""
        scale = max(1, self._records_scale())
        rec = state.get("records_processed_this_epoch", 0)
        if rec % scale:
            raise ValueError(
                f"mid-epoch resume: the snapshot's global records "
                f"counter ({rec}) does not divide by this run's records "
                f"scale ({scale}) — the world size/process count "
                f"changed since the snapshot was written and the "
                f"per-host skip would mis-position the dataset; resume "
                f"at a compatible scale or from an epoch boundary")
        skip = rec // scale
        from bigdl_tpu.dataset.prefetch import fast_forward_records
        skipped = fast_forward_records(data_iter, skip)
        if skipped:
            logger.info("resume: skipped %d already-processed local "
                        "records (of %d global)", skipped, skip * scale)

    def _tel_span(self, name: str, cat: str, **args):
        """Tracer span when telemetry is on; shared no-op otherwise —
        the off path allocates nothing."""
        tel = self._telemetry
        if tel is None:
            return NULL_SPAN
        return tel.tracer.span(name, cat=cat, **args)

    def _flight_event(self, event: str, **fields) -> None:
        """Record one driver event in the flight recorder (no-op when
        none is live), carrying the run's trace context when telemetry
        is on — the join key ``tools/obs_report.py`` correlates by."""
        fl = self._flight
        if fl is not None:
            tel = self._telemetry
            fl.record(event, cat="driver",
                      trace_id=(tel.trace_id if tel is not None
                                else None), **fields)

    def _checkpoint_manager(self) -> CheckpointManager:
        if self._ckpt_manager is None:
            from bigdl_tpu.utils.config import get_config
            cfg = get_config()
            pick = lambda v, d: d if v is None else v  # noqa: E731
            self._ckpt_manager = CheckpointManager(
                self.checkpoint_path,
                keep_last=pick(self.checkpoint_keep_last,
                               cfg.checkpoint_keep_last),
                keep_every=pick(self.checkpoint_keep_every,
                                cfg.checkpoint_keep_every),
                overwrite=self.overwrite_checkpoint,
                async_save=pick(self.checkpoint_async,
                                cfg.checkpoint_async),
                registry=self.metrics.registry)
        return self._ckpt_manager

    def _checkpoint_schema(self, params) -> dict:
        """Manifest schema of THIS run's training state (the SPMD
        subclass adds the grad_sync bucket plan)."""
        return build_schema(
            params, optim_method=type(self.optim_method).__name__)

    def _model_params_schema(self) -> Optional[dict]:
        """Shape/dtype fingerprint of THIS model's params — live params
        when present, else ``jax.eval_shape`` over init (no compute) —
        so ``CheckpointManager.restore_into`` can refuse an
        architecture-drifted snapshot BEFORE overwriting the model."""
        from bigdl_tpu.checkpoint.schema import describe_params
        if self.model._params is not None:
            return describe_params(self.model._params)
        try:
            shapes = jax.eval_shape(
                lambda r: self.model.init(r)[0], jax.random.PRNGKey(0))
        except Exception:  # init not eval_shape-able: the full-schema
            return None    # check at optimize() still runs
        return describe_params(shapes)

    def _validate_resume_schema(self, params) -> None:
        """Diff the restored snapshot's schema against this run —
        grad_sync flips, bucket-plan drift, and architecture drift fail
        loudly here instead of as a jit structure error.  An elastic
        run validates in elastic-compat mode: world-size/bucket-padding
        drift is the point, logical identity stays strict."""
        saved, self._resume_schema = self._resume_schema, None
        if saved is not None:
            validate_schema(saved, self._checkpoint_schema(params),
                            elastic=self._membership is not None)

    def _arm_membership_from_plan(self, faults) -> None:
        """Arm the membership layer when the fault plan carries
        ``resize``/``host_loss``/``device_loss`` clauses.  The base
        (single-device) trainer cannot resize — membership clauses in
        its plan are a configuration error, refused loudly instead of
        silently never firing.  DistriOptimizer overrides with the real
        arming (mesh roster → ClusterMembership)."""
        if faults is None or not faults.has_membership_kinds():
            return
        raise ValueError(
            "fault plan contains membership kinds (resize/host_loss/"
            "device_loss) but this is a LocalOptimizer — elastic "
            "training needs DistriOptimizer's device mesh to resize "
            "over")

    def _apply_membership_clause(self, clause) -> None:
        """Translate one fired membership fault clause into the
        corresponding ClusterMembership signal (the injector stays free
        of roster knowledge)."""
        m = self._membership
        if clause.kind == "resize":
            m.request_resize(clause.to)
        elif clause.kind == "host_loss":
            m.signal_host_loss(to=clause.to)
        else:  # device_loss
            m.signal_device_loss(to=clause.to)

    def _maybe_checkpoint(self, params, mstate, ostate):
        # the trigger reads only driver counters, which advance in
        # lockstep on every process (the replay adds the same global
        # increments)  # replicated-by: lockstep-driver-counters
        if self.checkpoint_trigger and self.checkpoint_path \
                and self.checkpoint_trigger(self.state):
            with self._tel_span("checkpoint", "trigger",
                                neval=self.state["neval"]):
                self._do_checkpoint(params, mstate, ostate)

    def _do_checkpoint(self, params, mstate, ostate,
                       sync: bool = False) -> None:
        """Snapshot the full training state at the CURRENT replayed
        iteration.  Called only at replay boundaries, where the
        one-block-behind loss fetch has already synced the producing
        block — the capture inside ``CheckpointManager.save`` is a
        D2H copy, never a pipeline drain (GL107 discipline)."""
        # spmdcheck: checkpoint capture gathers sharded state — every
        # process must reach it at the same replayed iteration
        spmdcheck.note("checkpoint", payload=params)
        mgr = self._checkpoint_manager()
        pos = getattr(self.dataset, "position_state", None)
        run_state = {"seed": self.seed,
                     "dataset_position": pos() if pos is not None else None}
        mgr.save(self.state["neval"], params, mstate, ostate,
                 driver_state=dict(self.state), run_state=run_state,
                 schema=self._checkpoint_schema(params), sync=sync)

    def _run_validation(self, params, mstate) -> Optional[dict]:
        # same lockstep counters as the checkpoint trigger: validation
        # (a collective under multi-host eval) fires on every process
        # or none  # replicated-by: lockstep-driver-counters
        if not (self.validation_trigger and self.validation_methods
                and self.validation_dataset is not None
                and self.validation_trigger(self.state)):
            return None
        with self._tel_span("validation", "trigger",
                            neval=self.state["neval"]):
            results = self.evaluate_with(params, mstate)
        for name, res in results.items():
            logger.info("validation %s = %s", name, res)
            if self.validation_summary is not None:
                self.validation_summary.add_scalar(name, res.result,
                                                   self.state["neval"])
        # expose primary score to triggers; feed metric-driven schedules
        # (Plateau) exactly once per validation — NOT once per iteration
        first = next(iter(results.values()))
        self.state["score"] = first.result
        sched = self.optim_method.learning_rate_schedule
        if sched is not None and hasattr(sched, "record"):
            sched.record(first.result)
        return results

    # ------------------------------------------------- train-loop hooks
    # DistriOptimizer overrides these to shard the work over the mesh;
    # the driver loop itself lives only here.
    def _place_train_block(self, xs, ys):
        """Host-stacked (K, batch, ...) trees → device arrays."""
        xs = tmap(jnp.asarray, xs)
        ys = None if ys is None else tmap(jnp.asarray, ys)
        return xs, ys

    def _records_scale(self) -> int:
        """Host-local batch rows → global records (process_count under
        multi-host SPMD)."""
        return 1

    def _constrain_step_outputs(self, params, ostate):
        """Inside the jit'd step, after the optimizer update — the SPMD
        subclass pins output shardings here."""
        return params, ostate

    def _log_train_iteration(self, lr: float) -> None:
        # reference per-iteration log line (DistriOptimizer.scala:388-394)
        s = self.state
        logger.info(
            "epoch %d iter %d loss %.4f lr %.5g throughput %.1f rec/s",
            s["epoch"], s["neval"], s["loss"], lr, s["throughput"])

    def _log_parameter_histograms(self, params) -> None:
        """Trigger-gated per-parameter summaries (SPMD subclass)."""

    # --------------------------------------------------- fused train step
    def _block_body(self, one_step, k: int):
        """Wrap ``one_step(params, mstate, ostate, x, y, lr, step, rng)``
        into the K-block calling convention every block fn shares:
        ``k == 1`` squeezes the leading step axis off ``xs``/``ys`` and
        returns the loss as a length-1 vector; ``k > 1`` runs the step
        under ``lax.scan``.  The returned per-step loss vector is what
        ``_replay_block`` consumes — this wrapper is the ONE place that
        encodes the convention (the SPMD grad_sync block builds on the
        same body, inside a shard_map)."""
        if k == 1:
            def body(params, mstate, ostate, xs, ys, lrs, steps, rngs):
                x = tmap(lambda a: a[0], xs)
                y = None if ys is None else tmap(lambda a: a[0], ys)
                params, mstate, ostate, out = one_step(
                    params, mstate, ostate, x, y, lrs[0], steps[0],
                    rngs[0])
                # `out` is the loss scalar — or (loss, finite) under a
                # live numeric guard; either way every leaf grows the
                # length-1 step axis the replay convention expects
                return params, mstate, ostate, tmap(lambda l: l[None],
                                                    out)
            return body

        def body(params, mstate, ostate, xs, ys, lrs, steps, rngs):
            def scan_body(carry, inp):
                params, mstate, ostate = carry
                x, y, lr, step, rng = inp
                params, mstate, ostate, loss = one_step(
                    params, mstate, ostate, x, y, lr, step, rng)
                return (params, mstate, ostate), loss

            (params, mstate, ostate), losses = jax.lax.scan(
                scan_body, (params, mstate, ostate),
                (xs, ys, lrs, steps, rngs))
            return params, mstate, ostate, losses
        return body

    def _build_block_fn(self, grad_fn, k: int):
        """One jit'd dispatch covering ``k`` consecutive train steps.

        ``k == 1`` stays a straight-line step (identical HLO to the
        classic per-iteration dispatch, minus a leading-axis squeeze);
        ``k > 1`` runs the step under ``lax.scan`` so XLA sees one
        program — no per-iteration dispatch, and donated
        params/mstate/ostate update in place across the whole block.
        Inputs: ``xs``/``ys`` carry a leading ``k`` step axis (sharded
        over `data` on axis 1 in the SPMD path); ``lrs``/``steps``/
        ``rngs`` are per-step vectors so host-side LR schedules never
        retrace.  Returns the per-step loss vector — every iteration
        stays observable to triggers and summaries."""
        grad_clip = self.grad_clip
        optim = self.optim_method
        constrain = self._constrain_step_outputs
        guard = self._resolved_numeric_guard()

        def one_step(params, mstate, ostate, x, y, lr, step, rng):
            (loss, new_mstate), grads = grad_fn(params, mstate, x, y, rng)
            if grad_clip is not None:
                grads = grad_clip(grads)
            if guard == "off":
                # byte-identical to the pre-guard step — the provably
                # inert state (gated in tests/test_resilience.py)
                params, ostate = optim.update(grads, params, ostate, lr,
                                              step)
                params, ostate = constrain(params, ostate)
                return params, new_mstate, ostate, loss
            finite = step_finite(loss, grads)
            new_params, new_ostate = optim.update(grads, params, ostate,
                                                  lr, step)
            new_params, new_ostate = constrain(new_params, new_ostate)
            if guard == "skip":
                # gate the whole update on device: a non-finite step
                # leaves params/mstate/ostate exactly as before it
                return (select_step(finite, new_params, params),
                        select_step(finite, new_mstate, mstate),
                        select_step(finite, new_ostate, ostate),
                        (loss, finite))
            # rollback/abort: update as usual, just report the flag —
            # the replay raises at the exact iteration and recovery
            # discards these params anyway
            return new_params, new_mstate, new_ostate, (loss, finite)

        return jax.jit(self._block_body(one_step, k),
                       donate_argnums=(0, 1, 2))

    # ------------------------------------------------------ driver loop
    def _train_driver(self, params, mstate, ostate, grad_fn, rng):
        """The shared training loop (see module docstring for the
        fusion/pipelining design).  Returns the final (params, mstate,
        ostate) bindings."""
        state = self.state
        k_max = self.steps_per_dispatch \
            or Engine.steps_per_dispatch(workload=self.workload)
        k_max = max(1, int(k_max))
        scale = self._records_scale()
        # telemetry: resolve the enable knob (per-run override → config),
        # share the Metrics registry so phase accumulators + watchdog
        # counters land in one snapshot.  self._telemetry stays None when
        # off — every call site below is gated on that, so the disabled
        # path is byte-identical to the pre-telemetry driver.
        from bigdl_tpu.utils.config import get_config
        cfg = get_config()
        tel_on = (self.telemetry_enabled if self.telemetry_enabled
                  is not None else cfg.telemetry_enabled)
        # flight recorder: None (inert) unless Config.flight_recorder_
        # path is set — every driver event site guards on that
        from bigdl_tpu.telemetry import flight as _flight_mod
        self._flight = _flight_mod.from_config()
        tel = None
        if tel_on:
            tel = self._telemetry = DriverTelemetry(
                registry=self.metrics.registry,
                trace_capacity=cfg.telemetry_trace_capacity,
                trace_path=(self.telemetry_trace_path
                            or cfg.telemetry_trace_path or None),
                flight=self._flight)
        else:
            # drop any bundle from a previous enabled run on this
            # optimizer — _tel_span/_replay_block read self._telemetry,
            # so a stale one would keep recording through an "off" run
            self._telemetry = None
        # admin plane: config-driven (admin_port=0 → None, no thread);
        # the driver registry, tracer, and watchdog verdicts become
        # scrape-able while the run is live.  The source name is
        # unique-per-optimizer (stable across this optimizer's runs) so
        # concurrent drivers don't overwrite each other's registration.
        from bigdl_tpu.telemetry import admin as _admin
        _srv = _admin.maybe_start()
        if _srv is not None:
            if getattr(self, "_admin_name", None) is None:
                self._admin_name = _srv.unique_source_name("driver")
            _srv.add_registry(self._admin_name, self.metrics.registry)
            if tel is not None:
                _srv.add_tracer(self._admin_name, tel.tracer)
                _srv.add_health(self._admin_name, tel.health_snapshot)
            else:
                # a telemetry-off rerun on this optimizer must not
                # leave the PREVIOUS run's tracer/health serving as
                # current — /healthz would report a dead run's
                # watchdog verdicts
                _srv.drop_tracer(self._admin_name)
                _srv.drop_health(self._admin_name)
            if self._flight is not None:
                _srv.set_flight(self._flight)
        # resilience: the numeric-guard policy this run's block fns and
        # replay share, and the fault injector (None — the provably
        # inert state — unless Config.fault_plan is live; every site
        # below guards on that)
        guard = self._guard_policy = self._resolved_numeric_guard()
        if guard == "rollback" and not self.checkpoint_path:
            raise ValueError(
                "numeric_guard='rollback' needs set_checkpoint(path, "
                "trigger) — there is no snapshot to roll back to")
        from bigdl_tpu.utils.config import get_config
        cfg_plan = get_config().fault_plan or ""
        if self._fault_injector is not None \
                and self._fault_injector.plan != cfg_plan:
            # the configured plan CHANGED since this injector was
            # built (a reused optimizer across configure() calls) —
            # honor the knob, including clearing it back to inert
            self._fault_injector = None
        if self._fault_injector is None and cfg_plan:
            # built once per (optimizer, plan), not per attempt: a
            # fault plan describes one timeline of the outside world,
            # so clause firing budgets (count=) must survive the
            # rollback/retry loops re-entering this driver
            self._fault_injector = FaultInjector.from_config(
                registry=self.metrics.registry)
            logger.warning("fault injection live: %s",
                           self._fault_injector.describe())
        faults = self._fault_injector
        # elastic membership: armed only when the plan carries
        # membership kinds or set_elastic() was called — otherwise
        # self._membership stays None and every site below is inert
        self._arm_membership_from_plan(faults)
        membership = self._membership
        if membership is not None and not self.checkpoint_path:
            raise ValueError(
                "elastic training (membership fault kinds / "
                "set_elastic) needs set_checkpoint(path, trigger) — a "
                "resize resumes from the latest valid snapshot")
        # the epoch this driver run dispatches under; the loop compares
        # it against the live epoch at the replay boundary it already
        # crosses — detection costs zero additional host syncs
        run_epoch = membership.epoch() if membership is not None else 0
        # checkpointing: manager built up front so the stall-fraction
        # denominator starts at the run, and preemption (SIGTERM/SIGINT
        # → finish block + final snapshot + clean return) has somewhere
        # to write.  Both are inert when unconfigured.  A previous
        # run's preempted verdict must not leak into this run's state
        # (or its checkpoints).
        state.pop("preempted", None)
        mgr: Optional[CheckpointManager] = None
        if self.checkpoint_path:
            mgr = self._checkpoint_manager()
            mgr.mark_run_start()
            # the manager outlives runs (cached) — stamp THIS run's
            # flight recorder + trace context so its commit events
            # correlate with this run's trace
            mgr.flight = self._flight
            mgr.trace_id = tel.trace_id if tel is not None else None
        epoch_size = self._epoch_size = self.dataset.size()
        data_iter = self.dataset.data(train=True)
        self._fast_forward(data_iter, state)
        stager = DeviceBlockStager(data_iter, self._place_train_block,
                                   tracer=tel.tracer if tel else None)
        self._stager = stager
        if self._resize_t0 is not None:
            # this run is the elastic resume: the driver is about to
            # stage again — the detection→here window is the measured
            # resize downtime
            downtime = time.monotonic() - self._resize_t0
            self._resize_t0 = None
            self.metrics.registry.histogram(
                "resilience/resize_downtime_s").observe(downtime)
            self._flight_event("resize_resumed",
                               downtime_s=round(downtime, 4),
                               iteration=state["neval"],
                               epoch=run_epoch)
        # the Parameters-histogram summary trigger is probed too: its
        # firing iteration must end a sync block so the histogram sees
        # exactly that iteration's params, not the end-of-block binding
        param_trig = getattr(self.train_summary, "trigger_for",
                             lambda _n: None)("Parameters") \
            if self.train_summary is not None else None
        triggers = (self.validation_trigger, self.checkpoint_trigger,
                    self.end_when, param_trig)
        block_fns: dict = {}
        self._dispatch_count = 0
        bsz_hint = 0
        # planning counters: where the driver state WILL be once every
        # dispatched block has been replayed (at most one block ahead)
        p_neval = state["neval"]
        p_epoch = state["epoch"]
        p_records = state["records_processed_this_epoch"]

        def stage_next():
            """Plan (trigger probe + epoch budget) and stage one block.
            Runs right after a dispatch, so the host stacking and the
            asynchronous host→device transfer overlap the in-flight
            block's compute — the double buffer."""
            nonlocal bsz_hint
            t_stage0 = time.perf_counter()
            probe_state = dict(state)
            probe_state.update(
                neval=p_neval, epoch=p_epoch,
                records_processed_this_epoch=p_records)
            fire = probe_fire_step(probe_state, k_max, bsz_hint * scale,
                                   epoch_size, triggers)
            k_plan = fire if fire is not None else k_max
            budget = max(1, -(-(epoch_size - p_records) // scale))
            with self.metrics.time("data"):
                xs, ys, sizes = stager.take(k_plan, budget)
            k = len(sizes)
            if faults is not None:
                # batch-poison fault site (corrupt_batch/nonfinite_grads
                # clauses, keyed by global iteration number) — only ever
                # reached with a live plan
                xs = faults.corrupt_staged(xs, p_neval, k)
            bsz_hint = sizes[0]
            # per-step host scalars, one current_lr call per iteration in
            # order (schedules and the retry tests rely on that cadence)
            lrs = [float(self.optim_method.current_lr(p_neval + j, p_epoch))
                   for j in range(k)]
            # per-step dropout keys are a PURE FUNCTION of (run key,
            # iteration number) — fold_in, not sequential splits — so a
            # mid-epoch resume re-derives exactly the keys the
            # uninterrupted run used (bitwise-resume contract of
            # bigdl_tpu.checkpoint), and the derivation is K-invariant
            keys = [jax.random.fold_in(rng, p_neval + j)
                    for j in range(k)]
            ends_epoch = p_records + sum(sizes) * scale >= epoch_size
            sync = ends_epoch or fire == k
            return _Staged(xs, ys, sizes, lrs,
                           jnp.asarray(np.asarray(lrs, np.float32)),
                           jnp.asarray(np.arange(p_neval, p_neval + k,
                                                 dtype=np.int32)),
                           jnp.stack(keys), sync,
                           stage_s=time.perf_counter() - t_stage0)

        pending: Optional[_InFlight] = None
        staged: Optional[_Staged] = None
        # installed LAST, immediately before the try whose finally
        # uninstalls — an exception anywhere in run setup must never
        # leave the process with hijacked (flag-only) signal handlers
        preempt = None
        if self.preemption_handling and mgr is not None:
            preempt = self._preemption = PreemptionHandler()
            preempt.install()
        try:
            while True:
                # the scheduler evicts the whole slice at once — every
                # host's grace window opens together, so polling the
                # flag at block granularity stays uniform
                # replicated-by: pod-eviction-broadcast
                if preempt is not None and preempt.triggered:
                    # preemption: finish the in-flight block (replay
                    # syncs it — params/state land on an exact block
                    # boundary the uninterrupted run also hits), write
                    # ONE final synchronous snapshot, return cleanly.
                    # The planned-ahead `staged` block is discarded; its
                    # batches are re-derived on resume from the saved
                    # shuffle position + records counter.
                    if pending is not None:
                        self._replay_block(pending, params, mstate,
                                           ostate)
                        pending = None
                    logger.warning(
                        "preemption signal: final snapshot at iteration "
                        "%d, exiting cleanly", state["neval"])
                    # flag-only handler fired; the heavy work (and this
                    # event) runs here on the driver thread — writing
                    # from a signal handler is how dumps get torn
                    self._flight_event("preemption",
                                       iteration=state["neval"])
                    mgr.wait()  # writer idle → no concurrent GC below
                    # every process records the step when a multi-host
                    # checkpoint commits (the PR-7 mirror write in
                    # DistriOptimizer._do_checkpoint), so this dedup
                    # cannot send hosts down different sides of the
                    # allgather  # replicated-by: checkpoint-step-mirror
                    if mgr.last_saved_step != state["neval"]:
                        # a trigger checkpoint that fired on this very
                        # iteration already covers it — don't burn the
                        # grace window on a redundant serialize+fsync
                        # (or trip over_write_checkpoint(False))
                        self._do_checkpoint(params, mstate, ostate,
                                            sync=True)
                    state["preempted"] = True
                    break
                if membership is not None:
                    changed = membership.changed_since(run_epoch)
                    if changed is not None:
                        # resize-on-preemption, riding the replay
                        # boundary the loop already crossed: graceful
                        # changes (resize request / preemption warning)
                        # finish the in-flight block and write a final
                        # synchronous snapshot (PR-7 semantics, zero
                        # steps lost); abrupt device loss abandons it —
                        # the device buffers are gone by assumption —
                        # and the resume pays the steps since the last
                        # snapshot.  The planned-ahead `staged` block is
                        # discarded either way; its batches re-derive
                        # from the saved records counter.
                        t_detect = time.monotonic()
                        if changed.graceful:
                            if pending is not None:
                                self._replay_block(pending, params,
                                                   mstate, ostate)
                                pending = None
                            mgr.wait()  # writer idle → no racing GC
                            # same mirror contract as the preemption
                            # dedup above (see DistriOptimizer.
                            # _do_checkpoint's non-zero-process write)
                            # replicated-by: checkpoint-step-mirror
                            if mgr.last_saved_step != state["neval"]:
                                self._do_checkpoint(params, mstate,
                                                    ostate, sync=True)
                        else:
                            pending = None
                        logger.warning(
                            "membership epoch %d (world %d, %s): "
                            "suspending at iteration %d for elastic "
                            "resume", changed.epoch, changed.world,
                            changed.reason, state["neval"])
                        self._flight_event(
                            "membership_change", epoch=changed.epoch,
                            world=changed.world, reason=changed.reason,
                            graceful=changed.graceful,
                            iteration=state["neval"])
                        raise MembershipChanged(
                            changed, changed.graceful, state["neval"],
                            t_detect)
                if staged is None:
                    if pending is None and self.end_when(state):
                        break
                    staged = stage_next()
                k = len(staged.sizes)
                fn = block_fns.get(k)
                new_fn = fn is None
                if new_fn:
                    fn = block_fns[k] = self._build_block_fn(grad_fn, k)
                # spmdcheck: the fused block is one SPMD program — every
                # process must dispatch the same block shape in the same
                # order or the in-step collectives go one-sided
                spmdcheck.note("dispatch", axis=f"k{k}", payload=staged.xs)
                t0 = time.perf_counter()
                with self._tel_span("dispatch", "dispatch", k=k,
                                    compile=new_fn):
                    if faults is None:
                        params, mstate, ostate, losses = fn(
                            params, mstate, ostate, staged.xs, staged.ys,
                            staged.lrs_dev, staged.steps_dev,
                            staged.rngs_dev)
                    else:
                        # dispatch fault site + bounded retry-with-
                        # backoff: the injector fires BEFORE the jit
                        # call, so a retried attempt still owns every
                        # donated buffer (a post-donation error is not
                        # transiently retryable — the inputs are gone)
                        params, mstate, ostate, losses = \
                            self._dispatch_with_retry(
                                lambda: fn(params, mstate, ostate,
                                           staged.xs, staged.ys,
                                           staged.lrs_dev,
                                           staged.steps_dev,
                                           staged.rngs_dev),
                                self._dispatch_count)
                self._dispatch_count += 1
                if tel is not None:
                    # recompile watchdog: the first compile of each block
                    # length k is the planned one; cache growth after
                    # that is a steady-state retrace (GL106 at runtime)
                    tel.recompile.observe(("block_fn", k),
                                          jit_cache_size(fn))
                block = _InFlight(losses, staged.sizes, staged.lrs, t0,
                                  stage_s=staged.stage_s,
                                  dispatch_s=time.perf_counter() - t0,
                                  first_compile=new_fn)
                p_neval += k
                p_records += sum(staged.sizes) * scale
                if p_records >= epoch_size:
                    p_epoch += 1
                    p_records = 0
                sync = staged.sync
                # double-buffer: next block's H2D lands while this one
                # runs (a sync block ends at a boundary the replay must
                # handle — shuffle/validation/stop — before any further
                # staging)
                staged = stage_next() if not sync else None
                if pending is not None:
                    ended = self._replay_block(pending, params, mstate,
                                               ostate)
                    pending = None
                    if ended:
                        break
                if sync:
                    if self._replay_block(block, params, mstate, ostate):
                        break
                else:
                    pending = block
        finally:
            run_failing = sys.exc_info()[0] is not None
            if run_failing:
                etype = sys.exc_info()[0]
                if not (isinstance(etype, type)
                        and issubclass(etype, MembershipChanged)):
                    # the black box's raison d'être: the crash is on
                    # disk (the recorder flushes per event) even if
                    # nothing below gets to run.  A membership change
                    # is a measured event, not a crash — it already
                    # recorded membership_change above.
                    self._flight_event("run_crash",
                                       error=getattr(etype, "__name__",
                                                     str(etype)),
                                       iteration=state["neval"])
            if preempt is not None:
                preempt.uninstall()
            if tel is not None:
                # dump the Chrome trace even on an interrupted run — a
                # crash timeline is precisely when you want the trace
                tel.finalize()
            if mgr is not None:
                # drain pending async snapshot writes so optimize()
                # returning means the checkpoints EXIST; a deferred
                # write error fails the run loudly — unless the run is
                # already failing (don't mask the original exception)
                try:
                    mgr.wait()
                except Exception:
                    if not run_failing:
                        raise
                    logger.exception(
                        "async checkpoint write also failed during "
                        "teardown of an already-failing run")
        return params, mstate, ostate

    def _on_nonfinite_step(self, j: int, losses) -> None:
        """One replayed iteration carried a non-finite loss/grad flag.
        ``skip``: the update was already gated away on device — count
        it and move on.  ``rollback``/``abort``: raise at the exact
        iteration (rollback is caught by the optimize() recovery loop,
        abort surfaces to the caller).  Reports the 0-based global step
        index — the same index fault plans (``corrupt_batch@at=N``) and
        lr schedules see, one less than the just-incremented
        ``state["neval"]`` completion count."""
        policy = self._guard_policy
        step = self.state["neval"] - 1
        reg = self.metrics.registry
        reg.counter("resilience/nonfinite_steps").inc()
        if policy == "skip":
            reg.counter("resilience/steps_skipped").inc()
            if self._telemetry is not None:
                self._telemetry.tracer.instant(
                    "nonfinite_step_skipped", cat="resilience",
                    step=step)
            self._flight_event("nonfinite_step", step=step,
                               policy="skip", loss=float(losses[j]))
            logger.warning(
                "non-finite step at iteration %d (loss=%s) — update "
                "skipped on device", step, float(losses[j]))
            return
        self._flight_event("nonfinite_step", step=step, policy=policy,
                           loss=float(losses[j]))
        raise NonFiniteStepError(step, float(losses[j]), policy)

    # replay-boundary: the failed block is torn down before the restore
    def _rollback_nonfinite(self, e: NonFiniteStepError,
                            attempts: int, retry_budget: int) -> None:
        """``numeric_guard="rollback"`` recovery shared by both
        drivers: restore the latest VALID snapshot, or re-raise ``e``
        (policy isn't rollback, budget spent, no checkpointing, or
        nothing valid on disk).  The ``resilience/rollbacks`` counter
        is bumped only once a restorable snapshot is in hand — it
        audits restores that actually happened."""
        if e.policy != "rollback":
            raise e
        if attempts > retry_budget or not self.checkpoint_path:
            raise e
        mgr = self._checkpoint_manager()
        mgr.wait()  # writer idle: see every committed snapshot
        ckpt = mgr.latest_valid()
        if ckpt is None:
            raise e
        self.metrics.registry.counter("resilience/rollbacks").inc()
        if self._telemetry is not None:
            self._telemetry.tracer.instant(
                "rollback", cat="resilience", step=e.step, ckpt=ckpt)
        self._flight_event("rollback", step=e.step, ckpt=ckpt,
                           attempt=attempts)
        logger.warning(
            "non-finite step at iteration %d; rollback %d/%d from %s",
            e.step, attempts, retry_budget, ckpt)
        mgr.restore_into(self, ckpt, verified=True)

    def _dispatch_with_retry(self, fire, index: int):
        """Bounded retry-with-backoff around one block dispatch, only
        reached when fault injection is live.  The injector's driver
        site raises BEFORE ``fire()`` runs, so a retried attempt still
        owns the donated buffers; ``InjectedFault`` is transient by
        construction, so retrying it is exactly the degradation path a
        real transient dispatch failure (preempted ICI, momentary
        RESOURCE_EXHAUSTED) would take."""
        from bigdl_tpu.utils.config import get_config
        retries = get_config().failure_retry_times
        faults = self._fault_injector
        attempt = 0
        while True:
            try:
                faults.driver_dispatch(index)
                return fire()
            except InjectedFault:
                attempt += 1
                self.metrics.registry.counter(
                    "resilience/dispatch_retries").inc()
                if attempt > retries:
                    raise
                backoff = min(0.01 * (2.0 ** (attempt - 1)), 1.0)
                logger.warning(
                    "transient dispatch failure at dispatch %d; retry "
                    "%d/%d in %.0f ms", index, attempt, retries,
                    backoff * 1e3)
                time.sleep(backoff)

    def _replay_block(self, block: _InFlight, params, mstate, ostate):
        """Fetch a dispatched block's per-step losses (the driver's only
        device→host sync — one block behind the dispatch on the steady
        path) and replay its iterations through the driver state:
        per-iteration logging/summaries, epoch rollover (shuffle + fresh
        iterator, exactly as the unfused loop did), validation and
        checkpoint triggers at their exact iteration numbers, and the
        end_when check.  Returns True when training should stop."""
        tel = self._telemetry
        t_wait0 = time.perf_counter()
        # spmdcheck: the fetch syncs the producing block on every
        # process — a one-sided fetch deadlocks the block's collectives
        spmdcheck.note("block_fetch", payload=block.losses)
        with self.metrics.time("computing"), \
                self._tel_span("device_wait", "device_wait",
                               steps=len(block.sizes)):
            # the driver's one and only device→host sync: the
            # one-block-behind loss fetch (GL107-safe — the span wraps
            # the fetch the driver already performs, never adds one).
            # Under a live numeric guard the block returns
            # (losses, finite_flags) — the flags ride the SAME fetch,
            # so no policy adds a sync
            fetched = jax.device_get(block.losses)
        t_wait1 = time.perf_counter()
        if isinstance(fetched, tuple):
            losses, finite = np.asarray(fetched[0]), np.asarray(fetched[1])
        else:
            losses, finite = np.asarray(fetched), None
        if tel is not None:
            # the block's in-flight window (dispatch → losses landed) on
            # a virtual "device" track, so Perfetto shows device blocks
            # overlapping the host phases without breaking span nesting
            tel.tracer.record("block_inflight", int(block.t0 * 1e9),
                              int(t_wait1 * 1e9), cat="pipeline",
                              track="device", steps=len(block.sizes))
        per_step = (time.perf_counter() - block.t0) / len(block.sizes)
        state = self.state
        scale = self._records_scale()
        ended = False
        t_replay0 = time.perf_counter()
        with self._tel_span("replay", "replay", steps=len(block.sizes)):
            for j, n_local in enumerate(block.sizes):
                n = n_local * scale
                state["neval"] += 1
                state["records_processed_this_epoch"] += n
                state["loss"] = float(losses[j])
                state["throughput"] = n / per_step
                # finite flags ride the psum'd global loss — every
                # process fetches the same reduced values
                # replicated-by: global-loss-reduction
                if finite is not None and not finite[j]:
                    self._on_nonfinite_step(j, losses)
                lr = block.lrs[j]
                self._log_train_iteration(lr)
                if self.train_summary is not None:
                    self.train_summary.add_train_step(
                        state["neval"], state["loss"], lr,
                        state["throughput"])
                    self._log_parameter_histograms(params)
                state["epoch_finished"] = \
                    state["records_processed_this_epoch"] >= self._epoch_size
                # the records counter advances by GLOBAL records, so
                # epoch rollover (shuffle + iterator reset) is uniform
                # replicated-by: lockstep-driver-counters
                if state["epoch_finished"]:
                    state["epoch"] += 1
                    state["records_processed_this_epoch"] = 0
                    self.dataset.shuffle()
                    self._stager.reset(self.dataset.data(train=True))
                self._run_validation(params, mstate)
                self._maybe_checkpoint(params, mstate, ostate)
                state["epoch_finished"] = False
                if self._fault_injector is not None \
                        and self._membership is not None:
                    # membership fault site (resize/host_loss/
                    # device_loss clauses, keyed by the same 0-based
                    # global iteration number as the batch kinds) —
                    # the signal lands here; the driver loop detects
                    # the epoch change at its next replay boundary
                    for clause in self._fault_injector \
                            .membership_events(state["neval"] - 1):
                        self._apply_membership_clause(clause)
                # end_when reads the same lockstep counters — training
                # stops on every process at the same iteration
                # replicated-by: lockstep-driver-counters
                if self.end_when(state):
                    ended = True
                    break
        if tel is not None:
            tel.stalls.record_block(block.stage_s, block.dispatch_s,
                                    t_wait1 - t_wait0,
                                    time.perf_counter() - t_replay0,
                                    first_compile=block.first_compile)
            tel.memory.observe()
            self._mirror_telemetry_scalars(tel)
        return ended

    def _mirror_telemetry_scalars(self, tel) -> None:
        """Mirror the driver gauges (pipeline-phase fractions, memory
        watermarks) into the TrainSummary event file, one scalar per
        gauge per replayed block — the telemetry view rides alongside
        Loss/Throughput in TensorBoard."""
        summary = self.train_summary
        add = getattr(summary, "add_scalar", None) if summary else None
        if add is None:
            return
        step = self.state["neval"]
        for name, val in tel.registry.gauges().items():
            add(f"Telemetry/{name}", float(val), step)

    # placement hooks — DistriOptimizer overrides these for sharded /
    # multi-host evaluation; the loop itself lives only here
    def _place_eval_input(self, x):
        return device_tree(x)

    def _place_eval_target(self, t):
        return device_tree(t)

    def _gather_eval_output(self, out):
        return out

    def evaluate_with(self, params, mstate) -> dict:
        """Forward the validation set through the model in eval mode."""
        if self._eval_fwd is None:
            model = self.model

            @jax.jit
            def fwd(params, mstate, x):
                out, _ = model.apply(params, mstate, x, training=False)
                return out

            self._eval_fwd = fwd

        acc: dict[str, ValidationResult] = {}
        for batch in self.validation_dataset.data(train=False):
            if not isinstance(batch, MiniBatch):
                raise TypeError("validation dataset must yield MiniBatch "
                                "(attach SampleToMiniBatch)")
            out = self._eval_fwd(params, mstate,
                                 self._place_eval_input(batch.input))
            out = self._gather_eval_output(out)
            tgt = self._place_eval_target(batch.target)
            for m in self.validation_methods:
                r = m(out, tgt)
                acc[m.name] = acc[m.name] + r if m.name in acc else r
        if not acc:
            raise ValueError(
                "validation dataset yielded no batches — its size is smaller "
                "than the batch size and SampleToMiniBatch dropped the "
                "remainder; use SampleToMiniBatch(n, drop_remainder=False) "
                "for validation or shrink the batch")
        return acc


class LocalOptimizer(Optimizer):
    """Single-host training loop (reference ``LocalOptimizer.scala:45``).

    The reference clones the model per core and sums gradients across
    thread replicas; under XLA one jit'd step-block uses the whole chip,
    so the loop is: stage next block → dispatch fused (loss, grad,
    update) block → replay triggers (see Optimizer._train_driver).
    """

    def optimize(self) -> Module:
        attempts = 0
        while True:
            try:
                return self._optimize_impl()
            except NonFiniteStepError as e:
                # numeric_guard="rollback": automatic loss-spike
                # recovery — restore the latest VALID snapshot (torn/
                # corrupt ones are skipped, never loaded) and re-run,
                # bounded by failure_retry_times.  "abort" (and an
                # exhausted budget) surfaces to the caller at the exact
                # failing iteration.
                attempts += 1
                from bigdl_tpu.utils.config import get_config
                self._rollback_nonfinite(
                    e, attempts, get_config().failure_retry_times)

    def _optimize_impl(self) -> Module:
        rng = jax.random.PRNGKey(self.seed)
        rng, init_rng = jax.random.split(rng)
        if self.model._params is not None:
            # copy: the block fn donates its inputs, and these arrays are
            # owned by the caller's model — donation would delete them,
            # corrupting the model on a failed/interrupted run
            params = jax.tree_util.tree_map(jnp.array, self.model._params)
            mstate = jax.tree_util.tree_map(jnp.array, self.model._state)
        else:
            params, mstate = self.model.init(init_rng)
        self._validate_resume_schema(params)
        if self._resume_opt_state is not None:
            ostate = self._resume_opt_state
            self._resume_opt_state = None
        else:
            ostate = self.optim_method.init_state(params)

        grad_fn = self._loss_and_grad_fn()
        logger.info("LocalOptimizer: %d samples/epoch, device=%s",
                    self.dataset.size(), jax.devices()[0])
        params, mstate, ostate = self._train_driver(params, mstate, ostate,
                                                    grad_fn, rng)

        # write trained weights back into the user's model object
        # (reference: final getModel copy, DistriOptimizer.scala:1063)
        self.model._params = params
        self.model._state = mstate
        self._final_opt_state = ostate
        return self.model
