"""bigdl_tpu.optim — optimization methods, schedules, triggers, metrics,
training loops (reference ``DL/optim/`` + ``DL/parameters/``)."""

from bigdl_tpu.optim.optim_method import (
    OptimMethod, SGD, Adam, ParallelAdam, Adagrad, Adadelta, Adamax,
    RMSprop, Ftrl, LBFGS,
)
from bigdl_tpu.optim.schedules import (
    LearningRateSchedule, Default, Step, MultiStep, EpochStep, EpochDecay,
    Poly, Exponential, NaturalExp, Warmup, SequentialSchedule, Plateau,
    EpochSchedule, EpochDecayWithWarmUp,
)
from bigdl_tpu.optim.trigger import (
    Trigger, every_epoch, several_iteration, max_epoch, max_iteration,
    max_score, min_loss,
)
from bigdl_tpu.optim.validation import (
    ValidationMethod, ValidationResult, Top1Accuracy, Top5Accuracy, Loss,
    MAE, HitRatio, NDCG, TreeNNAccuracy,
)
from bigdl_tpu.optim.optimizer import (
    Optimizer, LocalOptimizer, clip_by_value, clip_by_global_norm,
    global_norm,
)
from bigdl_tpu.optim.distri_optimizer import DistriOptimizer
from bigdl_tpu.optim.predictor import Predictor, Evaluator, PredictionService
