"""Optimization methods.

Reference: ``DL/optim/OptimMethod.scala:180`` + per-method files (``SGD.scala``,
``Adam.scala``, ``Adagrad``, ``Adadelta``, ``Adamax``, ``RMSprop``,
``Ftrl.scala``).  There, ``optimize(feval, x)`` mutates a flat weight slice
with state in a ``Table``.

Here the contract is functional and pytree-native (the flat-vector view the
reference needs for its BlockManager AllReduce is unnecessary under XLA —
collectives operate on the pytree leaves directly):

- ``init_state(params) -> opt_state`` (a pytree);
- ``update(grads, params, opt_state, lr, step) -> (new_params, new_opt_state)``
  is pure and jit-compatible; ``lr`` and ``step`` are traced scalars so
  host-side schedules never trigger recompilation.

Host-side driver state (iteration/epoch counters, schedule objects) lives in
the Optimizer, mirroring the reference's driver-side state Table.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.optim.schedules import Default, LearningRateSchedule
from bigdl_tpu.utils.precision import stochastic_round

tmap = jax.tree_util.tree_map


class OptimMethod:
    """Base optimizer. Subclasses define init_state/update."""

    def __init__(self, learning_rate: float = 1e-3,
                 learning_rate_schedule: Optional[LearningRateSchedule] = None,
                 weight_decay: float = 0.0):
        self.learning_rate = learning_rate
        self.learning_rate_schedule = learning_rate_schedule
        self.weight_decay = weight_decay

    # -- host side ---------------------------------------------------------
    def current_lr(self, iteration: int, epoch: int,
                   metric: Optional[float] = None) -> float:
        if self.learning_rate_schedule is None:
            return self.learning_rate
        return self.learning_rate_schedule(self.learning_rate, iteration,
                                           epoch, metric)

    # -- device side -------------------------------------------------------
    def init_state(self, params):
        return {}

    def update(self, grads, params, opt_state, lr, step):
        raise NotImplementedError

    def _apply_weight_decay(self, grads, params):
        """L2 weight decay folded into the gradient (reference: SGD
        weightDecay; layers' L2 regularizers do the same in
        accGradParameters)."""
        if self.weight_decay == 0.0:
            return grads
        wd = self.weight_decay
        return tmap(lambda g, p: g + wd * p, grads, params)


class SGD(OptimMethod):
    """SGD with momentum/dampening/nesterov (reference ``SGD.scala``;
    Torch semantics: v = mu*v + (1-dampening)*g; nesterov uses g + mu*v)."""

    def __init__(self, learning_rate: float = 1e-3,
                 learning_rate_decay: float = 0.0,
                 weight_decay: float = 0.0,
                 momentum: float = 0.0,
                 dampening: Optional[float] = None,
                 nesterov: bool = False,
                 learning_rate_schedule: Optional[LearningRateSchedule] = None,
                 state_dtype=None):
        """``state_dtype=jnp.bfloat16`` stores the velocity in bf16 with
        stochastic rounding (accumulate-in-f32, round-with-noise) —
        halves optimizer-state HBM traffic and footprint.  On ResNet-50
        that traffic is ~0.2 GB of a 78.7 GB/step budget (0.26%), so
        this is a memory-capacity knob, not a throughput one (measured:
        no difference beyond run noise)."""
        if learning_rate_schedule is None and learning_rate_decay != 0.0:
            learning_rate_schedule = Default(learning_rate_decay)
        super().__init__(learning_rate, learning_rate_schedule, weight_decay)
        self.momentum = momentum
        self.dampening = momentum if dampening is None else dampening
        self.nesterov = nesterov
        self.state_dtype = state_dtype
        if nesterov and (momentum <= 0 or self.dampening != 0):
            raise ValueError(
                "nesterov requires momentum > 0 and dampening = 0")

    def init_state(self, params):
        if self.momentum == 0.0:
            return {}
        dt = self.state_dtype
        mk = (lambda p: jnp.zeros(p.shape, dt)) if dt is not None \
            else jnp.zeros_like
        return {"velocity": tmap(mk, params)}

    def update(self, grads, params, opt_state, lr, step):
        grads = self._apply_weight_decay(grads, params)
        if self.momentum == 0.0:
            return tmap(lambda p, g: p - lr * g, params, grads), opt_state
        mu, damp = self.momentum, self.dampening
        # with a reduced-precision state, accumulate in f32 so the
        # stochastic rounding below is the ONLY precision loss — a bf16
        # accumulate would round-to-nearest first and systematically
        # drop sub-ulp updates (the bias SR exists to remove)
        acc = jnp.float32 if self.state_dtype is not None else None

        def _vel(v, g):
            # default path: accumulate at the WIDER of (velocity, grad)
            # dtypes — a bf16 gradient must not silently demote the f32
            # velocity (dtype flip ⇒ retrace + precision loss)
            dt = acc if acc is not None else jnp.promote_types(v.dtype,
                                                               g.dtype)
            return mu * v.astype(dt) + (1 - damp) * g.astype(dt)

        vel = tmap(_vel, opt_state["velocity"], grads)
        if self.nesterov:
            upd = tmap(lambda g, v: g + mu * v, grads, vel)
        else:
            upd = vel
        new_params = tmap(lambda p, u: p - lr * u, params, upd)
        if self.state_dtype is not None:
            key = jax.random.fold_in(jax.random.PRNGKey(0x5bd1), step)
            leaves = jax.tree_util.tree_leaves(vel)
            keys = jax.random.split(key, len(leaves))
            keys = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(vel), list(keys))
            vel = tmap(lambda v, k: _stochastic_round(v, self.state_dtype, k),
                       vel, keys)
        return new_params, {"velocity": vel}


# the unbiased downcast lives in utils/precision.py (shared with the
# grad_sync wire format); this alias keeps the historical private name
# importable for back-compat
_stochastic_round = stochastic_round


class Adam(OptimMethod):
    """Adam (reference ``Adam.scala``; bias-corrected)."""

    def __init__(self, learning_rate: float = 1e-3,
                 learning_rate_decay: float = 0.0,
                 beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-8, weight_decay: float = 0.0,
                 learning_rate_schedule: Optional[LearningRateSchedule] = None):
        if learning_rate_schedule is None and learning_rate_decay != 0.0:
            learning_rate_schedule = Default(learning_rate_decay)
        super().__init__(learning_rate, learning_rate_schedule, weight_decay)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_state(self, params):
        return {"m": tmap(jnp.zeros_like, params),
                "v": tmap(jnp.zeros_like, params)}

    def update(self, grads, params, opt_state, lr, step):
        grads = self._apply_weight_decay(grads, params)
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        t = step + 1
        m = tmap(lambda m_, g: b1 * m_ + (1 - b1) * g, opt_state["m"], grads)
        v = tmap(lambda v_, g: b2 * v_ + (1 - b2) * g * g, opt_state["v"], grads)
        bc1 = 1 - b1 ** t
        bc2 = 1 - b2 ** t
        new_params = tmap(
            lambda p, m_, v_: p - lr * (m_ / bc1) / (jnp.sqrt(v_ / bc2) + eps),
            params, m, v)
        return new_params, {"m": m, "v": v}


class ParallelAdam(Adam):
    """Reference ``ParallelAdam.scala`` multi-threads the update over chunks
    of the flat vector; XLA already parallelizes elementwise updates, so this
    is Adam (kept for API parity)."""
    pass


class Adagrad(OptimMethod):
    """Adagrad (reference ``Adagrad.scala``)."""

    def __init__(self, learning_rate: float = 1e-3,
                 learning_rate_decay: float = 0.0,
                 weight_decay: float = 0.0, epsilon: float = 1e-10):
        sched = Default(learning_rate_decay) if learning_rate_decay else None
        super().__init__(learning_rate, sched, weight_decay)
        self.epsilon = epsilon

    def init_state(self, params):
        return {"accum": tmap(jnp.zeros_like, params)}

    def update(self, grads, params, opt_state, lr, step):
        grads = self._apply_weight_decay(grads, params)
        acc = tmap(lambda a, g: a + g * g, opt_state["accum"], grads)
        eps = self.epsilon
        new_params = tmap(lambda p, g, a: p - lr * g / (jnp.sqrt(a) + eps),
                          params, grads, acc)
        return new_params, {"accum": acc}


class Adadelta(OptimMethod):
    """Adadelta (reference ``Adadelta.scala``; lr defaults to 1)."""

    def __init__(self, decay_rate: float = 0.9, epsilon: float = 1e-10,
                 weight_decay: float = 0.0):
        super().__init__(1.0, None, weight_decay)
        self.rho = decay_rate
        self.epsilon = epsilon

    def init_state(self, params):
        return {"accum": tmap(jnp.zeros_like, params),
                "accum_update": tmap(jnp.zeros_like, params)}

    def update(self, grads, params, opt_state, lr, step):
        grads = self._apply_weight_decay(grads, params)
        rho, eps = self.rho, self.epsilon
        acc = tmap(lambda a, g: rho * a + (1 - rho) * g * g,
                   opt_state["accum"], grads)
        delta = tmap(
            lambda g, a, au: g * jnp.sqrt(au + eps) / jnp.sqrt(a + eps),
            grads, acc, opt_state["accum_update"])
        accu = tmap(lambda au, d: rho * au + (1 - rho) * d * d,
                    opt_state["accum_update"], delta)
        new_params = tmap(lambda p, d: p - lr * d, params, delta)
        return new_params, {"accum": acc, "accum_update": accu}


class Adamax(OptimMethod):
    """Adamax (reference ``Adamax.scala``)."""

    def __init__(self, learning_rate: float = 2e-3, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-38,
                 weight_decay: float = 0.0):
        super().__init__(learning_rate, None, weight_decay)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_state(self, params):
        return {"m": tmap(jnp.zeros_like, params),
                "u": tmap(jnp.zeros_like, params)}

    def update(self, grads, params, opt_state, lr, step):
        grads = self._apply_weight_decay(grads, params)
        b1, b2 = self.beta1, self.beta2
        t = step + 1
        m = tmap(lambda m_, g: b1 * m_ + (1 - b1) * g, opt_state["m"], grads)
        u = tmap(lambda u_, g: jnp.maximum(b2 * u_, jnp.abs(g) + self.epsilon),
                 opt_state["u"], grads)
        bc = 1 - b1 ** t
        new_params = tmap(lambda p, m_, u_: p - (lr / bc) * m_ / u_,
                          params, m, u)
        return new_params, {"m": m, "u": u}


class RMSprop(OptimMethod):
    """RMSprop (reference ``RMSprop.scala``)."""

    def __init__(self, learning_rate: float = 1e-2,
                 learning_rate_decay: float = 0.0,
                 decay_rate: float = 0.99, epsilon: float = 1e-8,
                 weight_decay: float = 0.0):
        sched = Default(learning_rate_decay) if learning_rate_decay else None
        super().__init__(learning_rate, sched, weight_decay)
        self.rho = decay_rate
        self.epsilon = epsilon

    def init_state(self, params):
        return {"accum": tmap(jnp.zeros_like, params)}

    def update(self, grads, params, opt_state, lr, step):
        grads = self._apply_weight_decay(grads, params)
        rho, eps = self.rho, self.epsilon
        acc = tmap(lambda a, g: rho * a + (1 - rho) * g * g,
                   opt_state["accum"], grads)
        new_params = tmap(lambda p, g, a: p - lr * g / (jnp.sqrt(a) + eps),
                          params, grads, acc)
        return new_params, {"accum": acc}


class Ftrl(OptimMethod):
    """FTRL-proximal (reference ``Ftrl.scala``; the Wide&Deep recommender
    optimizer)."""

    def __init__(self, learning_rate: float = 1e-3,
                 learning_rate_power: float = -0.5,
                 initial_accumulator_value: float = 0.1,
                 l1_regularization_strength: float = 0.0,
                 l2_regularization_strength: float = 0.0,
                 l2_shrinkage_regularization_strength: float = 0.0):
        super().__init__(learning_rate, None, 0.0)
        self.lr_power = learning_rate_power
        self.init_accum = initial_accumulator_value
        self.l1 = l1_regularization_strength
        self.l2 = l2_regularization_strength
        self.l2_shrinkage = l2_shrinkage_regularization_strength

    def init_state(self, params):
        return {"accum": tmap(lambda p: jnp.full_like(p, self.init_accum),
                              params),
                "linear": tmap(jnp.zeros_like, params)}

    def update(self, grads, params, opt_state, lr, step):
        l1, l2, lrp = self.l1, self.l2, self.lr_power

        def upd(p, g, n, z):
            g_shrunk = g + 2 * self.l2_shrinkage * p
            n_new = n + g * g
            sigma = (n_new ** -lrp - n ** -lrp) / lr
            z_new = z + g_shrunk - sigma * p
            p_new = jnp.where(
                jnp.abs(z_new) > l1,
                -(z_new - jnp.sign(z_new) * l1)
                / (n_new ** -lrp / lr + 2 * l2),
                0.0)
            return p_new, n_new, z_new

        out = tmap(upd, params, grads, opt_state["accum"], opt_state["linear"],
                   is_leaf=lambda x: isinstance(x, jnp.ndarray))
        new_params = tmap(lambda t: t[0], out,
                          is_leaf=lambda x: isinstance(x, tuple))
        accum = tmap(lambda t: t[1], out,
                     is_leaf=lambda x: isinstance(x, tuple))
        linear = tmap(lambda t: t[2], out,
                      is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"accum": accum, "linear": linear}


class LBFGS(OptimMethod):
    """Limited-memory BFGS (reference ``DL/optim/LBFGS.scala`` 308 LoC +
    ``LineSearch.scala`` lswolfe).

    Two usage modes, mirroring the reference's two call patterns:

    - as an ``OptimMethod`` inside the training loop: ``update`` applies
      the two-loop recursion over a fixed-size (s, y) history kept in
      ``opt_state`` as stacked buffers — fully jit-compatible, step size
      ``lr`` (no line search: that needs loss re-evaluation, which the
      stochastic step contract doesn't provide; the reference's
      minibatch LBFGS without lineSearch does exactly a fixed
      ``learningRate`` step too, ``LBFGS.scala`` eval-free path);
    - full-batch via :meth:`minimize` with Wolfe line search — the
      deterministic-objective mode the reference pairs with
      ``LineSearch.lswolfe``.
    """

    def __init__(self, learning_rate: float = 1.0, history: int = 10,
                 weight_decay: float = 0.0,
                 learning_rate_schedule: Optional[LearningRateSchedule] = None):
        super().__init__(learning_rate, learning_rate_schedule, weight_decay)
        self.history = history

    def init_state(self, params):
        from jax.flatten_util import ravel_pytree
        flat, _ = ravel_pytree(params)
        n, m = flat.shape[0], self.history
        return {
            "s": jnp.zeros((m, n)), "y": jnp.zeros((m, n)),
            "rho": jnp.zeros((m,)),
            "prev_flat": jnp.zeros((n,)), "prev_grad": jnp.zeros((n,)),
            "count": jnp.zeros((), jnp.int32),   # steps taken
            "pairs": jnp.zeros((), jnp.int32),   # (s, y) pairs pushed
        }

    def update(self, grads, params, opt_state, lr, step):
        from jax.flatten_util import ravel_pytree
        grads = self._apply_weight_decay(grads, params)
        flat, unravel = ravel_pytree(params)
        g, _ = ravel_pytree(grads)
        m = self.history
        st = opt_state

        # push (s, y) from the previous step once we have a history
        s_new = flat - st["prev_flat"]
        y_new = g - st["prev_grad"]
        ys = jnp.dot(s_new, y_new)
        have_pair = (st["count"] > 0) & (ys > 1e-10)

        def push(st):
            rho_new = 1.0 / ys
            return {**st,
                    "s": jnp.roll(st["s"], -1, 0).at[-1].set(s_new),
                    "y": jnp.roll(st["y"], -1, 0).at[-1].set(y_new),
                    "rho": jnp.roll(st["rho"], -1).at[-1].set(rho_new),
                    "pairs": st["pairs"] + 1}

        st = jax.lax.cond(have_pair, push, lambda s: s, st)
        # count PUSHED pairs, not steps: a rejected first pair (curvature
        # s.y <= 0 under minibatch noise) must leave the direction as the
        # raw gradient, not a zero-history product that freezes params
        n_pairs = jnp.minimum(st["pairs"], m)

        # two-loop recursion over the (ring-ordered) history
        def bwd(i, carry):
            q, alphas = carry
            ix = m - 1 - i
            valid = i < n_pairs
            alpha = jnp.where(valid, st["rho"][ix]
                              * jnp.dot(st["s"][ix], q), 0.0)
            q = q - alpha * st["y"][ix]
            return q, alphas.at[ix].set(alpha)

        q, alphas = jax.lax.fori_loop(0, m, bwd,
                                      (g, jnp.zeros((m,))))
        # initial Hessian scaling gamma = s·y / y·y of newest pair
        y_last = st["y"][-1]
        s_last = st["s"][-1]
        yy = jnp.dot(y_last, y_last)
        gamma = jnp.where(n_pairs > 0,
                          jnp.dot(s_last, y_last) / jnp.maximum(yy, 1e-10),
                          1.0)
        r = gamma * q

        def fwd(i, r):
            valid = i < n_pairs
            start = m - n_pairs
            ix = start + i
            beta = jnp.where(valid, st["rho"][ix]
                             * jnp.dot(st["y"][ix], r), 0.0)
            return r + jnp.where(valid, (alphas[ix] - beta), 0.0) \
                * st["s"][ix]

        r = jax.lax.fori_loop(0, m, fwd, r)

        new_flat = flat - lr * r
        new_state = {**st, "prev_flat": flat, "prev_grad": g,
                     "count": st["count"] + 1}
        return unravel(new_flat), new_state

    # ------------------------------------------------- full-batch driver
    def minimize(self, feval, params, max_iter: int = 100,
                 tol_grad: float = 1e-5, c1: float = 1e-4, c2: float = 0.9,
                 max_ls: int = 20):
        """Deterministic full-batch L-BFGS with Wolfe line search
        (reference ``LineSearch.scala`` lswolfe conditions).  ``feval`` is
        ``params -> (loss, grads)`` (e.g. ``jax.value_and_grad`` of the
        objective).  Returns (params, final_loss, n_iter)."""
        from jax.flatten_util import ravel_pytree

        flat, unravel = ravel_pytree(params)
        fe = lambda x: feval(unravel(x))

        loss, grads = fe(flat)
        g, _ = ravel_pytree(grads)
        s_hist, y_hist, rho_hist = [], [], []
        it = 0
        for it in range(1, max_iter + 1):
            if float(jnp.max(jnp.abs(g))) < tol_grad:
                break
            # two-loop on python history (host loop; feval jit'd by caller)
            q = g
            alphas = []
            for s, y, rho in zip(reversed(s_hist), reversed(y_hist),
                                 reversed(rho_hist)):
                a = rho * jnp.dot(s, q)
                alphas.append(a)
                q = q - a * y
            if s_hist:
                gamma = (jnp.dot(s_hist[-1], y_hist[-1])
                         / jnp.maximum(jnp.dot(y_hist[-1], y_hist[-1]),
                                       1e-10))
            else:
                gamma = 1.0
            r = gamma * q
            for (s, y, rho), a in zip(zip(s_hist, y_hist, rho_hist),
                                      reversed(alphas)):
                b = rho * jnp.dot(y, r)
                r = r + (a - b) * s
            d = -r

            # Wolfe line search
            gtd = float(jnp.dot(g, d))
            if gtd > -1e-12:   # not a descent direction: reset
                d = -g
                gtd = float(jnp.dot(g, d))
                s_hist, y_hist, rho_hist = [], [], []
            t = 1.0
            f0 = float(loss)
            ok = False
            best_t, best_f = 0.0, f0
            loss_t = grads_t = None
            for _ in range(max_ls):
                loss_t, grads_t = fe(flat + t * d)
                f_t = float(loss_t)
                if f_t < best_f:
                    best_t, best_f = t, f_t
                g_t, _ = ravel_pytree(grads_t)
                if f_t > f0 + c1 * t * gtd:
                    t *= 0.5          # Armijo failed: backtrack
                elif float(jnp.dot(g_t, d)) < c2 * gtd:
                    t = min(t * 2.1, 1e4)  # curvature failed: extend
                else:
                    ok = True
                    break
            if ok:
                # the accepted point was just evaluated — reuse it
                new_flat = flat + t * d
                loss_n, grads_n = loss_t, grads_t
            else:
                # reference lswolfe falls back to the best evaluated point
                # rather than committing an unevaluated step size
                if best_t == 0.0:
                    break  # no evaluated step improved: converged/stuck
                t = best_t
                new_flat = flat + t * d
                loss_n, grads_n = fe(new_flat)
            g_n, _ = ravel_pytree(grads_n)
            s_new = new_flat - flat
            y_new = g_n - g
            ys = float(jnp.dot(s_new, y_new))
            if ys > 1e-10:
                s_hist.append(s_new)
                y_hist.append(y_new)
                rho_hist.append(1.0 / ys)
                if len(s_hist) > self.history:
                    s_hist.pop(0)
                    y_hist.pop(0)
                    rho_hist.pop(0)
            flat, loss, g = new_flat, loss_n, g_n
        return unravel(flat), float(loss), it
