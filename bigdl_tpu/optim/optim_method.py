"""Optimization methods.

Reference: ``DL/optim/OptimMethod.scala:180`` + per-method files (``SGD.scala``,
``Adam.scala``, ``Adagrad``, ``Adadelta``, ``Adamax``, ``RMSprop``,
``Ftrl.scala``).  There, ``optimize(feval, x)`` mutates a flat weight slice
with state in a ``Table``.

Here the contract is functional and pytree-native (the flat-vector view the
reference needs for its BlockManager AllReduce is unnecessary under XLA —
collectives operate on the pytree leaves directly):

- ``init_state(params) -> opt_state`` (a pytree);
- ``update(grads, params, opt_state, lr, step) -> (new_params, new_opt_state)``
  is pure and jit-compatible; ``lr`` and ``step`` are traced scalars so
  host-side schedules never trigger recompilation.

Host-side driver state (iteration/epoch counters, schedule objects) lives in
the Optimizer, mirroring the reference's driver-side state Table.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.optim.schedules import Default, LearningRateSchedule

tmap = jax.tree_util.tree_map


class OptimMethod:
    """Base optimizer. Subclasses define init_state/update."""

    def __init__(self, learning_rate: float = 1e-3,
                 learning_rate_schedule: Optional[LearningRateSchedule] = None,
                 weight_decay: float = 0.0):
        self.learning_rate = learning_rate
        self.learning_rate_schedule = learning_rate_schedule
        self.weight_decay = weight_decay

    # -- host side ---------------------------------------------------------
    def current_lr(self, iteration: int, epoch: int,
                   metric: Optional[float] = None) -> float:
        if self.learning_rate_schedule is None:
            return self.learning_rate
        return self.learning_rate_schedule(self.learning_rate, iteration,
                                           epoch, metric)

    # -- device side -------------------------------------------------------
    def init_state(self, params):
        return {}

    def update(self, grads, params, opt_state, lr, step):
        raise NotImplementedError

    def _apply_weight_decay(self, grads, params):
        """L2 weight decay folded into the gradient (reference: SGD
        weightDecay; layers' L2 regularizers do the same in
        accGradParameters)."""
        if self.weight_decay == 0.0:
            return grads
        wd = self.weight_decay
        return tmap(lambda g, p: g + wd * p, grads, params)


class SGD(OptimMethod):
    """SGD with momentum/dampening/nesterov (reference ``SGD.scala``;
    Torch semantics: v = mu*v + (1-dampening)*g; nesterov uses g + mu*v)."""

    def __init__(self, learning_rate: float = 1e-3,
                 learning_rate_decay: float = 0.0,
                 weight_decay: float = 0.0,
                 momentum: float = 0.0,
                 dampening: Optional[float] = None,
                 nesterov: bool = False,
                 learning_rate_schedule: Optional[LearningRateSchedule] = None):
        if learning_rate_schedule is None and learning_rate_decay != 0.0:
            learning_rate_schedule = Default(learning_rate_decay)
        super().__init__(learning_rate, learning_rate_schedule, weight_decay)
        self.momentum = momentum
        self.dampening = momentum if dampening is None else dampening
        self.nesterov = nesterov
        if nesterov and (momentum <= 0 or self.dampening != 0):
            raise ValueError(
                "nesterov requires momentum > 0 and dampening = 0")

    def init_state(self, params):
        if self.momentum == 0.0:
            return {}
        return {"velocity": tmap(jnp.zeros_like, params)}

    def update(self, grads, params, opt_state, lr, step):
        grads = self._apply_weight_decay(grads, params)
        if self.momentum == 0.0:
            return tmap(lambda p, g: p - lr * g, params, grads), opt_state
        mu, damp = self.momentum, self.dampening
        vel = tmap(lambda v, g: mu * v + (1 - damp) * g,
                   opt_state["velocity"], grads)
        if self.nesterov:
            upd = tmap(lambda g, v: g + mu * v, grads, vel)
        else:
            upd = vel
        return tmap(lambda p, u: p - lr * u, params, upd), {"velocity": vel}


class Adam(OptimMethod):
    """Adam (reference ``Adam.scala``; bias-corrected)."""

    def __init__(self, learning_rate: float = 1e-3,
                 learning_rate_decay: float = 0.0,
                 beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-8, weight_decay: float = 0.0,
                 learning_rate_schedule: Optional[LearningRateSchedule] = None):
        if learning_rate_schedule is None and learning_rate_decay != 0.0:
            learning_rate_schedule = Default(learning_rate_decay)
        super().__init__(learning_rate, learning_rate_schedule, weight_decay)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_state(self, params):
        return {"m": tmap(jnp.zeros_like, params),
                "v": tmap(jnp.zeros_like, params)}

    def update(self, grads, params, opt_state, lr, step):
        grads = self._apply_weight_decay(grads, params)
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        t = step + 1
        m = tmap(lambda m_, g: b1 * m_ + (1 - b1) * g, opt_state["m"], grads)
        v = tmap(lambda v_, g: b2 * v_ + (1 - b2) * g * g, opt_state["v"], grads)
        bc1 = 1 - b1 ** t
        bc2 = 1 - b2 ** t
        new_params = tmap(
            lambda p, m_, v_: p - lr * (m_ / bc1) / (jnp.sqrt(v_ / bc2) + eps),
            params, m, v)
        return new_params, {"m": m, "v": v}


class ParallelAdam(Adam):
    """Reference ``ParallelAdam.scala`` multi-threads the update over chunks
    of the flat vector; XLA already parallelizes elementwise updates, so this
    is Adam (kept for API parity)."""
    pass


class Adagrad(OptimMethod):
    """Adagrad (reference ``Adagrad.scala``)."""

    def __init__(self, learning_rate: float = 1e-3,
                 learning_rate_decay: float = 0.0,
                 weight_decay: float = 0.0, epsilon: float = 1e-10):
        sched = Default(learning_rate_decay) if learning_rate_decay else None
        super().__init__(learning_rate, sched, weight_decay)
        self.epsilon = epsilon

    def init_state(self, params):
        return {"accum": tmap(jnp.zeros_like, params)}

    def update(self, grads, params, opt_state, lr, step):
        grads = self._apply_weight_decay(grads, params)
        acc = tmap(lambda a, g: a + g * g, opt_state["accum"], grads)
        eps = self.epsilon
        new_params = tmap(lambda p, g, a: p - lr * g / (jnp.sqrt(a) + eps),
                          params, grads, acc)
        return new_params, {"accum": acc}


class Adadelta(OptimMethod):
    """Adadelta (reference ``Adadelta.scala``; lr defaults to 1)."""

    def __init__(self, decay_rate: float = 0.9, epsilon: float = 1e-10,
                 weight_decay: float = 0.0):
        super().__init__(1.0, None, weight_decay)
        self.rho = decay_rate
        self.epsilon = epsilon

    def init_state(self, params):
        return {"accum": tmap(jnp.zeros_like, params),
                "accum_update": tmap(jnp.zeros_like, params)}

    def update(self, grads, params, opt_state, lr, step):
        grads = self._apply_weight_decay(grads, params)
        rho, eps = self.rho, self.epsilon
        acc = tmap(lambda a, g: rho * a + (1 - rho) * g * g,
                   opt_state["accum"], grads)
        delta = tmap(
            lambda g, a, au: g * jnp.sqrt(au + eps) / jnp.sqrt(a + eps),
            grads, acc, opt_state["accum_update"])
        accu = tmap(lambda au, d: rho * au + (1 - rho) * d * d,
                    opt_state["accum_update"], delta)
        new_params = tmap(lambda p, d: p - lr * d, params, delta)
        return new_params, {"accum": acc, "accum_update": accu}


class Adamax(OptimMethod):
    """Adamax (reference ``Adamax.scala``)."""

    def __init__(self, learning_rate: float = 2e-3, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-38,
                 weight_decay: float = 0.0):
        super().__init__(learning_rate, None, weight_decay)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_state(self, params):
        return {"m": tmap(jnp.zeros_like, params),
                "u": tmap(jnp.zeros_like, params)}

    def update(self, grads, params, opt_state, lr, step):
        grads = self._apply_weight_decay(grads, params)
        b1, b2 = self.beta1, self.beta2
        t = step + 1
        m = tmap(lambda m_, g: b1 * m_ + (1 - b1) * g, opt_state["m"], grads)
        u = tmap(lambda u_, g: jnp.maximum(b2 * u_, jnp.abs(g) + self.epsilon),
                 opt_state["u"], grads)
        bc = 1 - b1 ** t
        new_params = tmap(lambda p, m_, u_: p - (lr / bc) * m_ / u_,
                          params, m, u)
        return new_params, {"m": m, "u": u}


class RMSprop(OptimMethod):
    """RMSprop (reference ``RMSprop.scala``)."""

    def __init__(self, learning_rate: float = 1e-2,
                 learning_rate_decay: float = 0.0,
                 decay_rate: float = 0.99, epsilon: float = 1e-8,
                 weight_decay: float = 0.0):
        sched = Default(learning_rate_decay) if learning_rate_decay else None
        super().__init__(learning_rate, sched, weight_decay)
        self.rho = decay_rate
        self.epsilon = epsilon

    def init_state(self, params):
        return {"accum": tmap(jnp.zeros_like, params)}

    def update(self, grads, params, opt_state, lr, step):
        grads = self._apply_weight_decay(grads, params)
        rho, eps = self.rho, self.epsilon
        acc = tmap(lambda a, g: rho * a + (1 - rho) * g * g,
                   opt_state["accum"], grads)
        new_params = tmap(lambda p, g, a: p - lr * g / (jnp.sqrt(a) + eps),
                          params, grads, acc)
        return new_params, {"accum": acc}


class Ftrl(OptimMethod):
    """FTRL-proximal (reference ``Ftrl.scala``; the Wide&Deep recommender
    optimizer)."""

    def __init__(self, learning_rate: float = 1e-3,
                 learning_rate_power: float = -0.5,
                 initial_accumulator_value: float = 0.1,
                 l1_regularization_strength: float = 0.0,
                 l2_regularization_strength: float = 0.0,
                 l2_shrinkage_regularization_strength: float = 0.0):
        super().__init__(learning_rate, None, 0.0)
        self.lr_power = learning_rate_power
        self.init_accum = initial_accumulator_value
        self.l1 = l1_regularization_strength
        self.l2 = l2_regularization_strength
        self.l2_shrinkage = l2_shrinkage_regularization_strength

    def init_state(self, params):
        return {"accum": tmap(lambda p: jnp.full_like(p, self.init_accum),
                              params),
                "linear": tmap(jnp.zeros_like, params)}

    def update(self, grads, params, opt_state, lr, step):
        l1, l2, lrp = self.l1, self.l2, self.lr_power

        def upd(p, g, n, z):
            g_shrunk = g + 2 * self.l2_shrinkage * p
            n_new = n + g * g
            sigma = (n_new ** -lrp - n ** -lrp) / lr
            z_new = z + g_shrunk - sigma * p
            p_new = jnp.where(
                jnp.abs(z_new) > l1,
                -(z_new - jnp.sign(z_new) * l1)
                / (n_new ** -lrp / lr + 2 * l2),
                0.0)
            return p_new, n_new, z_new

        out = tmap(upd, params, grads, opt_state["accum"], opt_state["linear"],
                   is_leaf=lambda x: isinstance(x, jnp.ndarray))
        new_params = tmap(lambda t: t[0], out,
                          is_leaf=lambda x: isinstance(x, tuple))
        accum = tmap(lambda t: t[1], out,
                     is_leaf=lambda x: isinstance(x, tuple))
        linear = tmap(lambda t: t[2], out,
                      is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"accum": accum, "linear": linear}
