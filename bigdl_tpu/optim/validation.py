"""Validation methods (metrics).

Reference: ``DL/optim/ValidationMethod.scala`` — ``Top1Accuracy:170``,
``Top5Accuracy:224``, ``HitRatio:279``, ``NDCG:346``, ``Loss:475``,
``MAE:500``.  Metrics are **associative** ``ValidationResult``s so they
reduce across partitions/devices — the same property lets us ``psum`` the
(numerator, denominator) pair across a mesh here.

Each method exposes ``batch_stats(output, target) -> (value, count)`` as a
pure jit-able function; ``ValidationResult``s accumulate host-side.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


class ValidationResult:
    """Associative (value, count) accumulator (reference
    ``ContiguousResult``/``LossResult``)."""

    def __init__(self, value: float, count: float, fmt: str = "{:.6f}"):
        self.value = float(value)
        self.count = float(count)
        self.fmt = fmt

    @property
    def result(self) -> float:
        return self.value / max(self.count, 1e-12)

    def __add__(self, other: "ValidationResult") -> "ValidationResult":
        return ValidationResult(self.value + other.value,
                                self.count + other.count, self.fmt)

    def __repr__(self):
        return f"{self.fmt.format(self.result)} ({int(self.count)} samples)"


class ValidationMethod:
    name = "ValidationMethod"

    def batch_stats(self, output, target):
        """Pure: return (summed value, count) for one batch."""
        raise NotImplementedError

    def __call__(self, output, target) -> ValidationResult:
        v, c = self.batch_stats(output, target)
        return ValidationResult(float(v), float(c))

    def __repr__(self):
        return self.name


def _as_class_indices(target, output):
    """Accept class indices (N,), (N,1) column labels, or one-hot
    (N, C): one-hot only when the class axis matches the output's (a
    (N,1) index column must NOT be argmax'd — it would collapse every
    label to 0)."""
    if target.ndim == output.ndim and \
            target.shape[-1] == output.shape[-1] and output.shape[-1] > 1:
        return jnp.argmax(target, axis=-1)
    if target.ndim == output.ndim and target.shape[-1] == 1:
        return target[..., 0]
    return target


class Top1Accuracy(ValidationMethod):
    """(reference ``ValidationMethod.scala:170``; like the reference it
    accepts one-hot targets — Keras categorical losses train against
    one-hot — as well as class indices, including (N,1) columns)"""
    name = "Top1Accuracy"

    def batch_stats(self, output, target):
        pred = jnp.argmax(output, axis=-1)
        target = _as_class_indices(target, output)
        correct = jnp.sum(pred == target.astype(pred.dtype))
        return correct, target.shape[0]


class Top5Accuracy(ValidationMethod):
    """(reference ``ValidationMethod.scala:224``)"""
    name = "Top5Accuracy"

    def batch_stats(self, output, target):
        _, top5 = jax.lax.top_k(output, 5)
        target = _as_class_indices(target, output)
        hit = jnp.any(top5 == target.astype(top5.dtype)[..., None], axis=-1)
        return jnp.sum(hit), target.shape[0]


class Loss(ValidationMethod):
    """Criterion value as a metric (reference ``ValidationMethod.scala:475``)."""
    name = "Loss"

    def __init__(self, criterion=None):
        from bigdl_tpu.nn.criterion import CrossEntropyCriterion
        self.criterion = criterion or CrossEntropyCriterion()

    def batch_stats(self, output, target):
        n = output.shape[0] if hasattr(output, "shape") else 1
        return self.criterion.apply(output, target) * n, n


class MAE(ValidationMethod):
    """Mean absolute error (reference ``ValidationMethod.scala:500``)."""
    name = "MAE"

    def batch_stats(self, output, target):
        err = jnp.mean(jnp.abs(output - target),
                       axis=tuple(range(1, output.ndim)))
        return jnp.sum(err), output.shape[0]


class HitRatio(ValidationMethod):
    """HR@k for recommendation (reference ``ValidationMethod.scala:279``):
    output = scores over [positive, negatives...] per row; hit if the
    positive (column 0) ranks in top-k."""
    name = "HitRatio"

    def __init__(self, k: int = 10):
        self.k = k

    def batch_stats(self, output, target=None):
        pos = output[:, 0:1]
        rank = jnp.sum(output[:, 1:] > pos, axis=-1) + 1
        return jnp.sum(rank <= self.k), output.shape[0]


class NDCG(ValidationMethod):
    """NDCG@k, positive item at column 0 (reference
    ``ValidationMethod.scala:346``)."""
    name = "NDCG"

    def __init__(self, k: int = 10):
        self.k = k

    def batch_stats(self, output, target=None):
        pos = output[:, 0:1]
        rank = jnp.sum(output[:, 1:] > pos, axis=-1) + 1
        gain = jnp.where(rank <= self.k, 1.0 / jnp.log2(rank + 1.0), 0.0)
        return jnp.sum(gain), output.shape[0]


class TreeNNAccuracy(ValidationMethod):
    """(reference ``ValidationMethod.scala:118``) accuracy on the root
    prediction of tree outputs — output (N, T, C), root at t=0."""
    name = "TreeNNAccuracy"

    def batch_stats(self, output, target):
        pred = jnp.argmax(output[:, 0], axis=-1)
        return jnp.sum(pred == target.astype(pred.dtype)), target.shape[0]
