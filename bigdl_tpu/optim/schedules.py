"""Learning-rate schedules.

Reference: the schedule family nested in ``DL/optim/SGD.scala:200-690`` —
``Default``, ``Step:329``, ``MultiStep:360``, ``EpochStep``, ``EpochDecay:397``,
``Poly:290``, ``Exponential``, ``NaturalExp``, ``Regime``/``EpochSchedule:233``,
``Plateau``, ``Warmup:+600``, ``SequentialSchedule:+624`` — required by the
ResNet/Inception training recipes.

Contract: ``schedule(base_lr, iteration, epoch, metric=None) -> lr`` runs on
the **host** each step; the resulting scalar is fed into the jit'd update as
a traced argument, so changing lr never recompiles.  Stateful schedules
(Plateau) keep their state on the python object — host-side, like the
reference's driver-side SGD state table.

Iterations and epochs are 0-based.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence


class LearningRateSchedule:
    def __call__(self, base_lr: float, iteration: int, epoch: int,
                 metric: Optional[float] = None) -> float:
        raise NotImplementedError

    #: iterations consumed (used by SequentialSchedule)
    def __len__(self):  # pragma: no cover - overridden where meaningful
        return 0


class Default(LearningRateSchedule):
    """lr / (1 + decay * iteration) (reference SGD default when
    learningRateDecay is set)."""

    def __init__(self, learning_rate_decay: float = 0.0):
        self.decay = learning_rate_decay

    def __call__(self, base_lr, iteration, epoch, metric=None):
        return base_lr / (1.0 + self.decay * iteration)


class Step(LearningRateSchedule):
    """lr * gamma^(floor(iter/step_size)) (reference ``SGD.scala:329``)."""

    def __init__(self, step_size: int, gamma: float = 0.1):
        self.step_size, self.gamma = step_size, gamma

    def __call__(self, base_lr, iteration, epoch, metric=None):
        return base_lr * self.gamma ** (iteration // self.step_size)


class MultiStep(LearningRateSchedule):
    """Drop by gamma at each listed iteration (reference ``SGD.scala:360``);
    ``epoch_based=True`` reads the thresholds as epochs instead (the
    reference expresses that via an ``EpochSchedule`` Regime — e.g. the
    TrainCIFAR10 80/120 recipe)."""

    def __init__(self, step_sizes: Sequence[int], gamma: float = 0.1,
                 epoch_based: bool = False):
        self.step_sizes, self.gamma = list(step_sizes), gamma
        self.epoch_based = epoch_based

    def __call__(self, base_lr, iteration, epoch, metric=None):
        at = epoch if self.epoch_based else iteration
        n = sum(1 for s in self.step_sizes if at >= s)
        return base_lr * self.gamma ** n


class EpochStep(LearningRateSchedule):
    """lr * gamma^(floor(epoch/step_size)) (reference EpochStep)."""

    def __init__(self, step_size: int, gamma: float = 0.1):
        self.step_size, self.gamma = step_size, gamma

    def __call__(self, base_lr, iteration, epoch, metric=None):
        return base_lr * self.gamma ** (epoch // self.step_size)


class EpochDecay(LearningRateSchedule):
    """lr * 0.1^decay_fn(epoch) with a user fn (reference ``SGD.scala:397``)."""

    def __init__(self, decay_fn):
        self.decay_fn = decay_fn

    def __call__(self, base_lr, iteration, epoch, metric=None):
        return base_lr * 0.1 ** self.decay_fn(epoch)


class Poly(LearningRateSchedule):
    """lr * (1 - iter/max_iter)^power (reference ``SGD.scala:290``; the
    Inception-v1 recipe's schedule)."""

    def __init__(self, power: float, max_iteration: int):
        self.power, self.max_iteration = power, max_iteration

    def __call__(self, base_lr, iteration, epoch, metric=None):
        if iteration >= self.max_iteration:
            return 0.0
        return base_lr * (1.0 - iteration / self.max_iteration) ** self.power

    def __len__(self):
        return self.max_iteration


class Exponential(LearningRateSchedule):
    """lr * gamma^(iter/decay_step), optionally staircased
    (reference Exponential)."""

    def __init__(self, decay_step: int, decay_rate: float,
                 stair_case: bool = False):
        self.decay_step, self.decay_rate = decay_step, decay_rate
        self.stair_case = stair_case

    def __call__(self, base_lr, iteration, epoch, metric=None):
        p = iteration / self.decay_step
        if self.stair_case:
            p = math.floor(p)
        return base_lr * self.decay_rate ** p


class NaturalExp(LearningRateSchedule):
    def __init__(self, decay_step: int, gamma: float):
        self.decay_step, self.gamma = decay_step, gamma

    def __call__(self, base_lr, iteration, epoch, metric=None):
        return base_lr * math.exp(-self.gamma * (iteration // self.decay_step))


class Warmup(LearningRateSchedule):
    """Linear ramp base_lr → base_lr + delta*warmup_iters over warmup_iters
    (reference ``SGD.scala`` Warmup; the ResNet batch-8192 recipe warms up
    5 epochs to maxLr)."""

    def __init__(self, delta: float, warmup_iteration: int):
        self.delta = delta
        self.warmup_iteration = warmup_iteration

    def __call__(self, base_lr, iteration, epoch, metric=None):
        return base_lr + self.delta * min(iteration, self.warmup_iteration)

    def __len__(self):
        return self.warmup_iteration


class SequentialSchedule(LearningRateSchedule):
    """Chain schedules, each consuming its ``len()`` iterations
    (reference ``SGD.scala`` SequentialSchedule)."""

    def __init__(self, *schedules: LearningRateSchedule):
        self.schedules = list(schedules)

    def add(self, schedule: LearningRateSchedule,
            max_iteration: Optional[int] = None):
        if max_iteration is not None:
            schedule._seq_len = max_iteration  # type: ignore[attr-defined]
        self.schedules.append(schedule)
        return self

    @staticmethod
    def _length(s):
        return getattr(s, "_seq_len", None) or len(s)

    def __call__(self, base_lr, iteration, epoch, metric=None):
        it = iteration
        for s in self.schedules[:-1]:
            n = self._length(s)
            if it < n:
                return s(base_lr, it, epoch, metric)
            it -= n
        return self.schedules[-1](base_lr, it, epoch, metric)


class Plateau(LearningRateSchedule):
    """Drop lr by ``factor`` when the monitored metric stops improving
    (reference SGD Plateau; metric-driven, stateful)."""

    def __init__(self, monitor: str = "score", factor: float = 0.1,
                 patience: int = 10, mode: str = "min", epsilon: float = 1e-4,
                 cooldown: int = 0, min_lr: float = 0.0):
        self.monitor, self.factor, self.patience = monitor, factor, patience
        self.mode, self.epsilon = mode, epsilon
        self.cooldown, self.min_lr = cooldown, min_lr
        self._best: Optional[float] = None
        self._wait = 0
        self._cooldown_left = 0
        self._scale = 1.0

    def record(self, metric: float):
        """Feed the monitored metric (called by the optimizer after each
        validation)."""
        better = (self._best is None
                  or (self.mode == "min" and metric < self._best - self.epsilon)
                  or (self.mode == "max" and metric > self._best + self.epsilon))
        if self._cooldown_left > 0:
            self._cooldown_left -= 1
        if better:
            self._best = metric
            self._wait = 0
        elif self._cooldown_left == 0:
            self._wait += 1
            if self._wait >= self.patience:
                self._scale *= self.factor
                self._wait = 0
                self._cooldown_left = self.cooldown

    def __call__(self, base_lr, iteration, epoch, metric=None):
        if metric is not None:
            self.record(metric)
        return max(base_lr * self._scale, self.min_lr)


class EpochSchedule(LearningRateSchedule):
    """Piecewise regimes by epoch range (reference ``SGD.scala:233``
    Regime/EpochSchedule — AlexNet-style recipes)."""

    def __init__(self, regimes: Sequence[tuple[int, int, float]]):
        """regimes: (start_epoch, end_epoch_inclusive, lr) with 0-based epochs."""
        self.regimes = list(regimes)

    def __call__(self, base_lr, iteration, epoch, metric=None):
        for start, end, lr in self.regimes:
            if start <= epoch <= end:
                return lr
        return base_lr


class EpochDecayWithWarmUp(LearningRateSchedule):
    """Linear warmup then epoch-wise decay fn (reference
    EpochDecayWithWarmUp)."""

    def __init__(self, warmup_iteration: int, warmup_delta: float, decay_fn):
        self.warmup_iteration = warmup_iteration
        self.warmup_delta = warmup_delta
        self.decay_fn = decay_fn

    def __call__(self, base_lr, iteration, epoch, metric=None):
        if iteration < self.warmup_iteration:
            return base_lr + self.warmup_delta * iteration
        max_lr = base_lr + self.warmup_delta * self.warmup_iteration
        return max_lr * 0.1 ** self.decay_fn(epoch)
