"""Triggers — cadence/stop conditions for training loops.

Reference: ``DL/optim/Trigger.scala:30-119`` (``everyEpoch``,
``severalIteration``, ``maxEpoch``, ``maxIteration``, ``maxScore``,
``minLoss``), composable with and/or.  A trigger is a predicate over the
driver's training state dict.

State keys (mirroring the reference's state Table): ``epoch`` (0-based,
current), ``neval`` (iteration counter, 1-based after first step),
``loss``, ``score``, and ``epoch_finished`` (set by the loop at epoch
boundaries so everyEpoch fires once per rollover).

The fused K-step driver (optimizer.py) additionally *probes* triggers
ahead of time via :func:`probe_fire_step` so a dispatch block never runs
past an iteration where a trigger needs host-side action.  Probed states
carry ``probe: True`` so stateful trigger-like objects (test spies,
metric recorders) can tell a simulation from the real per-iteration
replay; ``loss``/``score`` hold their last REAL values during a probe
(a block is planned before its losses exist).
"""

from __future__ import annotations

from typing import Iterable, Optional


class Trigger:
    def __call__(self, state: dict) -> bool:
        raise NotImplementedError

    def and_(self, other: "Trigger") -> "Trigger":
        return _And(self, other)

    def or_(self, other: "Trigger") -> "Trigger":
        return _Or(self, other)


class _And(Trigger):
    def __init__(self, a, b):
        self.a, self.b = a, b

    def __call__(self, state):
        return self.a(state) and self.b(state)


class _Or(Trigger):
    def __init__(self, a, b):
        self.a, self.b = a, b

    def __call__(self, state):
        return self.a(state) or self.b(state)


class _EveryEpoch(Trigger):
    def __call__(self, state):
        return bool(state.get("epoch_finished", False))


class _SeveralIteration(Trigger):
    def __init__(self, interval: int):
        self.interval = interval

    def __call__(self, state):
        n = state.get("neval", 0)
        # neval advances identically on every process (lockstep driver)
        # replicated-by: lockstep-driver-counters
        return n > 0 and n % self.interval == 0


class _MaxEpoch(Trigger):
    def __init__(self, max_epoch: int):
        self.max_epoch = max_epoch

    def __call__(self, state):
        return state.get("epoch", 0) >= self.max_epoch


class _MaxIteration(Trigger):
    def __init__(self, max_iteration: int):
        self.max_iteration = max_iteration

    def __call__(self, state):
        return state.get("neval", 0) >= self.max_iteration


class _MaxScore(Trigger):
    def __init__(self, max_score: float):
        self.max_score = max_score

    def __call__(self, state):
        s = state.get("score")
        # score is set from the gathered (multi-host: allgathered)
        # validation result — the same value lands on every process
        # replicated-by: global-loss-reduction
        return s is not None and s >= self.max_score


class _MinLoss(Trigger):
    def __init__(self, min_loss: float):
        self.min_loss = min_loss

    def __call__(self, state):
        l = state.get("loss")
        # loss is the psum'd global mean — uniform by reduction
        # replicated-by: global-loss-reduction
        return l is not None and l <= self.min_loss


def every_epoch() -> Trigger:
    return _EveryEpoch()


def several_iteration(interval: int) -> Trigger:
    return _SeveralIteration(interval)


def max_epoch(n: int) -> Trigger:
    return _MaxEpoch(n)


def max_iteration(n: int) -> Trigger:
    return _MaxIteration(n)


def max_score(s: float) -> Trigger:
    return _MaxScore(s)


def min_loss(l: float) -> Trigger:
    return _MinLoss(l)


def probe_fire_step(state: dict, k_max: int, records_per_step: int,
                    epoch_size: int,
                    triggers: Iterable[Trigger]) -> Optional[int]:
    """First step offset j in ``1..k_max`` at which any trigger would
    fire, simulating the driver-state advance from ``state`` — or None
    when a full ``k_max``-step block is trigger-free.

    This is how the fused loop keeps trigger semantics EXACT for
    iteration/epoch-count triggers at K>1: a block is capped so that a
    firing iteration is always the block's LAST step, and the host
    replay (validation/checkpoint/stop) happens with the params of
    exactly that iteration.  Loss/score-keyed triggers are probed with
    their last known values (the block's losses don't exist yet); they
    still fire at the right iteration during the replay, but the probe
    can't pre-sync on them — see the "stepping & input pipeline"
    section of the README for the documented divergence.

    ``records_per_step`` is the GLOBAL batch size (0 = unknown: epoch
    rollover is then left to the stager's records budget, which stops a
    block at the boundary from the actual batch sizes)."""
    triggers = [t for t in triggers if t is not None]
    neval = state.get("neval", 0)
    epoch = state.get("epoch", 0)
    records = state.get("records_processed_this_epoch", 0)
    for j in range(1, int(k_max) + 1):
        sim = dict(state)
        sim["probe"] = True
        sim["neval"] = neval + j
        rec = records + j * records_per_step
        finishes_epoch = records_per_step > 0 and rec >= epoch_size
        sim["records_processed_this_epoch"] = 0 if finishes_epoch else rec
        sim["epoch"] = epoch + 1 if finishes_epoch else epoch
        sim["epoch_finished"] = finishes_epoch
        if finishes_epoch or any(t(sim) for t in triggers):
            return j
    return None
