"""Triggers — cadence/stop conditions for training loops.

Reference: ``DL/optim/Trigger.scala:30-119`` (``everyEpoch``,
``severalIteration``, ``maxEpoch``, ``maxIteration``, ``maxScore``,
``minLoss``), composable with and/or.  A trigger is a predicate over the
driver's training state dict.

State keys (mirroring the reference's state Table): ``epoch`` (0-based,
current), ``neval`` (iteration counter, 1-based after first step),
``loss``, ``score``, and ``epoch_finished`` (set by the loop at epoch
boundaries so everyEpoch fires once per rollover).
"""

from __future__ import annotations


class Trigger:
    def __call__(self, state: dict) -> bool:
        raise NotImplementedError

    def and_(self, other: "Trigger") -> "Trigger":
        return _And(self, other)

    def or_(self, other: "Trigger") -> "Trigger":
        return _Or(self, other)


class _And(Trigger):
    def __init__(self, a, b):
        self.a, self.b = a, b

    def __call__(self, state):
        return self.a(state) and self.b(state)


class _Or(Trigger):
    def __init__(self, a, b):
        self.a, self.b = a, b

    def __call__(self, state):
        return self.a(state) or self.b(state)


class _EveryEpoch(Trigger):
    def __call__(self, state):
        return bool(state.get("epoch_finished", False))


class _SeveralIteration(Trigger):
    def __init__(self, interval: int):
        self.interval = interval

    def __call__(self, state):
        n = state.get("neval", 0)
        return n > 0 and n % self.interval == 0


class _MaxEpoch(Trigger):
    def __init__(self, max_epoch: int):
        self.max_epoch = max_epoch

    def __call__(self, state):
        return state.get("epoch", 0) >= self.max_epoch


class _MaxIteration(Trigger):
    def __init__(self, max_iteration: int):
        self.max_iteration = max_iteration

    def __call__(self, state):
        return state.get("neval", 0) >= self.max_iteration


class _MaxScore(Trigger):
    def __init__(self, max_score: float):
        self.max_score = max_score

    def __call__(self, state):
        s = state.get("score")
        return s is not None and s >= self.max_score


class _MinLoss(Trigger):
    def __init__(self, min_loss: float):
        self.min_loss = min_loss

    def __call__(self, state):
        l = state.get("loss")
        return l is not None and l <= self.min_loss


def every_epoch() -> Trigger:
    return _EveryEpoch()


def several_iteration(interval: int) -> Trigger:
    return _SeveralIteration(interval)


def max_epoch(n: int) -> Trigger:
    return _MaxEpoch(n)


def max_iteration(n: int) -> Trigger:
    return _MaxIteration(n)


def max_score(s: float) -> Trigger:
    return _MaxScore(s)


def min_loss(l: float) -> Trigger:
    return _MinLoss(l)
