"""DataFrame-free Estimator/Transformer facade.

Reference: ``DL/dlframes/DLEstimator.scala`` + the Spark-ML thin aliases
``org/apache/spark/ml/DLEstimator.scala:49`` / ``DLClassifier.scala:83`` —
an ``Estimator.fit(DataFrame) -> Model`` / ``Model.transform(DataFrame)``
pair wrapping Optimizer and Predictor.

TPU redesign (SURVEY §7 stage 7): Spark DataFrames don't exist here, so
``fit``/``transform`` operate on array-likes (or ``AbstractDataSet``s) —
the scikit-learn-shaped contract the Spark-ML API itself imitates.  The
parameter surface (feature/label sizes, batch size, epochs, optim method,
validation) mirrors ``DLEstimator``'s params.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

from bigdl_tpu import nn, optim
from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.nn.module import Module
from bigdl_tpu.optim.predictor import Predictor


class NNModel:
    """Fitted transformer (reference ``DLModel``/``DLTransformerBase``)."""

    def __init__(self, model: Module, params=None, state=None,
                 batch_size: int = 128):
        self.model = model
        self.params = params if params is not None else model._params
        self.state = state if state is not None else model._state
        self.batch_size = batch_size
        self._predictor = Predictor(model, params=self.params,
                                    state=self.state, batch_size=batch_size)

    def transform(self, features) -> np.ndarray:
        """Batched forward over features (reference ``DLModel.transform``)."""
        return self._predictor.predict(np.asarray(features))

    def set_batch_size(self, n: int) -> "NNModel":
        self.batch_size = n
        self._predictor.batch_size = n
        return self


class NNClassifierModel(NNModel):
    """Classifier variant: transform returns class ids
    (reference ``DLClassifierModel`` — argmax + 1-based labels; here
    0-based like the rest of the TPU build)."""

    def transform(self, features) -> np.ndarray:
        return np.argmax(super().transform(features), axis=-1)


class NNEstimator:
    """Unfitted estimator (reference ``DLEstimator.scala``)."""

    model_cls = NNModel

    def __init__(self, model: Module, criterion: nn.Criterion,
                 batch_size: int = 32, max_epoch: int = 10,
                 optim_method: Optional[optim.OptimMethod] = None,
                 distributed: bool = False):
        self.model = model
        self.criterion = criterion
        self.batch_size = batch_size
        self.max_epoch = max_epoch
        self.optim_method = optim_method or optim.SGD(learning_rate=0.01)
        self.distributed = distributed
        self.validation: Optional[tuple] = None
        self.end_when: Optional[optim.Trigger] = None

    # ---------------------------------------------------------- builders
    def set_batch_size(self, n: int) -> "NNEstimator":
        self.batch_size = n
        return self

    def set_max_epoch(self, n: int) -> "NNEstimator":
        self.max_epoch = n
        return self

    def set_optim_method(self, m: optim.OptimMethod) -> "NNEstimator":
        self.optim_method = m
        return self

    def set_end_when(self, trigger: optim.Trigger) -> "NNEstimator":
        self.end_when = trigger
        return self

    def set_validation(self, trigger: optim.Trigger, features, labels,
                       methods: Sequence[optim.ValidationMethod],
                       batch_size: Optional[int] = None) -> "NNEstimator":
        self.validation = (trigger, features, labels,
                           list(methods), batch_size or self.batch_size)
        return self

    # --------------------------------------------------------------- fit
    def _to_dataset(self, features, labels, batch_size,
                    drop_remainder=True) -> AbstractDataSet:
        if isinstance(features, AbstractDataSet):
            return features
        f = np.asarray(features)
        l = None if labels is None else np.asarray(labels)
        samples = [Sample(f[i], None if l is None else l[i])
                   for i in range(len(f))]
        return DataSet.array(samples) >> SampleToMiniBatch(
            batch_size, drop_remainder=drop_remainder)

    def fit(self, features, labels=None) -> NNModel:
        """Train and return the fitted ``NNModel``
        (reference ``DLEstimator.fit`` → internal Optimizer)."""
        train_set = self._to_dataset(features, labels, self.batch_size)
        cls = (optim.DistriOptimizer if self.distributed
               else optim.LocalOptimizer)
        optimizer = (cls(self.model, train_set, self.criterion)
                     .set_optim_method(self.optim_method)
                     .set_end_when(self.end_when
                                   or optim.max_epoch(self.max_epoch)))
        if self.validation is not None:
            trig, vf, vl, methods, vbs = self.validation
            val_set = self._to_dataset(vf, vl, vbs, drop_remainder=False)
            optimizer.set_validation(trig, val_set, methods)
        optimizer.optimize()
        return self.model_cls(self.model, batch_size=self.batch_size)


class NNClassifier(NNEstimator):
    """Classification estimator (reference ``DLClassifier.scala``)."""

    model_cls = NNClassifierModel

    def __init__(self, model: Module,
                 criterion: Optional[nn.Criterion] = None, **kw):
        super().__init__(model, criterion or nn.ClassNLLCriterion(), **kw)
