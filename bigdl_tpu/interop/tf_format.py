"""TensorFlow GraphDef importer.

Reference: ``DL/utils/tf/TensorflowLoader.scala`` — ``load:55`` parses the
GraphDef protobuf, ``buildTFGraph:201`` reverse-DFSes from the requested
outputs to prune the (often training-) graph down to the inference
subgraph, ``buildBigDLModel:358`` maps nodes through 159 per-op loaders.

TPU redesign: instead of pattern-matching fused subgraphs into nn layers,
the pruned graph executes directly as ONE pure jax function over the
``bigdl_tpu.ops`` registry — XLA re-fuses it better than hand-matching
would, and a single registry replaces the 159 loader files.  Variables
(``VariableV2``) become trainable parameters of the returned module
(initialized from their ``Assign`` initializer subgraph when it is
evaluable); ``Const`` nodes fold into the trace.

Reads both binary ``.pb`` and text ``.pbtxt`` GraphDefs (the reference
test fixtures are pbtxt) with no generated protobuf code — wire decoding
via ``utils/protowire``, text decoding via a minimal recursive parser.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.nn.module import Module
from bigdl_tpu.interop.tf_loops import extract_frames
from bigdl_tpu.ops import get_op
from bigdl_tpu.utils import protowire as pw

# tensorflow DataType enum values
_DT_NP = {1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8,
          5: np.int16, 6: np.int8, 7: np.bytes_, 9: np.int64, 10: np.bool_}


# ===========================================================================
# binary GraphDef decode
# ===========================================================================
def _decode_tensor_proto(m: Dict[int, list]) -> np.ndarray:
    dtype = int(m.get(1, [1])[0])
    np_dt = _DT_NP.get(dtype, np.float32)
    shape: List[int] = []
    if 2 in m:
        sm = pw.decode_message(m[2][0])
        for dim in sm.get(2, []):
            dm = pw.decode_message(dim)
            shape.append(pw.as_sint(dm.get(1, [0])[0]))
    if 4 in m and m[4][0]:
        arr = np.frombuffer(m[4][0], dtype=np_dt)
    elif dtype == 1 and 5 in m:
        vals = []
        for v in m[5]:
            vals.extend(pw.unpack_packed(v, "float")
                        if isinstance(v, bytes) else [pw.as_float(v)])
        arr = np.asarray(vals, np.float32)
    elif dtype == 2 and 6 in m:
        vals = []
        for v in m[6]:
            vals.extend(pw.unpack_packed(v, "double")
                        if isinstance(v, bytes) else [pw.as_double(v)])
        arr = np.asarray(vals, np.float64)
    elif dtype in (3, 4, 5, 6) and 7 in m:
        arr = np.asarray([pw.as_sint(v) for v in pw.ints(m, 7)], np_dt)
    elif dtype == 9 and 10 in m:
        arr = np.asarray([pw.as_sint(v) for v in pw.ints(m, 10)], np.int64)
    elif dtype == 10 and 11 in m:
        arr = np.asarray(pw.ints(m, 11), np.bool_)
    elif dtype == 7 and 8 in m:
        return np.asarray(m[8], object)
    else:
        arr = np.zeros(0, np_dt)
    n = int(np.prod(shape)) if shape else arr.size
    if arr.size == 1 and n > 1:   # splat-encoded constant
        arr = np.full(n, arr[0], arr.dtype)
    return arr.reshape(shape) if shape else (
        arr.reshape(()) if arr.size == 1 else arr)


def _decode_attr_value(data: bytes) -> Any:
    m = pw.decode_message(data)
    if 2 in m:
        return m[2][0]                       # s (bytes)
    if 3 in m:
        return pw.as_sint(m[3][0])           # i
    if 4 in m:
        return pw.as_float(m[4][0])          # f
    if 5 in m:
        return bool(m[5][0])                 # b
    if 6 in m:
        return int(m[6][0])                  # type enum
    if 8 in m:
        return _decode_tensor_proto(pw.decode_message(m[8][0]))  # tensor
    if 7 in m:
        sm = pw.decode_message(m[7][0])      # shape
        dims = []
        for dim in sm.get(2, []):
            dm = pw.decode_message(dim)
            dims.append(pw.as_sint(dm.get(1, [0])[0]))
        return dims
    if 1 in m:                               # list
        lm = pw.decode_message(m[1][0])
        if 3 in lm:
            return [pw.as_sint(v) for v in pw.ints(lm, 3)]
        if 4 in lm:
            out = []
            for v in lm[4]:
                out.extend(pw.unpack_packed(v, "float")
                           if isinstance(v, bytes) else [pw.as_float(v)])
            return out
        if 2 in lm:
            return list(lm[2])
        if 5 in lm:
            return [bool(v) for v in pw.ints(lm, 5)]
        if 7 in lm:                          # list(shape) — ParseExample
            out = []
            for sh in lm[7]:
                sm2 = pw.decode_message(sh)
                out.append([pw.as_sint(pw.decode_message(d).get(1, [0])[0])
                            for d in sm2.get(2, [])])
            return out
        return []
    return None


def parse_graphdef_binary(data: bytes) -> List[dict]:
    g = pw.decode_message(data)
    nodes = []
    for nd in g.get(1, []):
        m = pw.decode_message(nd)
        attrs = {}
        for e in m.get(5, []):
            em = pw.decode_message(e)
            attrs[pw.as_str(em[1][0])] = _decode_attr_value(em[2][0])
        nodes.append({
            "name": pw.as_str(m[1][0]),
            "op": pw.as_str(m[2][0]) if 2 in m else "",
            "inputs": [pw.as_str(v) for v in m.get(3, [])],
            "attrs": attrs,
        })
    return nodes


# ===========================================================================
# text GraphDef (.pbtxt) decode
# ===========================================================================
_TOKEN = re.compile(
    r'\s*(?:(#[^\n]*)|([A-Za-z_][A-Za-z0-9_]*)|("(?:\\.|[^"\\])*")'
    r"|([{}:])|(-?[0-9][0-9eE+\-.]*)|(-inf|inf|nan))")


def _tokenize(text: str):
    pos = 0
    n = len(text)
    while pos < n:
        mt = _TOKEN.match(text, pos)
        if not mt:
            if text[pos:].strip() == "":
                return
            raise ValueError(f"pbtxt parse error at {text[pos:pos+40]!r}")
        pos = mt.end()
        if mt.group(1):
            continue  # comment
        yield mt.group(0).strip()


_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "'": "'",
            "\\": "\\", "a": "\a", "b": "\b", "f": "\f", "v": "\v"}


def _unescape(s: str) -> bytes:
    """C-style escaped text-proto string → bytes."""
    out = bytearray()
    i = 0
    while i < len(s):
        c = s[i]
        if c != "\\":
            out.extend(c.encode("utf-8", "surrogateescape"))
            i += 1
            continue
        i += 1
        c = s[i]
        if c in _ESCAPES:
            out.append(ord(_ESCAPES[c]))
            i += 1
        elif c in "01234567":
            oct_digits = s[i:i + 3]
            j = 1
            while j < 3 and j < len(oct_digits) and oct_digits[j] in \
                    "01234567":
                j += 1
            out.append(int(s[i:i + j], 8))
            i += j
        elif c == "x":
            out.append(int(s[i + 1:i + 3], 16))
            i += 3
        else:
            out.append(ord(c))
            i += 1
    return bytes(out)


def _parse_textproto(tokens) -> dict:
    """Parse one message body; repeated keys collect into lists."""
    msg: Dict[str, list] = {}
    for tok in tokens:
        if tok == "}":
            return msg
        key = tok
        nxt = next(tokens)
        if nxt == "{":
            val = _parse_textproto(tokens)
        elif nxt == ":":
            v = next(tokens)
            if v == "{":
                val = _parse_textproto(tokens)
            elif v.startswith('"'):
                val = _unescape(v[1:-1])
            elif v in ("true", "false"):
                val = v == "true"
            else:
                try:
                    val = int(v)
                except ValueError:
                    try:
                        val = float(v)
                    except ValueError:
                        val = v  # enum name (DT_FLOAT etc.)
        else:
            raise ValueError(f"unexpected token {nxt!r} after {key!r}")
        msg.setdefault(key, []).append(val)
    return msg


_DT_NAMES = {"DT_FLOAT": 1, "DT_DOUBLE": 2, "DT_INT32": 3, "DT_UINT8": 4,
             "DT_INT16": 5, "DT_INT8": 6, "DT_STRING": 7, "DT_INT64": 9,
             "DT_BOOL": 10}


def _text_tensor(t: dict) -> np.ndarray:
    dtype = _DT_NAMES.get(t.get("dtype", ["DT_FLOAT"])[0], 1)
    np_dt = _DT_NP.get(dtype, np.float32)
    shape: List[int] = []
    for sh in t.get("tensor_shape", []):
        for dim in sh.get("dim", []):
            shape.append(int(dim.get("size", [0])[0]))
    if "tensor_content" in t:
        arr = np.frombuffer(t["tensor_content"][0], dtype=np_dt)
    elif "float_val" in t:
        arr = np.asarray([float(v) for v in t["float_val"]], np.float32)
    elif "int_val" in t:
        arr = np.asarray([int(v) for v in t["int_val"]], np_dt)
    elif "int64_val" in t:
        arr = np.asarray([int(v) for v in t["int64_val"]], np.int64)
    elif "double_val" in t:
        arr = np.asarray([float(v) for v in t["double_val"]], np.float64)
    elif "bool_val" in t:
        arr = np.asarray(t["bool_val"], np.bool_)
    elif "string_val" in t:
        return np.asarray(t["string_val"], object)
    else:
        arr = np.zeros(0, np_dt)
    n = int(np.prod(shape)) if shape else arr.size
    if arr.size == 1 and n > 1:
        arr = np.full(n, arr[0], arr.dtype)
    return arr.reshape(shape) if shape else (
        arr.reshape(()) if arr.size == 1 else arr)


def _text_attr(v: dict) -> Any:
    if "s" in v:
        return v["s"][0]
    if "i" in v:
        return int(v["i"][0])
    if "f" in v:
        return float(v["f"][0])
    if "b" in v:
        return bool(v["b"][0])
    if "type" in v:
        return _DT_NAMES.get(v["type"][0], 1)
    if "tensor" in v:
        return _text_tensor(v["tensor"][0])
    if "shape" in v:
        dims = []
        for dim in v["shape"][0].get("dim", []):
            dims.append(int(dim.get("size", [0])[0]))
        return dims
    if "list" in v:
        lv = v["list"][0]
        for k in ("i", "f", "s", "b"):
            if k in lv:
                return [int(x) if k == "i" else x for x in lv[k]]
        return []
    return None


def parse_graphdef_text(text: str) -> List[dict]:
    root = _parse_textproto(_tokenize(text))
    nodes = []
    for nd in root.get("node", []):
        attrs = {}
        for a in nd.get("attr", []):
            key = a["key"][0]
            key = key.decode() if isinstance(key, bytes) else key
            attrs[key] = _text_attr(a["value"][0])
        name = nd["name"][0]
        op = nd["op"][0]
        nodes.append({
            "name": name.decode() if isinstance(name, bytes) else name,
            "op": op.decode() if isinstance(op, bytes) else op,
            "inputs": [i.decode() if isinstance(i, bytes) else i
                       for i in nd.get("input", [])],
            "attrs": attrs,
        })
    return nodes


# ===========================================================================
# graph build + execution
# ===========================================================================
def _base_name(inp: str) -> Tuple[str, int]:
    """'node:2' → ('node', 2); '^ctrl' → ('ctrl', -1)."""
    if inp.startswith("^"):
        return inp[1:], -1
    if ":" in inp:
        name, ix = inp.rsplit(":", 1)
        return name, int(ix)
    return inp, 0


# ------------------------------------------------ control flow (tf.cond)
# The reference executes loaded control flow with a dataflow Scheduler over
# Enter/Exit/Switch/Merge frames (``DL/nn/Scheduler.scala:104-145``) —
# dead-token propagation, host-driven.  Under XLA, data-dependent
# branching compiles to "execute both branches, select" — so Switch tags
# each branch's values with (predicate, branch) provenance and Merge emits
# ``jnp.where(pred, true_val, false_val)``.  Loop frames would need
# ``lax.while_loop`` reconstruction and are rejected explicitly.
class _Tagged:
    """A value that flowed through a Switch branch; ``tags`` maps the
    predicate node name → (pred array, branch bool)."""

    __slots__ = ("value", "tags")

    def __init__(self, value, tags):
        self.value = value
        self.tags = tags


def _tag_value(a):
    return a.value if isinstance(a, _Tagged) else a


def _union_tags(args) -> dict:
    tags: dict = {}
    for a in args:
        if isinstance(a, _Tagged):
            tags.update(a.tags)
    return tags


def _exec_switch(args, pred_name: str):
    data, pred = args[0], args[1]
    base = _union_tags(args)
    d, p = _tag_value(data), _tag_value(pred)
    false_out = _Tagged(d, {**base, pred_name: (p, False)})
    true_out = _Tagged(d, {**base, pred_name: (p, True)})
    return (false_out, true_out)  # TF Switch ports: 0=false, 1=true


def _exec_merge(args):
    import jax.numpy as jnp
    tagged = [a for a in args if isinstance(a, _Tagged)]
    keys: set = set()
    for t in tagged:
        keys |= set(t.tags)
    for key in keys:
        branches = {}
        for a in tagged:
            if key in a.tags:
                branches[a.tags[key][1]] = a
        if True in branches and False in branches:
            pred = branches[True].tags[key][0]
            sel = jnp.where(pred, _tag_value(branches[True]),
                            _tag_value(branches[False]))
            rest = _union_tags(tagged)
            rest.pop(key, None)
            out = _Tagged(sel, rest) if rest else sel
            return (out, jnp.asarray(0, jnp.int32))
    if len(args) == 1:  # one live input (other side pruned)
        return (args[0], jnp.asarray(0, jnp.int32))
    raise NotImplementedError(
        "Merge whose inputs don't trace to complementary Switch branches")


class TFGraphModule(Module):
    """Executable imported graph (reference ``Session``-less analog of the
    BigDL ``Graph`` built by ``buildBigDLModel``).

    - ``params``: the VariableV2 nodes (trainable, initialized from their
      Assign-initializer when evaluable, else zeros);
    - ``apply(params, state, input)``: runs the pruned graph; ``input`` is
      one array (single placeholder) or a dict {placeholder_name: array}.
    """

    def __init__(self, nodes: List[dict], inputs: Sequence[str],
                 outputs: Sequence[str], name: Optional[str] = None):
        super().__init__(name)
        self.by_name = {n["name"]: n for n in nodes}
        self.input_names = list(inputs)
        self.output_names = list(outputs)
        self._var_init: Dict[str, np.ndarray] = {}
        # while-loop frames (Enter/Merge/Switch/Exit wiring -> one
        # lax.while_loop each; see interop/tf_loops.py)
        self._frames = extract_frames(nodes)
        self._node_frame: Dict[str, "object"] = {}
        for fr in self._frames.values():
            for nm in fr.interior:
                self._node_frame[nm] = fr

        # prune: reverse DFS from outputs (reference buildTFGraph:201).
        # Nodes named in ``inputs`` become feed points whatever their op —
        # that is how the reference substitutes queue/reader sources with
        # user-fed endpoints (TensorflowLoader inputs param).
        feed_points = {_base_name(i)[0] for i in inputs}
        needed: List[str] = []
        seen = set()
        stack = [_base_name(o)[0] for o in outputs]
        while stack:
            nm = stack.pop()
            if nm in seen:
                continue
            seen.add(nm)
            node = self.by_name.get(nm)
            if node is None:
                raise KeyError(f"graph has no node {nm!r}")
            needed.append(nm)
            if node["op"] in ("Placeholder", "PlaceholderV2") \
                    or nm in feed_points:
                continue
            if nm in self._node_frame:
                err = self._node_frame[nm].nest_error()
                if err:
                    raise NotImplementedError(err)
            if node["op"] == "Exit" and nm in self._node_frame:
                # pull the whole frame NEST + every external input it reads
                fr = self._node_frame[nm]
                for inm in fr.all_interior():
                    if inm not in seen:
                        seen.add(inm)
                        needed.append(inm)
                stack.extend(fr.all_externals())
                continue
            for inp in node["inputs"]:
                b, ix = _base_name(inp)
                if ix >= 0:   # skip control deps
                    stack.append(b)
        self.needed = set(needed)
        self.feed_points = feed_points

        # resolve VariableV2 initial values via their Assign nodes
        assigns = {}
        for n in nodes:
            if n["op"] == "Assign" and n["inputs"]:
                target = _base_name(n["inputs"][0])[0]
                assigns[target] = _base_name(n["inputs"][1])[0]
        for nm in self.needed:
            node = self.by_name[nm]
            if node["op"] in ("VariableV2", "Variable"):
                shape = node["attrs"].get("shape", [])
                init = None
                if nm in assigns:
                    init = self._try_const_eval(assigns[nm])
                if init is None:
                    init = np.zeros([int(d) for d in shape], np.float32)
                self._var_init[nm] = np.asarray(init, np.float32)

        # topological order over the pruned subgraph
        order: List[str] = []
        state = {}

        def visit(nm: str):
            st = state.get(nm)
            if st == 2:
                return
            if st == 1:
                raise ValueError(f"cycle through {nm} (control flow needs "
                                 "the DynamicGraph scheduler)")
            state[nm] = 1
            node = self.by_name[nm]
            fr = self._node_frame.get(nm)
            top_exit = (fr is not None and node["op"] == "Exit"
                        and fr.parent is None)
            if top_exit:
                # an Exit depends on every EXTERNAL input of its nest
                for b in fr.all_externals():
                    if b in self.needed:
                        visit(b)
            elif fr is not None:
                pass  # interior nodes execute inside the frame's while
            elif node["op"] not in ("Placeholder", "PlaceholderV2",
                                    "VariableV2", "Variable", "Const") \
                    and nm not in self.feed_points:
                for inp in node["inputs"]:
                    b, ix = _base_name(inp)
                    if ix >= 0 and b in self.needed:
                        visit(b)
            state[nm] = 2
            if fr is None or top_exit:
                order.append(nm)

        import sys
        old = sys.getrecursionlimit()
        sys.setrecursionlimit(max(old, 10 * len(self.needed) + 100))
        try:
            for o in outputs:
                visit(_base_name(o)[0])
        finally:
            sys.setrecursionlimit(old)
        # requesting a loop-INTERIOR node as an output cannot work (only
        # Exit values exist after the fused while); fail at load, clearly
        for o in outputs:
            b = _base_name(o)[0]
            fr = self._node_frame.get(b)
            if fr is not None and (self.by_name[b]["op"] != "Exit"
                                   or fr.parent is not None):
                raise NotImplementedError(
                    f"output {o!r} is inside while frame {fr.name!r}; "
                    "only Exit values of a TOP-LEVEL loop are addressable")
        self.order = order
        self._fold_constants()

    def _fold_constants(self) -> None:
        """Pre-evaluate every node that depends only on Consts, in numpy,
        at build time.  Required for correctness, not just speed: inside a
        jit trace every jax op output is a tracer, so shape-computation
        subgraphs (Shape→Slice→Pack→Reshape chains) would feed tracers
        into ``Reshape``'s static shape argument and fail.  The reference
        constant-folds the same chains during import
        (``TensorflowToBigDL`` pattern matching)."""
        folded: Dict[str, np.ndarray] = {}
        dynamic_ops = {"Placeholder", "PlaceholderV2", "VariableV2",
                       "Variable", "RandomUniform", "RandomStandardNormal",
                       "TruncatedNormal"}
        for nm in self.order:
            node = self.by_name[nm]
            op = node["op"]
            if op == "Const":
                folded[nm] = np.asarray(node["attrs"]["value"])
                continue
            if op in dynamic_ops or nm in self.feed_points \
                    or nm in self._node_frame \
                    or op.startswith("TensorArray"):
                # TensorArray ops produce handle/flow objects, not
                # foldable arrays
                continue
            args = []
            ok = True
            for inp in node["inputs"]:
                b, ix = _base_name(inp)
                if ix < 0:
                    continue
                if b not in folded:
                    ok = False
                    break
                v = folded[b]
                args.append(v[ix] if isinstance(v, tuple) else v)
            if not ok:
                continue
            try:
                out = get_op(op)(
                    {**node["attrs"], "_node_name": nm}, *args)
            except NotImplementedError:
                continue
            folded[nm] = (tuple(np.asarray(o) for o in out)
                          if isinstance(out, tuple) else np.asarray(out))
        self._folded = folded

    def _try_const_eval(self, nm: str, depth: int = 0) -> Optional[np.ndarray]:
        """Eagerly evaluate an initializer subgraph — Consts plus any op
        the registry knows, including the random ops (TruncatedNormal
        initializers evaluate with a node-seeded key, so an imported
        un-frozen graph gets REAL initial weights, not zeros — all-zero
        convs would train dead)."""
        if depth > 32:
            return None
        node = self.by_name.get(nm)
        if node is None:
            return None
        if node["op"].startswith("TensorArray"):
            return None  # handle/flow objects, not arrays
        if node["op"] == "Const":
            return np.asarray(node["attrs"]["value"])
        args = []
        for inp in node["inputs"]:
            b, ix = _base_name(inp)
            if ix < 0:
                continue
            v = self._try_const_eval(b, depth + 1)
            if v is None:
                return None
            args.append(v)
        try:
            out = get_op(node["op"])(
                {**node["attrs"], "_node_name": nm}, *args)
        except Exception:
            return None
        return None if isinstance(out, tuple) else np.asarray(out)

    # ----------------------------------------------------- while frames
    def _eval_interior(self, fr, bind, values, target: str,
                       memo: Optional[Dict[str, Any]] = None):
        """Evaluate interior node ``target`` with Merge/invariant-Enter
        nodes bound via ``bind`` and exterior values from ``values``.
        Pass one ``memo`` across several targets of the same invocation so
        shared body subgraphs trace once, not once per loop variable."""
        if memo is None:
            memo = {}

        def ev(nm: str):
            if nm in memo:
                return memo[nm]
            if nm in bind:
                memo[nm] = bind[nm]
                return bind[nm]
            if nm not in fr.interior:
                sub = self._node_frame.get(nm)
                if sub is not None and sub is not fr \
                        and sub.parent is not None:
                    # NESTED frame's Exit demanded by this body: run the
                    # child loop as one fused sub-loop, resolving its
                    # outer inputs through THIS evaluation context
                    # (reference FrameManager parent/child frames,
                    # Scheduler.scala:104-145)
                    err = sub.nest_error()
                    if err:
                        raise NotImplementedError(err)

                    class _Ctx:
                        def __getitem__(_self, key):
                            if key in memo or key in bind \
                                    or key in fr.interior:
                                return ev(key)
                            return values[key]

                        def __setitem__(_self, key, val):
                            memo[key] = val

                    self._run_frame(sub, _Ctx())
                    return memo[nm]
                return values[nm]  # port/tag handling at the consumer
            node = self.by_name[nm]
            op = node["op"]
            if op in ("Merge",):  # bound above; a Merge not in bind is odd
                raise NotImplementedError(
                    f"unbound Merge {nm} in while frame {fr.name}")
            if op in ("Switch", "LoopCond", "Identity", "NextIteration",
                      "Enter"):
                b0, ix0 = _base_name(node["inputs"][0])
                out = ev(b0)
                out = out[ix0] if isinstance(out, tuple) else out
                memo[nm] = out
                return out
            args = []
            for inp in node["inputs"]:
                b, ix = _base_name(inp)
                if ix < 0:
                    continue
                v = ev(b)
                v = v[ix] if isinstance(v, tuple) else v
                args.append(_tag_value(v))
            out = get_op(op)({**node["attrs"], "_node_name": nm}, *args)
            memo[nm] = out
            return out

        b, ix = _base_name(target)
        v = ev(b)
        v = v[ix] if isinstance(v, tuple) else v
        return _tag_value(v)

    def _run_frame(self, fr, values) -> None:
        """Execute one while frame with lax.while_loop; store every
        Exit's value into ``values``."""
        import jax.numpy as jnp
        from jax import lax

        def outer_value(inp: str):
            b, ix = _base_name(inp)
            v = values[b]
            v = v[ix] if isinstance(v, tuple) else v
            return _tag_value(v)

        invariant_bind = {inv["name"]: outer_value(inv["inputs"][0])
                          for inv in fr.invariants}

        # map each NextIteration to its loop variable (via its Merge)
        nextit_of_merge = {}
        for m, e in zip(fr.merges, fr.enters):
            for inp in m["inputs"]:
                bse = _base_name(inp)[0]
                if bse != e["name"]:
                    nextit_of_merge[m["name"]] = self.by_name[bse]

        # initial carry: the Enter inputs (outer values), merge-ordered.
        # A TensorArray flow entering with unknown element shape
        # (TAPending) is resolved by probing the body once: the write op
        # inside allocates real storage, whose shape/dtype seeds the
        # zero-initialised carry (ops/registry.py TensorArray family).
        from bigdl_tpu.ops.registry import TAPending
        raw0 = [outer_value(e["inputs"][0]) for e in fr.enters]
        if any(isinstance(v, TAPending) for v in raw0):
            probe_bind = dict(invariant_bind)
            for m, c in zip(fr.merges, raw0):
                probe_bind[m["name"]] = c
            probe_memo: Dict[str, Any] = {}
            for i, (m, v) in enumerate(zip(fr.merges, raw0)):
                if not isinstance(v, TAPending):
                    continue
                ni = nextit_of_merge.get(m["name"])
                if ni is None:
                    raise NotImplementedError(
                        f"TensorArray flow {m['name']} is never written "
                        "inside its loop; element shape unknown")
                out = self._eval_interior(fr, probe_bind, values,
                                          ni["inputs"][0], probe_memo)
                raw0[i] = jnp.zeros_like(out)
        carry0 = tuple(jnp.asarray(v) for v in raw0)

        def bindings(carry):
            bind = dict(invariant_bind)
            for m, c in zip(fr.merges, carry):
                bind[m["name"]] = c
            return bind

        def cond(carry):
            b = self._eval_interior(fr, bindings(carry), values,
                                    fr.loop_cond["inputs"][0])
            return jnp.reshape(jnp.asarray(b, bool), ())

        def body(carry):
            bind = bindings(carry)
            memo: Dict[str, Any] = {}
            outs = []
            for m, c in zip(fr.merges, carry):
                ni = nextit_of_merge.get(m["name"])
                if ni is None:
                    outs.append(c)
                    continue
                v = self._eval_interior(fr, bind, values,
                                        ni["inputs"][0], memo)
                outs.append(jnp.asarray(v, c.dtype).reshape(c.shape))
            return tuple(outs)

        # bounded loop with a statically recoverable trip count → scan
        # (reverse-differentiable, so imported graphs with loops TRAIN);
        # else dynamic while_loop (forward-only, a JAX fundamental)
        from bigdl_tpu.interop.tf_loops import static_trip_count
        n_trip = static_trip_count(fr, self.by_name, self._try_const_eval)
        if n_trip is not None:
            def scan_body(carry, _):
                return body(carry), None
            final, _ = lax.scan(scan_body, carry0, None, length=n_trip)
        else:
            final = lax.while_loop(cond, body, carry0)

        # each Exit's input chains (through Switch:0) to a Merge
        merge_ix = {m["name"]: i for i, m in enumerate(fr.merges)}
        for ex in fr.exits:
            nm = _base_name(ex["inputs"][0])[0]
            # walk passthroughs until a Merge
            hops = 0
            while nm not in merge_ix and hops < 16:
                nm = _base_name(self.by_name[nm]["inputs"][0])[0]
                hops += 1
            if nm not in merge_ix:
                raise NotImplementedError(
                    f"Exit {ex['name']} does not trace to a loop variable")
            values[ex["name"]] = final[merge_ix[nm]]

    # ---------------------------------------------------------------- API
    def init(self, rng):
        import jax.numpy as jnp
        params = {k: jnp.asarray(v) for k, v in self._var_init.items()}
        return params, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        import jax.numpy as jnp
        values: Dict[str, Any] = {}
        if isinstance(input, dict):
            # normalize: users may feed by 'x' or port-suffixed 'x:0';
            # feeding SEVERAL ports of one node ('parse', 'parse:1' — the
            # ParseExample idiom) assembles a tuple value
            port_feeds: Dict[str, Dict[int, Any]] = {}
            for k, v in input.items():
                b, ix = _base_name(k)
                port_feeds.setdefault(b, {})[max(ix, 0)] = v
            feeds = {}
            for b, pf in port_feeds.items():
                if len(pf) == 1 and 0 in pf:
                    feeds[b] = jnp.asarray(pf[0])
                else:
                    hi = max(pf)
                    missing = [i for i in range(hi + 1) if i not in pf]
                    if missing:
                        raise ValueError(
                            f"feed {b!r}: ports {missing} not fed (got "
                            f"{sorted(pf)})")
                    feeds[b] = tuple(jnp.asarray(pf[i])
                                     for i in range(hi + 1))
        else:
            if len(self.input_names) != 1:
                raise ValueError(
                    f"graph has inputs {self.input_names}; feed a dict")
            feeds = {_base_name(self.input_names[0])[0]:
                     jnp.asarray(input)}
        for nm in self.order:
            node = self.by_name[nm]
            op = node["op"]
            if op in ("Placeholder", "PlaceholderV2") \
                    or nm in self.feed_points:
                values[nm] = feeds[nm]
            elif nm in self._folded:
                values[nm] = self._folded[nm]
            elif op in ("VariableV2", "Variable"):
                values[nm] = params[nm]
            elif op == "Exit" and nm in self._node_frame:
                if nm not in values:  # first Exit runs the whole frame
                    self._run_frame(self._node_frame[nm], values)
            else:
                args = []
                for inp in node["inputs"]:
                    b, ix = _base_name(inp)
                    if ix < 0:
                        continue
                    v = values[b]
                    args.append(v[ix] if isinstance(v, tuple) else v)
                if op in ("Enter", "Exit", "NextIteration", "LoopCond"):
                    raise NotImplementedError(
                        f"stray while-frame op {op!r} ({nm}) outside a "
                        "recognized loop frame")
                if op == "Switch":
                    pred_name = _base_name(node["inputs"][1])[0]
                    values[nm] = _exec_switch(args, pred_name)
                elif op == "Merge":
                    values[nm] = _exec_merge(args)
                else:
                    raw = [_tag_value(a) for a in args]
                    tags = _union_tags(args)
                    out = get_op(op)(
                        {**node["attrs"], "_node_name": nm}, *raw)
                    if not tags:
                        values[nm] = out
                    elif isinstance(out, tuple):
                        # tag each port so downstream `v[ix]` still works
                        values[nm] = tuple(_Tagged(o, tags) for o in out)
                    else:
                        values[nm] = _Tagged(out, tags)
        outs = []
        for o in self.output_names:
            b, ix = _base_name(o)
            v = values[b]
            v = v[ix] if isinstance(v, tuple) else v
            outs.append(_tag_value(v))
        out = outs[0] if len(outs) == 1 else tuple(outs)
        return out, state


def load_tf_graph(path: str, inputs: Sequence[str],
                  outputs: Sequence[str]) -> TFGraphModule:
    """Load a GraphDef (binary ``.pb`` or text ``.pbtxt``) and return the
    executable module for the subgraph inputs→outputs (reference
    ``Module.loadTF`` / ``TensorflowLoader.load:55``)."""
    with open(path, "rb") as f:
        data = f.read()
    if path.endswith(".pbtxt") or path.endswith(".txt"):
        nodes = parse_graphdef_text(data.decode("utf-8"))
    else:
        nodes = parse_graphdef_binary(data)
    mod = TFGraphModule(nodes, inputs, outputs)
    mod.initialize()
    return mod
