"""Train a loaded TF graph — the ``BigDLSessionImpl.train`` analog.

Reference: ``DL/utils/tf/Session.scala:43,105`` — ``train:111`` takes the
loss-node endpoints of an imported GraphDef, wires the queue-runner inputs
to an RDD, and hands the whole thing to DistriOptimizer.

TPU redesign: the imported :class:`TFGraphModule` is already a normal
functional module whose VariableV2 nodes are trainable params, so
"session training" is just adapter glue: pick the loss output (or an
output + criterion), feed batches from a ``DataSet``, and drive
``LocalOptimizer``/``DistriOptimizer``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from bigdl_tpu import nn, optim
from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.interop.tf_format import TFGraphModule, load_tf_graph


class TFSession:
    """(reference ``BigDLSessionImpl``) — train/fine-tune an imported
    GraphDef with the framework's optimizers."""

    def __init__(self, graph_or_path, inputs: Optional[Sequence[str]] = None,
                 outputs: Optional[Sequence[str]] = None):
        if isinstance(graph_or_path, TFGraphModule):
            self.graph = graph_or_path
        else:
            if inputs is None or outputs is None:
                raise ValueError("loading from a path needs inputs= and "
                                 "outputs= node names")
            self.graph = load_tf_graph(graph_or_path, inputs, outputs)

    def train(self, dataset: AbstractDataSet,
              criterion: nn.Criterion,
              optim_method: Optional[optim.OptimMethod] = None,
              end_when: Optional[optim.Trigger] = None,
              distributed: bool = False, mesh=None):
        """Train the imported graph's variables on ``dataset``
        (reference ``Session.train:111``).  The optimizer pairs the
        graph's output with ``criterion`` against each batch's target and
        writes the trained variables back onto the module.  Returns the
        optimizer (its ``state`` carries loss/epoch)."""
        if distributed:
            opt = optim.DistriOptimizer(self.graph, dataset, criterion,
                                        mesh=mesh)
        else:
            opt = optim.LocalOptimizer(self.graph, dataset, criterion)
        opt.set_optim_method(optim_method or optim.SGD(
            learning_rate=0.01, momentum=0.9, dampening=0.0))
        opt.set_end_when(end_when or optim.max_epoch(1))
        opt.optimize()
        return opt

    def run(self, feeds) -> np.ndarray:
        """Forward the graph on host arrays (``session.run`` analog)."""
        out = self.graph.forward(feeds)
        import jax
        return jax.tree_util.tree_map(np.asarray, out)
