"""Train a loaded TF graph — the ``BigDLSessionImpl.train`` analog.

Reference: ``DL/utils/tf/Session.scala:43,105`` — ``train:111`` takes the
loss-node endpoints of an imported GraphDef, wires the queue-runner inputs
to an RDD, and hands the whole thing to DistriOptimizer.

TPU redesign: the imported :class:`TFGraphModule` is already a normal
functional module whose VariableV2 nodes are trainable params, so
"session training" is just adapter glue: pick the loss output (or an
output + criterion), feed batches from a ``DataSet`` — or, when the
graph carries its OWN input pipeline (queue runners), replay that
pipeline host-side (``interop/tf_queues.py``) and feed the dequeue
node, exactly the substitution the reference makes.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from bigdl_tpu import nn, optim
from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.interop.tf_format import (TFGraphModule, load_tf_graph,
                                         parse_graphdef_binary,
                                         parse_graphdef_text)


class TFSession:
    """(reference ``BigDLSessionImpl``) — train/fine-tune an imported
    GraphDef with the framework's optimizers.

    With ``inputs=None``, the graph must be queue-fed: the in-graph
    input pipeline (filename queue → reader → decode → example queue →
    dequeue) is detected and replayed host-side, and the dequeue node
    becomes the feed point (``Session.scala:111-165``)."""

    def __init__(self, graph_or_path, inputs: Optional[Sequence[str]] = None,
                 outputs: Optional[Sequence[str]] = None):
        self.pipeline = None
        if isinstance(graph_or_path, TFGraphModule):
            self.graph = graph_or_path
            return
        if outputs is None:
            raise ValueError("loading from a path needs outputs= node names")
        if inputs is not None:
            self.graph = load_tf_graph(graph_or_path, inputs, outputs)
            return
        # queue-fed: detect the in-graph pipeline, feed at the dequeue
        from bigdl_tpu.interop.tf_queues import QueuePipeline
        with open(graph_or_path, "rb") as f:
            data = f.read()
        if str(graph_or_path).endswith((".pbtxt", ".txt")):
            nodes = parse_graphdef_text(data.decode("utf-8"))
        else:
            nodes = parse_graphdef_binary(data)
        self.pipeline = QueuePipeline(nodes, outputs)
        self.graph = TFGraphModule(nodes, [self.pipeline.dequeue], outputs)
        self.graph.initialize()

    def train(self, dataset: Optional[AbstractDataSet] = None,
              criterion: Optional[nn.Criterion] = None,
              optim_method: Optional[optim.OptimMethod] = None,
              end_when: Optional[optim.Trigger] = None,
              distributed: bool = False, mesh=None, epochs: int = 1):
        """Train the imported graph's variables (reference
        ``Session.train:111``).

        - with a ``dataset``: the optimizer pairs the graph's output
          with ``criterion`` against each batch's target;
        - with ``dataset=None`` (queue-fed graphs): batches come from
          the replayed in-graph pipeline, and the graph's (scalar)
          output is minimized directly — the loss lives in-graph, as in
          the reference's session training.
        Returns the optimizer (its ``state`` carries loss/epoch), or
        the per-step loss list for the queue-fed path."""
        if dataset is None:
            return self._train_queue_fed(optim_method, epochs, end_when)
        if criterion is None:
            raise ValueError("dataset training needs a criterion")
        if distributed:
            opt = optim.DistriOptimizer(self.graph, dataset, criterion,
                                        mesh=mesh)
        else:
            opt = optim.LocalOptimizer(self.graph, dataset, criterion)
        opt.set_optim_method(optim_method or optim.SGD(
            learning_rate=0.01, momentum=0.9, dampening=0.0))
        opt.set_end_when(end_when or optim.max_epoch(epochs))
        opt.optimize()
        return opt

    def _train_queue_fed(self, optim_method, epochs: int,
                         end_when: Optional[optim.Trigger] = None):
        if self.pipeline is None:
            raise ValueError(
                "train(dataset=None) needs an in-graph queue pipeline "
                "(load via TFSession(path, outputs=...) with inputs=None)")
        import jax
        import jax.numpy as jnp

        m = self.graph
        method = optim_method or optim.SGD(learning_rate=0.01,
                                           momentum=0.9, dampening=0.0)
        params = m._params
        ostate = method.init_state(params)

        @jax.jit
        def step(params, ostate, feeds, lr, it):
            def loss_fn(p):
                out, _ = m.apply(p, {}, feeds)
                return jnp.mean(jnp.asarray(out))
            loss, g = jax.value_and_grad(loss_fn)(params)
            params, ostate = method.update(g, params, ostate, lr, it)
            return params, ostate, loss

        losses = []
        it = 0
        stop = False
        for epoch in range(epochs):
            for feeds in self.pipeline.batches(epochs=1, seed=epoch):
                # pre-step check, like LocalOptimizer: max_epoch(N)
                # stops before the first step of epoch N, not after it
                if end_when is not None and end_when(
                        {"neval": it, "epoch": epoch,
                         "loss": losses[-1] if losses else float("inf")}):
                    stop = True
                    break
                feeds = {k: jnp.asarray(v) for k, v in feeds.items()}
                lr = method.current_lr(it, epoch)
                params, ostate, loss = step(params, ostate, feeds,
                                            np.float32(lr), it)
                losses.append(float(loss))
                it += 1
            if stop:
                break
        m._params = params
        return losses

    def run(self, feeds) -> np.ndarray:
        """Forward the graph on host arrays (``session.run`` analog)."""
        out = self.graph.forward(feeds)
        import jax
        return jax.tree_util.tree_map(np.asarray, out)
