"""TensorFlow GraphDef exporter.

Reference: ``DL/utils/tf/TensorflowSaver.scala`` + ``BigDLToTensorflow.scala``
(+ NodeDef builders in ``Tensorflow.scala``) — saves a BigDL model as a
frozen GraphDef so TF tooling can serve it.

Scope matches the reference's converter set: Sequential chains of
Linear / SpatialConvolution / pooling / BatchNorm (folded to scale+shift,
inference form) / activations / Reshape / Flatten / Dropout (exported as
Identity, like the reference's inference export).  Weights embed as
Const nodes (frozen graph) by default, or as VariableV2+Assign when
``save_tf_graph(..., trainable=True)`` — the re-imported graph then
exposes them as params and trains via ``TFSession.train`` (folded BN
statistics always stay Consts).  Round-trip guarantee: ``load_tf_graph``
on the exported file reproduces the source model's outputs.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.nn.module import Module, Sequential
from bigdl_tpu.utils import protowire as pw

_DT_FLOAT, _DT_INT32 = 1, 3


def _tensor_proto(arr: np.ndarray) -> bytes:
    arr = np.asarray(arr)
    dt = _DT_INT32 if np.issubdtype(arr.dtype, np.integer) else _DT_FLOAT
    arr = arr.astype(np.int32 if dt == _DT_INT32 else np.float32)
    t = pw.enc_varint(1, dt)
    shape = b"".join(pw.enc_bytes(2, pw.enc_varint(1, d))
                     for d in arr.shape)
    t += pw.enc_bytes(2, shape)
    t += pw.enc_bytes(4, arr.tobytes())
    return t


def _attr(key: str, payload: bytes) -> bytes:
    return pw.enc_bytes(5, pw.enc_str(1, key) + pw.enc_bytes(2, payload))


def _attr_tensor(key: str, arr) -> bytes:
    return _attr(key, pw.enc_bytes(8, _tensor_proto(arr)))


def _attr_type(key: str, dt: int = _DT_FLOAT) -> bytes:
    return _attr(key, pw.enc_varint(6, dt))


def _attr_s(key: str, s: str) -> bytes:
    return _attr(key, pw.enc_bytes(2, s.encode()))


def _attr_b(key: str, v: bool) -> bytes:
    return _attr(key, pw.enc_varint(5, 1 if v else 0))


def _attr_ilist(key: str, vals) -> bytes:
    lst = b"".join(pw.enc_varint(3, int(v)) for v in vals)
    return _attr(key, pw.enc_bytes(1, lst))


class _GraphBuilder:
    def __init__(self):
        self.nodes: List[bytes] = []
        self.counter = 0

    def fresh(self, base: str) -> str:
        self.counter += 1
        return f"{base}_{self.counter}"

    def node(self, name: str, op: str, inputs: Sequence[str] = (),
             *attrs: bytes) -> str:
        body = pw.enc_str(1, name) + pw.enc_str(2, op)
        for i in inputs:
            body += pw.enc_str(3, i)
        for a in attrs:
            body += a
        self.nodes.append(pw.enc_bytes(1, body))
        return name

    trainable = False  # const() emits VariableV2+Assign when True

    def const(self, base: str, arr) -> str:
        arr = np.asarray(arr)
        is_int = np.issubdtype(arr.dtype, np.integer)
        dt = _DT_INT32 if is_int else _DT_FLOAT
        if self.trainable and not is_int and arr.ndim >= 1:
            # weight as a trainable VariableV2 with a Const initializer
            # wired through Assign — the layout load_tf_graph's variable
            # resolution consumes (reference un-frozen checkpoints)
            name = self.fresh(base)
            init = self.node(f"{name}/init", "Const", (),
                             _attr_tensor("value", arr),
                             _attr_type("dtype", dt))
            shape = b"".join(pw.enc_bytes(2, pw.enc_varint(1, d))
                             for d in arr.shape)
            self.node(name, "VariableV2", (),
                      _attr("shape", pw.enc_bytes(7, shape)),
                      _attr_type("dtype", dt))
            self.node(f"{name}/assign", "Assign", (name, init),
                      _attr_type("T", dt))
            return name
        return self.node(self.fresh(base), "Const", (),
                         _attr_tensor("value", arr),
                         _attr_type("dtype", dt))

    def const_frozen(self, base: str, arr) -> str:
        """Always a Const, regardless of ``trainable`` (for values that
        are data, not weights — folded BN stats, shape vectors)."""
        prev = self.trainable
        self.trainable = False
        try:
            return self.const(base, arr)
        finally:
            self.trainable = prev


def _pad_mode(m) -> str:
    ph, pw_ = m.pad
    if ph == -1 or pw_ == -1:
        return "SAME"
    if ph == 0 and pw_ == 0:
        return "VALID"
    raise NotImplementedError(
        f"{type(m).__name__} with explicit padding {m.pad} has no TF "
        "conv/pool padding-string equivalent; re-export with pad=0 or -1")


def _out_shape(m: Module, params, state, in_shape) -> tuple:
    """Static output shape of one leaf on ``in_shape`` inputs (a tuple of
    shapes for table-valued modules like ConcatTable)."""
    import jax
    import jax.numpy as jnp
    x = jax.ShapeDtypeStruct(tuple(in_shape), jnp.float32)

    def fwd(x):
        out, _ = m.apply(
            jax.tree_util.tree_map(jnp.asarray, params),
            jax.tree_util.tree_map(jnp.asarray, state), x, training=False)
        return out

    out = jax.eval_shape(fwd, x)
    if isinstance(out, (tuple, list)):
        return tuple(tuple(o.shape) for o in out)
    return tuple(out.shape)


def _emit(g: _GraphBuilder, m: Module, params, state, cur: str,
          shape: tuple) -> Tuple[str, tuple]:
    from bigdl_tpu.nn.module import Remat
    if isinstance(m, Remat):
        # execution hint only — export the wrapped module
        return _emit(g, m.inner, params, state, cur, shape)
    t = type(m).__name__
    if isinstance(m, Sequential):
        for i, c in enumerate(m.modules):
            cur, shape = _emit(g, c, params.get(str(i), {}),
                               state.get(str(i), {}), cur, shape)
        return cur, shape
    # table ops: residual/branch structures (ConcatTable fan-out, the
    # C*Table reducers) map onto plain TF dataflow
    if t == "ConcatTable":
        outs = []
        for i, c in enumerate(m.modules):
            o, s = _emit(g, c, params.get(str(i), {}),
                         state.get(str(i), {}), cur, shape)
            outs.append((o, s))
        return [o for o, _ in outs], tuple(s for _, s in outs)
    if isinstance(cur, list):
        if t == "CAddTable":
            out = g.node(g.fresh("addn"), "AddN", tuple(cur),
                         _attr_type("T"))
            return out, shape[0]
        if t in ("CMulTable", "CMaxTable"):
            op = "Mul" if t == "CMulTable" else "Maximum"
            out = cur[0]
            for nxt in cur[1:]:
                out = g.node(g.fresh(op.lower()), op, (out, nxt),
                             _attr_type("T"))
            return out, shape[0]
        if t == "JoinTable":
            axis = g.const("axis", np.asarray(m.dimension, np.int32))
            out = g.node(g.fresh("concat"), "ConcatV2",
                         tuple(cur) + (axis,), _attr_type("T"))
            cat = list(shape[0])
            cat[m.dimension] = sum(s[m.dimension] for s in shape)
            return out, tuple(cat)
        raise NotImplementedError(
            f"TF export: table op {t} after ConcatTable is not mapped")
    out_shape = _out_shape(m, params, state, shape)
    if t == "Linear":
        w = g.const("weight", np.asarray(params["weight"]))
        out = g.node(g.fresh("matmul"), "MatMul", (cur, w),
                     _attr_b("transpose_b", True), _attr_type("T"))
        if "bias" in params:
            b = g.const("bias", np.asarray(params["bias"]))
            out = g.node(g.fresh("biasadd"), "BiasAdd", (out, b),
                         _attr_type("T"))
        return out, out_shape
    if t == "SpatialConvolution":
        if m.n_group != 1:
            raise NotImplementedError("grouped conv export")
        # OIHW -> HWIO
        w = np.transpose(np.asarray(params["weight"]), (2, 3, 1, 0))
        wn = g.const("kernel", w)
        df = m.format
        strides = ([1, m.stride[0], m.stride[1], 1] if df == "NHWC"
                   else [1, 1, m.stride[0], m.stride[1]])
        ph, pw_ = m.pad
        if ph > 0 or pw_ > 0:
            # explicit symmetric padding: zero-Pad node + VALID conv is
            # exactly equivalent (TF has no explicit conv padding attr)
            pads = ([[0, 0], [ph, ph], [pw_, pw_], [0, 0]] if df == "NHWC"
                    else [[0, 0], [0, 0], [ph, ph], [pw_, pw_]])
            pc = g.const("pads", np.asarray(pads, np.int32))
            cur = g.node(g.fresh("pad"), "Pad", (cur, pc), _attr_type("T"))
            pad_str = "VALID"
        else:
            pad_str = _pad_mode(m)
        dils = ([1, m.dilation[0], m.dilation[1], 1] if df == "NHWC"
                else [1, 1, m.dilation[0], m.dilation[1]])
        out = g.node(g.fresh("conv"), "Conv2D", (cur, wn),
                     _attr_s("padding", pad_str),
                     _attr_s("data_format", df),
                     _attr_ilist("strides", strides),
                     _attr_ilist("dilations", dils), _attr_type("T"))
        if m.with_bias:
            b = g.const("bias", np.asarray(params["bias"]))
            out = g.node(g.fresh("biasadd"), "BiasAdd", (out, b),
                         _attr_s("data_format", df), _attr_type("T"))
        return out, out_shape
    if t in ("SpatialMaxPooling", "SpatialAveragePooling"):
        df = m.format
        ks = ([1, m.kernel[0], m.kernel[1], 1] if df == "NHWC"
              else [1, 1, m.kernel[0], m.kernel[1]])
        st = ([1, m.stride[0], m.stride[1], 1] if df == "NHWC"
              else [1, 1, m.stride[0], m.stride[1]])
        op = "MaxPool" if t == "SpatialMaxPooling" else "AvgPool"
        return g.node(g.fresh(op.lower()), op, (cur,),
                      _attr_s("padding", _pad_mode(m)),
                      _attr_s("data_format", df),
                      _attr_ilist("ksize", ks), _attr_ilist("strides", st),
                      _attr_type("T")), out_shape
    if t in ("SpatialBatchNormalization", "BatchNormalization"):
        # inference fold: y = x*scale + shift (reference exports BN via
        # its frozen statistics too)
        mean = np.asarray(state["running_mean"])
        var = np.asarray(state["running_var"])
        gamma = np.asarray(params.get("weight", np.ones_like(mean)))
        beta = np.asarray(params.get("bias", np.zeros_like(mean)))
        scale = gamma / np.sqrt(var + m.eps)
        shift = beta - mean * scale
        if t == "SpatialBatchNormalization" and m.format == "NCHW":
            scale = scale[:, None, None]
            shift = shift[:, None, None]
        # folded running statistics are NOT weights: keep them Consts
        # even under trainable=True (optimizing frozen normalization
        # stats as free affine params would diverge from training the
        # source model)
        sc = g.const_frozen("bn_scale", scale.astype(np.float32))
        sh = g.const_frozen("bn_shift", shift.astype(np.float32))
        out = g.node(g.fresh("bn_mul"), "Mul", (cur, sc), _attr_type("T"))
        return g.node(g.fresh("bn_add"), "Add", (out, sh),
                      _attr_type("T")), out_shape
    if t in ("Reshape", "View", "Flatten"):
        tgt = g.const("shape", np.asarray((-1,) + tuple(out_shape[1:]),
                                          np.int32))
        return g.node(g.fresh("reshape"), "Reshape", (cur, tgt),
                      _attr_type("T")), out_shape
    if t == "Dropout":
        return g.node(g.fresh("dropout_identity"), "Identity", (cur,),
                      _attr_type("T")), out_shape
    simple = {"ReLU": "Relu", "ReLU6": "Relu6", "Tanh": "Tanh",
              "Sigmoid": "Sigmoid", "SoftMax": "Softmax",
              "LogSoftMax": "LogSoftmax", "ELU": "Elu",
              "SoftPlus": "Softplus", "Identity": "Identity",
              "Abs": "Abs", "Exp": "Exp", "Sqrt": "Sqrt",
              "Square": "Square"}
    if t in simple:
        return g.node(g.fresh(t.lower()), simple[t], (cur,),
                      _attr_type("T")), out_shape
    raise NotImplementedError(
        f"TF export for module {t} (reference BigDLToTensorflow covers a "
        "similar converter set)")


def save_tf_graph(model: Module, path: str, input_shape: Sequence[int],
                  input_name: str = "input",
                  output_name: str = "output",
                  trainable: bool = False) -> Tuple[str, str]:
    """Export a materialized module as a GraphDef (reference
    ``TensorflowSaver.saveGraph``).  ``input_shape`` includes the batch
    dim (any positive placeholder batch works — shapes are only used to
    make Reshape targets static).  Returns (input_name, output_name);
    ``load_tf_graph(path, [input], [output])`` round-trips it.

    ``trainable=False`` freezes weights as Consts (inference export);
    ``trainable=True`` emits them as VariableV2 nodes with Assign
    initializers so the re-imported graph exposes them as params and
    ``TFSession.train`` can optimize them."""
    model._ensure_init()
    import jax
    params = jax.tree_util.tree_map(np.asarray, model._params)
    state = jax.tree_util.tree_map(np.asarray, model._state)
    g = _GraphBuilder()
    g.trainable = trainable
    g.node(input_name, "Placeholder", (), _attr_type("dtype"))
    last, _ = _emit(g, model, params, state, input_name,
                    tuple(input_shape))
    g.node(output_name, "Identity", (last,), _attr_type("T"))
    with open(path, "wb") as f:
        f.write(b"".join(g.nodes))
    return input_name, output_name
