"""Host-side replay of in-graph TF input pipelines (queue runners).

Reference: ``BigDLSessionImpl.train`` (``DL/utils/tf/Session.scala:
111-165``) — a TF training GraphDef often carries its OWN input
pipeline: filename queue → ``ReaderReadV2`` → decode subgraph →
example queue → ``QueueDequeueManyV2`` → model.  The reference walks
those queue runners and rebuilds them as an RDD; here they are rebuilt
as a host generator:

- the dequeue node becomes the imported module's feed point (the same
  substitution ``TensorflowLoader`` makes for user-specified inputs);
- the enqueue side (readers, decode ops) is replayed record-by-record
  on the host with the SAME op registry the device path uses, batched
  to the dequeue's batch size.

The device never sees a queue: queues are host-side sequencing, which
is exactly what a Python generator is.  Supported sources, matching
``Session.scala``'s three cases: TFRecord/text/whole-file readers fed
by a string_input_producer, and constant ("cached") enqueues.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

DEQUEUE_OPS = {"QueueDequeueManyV2", "QueueDequeueMany",
               "QueueDequeueUpToV2", "QueueDequeueUpTo",
               "QueueDequeueV2", "QueueDequeue"}
ENQUEUE_OPS = {"QueueEnqueueV2", "QueueEnqueue",
               "QueueEnqueueManyV2", "QueueEnqueueMany"}
QUEUE_OPS = {"FIFOQueueV2", "FIFOQueue", "RandomShuffleQueueV2",
             "RandomShuffleQueue", "PaddingFIFOQueueV2", "PaddingFIFOQueue"}
READER_OPS = {"TFRecordReaderV2": "tfrecord", "TFRecordReader": "tfrecord",
              "TextLineReaderV2": "textline", "TextLineReader": "textline",
              "WholeFileReaderV2": "wholefile",
              "WholeFileReader": "wholefile",
              "IdentityReaderV2": "identity", "IdentityReader": "identity"}


from bigdl_tpu.interop.tf_format import _base_name as _base


class _HostEval:
    """Evaluate a decode subgraph on host numpy values with the op
    registry (the same ops the device path executes)."""

    def __init__(self, by_name: Dict[str, dict]):
        self.by_name = by_name

    def eval(self, name: str, bind: Dict[str, object],
             memo: Optional[dict] = None):
        from bigdl_tpu.ops.registry import get_op
        memo = {} if memo is None else memo

        def ev(nm):
            if nm in memo:
                return memo[nm]
            if nm in bind:
                memo[nm] = bind[nm]
                return bind[nm]
            node = self.by_name[nm]
            op = node["op"]
            if op == "Const":
                out = np.asarray(node["attrs"]["value"])
            elif op in ("Identity", "StopGradient"):
                out = arg(node["inputs"][0])
            else:
                args = [arg(i) for i in node["inputs"]
                        if not i.startswith("^")]
                out = get_op(op)(
                    {**node["attrs"], "_node_name": nm}, *args)
                if isinstance(out, tuple):
                    out = tuple(np.asarray(o) for o in out)
                else:
                    out = np.asarray(out)
            memo[nm] = out
            return out

        def arg(inp):
            b, ix = _base(inp)
            v = ev(b)
            return v[ix] if isinstance(v, tuple) else v

        return arg(name)


class QueuePipeline:
    """Extracted in-graph input pipeline: batches() replays it."""

    def __init__(self, nodes: List[dict], outputs: Sequence[str]):
        self.by_name = {n["name"]: n for n in nodes}
        self._eval = _HostEval(self.by_name)

        # the dequeue feeding the requested outputs (reverse BFS)
        seen, stack = set(), [_base(o)[0] for o in outputs]
        dequeue = None
        while stack:
            nm = stack.pop()
            if nm in seen or nm not in self.by_name:
                continue
            seen.add(nm)
            node = self.by_name[nm]
            if node["op"] in DEQUEUE_OPS:
                dequeue = node
                break
            stack.extend(_base(i)[0] for i in node["inputs"])
        if dequeue is None:
            raise ValueError("no QueueDequeue* op on the path to "
                             f"{list(outputs)} — not a queue-fed graph")
        self.dequeue = dequeue["name"]
        if dequeue["op"] in ("QueueDequeueManyV2", "QueueDequeueMany",
                             "QueueDequeueUpToV2", "QueueDequeueUpTo"):
            self.batch_size = int(np.asarray(
                self._eval.eval(dequeue["inputs"][1], {})).reshape(-1)[0])
        else:
            self.batch_size = 1

        # the example queue and its enqueues
        qname = _base(dequeue["inputs"][0])[0]
        self.queue = self.by_name[qname]
        if self.queue["op"] not in QUEUE_OPS:
            raise NotImplementedError(
                f"dequeue reads from op {self.queue['op']!r}, not a queue")
        self.shuffle = "RandomShuffle" in self.queue["op"]
        enq = [n for n in nodes if n["op"] in ENQUEUE_OPS
               and _base(n["inputs"][0])[0] == qname]
        if len(enq) != 1:
            raise NotImplementedError(
                f"queue {qname!r} has {len(enq)} enqueue ops; expected 1")
        self.enqueue = enq[0]
        self.enqueue_many = "Many" in self.enqueue["op"]
        self.components = [i for i in self.enqueue["inputs"][1:]
                           if not i.startswith("^")]

        # source: a reader (which file/record stream?) or pure consts
        self.read_node = self._find_reader(self.components)
        if self.read_node is not None:
            read = self.by_name[self.read_node]
            reader = self.by_name[_base(read["inputs"][0])[0]]
            self.reader_kind = READER_OPS[reader["op"]]
            self.filenames = self._filename_list(
                _base(read["inputs"][1])[0])

    def _find_reader(self, roots) -> Optional[str]:
        seen, stack = set(), [_base(r)[0] for r in roots]
        while stack:
            nm = stack.pop()
            if nm in seen or nm not in self.by_name:
                continue
            seen.add(nm)
            node = self.by_name[nm]
            if node["op"] in ("ReaderReadV2", "ReaderRead"):
                return nm
            stack.extend(_base(i)[0] for i in node["inputs"])
        return None

    def _filename_list(self, fq_name: str) -> List[str]:
        """Resolve a string_input_producer-style filename queue to its
        constant filename list."""
        node = self.by_name[fq_name]
        if node["op"] not in QUEUE_OPS:
            raise NotImplementedError(
                f"reader's filename source {fq_name!r} is {node['op']!r}")
        enq = [n for n in self.by_name.values() if n["op"] in ENQUEUE_OPS
               and _base(n["inputs"][0])[0] == fq_name]
        if not enq:
            raise NotImplementedError(
                f"filename queue {fq_name!r} has no enqueue")
        names = self._eval.eval(enq[0]["inputs"][1], {})
        out = []
        for v in np.asarray(names).reshape(-1):
            out.append(v.decode() if isinstance(v, bytes) else str(v))
        return out

    # ------------------------------------------------------------------
    def _records(self):
        """Yield per-element bindings for the enqueue components."""
        if self.read_node is None:
            # "cached" case: constant enqueue; EnqueueMany rows are the
            # elements
            vals = [np.asarray(self._eval.eval(c, {}))
                    for c in self.components]
            if self.enqueue_many:
                for i in range(vals[0].shape[0]):
                    yield [v[i] for v in vals]
            else:
                yield list(vals)
            return
        from bigdl_tpu.dataset import tfrecord
        for fn in self.filenames:
            if self.reader_kind == "tfrecord":
                for rec in tfrecord.read_records(fn):
                    yield (fn.encode(), rec)
            elif self.reader_kind == "textline":
                with open(fn, "rb") as f:
                    for line in f:
                        yield (fn.encode(), line.rstrip(b"\n"))
            elif self.reader_kind == "wholefile":
                with open(fn, "rb") as f:
                    yield (fn.encode(), f.read())
            else:  # identity
                yield (fn.encode(), fn.encode())

    def _decoded_elements(self) -> list:
        """Decode the whole record stream once (deterministic host
        work); epochs reuse the cache and only reshuffle/rebatch."""
        if getattr(self, "_cache", None) is not None:
            return self._cache
        elements = []
        for rec in self._records():
            if self.read_node is None:
                elements.append(rec)
            else:
                bind = {self.read_node: (np.asarray(rec[0], object),
                                         np.asarray(rec[1], object))}
                memo: dict = {}
                elements.append([
                    np.asarray(self._eval.eval(c, bind, memo))
                    for c in self.components])
        self._cache = elements
        return elements

    def batches(self, epochs: int = 1, seed: int = 0,
                drop_remainder: Optional[bool] = None):
        """Yield feed dicts {f"{dequeue}:{i}": batched array}.

        ``drop_remainder`` defaults to the dequeue op's TF semantics:
        DequeueMany only pops full batches (tail dropped), DequeueUpTo
        allows a final partial batch."""
        if drop_remainder is None:
            drop_remainder = "UpTo" not in self.by_name[self.dequeue]["op"]
        rng = np.random.default_rng(seed)
        n_yielded = 0
        for _ in range(epochs):
            elements = list(self._decoded_elements())
            if self.shuffle:
                rng.shuffle(elements)
            for i in range(0, len(elements) - self.batch_size + 1
                           if drop_remainder else len(elements),
                           self.batch_size):
                chunk = elements[i:i + self.batch_size]
                if not chunk:
                    break
                feeds = {}
                many = self.by_name[self.dequeue]["op"] not in (
                    "QueueDequeueV2", "QueueDequeue")
                for ci in range(len(self.components)):
                    col = np.stack([e[ci] for e in chunk])
                    # a non-Many dequeue pops ONE element, unbatched
                    feeds[f"{self.dequeue}:{ci}"] = col if many else col[0]
                n_yielded += 1
                yield feeds
        if n_yielded == 0:
            raise ValueError(
                f"queue pipeline produced 0 batches: "
                f"{len(self._decoded_elements())} element(s) < batch size "
                f"{self.batch_size} (DequeueMany drops partial batches; "
                "use QueueDequeueUpToV2 or more data)")
