"""Keras 1.2 JSON definition importer.

Reference: ``pyspark/bigdl/keras/converter.py`` — ``DefinitionLoader:289``
maps a Keras-1.2.2 ``model.to_json()`` document onto BigDL layers;
``WeightLoader:32`` pulls weights from the companion HDF5.

TPU redesign: the JSON maps onto the deferred ``bigdl_tpu.keras``
wrappers (which already reproduce the Keras-1.2 layer surface + shape
inference), so the converter is a thin config translation.  HDF5 weight
loading uses ``h5py`` (``load_keras_hdf5_weights``);
``set_keras_weights`` applies a plain list of arrays in Keras order for
callers who extracted weights themselves.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

from bigdl_tpu import keras as K


def _batchless_shape(bis) -> tuple:
    """batch_input_shape → batch-less tuple, rejecting dynamic dims with
    a clear message (None in non-batch positions)."""
    dims = bis[1:]
    if any(d is None for d in dims):
        raise NotImplementedError(
            f"dynamic (null) input dimensions {bis} are not supported; "
            "fix the shape in the Keras config before import")
    return tuple(int(d) for d in dims)


def _layer_from_config(entry: Dict[str, Any]):
    cls = entry["class_name"]
    cfg = entry.get("config", {})

    def input_shape():
        bis = cfg.get("batch_input_shape")
        if bis:
            return _batchless_shape(bis)
        if cfg.get("input_dim"):
            return (int(cfg["input_dim"]),)
        return None

    common = {"input_shape": input_shape(), "name": cfg.get("name")}
    if cls == "Dense":
        return K.Dense(int(cfg["output_dim"]),
                       activation=cfg.get("activation"),
                       bias=cfg.get("bias", True), **common)
    if cls == "Activation":
        return K.Activation(cfg["activation"], **common)
    if cls == "Dropout":
        return K.Dropout(float(cfg.get("p", 0.5)), **common)
    if cls == "Flatten":
        return K.Flatten(**common)
    if cls == "Reshape":
        return K.Reshape(tuple(cfg["target_shape"]), **common)
    if cls == "Convolution2D":
        return K.Convolution2D(
            int(cfg["nb_filter"]), int(cfg["nb_row"]), int(cfg["nb_col"]),
            activation=cfg.get("activation"),
            border_mode=cfg.get("border_mode", "valid"),
            subsample=tuple(cfg.get("subsample", (1, 1))),
            dim_ordering=cfg.get("dim_ordering", "th"),
            bias=cfg.get("bias", True), **common)
    if cls == "Convolution1D":
        return K.Convolution1D(
            int(cfg["nb_filter"]), int(cfg["filter_length"]),
            activation=cfg.get("activation"),
            subsample_length=int(cfg.get("subsample_length", 1)), **common)
    if cls in ("MaxPooling2D", "AveragePooling2D"):
        klass = K.MaxPooling2D if cls == "MaxPooling2D" \
            else K.AveragePooling2D
        return klass(pool_size=tuple(cfg.get("pool_size", (2, 2))),
                     strides=(tuple(cfg["strides"])
                              if cfg.get("strides") else None),
                     border_mode=cfg.get("border_mode", "valid"),
                     dim_ordering=cfg.get("dim_ordering", "th"), **common)
    if cls == "GlobalAveragePooling2D":
        return K.GlobalAveragePooling2D(
            dim_ordering=cfg.get("dim_ordering", "th"), **common)
    if cls == "GlobalMaxPooling2D":
        return K.GlobalMaxPooling2D(
            dim_ordering=cfg.get("dim_ordering", "th"), **common)
    if cls == "ZeroPadding2D":
        return K.ZeroPadding2D(tuple(cfg.get("padding", (1, 1))),
                               dim_ordering=cfg.get("dim_ordering", "th"),
                               **common)
    if cls == "BatchNormalization":
        return K.BatchNormalization(
            epsilon=float(cfg.get("epsilon", 1e-3)),
            momentum=float(cfg.get("momentum", 0.99)),
            dim_ordering=cfg.get("dim_ordering", "th"), **common)
    if cls == "Embedding":
        return K.Embedding(int(cfg["input_dim"]), int(cfg["output_dim"]),
                           input_length=cfg.get("input_length"), **common)
    if cls in ("LSTM", "GRU", "SimpleRNN"):
        klass = {"LSTM": K.LSTM, "GRU": K.GRU,
                 "SimpleRNN": K.SimpleRNN}[cls]
        return klass(int(cfg["output_dim"]),
                     return_sequences=cfg.get("return_sequences", False),
                     go_backwards=cfg.get("go_backwards", False), **common)
    raise NotImplementedError(
        f"Keras 1.2 layer {cls!r} is not mapped (reference "
        "converter.py LAYER mapping)")


def load_keras_json(json_str_or_path: str):
    """Keras-1.2 ``model.to_json()`` → topology (reference
    ``DefinitionLoader.from_json_path``).  ``Sequential`` JSON gives a
    :class:`bigdl_tpu.keras.Sequential`; functional ``Model`` JSON gives a
    core :class:`bigdl_tpu.nn.Graph` wrapped in ``keras.Model``."""
    text = json_str_or_path
    if not text.lstrip().startswith("{"):
        with open(json_str_or_path) as f:
            text = f.read()
    doc = json.loads(text)
    cls = doc.get("class_name")
    if cls == "Sequential":
        model = K.Sequential()
        for entry in doc.get("config", []):
            model.add(_layer_from_config(entry))
        return model
    if cls == "Model":
        return _load_functional_model(doc["config"])
    raise NotImplementedError(f"Keras model class {cls!r}")


def _load_functional_model(cfg: dict) -> "K.Model":
    """Functional-API graph: layers connected by ``inbound_nodes``
    (reference converter's Model path).  Each deferred wrapper builds
    once its input shape is known, walked in topological (listed) order;
    edges become ``nn.Graph`` nodes.  Multi-input layers (Merge) receive
    a node list.

    **Shared (multi-call) layers**: a layer with several
    ``inbound_nodes`` entries is built ONCE and applied per call; the
    resulting graph nodes share the module instance, which
    :class:`bigdl_tpu.nn.Graph` resolves to tied weights (reference
    converter handles multi-call layers the same way — one BigDL module,
    many graph occurrences).  Graph tensors are keyed by
    ``(layer_name, node_index)`` to address each call's output."""
    from bigdl_tpu.keras.layers import infer_output_shape
    from bigdl_tpu.nn.graph import Graph, Input as GInput

    nodes: Dict[tuple, Any] = {}
    shapes: Dict[tuple, tuple] = {}

    def src_key(ib_entry) -> tuple:
        # inbound ref = [layer_name, node_index, tensor_index, ...]
        return (ib_entry[0], int(ib_entry[1]) if len(ib_entry) > 1 else 0)

    for entry in cfg.get("layers", []):
        name = entry.get("name") or entry["config"].get("name")
        lcls = entry["class_name"]
        inbound = entry.get("inbound_nodes") or []
        if lcls == "InputLayer":
            n = GInput()
            nodes[(name, 0)] = n
            bis = entry["config"].get("batch_input_shape")
            shapes[(name, 0)] = _batchless_shape(bis or [None])
            continue
        if lcls == "Merge":
            cfg_m = entry["config"]
            mode = cfg_m.get("mode", "sum")
            axis = int(cfg_m.get("concat_axis", -1))
            core = K.Merge(mode=mode, concat_axis=axis).build(None)
            for call_ix, ib in enumerate(inbound):
                srcs = [src_key(s) for s in ib]
                nodes[(name, call_ix)] = core([nodes[s] for s in srcs])
                s0 = shapes[srcs[0]]
                if mode == "concat":
                    # Keras concat_axis counts the batch dim; our
                    # bookkeeping shapes are batch-less, so positive axes
                    # shift down by 1
                    ax = axis - 1 if axis > 0 else len(s0) + axis
                    cat = list(s0)
                    cat[ax] = sum(shapes[s][ax] for s in srcs)
                    shapes[(name, call_ix)] = tuple(cat)
                else:
                    shapes[(name, call_ix)] = s0
            continue
        if not inbound:
            raise NotImplementedError(
                f"layer {name!r} ({lcls}) has no inbound nodes")
        core = None
        built_shape = None
        for call_ix, ib in enumerate(inbound):
            srcs = [src_key(s) for s in ib]
            if len(srcs) != 1:
                raise NotImplementedError(
                    f"layer {name!r} ({lcls}) with {len(srcs)} inbound "
                    "tensors")
            in_shape = shapes[srcs[0]]
            if core is None:
                core = _layer_from_config(entry).build(in_shape)
                built_shape = in_shape
            elif in_shape != built_shape:
                raise NotImplementedError(
                    f"shared layer {name!r} called with differing input "
                    f"shapes {built_shape} vs {in_shape}")
            shapes[(name, call_ix)] = infer_output_shape(core, in_shape)
            nodes[(name, call_ix)] = core(nodes[srcs[0]])

    # bind inputs in the DECLARED order (cfg["input_layers"]), which may
    # differ from the layer-listing order Keras serializes
    in_keys = [src_key(i) for i in cfg.get("input_layers", [])]
    if not in_keys:  # fall back to listing order
        in_keys = [(e.get("name") or e["config"].get("name"), 0)
                   for e in cfg.get("layers", [])
                   if e["class_name"] == "InputLayer"]
    inputs = [nodes[i] for i in in_keys]
    out_keys = [src_key(o) for o in cfg.get("output_layers", [])]
    graph = Graph(inputs, [nodes[o] for o in out_keys],
                  name=cfg.get("name", "KerasModel"))
    return K.Model(graph)


def set_keras_weights(model: "K.Sequential",
                      weights: List[np.ndarray]) -> None:
    """Install a flat Keras-order weight list (each layer's
    ``get_weights()`` concatenated) into the built core module
    (reference ``WeightLoader``; Keras Dense stores W as (in, out) —
    transposed into our (out, in)).

    The walk pairs each *module* with its params/state subtree via
    ``spec_children()`` so stateful layers can consume the right number
    of arrays: Keras-1.2 BatchNormalization saves FOUR (gamma, beta,
    running_mean, running_std) — and its ``running_std`` attribute
    actually holds the *variance* (the reference's ``setRunningStd``
    writes it straight into ``runningVar``,
    ``PythonBigDLKeras.scala:151-154``), so it is installed as
    ``running_var`` unchanged."""
    import jax
    import jax.numpy as jnp

    core = model.core_module()
    core._ensure_init()
    params = jax.tree_util.tree_map(np.asarray, core._params)
    states = jax.tree_util.tree_map(np.asarray, core._state)
    w_ix = 0

    def take():
        nonlocal w_ix
        w = np.asarray(weights[w_ix])
        w_ix += 1
        return w

    def fill(module, p, s):
        nonlocal w_ix
        if isinstance(s, dict) and "running_mean" in s:
            # BatchNormalization: gamma, beta, mean, std(=var; see docstring)
            if isinstance(p, dict) and "weight" in p:
                p["weight"] = take().reshape(p["weight"].shape)
                p["bias"] = take().reshape(p["bias"].shape)
            s["running_mean"] = take().reshape(s["running_mean"].shape)
            s["running_var"] = take().reshape(s["running_var"].shape)
            return
        if not isinstance(p, dict):
            return
        if "weight" in p:
            w = take()
            tgt = p["weight"]
            if w.ndim == 2 and w.shape == tgt.shape[::-1]:
                w = w.T               # Keras Dense (in,out) -> (out,in)
            elif w.ndim == 4 and w.shape != tgt.shape:
                # Keras th conv kernels are already (out,in,kh,kw);
                # tf ordering (kh,kw,in,out) -> OIHW
                w = np.transpose(w, (3, 2, 0, 1))
            p["weight"] = w.reshape(tgt.shape)
        if "bias" in p:
            p["bias"] = take().reshape(p["bias"].shape)

    def walk(module, p, s):
        children = module.spec_children()
        if children is None:
            fill(module, p, s)
            return
        if isinstance(children, dict):
            keys = list(children.keys())
            if all(k.isdigit() for k in keys):
                keys.sort(key=int)
            for k in keys:
                walk(children[k],
                     p.get(k, {}) if isinstance(p, dict) else {},
                     s.get(k, {}) if isinstance(s, dict) else {})
            return
        walk(children, p, s)  # single-child delegating wrapper

    walk(core, params, states)
    if w_ix != len(weights):
        raise ValueError(f"consumed {w_ix} of {len(weights)} weight arrays")
    core._params = jax.tree_util.tree_map(jnp.asarray, params)
    core._state = jax.tree_util.tree_map(jnp.asarray, states)
    model._params = core._params
    model._mstate = core._state


def load_keras_hdf5_weights(model: "K.Sequential", h5_path: str) -> None:
    """Load weights from a Keras-1.2 HDF5 file (needs ``h5py``, which is
    optional in this image)."""
    try:
        import h5py
    except ImportError as e:
        raise ImportError(
            "h5py is not installed; extract the weight arrays yourself "
            "and call set_keras_weights(model, arrays)") from e
    arrays: List[np.ndarray] = []
    with h5py.File(h5_path, "r") as f:
        grp = f["model_weights"] if "model_weights" in f else f
        names = [n.decode() if isinstance(n, bytes) else n
                 for n in grp.attrs.get("layer_names", [])]
        for lname in names:
            g = grp[lname]
            wn = [n.decode() if isinstance(n, bytes) else n
                  for n in g.attrs.get("weight_names", [])]
            for w in wn:
                arrays.append(np.asarray(g[w]))
    set_keras_weights(model, arrays)
