"""Torch7 ``.t7`` binary reader (and writer for tensors/tables).

Reference: ``DL/utils/TorchFile.scala`` (~1k LoC) — the Lua Torch
serialization format, used by the reference both for model exchange and
as the transport of its golden-parity test oracle (``TEST/torch/TH.scala``
writes inputs as .t7, shells out to ``th``, reads results back).

Format (little-endian):
  value   := int32 type, payload
  type    := 0 nil | 1 number (f64) | 2 string (int32 len + bytes)
           | 3 table | 4 torch object | 5 boolean (int32)
           | 6/7/8 function (unsupported)
  table   := int32 ref-index; if new: int32 count, then count key/value
             pairs
  object  := int32 ref-index; if new: string version ("V <n>" or legacy
             class name), string class name, class payload
  Tensor  := int32 ndim, int64 sizes[ndim], int64 strides[ndim],
             int64 storageOffset (1-based), storage object
  Storage := int64 size, raw elements (f32/f64/i32/i64/u8 by class)
"""

from __future__ import annotations

import struct
from typing import Any, BinaryIO, Dict

import numpy as np

TYPE_NIL, TYPE_NUMBER, TYPE_STRING, TYPE_TABLE = 0, 1, 2, 3
TYPE_TORCH, TYPE_BOOLEAN = 4, 5

_STORAGE_DTYPES = {
    "torch.FloatStorage": (np.float32, 4),
    "torch.DoubleStorage": (np.float64, 8),
    "torch.IntStorage": (np.int32, 4),
    "torch.LongStorage": (np.int64, 8),
    "torch.ByteStorage": (np.uint8, 1),
    "torch.CharStorage": (np.int8, 1),
    "torch.ShortStorage": (np.int16, 2),
}
_TENSOR_TO_STORAGE = {
    "torch.FloatTensor": "torch.FloatStorage",
    "torch.DoubleTensor": "torch.DoubleStorage",
    "torch.IntTensor": "torch.IntStorage",
    "torch.LongTensor": "torch.LongStorage",
    "torch.ByteTensor": "torch.ByteStorage",
    "torch.CharTensor": "torch.CharStorage",
    "torch.ShortTensor": "torch.ShortStorage",
}


class _Reader:
    def __init__(self, f: BinaryIO):
        self.f = f
        self.refs: Dict[int, Any] = {}

    def i32(self) -> int:
        return struct.unpack("<i", self.f.read(4))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self.f.read(8))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self.f.read(8))[0]

    def string(self) -> str:
        n = self.i32()
        return self.f.read(n).decode("utf-8", "replace")

    def read(self):
        t = self.i32()
        if t == TYPE_NIL:
            return None
        if t == TYPE_NUMBER:
            v = self.f64()
            return int(v) if v.is_integer() else v
        if t == TYPE_STRING:
            return self.string()
        if t == TYPE_BOOLEAN:
            return bool(self.i32())
        if t == TYPE_TABLE:
            return self._table()
        if t == TYPE_TORCH:
            return self._object()
        raise NotImplementedError(f".t7 value type {t} (functions are not "
                                  "supported)")

    def _table(self):
        ix = self.i32()
        if ix in self.refs:
            return self.refs[ix]
        out: Dict[Any, Any] = {}
        self.refs[ix] = out
        count = self.i32()
        for _ in range(count):
            k = self.read()
            v = self.read()
            out[k] = v
        # lua array table → list
        if out and all(isinstance(k, int) for k in out) \
                and sorted(out) == list(range(1, len(out) + 1)):
            lst = [out[i] for i in range(1, len(out) + 1)]
            self.refs[ix] = lst
            return lst
        return out

    def _object(self):
        ix = self.i32()
        if ix in self.refs:
            return self.refs[ix]
        version = self.string()
        if version.startswith("V "):
            cls = self.string()
        else:
            cls = version  # legacy layout: the string was the class name
        if cls in _TENSOR_TO_STORAGE:
            out = self._tensor(cls)
        elif cls in _STORAGE_DTYPES:
            out = self._storage(cls)
        else:
            # generic torch class (e.g. an nn module): its payload is a
            # table of fields
            out = {"_torch_class": cls, "fields": self.read()}
        self.refs[ix] = out
        return out

    def _tensor(self, cls: str) -> np.ndarray:
        nd = self.i32()
        sizes = [self.i64() for _ in range(nd)]
        strides = [self.i64() for _ in range(nd)]
        offset = self.i64()  # 1-based
        storage = self.read()
        if storage is None:
            return np.zeros(sizes, _STORAGE_DTYPES[
                _TENSOR_TO_STORAGE[cls]][0])
        flat = np.asarray(storage)
        if nd == 0:
            return flat[:0]
        # materialize via strides (t7 tensors can be non-contiguous views)
        out = np.lib.stride_tricks.as_strided(
            flat[offset - 1:],
            shape=sizes,
            strides=[s * flat.itemsize for s in strides]).copy()
        return out

    def _storage(self, cls: str) -> np.ndarray:
        dtype, width = _STORAGE_DTYPES[cls]
        n = self.i64()
        return np.frombuffer(self.f.read(n * width), dtype=dtype).copy()


def load_t7(path: str):
    """Read one serialized value from a .t7 file (reference
    ``TorchFile.load``).  Tensors → numpy arrays; tables → dict/list;
    nn modules → {"_torch_class": ..., "fields": {...}} trees."""
    with open(path, "rb") as f:
        return _Reader(f).read()


class _Writer:
    def __init__(self, f: BinaryIO):
        self.f = f
        self.next_ref = 1

    def i32(self, v: int):
        self.f.write(struct.pack("<i", v))

    def i64(self, v: int):
        self.f.write(struct.pack("<q", v))

    def write(self, v):
        import numbers
        if v is None:
            self.i32(TYPE_NIL)
        elif isinstance(v, bool):
            self.i32(TYPE_BOOLEAN)
            self.i32(int(v))
        elif isinstance(v, numbers.Number):
            self.i32(TYPE_NUMBER)
            self.f.write(struct.pack("<d", float(v)))
        elif isinstance(v, str):
            self.i32(TYPE_STRING)
            b = v.encode()
            self.i32(len(b))
            self.f.write(b)
        elif isinstance(v, np.ndarray):
            self._tensor(v)
        elif isinstance(v, dict) and "_torch_class" in v:
            # generic torch object (e.g. an nn module): class name +
            # field table — the mirror of _Reader._object
            self.i32(TYPE_TORCH)
            self.i32(self.next_ref)
            self.next_ref += 1
            self._string("V 1")
            self._string(v["_torch_class"])
            self.write(v.get("fields", {}))
        elif isinstance(v, (dict, list, tuple)):
            self._table(v)
        else:
            raise TypeError(f"cannot write {type(v)} to .t7")

    def _table(self, v):
        self.i32(TYPE_TABLE)
        self.i32(self.next_ref)
        self.next_ref += 1
        items = (list(enumerate(v, 1)) if isinstance(v, (list, tuple))
                 else list(v.items()))
        self.i32(len(items))
        for k, val in items:
            self.write(k)
            self.write(val)

    def _tensor(self, arr: np.ndarray):
        arr = np.ascontiguousarray(arr)
        if arr.dtype == np.float64:
            cls, scls = "torch.DoubleTensor", "torch.DoubleStorage"
        elif arr.dtype == np.int64:
            cls, scls = "torch.LongTensor", "torch.LongStorage"
        elif arr.dtype == np.int32:
            cls, scls = "torch.IntTensor", "torch.IntStorage"
        elif arr.dtype == np.int16:
            cls, scls = "torch.ShortTensor", "torch.ShortStorage"
        elif arr.dtype == np.int8:
            cls, scls = "torch.CharTensor", "torch.CharStorage"
        elif arr.dtype == np.uint8:
            cls, scls = "torch.ByteTensor", "torch.ByteStorage"
        else:
            arr = arr.astype(np.float32)
            cls, scls = "torch.FloatTensor", "torch.FloatStorage"
        self.i32(TYPE_TORCH)
        self.i32(self.next_ref)
        self.next_ref += 1
        self._string("V 1")
        self._string(cls)
        self.i32(arr.ndim)
        for s in arr.shape:
            self.i64(s)
        stride = [int(np.prod(arr.shape[i + 1:]))
                  for i in range(arr.ndim)]
        for s in stride:
            self.i64(s)
        self.i64(1)  # storage offset
        # storage object
        self.i32(TYPE_TORCH)
        self.i32(self.next_ref)
        self.next_ref += 1
        self._string("V 1")
        self._string(scls)
        self.i64(arr.size)
        self.f.write(arr.tobytes())

    def _string(self, s: str):
        b = s.encode()
        self.i32(len(b))
        self.f.write(b)


def save_t7(path: str, value) -> None:
    """Write a value (tensor / table of tensors / scalars) as .t7
    (reference ``TorchFile.save``) — enough for the golden-oracle
    transport and simple tensor exchange."""
    with open(path, "wb") as f:
        _Writer(f).write(value)
