"""bigdl_tpu.interop — model import/export (reference L6 layer).

Reference: ``DL/utils/serializer/`` (BigDL protobuf checkpoints),
``DL/utils/tf/`` (TensorFlow GraphDef), ``DL/utils/caffe/``,
``DL/utils/TorchFile.scala``, ``DL/utils/ConvertModel.scala``.
"""

from bigdl_tpu.interop.bigdl_format import (
    load_bigdl_module, save_bigdl_module, decode_bigdl_module,
)
from bigdl_tpu.interop.tf_format import load_tf_graph
from bigdl_tpu.interop.caffe_format import load_caffe_model
from bigdl_tpu.interop.torch_format import load_t7, save_t7
from bigdl_tpu.interop.keras_format import (
    load_keras_json, set_keras_weights, load_keras_hdf5_weights,
)
from bigdl_tpu.interop.tf_export import save_tf_graph
from bigdl_tpu.interop.caffe_export import save_caffe
from bigdl_tpu.interop.torch_export import (
    save_torch_module, load_torch_module,
)
from bigdl_tpu.interop.session import TFSession
