"""Caffe model exporter (prototxt + caffemodel).

Reference: ``DL/utils/caffe/CaffePersister.scala:1`` — walks a BigDL
``Graph``, converts each module back to a Caffe ``LayerParameter``
(``Converter.toCaffe``), and writes both the text prototxt (topology +
hyper-params) and the binary caffemodel (weight blobs keyed by layer
name).

TPU redesign: the generated ``caffe/Caffe.java`` protos are replaced by
the hand wire codec (``utils/protowire``); the module walk runs over the
functional ``nn.Graph``/``Sequential`` containers and reads weights out
of the params/state pytrees instead of mutable module fields.  Caffe's
new-format ``layer`` schema is emitted (the reference's V1 path exists
only for reading old models).

Wire schema used (caffe.proto):
  NetParameter: name=1, layer=100
  LayerParameter: name=1, type=2, bottom=3, top=4, blobs=7
  BlobProto: data=5 (packed float), shape=7 {dim=1}
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.nn.module import Module, Sequential
from bigdl_tpu.nn.graph import Graph, Input as GInput
from bigdl_tpu.utils import protowire as pw


class _Layer:
    """One emitted Caffe layer: prototxt text params + weight blobs."""

    __slots__ = ("name", "type", "bottoms", "tops", "param_text", "blobs")

    def __init__(self, name, type_, bottoms, tops, param_text="", blobs=()):
        self.name = name
        self.type = type_
        self.bottoms = list(bottoms)
        self.tops = list(tops)
        self.param_text = param_text
        self.blobs = list(blobs)


def _np(x):
    return np.asarray(x, np.float32)


def _convert(mod: Module, p, s, name: str) -> List[Tuple[str, str, list]]:
    """module → [(caffe type, param text, blobs)] — one entry per emitted
    layer (BN with affine emits BatchNorm + Scale, the Caffe idiom)."""
    if isinstance(mod, nn.SpatialConvolution):
        kh, kw = mod.kernel
        sh, sw = mod.stride
        ph, pw_ = mod.pad
        dh, dw = mod.dilation
        if dh != dw:
            raise NotImplementedError(
                f"Caffe dilation is isotropic; conv {name!r} has "
                f"dilation {(dh, dw)}")
        txt = (f"  convolution_param {{\n"
               f"    num_output: {mod.n_output_plane}\n"
               f"    bias_term: {'true' if mod.with_bias else 'false'}\n"
               f"    kernel_h: {kh}\n    kernel_w: {kw}\n"
               f"    stride_h: {sh}\n    stride_w: {sw}\n"
               f"    pad_h: {ph}\n    pad_w: {pw_}\n"
               f"    group: {mod.n_group}\n"
               + (f"    dilation: {dh}\n" if dh == dw and dh != 1 else "")
               + "  }")
        blobs = [_np(p["weight"])]
        if mod.with_bias:
            blobs.append(_np(p["bias"]))
        return [("Convolution", txt, blobs)]
    if isinstance(mod, nn.Linear):
        txt = (f"  inner_product_param {{\n"
               f"    num_output: {mod.output_size}\n"
               f"    bias_term: {'true' if mod.with_bias else 'false'}\n"
               f"  }}")
        blobs = [_np(p["weight"])]
        if mod.with_bias:
            blobs.append(_np(p["bias"]))
        return [("InnerProduct", txt, blobs)]
    if isinstance(mod, (nn.SpatialMaxPooling, nn.SpatialAveragePooling)):
        pool = "MAX" if isinstance(mod, nn.SpatialMaxPooling) else "AVE"
        kh, kw = mod.kernel
        sh, sw = mod.stride
        ph, pw_ = mod.pad
        txt = (f"  pooling_param {{\n    pool: {pool}\n"
               f"    kernel_h: {kh}\n    kernel_w: {kw}\n"
               f"    stride_h: {sh}\n    stride_w: {sw}\n"
               f"    pad_h: {ph}\n    pad_w: {pw_}\n  }}")
        return [("Pooling", txt, [])]
    if isinstance(mod, nn.SpatialBatchNormalization):
        out = []
        mean, var = _np(s["running_mean"]), _np(s["running_var"])
        txt = (f"  batch_norm_param {{\n    use_global_stats: true\n"
               f"    eps: {mod.eps}\n  }}")
        out.append(("BatchNorm", txt,
                    [mean, var, np.asarray([1.0], np.float32)]))
        if mod.affine:
            out.append(("Scale", "  scale_param {\n    bias_term: true\n  }",
                        [_np(p["weight"]), _np(p["bias"])]))
        return out
    if isinstance(mod, nn.Scale):
        return [("Scale", "  scale_param {\n    bias_term: true\n  }",
                 [_np(p["mul"]["weight"]).reshape(-1),
                  _np(p["add"]["bias"]).reshape(-1)])]
    if isinstance(mod, nn.SpatialCrossMapLRN):
        txt = (f"  lrn_param {{\n    local_size: {mod.size}\n"
               f"    alpha: {mod.alpha}\n    beta: {mod.beta}\n"
               f"    k: {mod.k}\n  }}")
        return [("LRN", txt, [])]
    if isinstance(mod, nn.Dropout):
        return [("Dropout",
                 f"  dropout_param {{\n    dropout_ratio: {mod.p}\n  }}",
                 [])]
    if isinstance(mod, nn.JoinTable):
        return [("Concat",
                 f"  concat_param {{\n    axis: {mod.dimension}\n  }}", [])]
    simple = {nn.ReLU: "ReLU", nn.Tanh: "TanH", nn.Sigmoid: "Sigmoid",
              nn.SoftMax: "Softmax", nn.Flatten: "Flatten"}
    for cls, t in simple.items():
        if type(mod) is cls:
            return [(t, "", [])]
    if isinstance(mod, nn.CAddTable):
        return [("Eltwise", "  eltwise_param {\n    operation: SUM\n  }", [])]
    if isinstance(mod, nn.CMulTable):
        return [("Eltwise", "  eltwise_param {\n    operation: PROD\n  }", [])]
    if isinstance(mod, nn.CMaxTable):
        return [("Eltwise", "  eltwise_param {\n    operation: MAX\n  }", [])]
    if isinstance(mod, nn.Identity):
        return []
    raise NotImplementedError(
        f"no Caffe mapping for {type(mod).__name__} ({name}); reference "
        "CaffePersister supports the classic CNN layer set only")


def _emit(mod: Module, p, s, bottom: str, layers: List[_Layer],
          used: Dict[str, int]) -> str:
    """Emit `mod` (expanding Sequential chains), return its top name."""
    from bigdl_tpu.nn.module import Remat
    if isinstance(mod, Remat):
        # execution hint only — export the wrapped module
        return _emit(mod.inner, p, s, bottom, layers, used)
    if isinstance(mod, Sequential):
        top = bottom
        for i, child in enumerate(mod.modules):
            top = _emit(child, p.get(str(i), {}), s.get(str(i), {}),
                        top, layers, used)
        return top
    converted = _convert(mod, p, s, mod.name)
    top = bottom
    for type_, txt, blobs in converted:
        base = mod.name if len(converted) == 1 else \
            f"{mod.name}_{type_.lower()}"
        n = used.get(base, 0)
        used[base] = n + 1
        lname = base if n == 0 else f"{base}_{n}"
        layers.append(_Layer(lname, type_, [top], [lname], txt, blobs))
        top = lname
    return top


def save_caffe(module: Module, prototxt_path: str, model_path: str,
               input_shapes: Optional[Sequence[Sequence[int]]] = None
               ) -> None:
    """Write ``module`` as Caffe prototxt + caffemodel (reference
    ``CaffePersister.persist``).

    Supports :class:`nn.Graph` and :class:`nn.Sequential` trees over the
    classic CNN layer set (Convolution/InnerProduct/Pooling/BN/LRN/
    activations/Concat/Eltwise).  ``input_shapes`` (one ``[N,C,H,W]``
    per graph input) is emitted as ``input_shape`` so Caffe can
    materialize the net; omitted dims are left for the consumer.
    """
    module._ensure_init()
    params = module._params
    state = module._state

    layers: List[_Layer] = []
    used: Dict[str, int] = {}
    input_names: List[str] = []

    if isinstance(module, Graph):
        tops: Dict[int, str] = {}
        for i, inp in enumerate(module.input_nodes):
            nm = "data" if len(module.input_nodes) == 1 else f"data{i}"
            tops[id(inp)] = nm
            input_names.append(nm)
        for node, key in zip(module._order, module._param_keys):
            bots = [tops[id(b)] for b in node.inputs]
            mod = node.module
            if isinstance(mod, Sequential) or len(bots) == 1:
                top = _emit(mod, params.get(key, {}), state.get(key, {}),
                            bots[0], layers, used)
            else:
                converted = _convert(mod, params.get(key, {}),
                                     state.get(key, {}), mod.name)
                if len(converted) != 1:
                    raise NotImplementedError(
                        f"multi-input module {mod.name} must convert to "
                        "exactly one Caffe layer")
                type_, txt, blobs = converted[0]
                n = used.get(mod.name, 0)
                used[mod.name] = n + 1
                lname = mod.name if n == 0 else f"{mod.name}_{n}"
                layers.append(_Layer(lname, type_, bots, [lname], txt,
                                     blobs))
                top = lname
            tops[id(node)] = top
    else:
        input_names.append("data")
        _emit(module, params, state, "data", layers, used)

    net_name = module.name or "BigDLNet"
    # ---- prototxt
    lines = [f'name: "{net_name}"']
    for i, nm in enumerate(input_names):
        lines.append(f'input: "{nm}"')
        if input_shapes is not None:
            dims = "".join(f"\n  dim: {int(d)}" for d in input_shapes[i])
            lines.append(f"input_shape {{{dims}\n}}")
    for l in layers:
        body = [f'layer {{', f'  name: "{l.name}"', f'  type: "{l.type}"']
        for b in l.bottoms:
            body.append(f'  bottom: "{b}"')
        for t in l.tops:
            body.append(f'  top: "{t}"')
        if l.param_text:
            body.append(l.param_text)
        body.append("}")
        lines.append("\n".join(body))
    with open(prototxt_path, "w") as f:
        f.write("\n".join(lines) + "\n")

    # ---- caffemodel
    out = bytearray()
    out += pw.enc_str(1, net_name)
    for l in layers:
        msg = bytearray()
        msg += pw.enc_str(1, l.name)
        msg += pw.enc_str(2, l.type)
        for b in l.bottoms:
            msg += pw.enc_str(3, b)
        for t in l.tops:
            msg += pw.enc_str(4, t)
        for blob in l.blobs:
            shape = b"".join(pw.enc_varint(1, int(d)) for d in blob.shape)
            bp = pw.enc_packed_floats(5, blob.reshape(-1).tolist()) \
                + pw.enc_bytes(7, shape)
            msg += pw.enc_bytes(7, bp)
        out += pw.enc_bytes(100, bytes(msg))
    with open(model_path, "wb") as f:
        f.write(bytes(out))
