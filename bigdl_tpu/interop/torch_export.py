"""Torch7 nn-module tree export/import over the .t7 codec.

Reference: ``DL/utils/TorchFile.scala`` saves/loads whole Torch7 nn
module objects (class name + field table), which is what
``ConvertModel --to torch`` emits (``DL/utils/ConvertModel.scala:24-46``)
and ``Module.loadTorch`` consumes.

TPU redesign: modules are pure functional (params live in pytrees), so
export walks ``(module, params, state)`` and materializes the mutable
Torch field layout (weight/bias/gradWeight/gradBias arrays); import
reverses it.  The Lua-object wire layout itself is handled by
``torch_format._Writer``/``_Reader``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.nn.module import Module, Sequential
from bigdl_tpu.nn.graph import Graph
from bigdl_tpu.interop.torch_format import load_t7, save_t7


def _np(x):
    return np.asarray(x, np.float64)


def _obj(cls: str, **fields) -> Dict[str, Any]:
    return {"_torch_class": cls,
            "fields": {k: v for k, v in fields.items() if v is not None}}


def _with_grads(fields: Dict[str, Any]) -> Dict[str, Any]:
    if "weight" in fields:
        fields["gradWeight"] = np.zeros_like(fields["weight"])
    if "bias" in fields and fields["bias"] is not None:
        fields["gradBias"] = np.zeros_like(fields["bias"])
    return fields


def module_to_torch(mod: Module, p, s) -> Dict[str, Any]:
    """One module (+ its param/state subtree) → Torch7 object tree."""
    from bigdl_tpu.nn.module import Remat
    if isinstance(mod, Remat):
        # execution hint only — export the wrapped module
        return module_to_torch(mod.inner, p, s)
    if isinstance(mod, Sequential):
        mods = [module_to_torch(c, p.get(str(i), {}), s.get(str(i), {}))
                for i, c in enumerate(mod.modules)]
        return _obj("nn.Sequential", modules=mods)
    if isinstance(mod, nn.Linear):
        f = _with_grads({"weight": _np(p["weight"]),
                         "bias": _np(p["bias"]) if mod.with_bias else None})
        return _obj("nn.Linear", **f)
    if isinstance(mod, nn.SpatialConvolution):
        kh, kw = mod.kernel
        sh, sw = mod.stride
        ph, pw = mod.pad
        f = _with_grads({"weight": _np(p["weight"]),
                         "bias": _np(p["bias"]) if mod.with_bias else None})
        return _obj("nn.SpatialConvolution",
                    nInputPlane=mod.n_input_plane,
                    nOutputPlane=mod.n_output_plane,
                    kW=kw, kH=kh, dW=sw, dH=sh, padW=pw, padH=ph, **f)
    if isinstance(mod, nn.SpatialMaxPooling):
        kh, kw = mod.kernel
        sh, sw = mod.stride
        ph, pw = mod.pad
        return _obj("nn.SpatialMaxPooling", kW=kw, kH=kh, dW=sw, dH=sh,
                    padW=pw, padH=ph, ceil_mode=mod.ceil_mode)
    if isinstance(mod, nn.SpatialAveragePooling):
        kh, kw = mod.kernel
        sh, sw = mod.stride
        ph, pw = mod.pad
        return _obj("nn.SpatialAveragePooling", kW=kw, kH=kh, dW=sw, dH=sh,
                    padW=pw, padH=ph, ceil_mode=mod.ceil_mode,
                    count_include_pad=mod.count_include_pad)
    if isinstance(mod, nn.SpatialBatchNormalization):
        f: Dict[str, Any] = {"running_mean": _np(s["running_mean"]),
                             "running_var": _np(s["running_var"]),
                             "eps": mod.eps, "momentum": mod.momentum,
                             "affine": mod.affine,
                             "nOutput": mod.n_output}
        if mod.affine:
            f = _with_grads({**f, "weight": _np(p["weight"]),
                             "bias": _np(p["bias"])})
        return _obj("nn.SpatialBatchNormalization", **f)
    if isinstance(mod, nn.LookupTable):
        return _obj("nn.LookupTable",
                    **_with_grads({"weight": _np(p["weight"])}))
    if isinstance(mod, nn.SpatialCrossMapLRN):
        return _obj("nn.SpatialCrossMapLRN", size=mod.size, alpha=mod.alpha,
                    beta=mod.beta, k=mod.k)
    if isinstance(mod, nn.Dropout):
        return _obj("nn.Dropout", p=mod.p)
    if isinstance(mod, nn.Reshape):
        return _obj("nn.Reshape", size=list(mod.size))
    if isinstance(mod, nn.Flatten):
        # torch idiom for flatten-all-but-batch
        return _obj("nn.View", numElements=-1, size=[-1])
    simple = {nn.ReLU: "nn.ReLU", nn.Tanh: "nn.Tanh",
              nn.Sigmoid: "nn.Sigmoid", nn.SoftMax: "nn.SoftMax",
              nn.LogSoftMax: "nn.LogSoftMax", nn.Identity: "nn.Identity"}
    for cls, tname in simple.items():
        if type(mod) is cls:
            return _obj(tname)
    raise NotImplementedError(
        f"no Torch7 mapping for {type(mod).__name__} "
        "(reference TorchFile covers the classic torch nn layer set)")


def save_torch_module(module: Module, path: str) -> None:
    """Write ``module`` as a Torch7 nn object tree .t7 (reference
    ``ConvertModel --to torch`` / ``TorchFile.save``)."""
    module._ensure_init()
    save_t7(path, module_to_torch(module, module._params, module._state))


# --------------------------------------------------------------- importing
def torch_to_module(tree) -> Module:
    """Torch7 object tree (from :func:`load_t7`) → module with weights
    (reference ``Module.loadTorch``)."""
    if not (isinstance(tree, dict) and "_torch_class" in tree):
        raise ValueError(f"not a torch module object: {type(tree)}")
    cls = tree["_torch_class"].split(".")[-1]
    f = tree.get("fields", {}) or {}

    def arr(key):
        v = f.get(key)
        return None if v is None else np.asarray(v, np.float32)

    def sized(key, default=None):
        v = f.get(key, default)
        return int(v) if v is not None else None

    if cls == "Sequential":
        import jax
        children = [torch_to_module(m) for m in f.get("modules", [])]
        seq = nn.Sequential(*children)
        # assemble the parent pytree from the children's imported params
        # (a later _ensure_init on the Sequential would re-init randomly)
        for c in children:
            c._ensure_init()
        seq._params = {str(i): c._params for i, c in enumerate(children)}
        seq._state = {str(i): c._state for i, c in enumerate(children)}
        seq._grads = jax.tree_util.tree_map(np.zeros_like, seq._params)
        return seq
    if cls == "Linear":
        w = arr("weight")
        m = nn.Linear(w.shape[1], w.shape[0],
                      with_bias=arr("bias") is not None)
        m._set_import_params({"weight": w, "bias": arr("bias")})
        return m
    if cls in ("SpatialConvolution", "SpatialConvolutionMM"):
        w = arr("weight")
        n_out = sized("nOutputPlane", w.shape[0])
        n_in = sized("nInputPlane")
        kw, kh = sized("kW"), sized("kH")
        w = w.reshape(n_out, n_in, kh, kw)
        m = nn.SpatialConvolution(
            n_in, n_out, kw, kh, sized("dW", 1), sized("dH", 1),
            sized("padW", 0), sized("padH", 0),
            with_bias=arr("bias") is not None)
        m._set_import_params({"weight": w, "bias": arr("bias")})
        return m
    if cls == "SpatialMaxPooling":
        return nn.SpatialMaxPooling(
            sized("kW"), sized("kH"), sized("dW", 1), sized("dH", 1),
            sized("padW", 0), sized("padH", 0),
            ceil_mode=bool(f.get("ceil_mode", False)))
    if cls == "SpatialAveragePooling":
        return nn.SpatialAveragePooling(
            sized("kW"), sized("kH"), sized("dW", 1), sized("dH", 1),
            sized("padW", 0), sized("padH", 0),
            ceil_mode=bool(f.get("ceil_mode", False)),
            count_include_pad=bool(f.get("count_include_pad", True)))
    if cls == "SpatialBatchNormalization":
        mean = arr("running_mean")
        m = nn.SpatialBatchNormalization(
            sized("nOutput", mean.shape[0]),
            eps=float(f.get("eps", 1e-5)),
            momentum=float(f.get("momentum", 0.1)),
            affine=bool(f.get("affine", arr("weight") is not None)))
        m._set_import_params(
            {"weight": arr("weight"), "bias": arr("bias")}
            if m.affine else {},
            {"running_mean": mean, "running_var": arr("running_var")})
        return m
    if cls == "LookupTable":
        w = arr("weight")
        m = nn.LookupTable(w.shape[0], w.shape[1])
        m._set_import_params({"weight": w})
        return m
    if cls == "SpatialCrossMapLRN":
        return nn.SpatialCrossMapLRN(
            sized("size", 5), float(f.get("alpha", 1.0)),
            float(f.get("beta", 0.75)), float(f.get("k", 1.0)))
    if cls == "Dropout":
        return nn.Dropout(float(f.get("p", 0.5)))
    if cls == "Reshape":
        return nn.Reshape(tuple(int(d) for d in f.get("size", [])))
    if cls == "View":
        size = [int(d) for d in np.ravel(np.asarray(f.get("size", [-1])))]
        if size == [-1]:     # flatten-all-but-batch (our export idiom)
            return nn.Flatten()
        return nn.View(tuple(size))
    simple = {"ReLU": nn.ReLU, "Tanh": nn.Tanh, "Sigmoid": nn.Sigmoid,
              "SoftMax": nn.SoftMax, "LogSoftMax": nn.LogSoftMax,
              "Identity": nn.Identity}
    if cls in simple:
        return simple[cls]()
    raise NotImplementedError(f"torch class nn.{cls} is not mapped")


def load_torch_module(path: str) -> Module:
    """.t7 containing a Torch7 nn module tree → module (reference
    ``Module.loadTorch``)."""
    return torch_to_module(load_t7(path))
