"""Model conversion CLI.

Reference: ``DL/utils/ConvertModel.scala:24-46`` —
``--from {bigdl,caffe,torch,tensorflow} --to {bigdl,caffe,torch}`` with
``--prototxt`` for Caffe sources, ``--tf_inputs``/``--tf_outputs`` for
TF sources, and ``--quantize`` for int8 post-training quantization of
the saved model.

Usage:
    python -m bigdl_tpu.interop.convert_model \
        --from caffe --prototxt net.prototxt --input net.caffemodel \
        --to bigdl --output model.bigdl
"""

from __future__ import annotations

import argparse


def _load(args):
    if args.src_fmt == "bigdl":
        from bigdl_tpu.interop import load_bigdl_module
        return load_bigdl_module(args.input)
    if args.src_fmt == "caffe":
        if not args.prototxt:
            raise SystemExit("--from caffe requires --prototxt")
        from bigdl_tpu.interop import load_caffe_model
        return load_caffe_model(args.prototxt, args.input)
    if args.src_fmt == "torch":
        from bigdl_tpu.interop.torch_export import load_torch_module
        return load_torch_module(args.input)
    if args.src_fmt in ("tf", "tensorflow"):
        if not (args.tf_inputs and args.tf_outputs):
            raise SystemExit(
                "--from tensorflow requires --tf_inputs and --tf_outputs")
        from bigdl_tpu.interop import load_tf_graph
        return load_tf_graph(args.input, inputs=args.tf_inputs.split(","),
                             outputs=args.tf_outputs.split(","))
    if args.src_fmt == "keras":
        from bigdl_tpu.interop import load_keras_json
        model = load_keras_json(args.input)
        if args.weights:
            from bigdl_tpu.interop import load_keras_hdf5_weights
            load_keras_hdf5_weights(model, args.weights)
        return model.core_module()
    raise SystemExit(f"unknown source format {args.src_fmt}")


def _save(model, args):
    if args.dst_fmt == "bigdl":
        from bigdl_tpu.interop import save_bigdl_module
        save_bigdl_module(model, args.output)
    elif args.dst_fmt == "caffe":
        from bigdl_tpu.interop.caffe_export import save_caffe
        proto = args.output_def or args.output + ".prototxt"
        save_caffe(model, proto, args.output)
    elif args.dst_fmt == "torch":
        from bigdl_tpu.interop.torch_export import save_torch_module
        save_torch_module(model, args.output)
    else:
        raise SystemExit(f"unknown target format {args.dst_fmt}")


def main(argv=None):
    p = argparse.ArgumentParser(description="Convert models between formats")
    p.add_argument("--from", dest="src_fmt", required=True,
                   choices=["bigdl", "caffe", "torch", "tf", "tensorflow",
                            "keras"])
    p.add_argument("--to", dest="dst_fmt", required=True,
                   choices=["bigdl", "caffe", "torch"])
    p.add_argument("--input", required=True, help="source model file")
    p.add_argument("--output", required=True, help="destination file")
    p.add_argument("--prototxt", help="Caffe source net definition")
    p.add_argument("--output-def", dest="output_def",
                   help="Caffe target prototxt path "
                        "(default: <output>.prototxt)")
    p.add_argument("--tf_inputs", help="comma-separated TF input nodes")
    p.add_argument("--tf_outputs", help="comma-separated TF output nodes")
    p.add_argument("--weights", help="Keras HDF5 weight file")
    p.add_argument("--quantize", action="store_true",
                   help="int8-quantize before saving (bigdl target only, "
                        "reference ConvertModel.scala:40)")
    args = p.parse_args(argv)

    model = _load(args)
    if args.quantize:
        if args.dst_fmt != "bigdl":
            raise SystemExit("--quantize is only supported with --to bigdl")
        from bigdl_tpu.nn.quantized import quantize
        model = quantize(model)
    _save(model, args)
    print(f"converted {args.input} ({args.src_fmt}) -> "
          f"{args.output} ({args.dst_fmt})")


if __name__ == "__main__":
    main()
