"""Model conversion CLI.

Reference: ``DL/utils/ConvertModel.scala:24-46`` —
``--from {bigdl,caffe,torch,tensorflow} --to {bigdl,...}``.  Supported
here: ``tensorflow → bigdl`` and ``bigdl → bigdl`` (re-serialize); the
native ``.npz`` training checkpoint (``utils/checkpoint``) also exports
to the reference format via ``bigdl``.

Usage:
    python -m bigdl_tpu.interop.convert_model \
        --from tensorflow --input g.pb --inputs x --outputs out \
        --to bigdl --output model.bigdl
"""

from __future__ import annotations

import argparse


def main(argv=None):
    p = argparse.ArgumentParser(description="Convert models between formats")
    p.add_argument("--from", dest="src_fmt", required=True,
                   choices=["bigdl", "tensorflow"])
    p.add_argument("--to", dest="dst_fmt", required=True,
                   choices=["bigdl"])
    p.add_argument("--input", required=True, help="source model file")
    p.add_argument("--output", required=True, help="destination file")
    p.add_argument("--inputs", default=None,
                   help="comma-separated TF input node names")
    p.add_argument("--outputs", default=None,
                   help="comma-separated TF output node names")
    args = p.parse_args(argv)

    from bigdl_tpu.interop import (load_bigdl_module, load_tf_graph,
                                   save_bigdl_module)

    if args.src_fmt == "tensorflow":
        if not (args.inputs and args.outputs):
            p.error("tensorflow source needs --inputs and --outputs")
        model = load_tf_graph(args.input, args.inputs.split(","),
                              args.outputs.split(","))
    else:
        model = load_bigdl_module(args.input)

    if args.dst_fmt == "bigdl":
        if args.src_fmt == "tensorflow":
            raise SystemExit(
                "tensorflow→bigdl structural conversion is not supported: "
                "an imported TF graph executes natively (TFGraphModule); "
                "save its checkpoint with utils/checkpoint instead")
        save_bigdl_module(model, args.output)
    print(f"converted {args.input} ({args.src_fmt}) -> "
          f"{args.output} ({args.dst_fmt})")


if __name__ == "__main__":
    main()
