"""Model conversion CLI.

Reference: ``DL/utils/ConvertModel.scala:24-46`` —
``--from {bigdl,caffe,torch,tensorflow} --to {bigdl,caffe,torch}`` with
``--prototxt`` for Caffe sources, ``--tf_inputs``/``--tf_outputs`` for
TF sources, and ``--quantize`` for int8 post-training quantization of
the saved model.

Usage:
    python -m bigdl_tpu.interop.convert_model \
        --from caffe --prototxt net.prototxt --input net.caffemodel \
        --to bigdl --output model.bigdl
"""

from __future__ import annotations

import argparse


def _load(args):
    if args.src_fmt == "bigdl":
        from bigdl_tpu.interop import load_bigdl_module
        return load_bigdl_module(args.input)
    if args.src_fmt == "caffe":
        if not args.prototxt:
            raise SystemExit("--from caffe requires --prototxt")
        from bigdl_tpu.interop import load_caffe_model
        return load_caffe_model(args.prototxt, args.input)
    if args.src_fmt == "torch":
        from bigdl_tpu.interop.torch_export import load_torch_module
        return load_torch_module(args.input)
    if args.src_fmt in ("tf", "tensorflow"):
        if not (args.tf_inputs and args.tf_outputs):
            raise SystemExit(
                "--from tensorflow requires --tf_inputs and --tf_outputs")
        from bigdl_tpu.interop import load_tf_graph
        return load_tf_graph(args.input, inputs=args.tf_inputs.split(","),
                             outputs=args.tf_outputs.split(","))
    if args.src_fmt == "keras":
        from bigdl_tpu.interop import load_keras_json
        model = load_keras_json(args.input)
        if args.weights:
            from bigdl_tpu.interop import load_keras_hdf5_weights
            load_keras_hdf5_weights(model, args.weights)
        return model.core_module()
    raise SystemExit(f"unknown source format {args.src_fmt}")


def _probe_input(model):
    """Derive a forward-probe batch from the first weighted layer.

    Walks the module tree in declaration order and shapes a small f32
    batch for the first ``Linear`` ((4, input_size)) or
    ``SpatialConvolution`` ((2, C, H, W) honoring the layer's data
    format) it finds.  Returns ``None`` when the tree has neither
    (e.g. embedding-only models) — the parity check is then skipped
    loudly rather than guessed at."""
    import numpy as np

    from bigdl_tpu.nn.layers import Linear, SpatialConvolution
    from bigdl_tpu.nn.module import Container

    queue = [model]
    while queue:
        m = queue.pop(0)
        if isinstance(m, Linear):
            shape = (4, m.input_size)
        elif isinstance(m, SpatialConvolution):
            kh, kw = m.kernel
            h, w = max(8, kh), max(8, kw)
            shape = ((2, m.n_input_plane, h, w) if m.format == "NCHW"
                     else (2, h, w, m.n_input_plane))
        elif isinstance(m, Container):
            queue = list(m.modules) + queue
            continue
        else:
            continue
        return np.random.default_rng(0).standard_normal(shape) \
            .astype(np.float32)
    return None


def _validate_quantized(source, quantized, tol):
    """Forward-parity gate for ``--quantize``: the int8 model must agree
    with the float source on a probe batch within ``tol`` relative
    error, or the conversion aborts before anything is saved.  (The CLI
    used to quantize blind — a panel with a saturated outlier channel
    would serialize garbage silently.)"""
    import numpy as np

    x = _probe_input(source)
    if x is None:
        print("quantize parity: no Linear/SpatialConvolution in the "
              "model tree; forward check skipped")
        return None
    y0 = np.asarray(source.forward(x), dtype=np.float32)
    y1 = np.asarray(quantized.forward(x), dtype=np.float32)
    denom = max(float(np.max(np.abs(y0))), 1e-6)
    err = float(np.max(np.abs(y1 - y0))) / denom
    if err > tol:
        raise SystemExit(
            f"--quantize parity check FAILED: max relative error "
            f"{err:.4f} > tolerance {tol} — refusing to save the "
            f"quantized model (raise --quantize-tolerance to override)")
    print(f"quantize parity: max relative error {err:.4f} "
          f"(tolerance {tol})")
    return err


def _save(model, args):
    if args.dst_fmt == "bigdl":
        from bigdl_tpu.interop import save_bigdl_module
        save_bigdl_module(model, args.output)
    elif args.dst_fmt == "caffe":
        from bigdl_tpu.interop.caffe_export import save_caffe
        proto = args.output_def or args.output + ".prototxt"
        save_caffe(model, proto, args.output)
    elif args.dst_fmt == "torch":
        from bigdl_tpu.interop.torch_export import save_torch_module
        save_torch_module(model, args.output)
    else:
        raise SystemExit(f"unknown target format {args.dst_fmt}")


def main(argv=None):
    p = argparse.ArgumentParser(description="Convert models between formats")
    p.add_argument("--from", dest="src_fmt", required=True,
                   choices=["bigdl", "caffe", "torch", "tf", "tensorflow",
                            "keras"])
    p.add_argument("--to", dest="dst_fmt", required=True,
                   choices=["bigdl", "caffe", "torch"])
    p.add_argument("--input", required=True, help="source model file")
    p.add_argument("--output", required=True, help="destination file")
    p.add_argument("--prototxt", help="Caffe source net definition")
    p.add_argument("--output-def", dest="output_def",
                   help="Caffe target prototxt path "
                        "(default: <output>.prototxt)")
    p.add_argument("--tf_inputs", help="comma-separated TF input nodes")
    p.add_argument("--tf_outputs", help="comma-separated TF output nodes")
    p.add_argument("--weights", help="Keras HDF5 weight file")
    p.add_argument("--quantize", action="store_true",
                   help="int8-quantize before saving (bigdl target only, "
                        "reference ConvertModel.scala:40)")
    p.add_argument("--quantize-mode", dest="quantize_mode",
                   choices=["weight_only", "dynamic"],
                   help="int8 activation mode (default: "
                        "Config.int8_activation_mode)")
    p.add_argument("--quantize-tolerance", dest="quantize_tolerance",
                   type=float, default=0.05,
                   help="max relative forward error accepted by the "
                        "--quantize parity check (default 0.05)")
    args = p.parse_args(argv)

    model = _load(args)
    if args.quantize:
        if args.dst_fmt != "bigdl":
            raise SystemExit("--quantize is only supported with --to bigdl")
        from bigdl_tpu.nn.quantized import quantize
        source = model
        model = quantize(model, mode=args.quantize_mode)
        _validate_quantized(source, model, args.quantize_tolerance)
    _save(model, args)
    print(f"converted {args.input} ({args.src_fmt}) -> "
          f"{args.output} ({args.dst_fmt})")


if __name__ == "__main__":
    main()
