"""Model conversion CLI.

Reference: ``DL/utils/ConvertModel.scala:24-46`` —
``--from {bigdl,caffe,torch,tensorflow} --to {bigdl,...}``.  Supported
conversion: ``bigdl → bigdl`` (re-serialize, e.g. to normalize storage
layout).  ``tensorflow`` sources load and execute natively as
``TFGraphModule`` (no structural conversion to the bigdl layer tree), so
``tensorflow → bigdl`` is rejected up front — save an imported graph's
weights with ``utils/checkpoint`` instead.

Usage:
    python -m bigdl_tpu.interop.convert_model \
        --from bigdl --input model.bigdl --to bigdl --output copy.bigdl
"""

from __future__ import annotations

import argparse


def main(argv=None):
    p = argparse.ArgumentParser(description="Convert models between formats")
    p.add_argument("--from", dest="src_fmt", required=True,
                   choices=["bigdl", "tensorflow"])
    p.add_argument("--to", dest="dst_fmt", required=True,
                   choices=["bigdl"])
    p.add_argument("--input", required=True, help="source model file")
    p.add_argument("--output", required=True, help="destination file")
    p.add_argument("--inputs", default=None,
                   help="comma-separated TF input node names")
    p.add_argument("--outputs", default=None,
                   help="comma-separated TF output node names")
    args = p.parse_args(argv)

    # validate the combination BEFORE any expensive load
    if args.src_fmt == "tensorflow" and args.dst_fmt == "bigdl":
        p.error(
            "tensorflow->bigdl structural conversion is not supported: an "
            "imported TF graph executes natively (TFGraphModule); load it "
            "with interop.load_tf_graph and save its weights with "
            "utils/checkpoint instead")
    if args.src_fmt == "tensorflow" and not (args.inputs and args.outputs):
        p.error("tensorflow source needs --inputs and --outputs")

    from bigdl_tpu.interop import load_bigdl_module, save_bigdl_module

    model = load_bigdl_module(args.input)
    if args.dst_fmt == "bigdl":
        save_bigdl_module(model, args.output)
    print(f"converted {args.input} ({args.src_fmt}) -> "
          f"{args.output} ({args.dst_fmt})")


if __name__ == "__main__":
    main()
