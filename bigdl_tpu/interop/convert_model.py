"""Model conversion CLI.

Reference: ``DL/utils/ConvertModel.scala:24-46`` —
``--from {bigdl,caffe,torch,tensorflow} --to {bigdl,...}``.  Supported
conversion: ``bigdl → bigdl`` (re-serialize, e.g. to normalize storage
layout).  TF/Caffe/Torch sources load and execute natively via
``interop.load_tf_graph`` / ``load_caffe_model`` / ``load_t7`` — there is
no structural conversion into the bigdl layer tree to re-serialize.

Usage:
    python -m bigdl_tpu.interop.convert_model \
        --from bigdl --input model.bigdl --to bigdl --output copy.bigdl
"""

from __future__ import annotations

import argparse


def main(argv=None):
    p = argparse.ArgumentParser(description="Convert models between formats")
    p.add_argument("--from", dest="src_fmt", required=True,
                   choices=["bigdl"],
                   help="source format; tensorflow/caffe/torch models "
                        "import via interop.load_tf_graph / "
                        "load_caffe_model / load_t7 and execute natively "
                        "(no structural conversion to re-serialize)")
    p.add_argument("--to", dest="dst_fmt", required=True,
                   choices=["bigdl"])
    p.add_argument("--input", required=True, help="source model file")
    p.add_argument("--output", required=True, help="destination file")
    args = p.parse_args(argv)

    from bigdl_tpu.interop import load_bigdl_module, save_bigdl_module

    model = load_bigdl_module(args.input)
    save_bigdl_module(model, args.output)
    print(f"converted {args.input} ({args.src_fmt}) -> "
          f"{args.output} ({args.dst_fmt})")


if __name__ == "__main__":
    main()
