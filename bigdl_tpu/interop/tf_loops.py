"""TF v1 while-loop frame reconstruction → ``lax.while_loop``/``lax.scan``.

Reference: ``DL/nn/tf/ControlOps.scala`` (Enter/Exit/NextIteration/
LoopCondition/Switch/Merge) executed by the dataflow ``Scheduler``
(``DL/nn/Scheduler.scala:104-145``) with dead-token propagation and
arbitrary frame NESTING (``FrameManager`` parent/child frames).

TPU redesign: a loop frame compiles to ONE ``lax.while_loop`` (or a
``lax.scan`` when the trip count is statically recoverable — see
``static_trip_count`` — which restores reverse-mode differentiability
for bounded loops).  The v1 wiring per loop variable is

    outer ──Enter(frame)──▶ Merge ◀── NextIteration ◀── body value
                              │
                              ├──▶ (cond subgraph) ──▶ LoopCond
                              ▼
                           Switch(data, LoopCond)
                        port0=false ▶ Exit ▶ downstream
                        port1=true  ▶ (body subgraph)

so: carry = Merge values; ``cond`` evaluates the LoopCond input with
merges bound to the carry; ``body`` evaluates each NextIteration input
the same way; Exit yields the final carry.  Loop-invariant Enters (no
Merge consumer) bind straight to their outer value.

**Nesting** (the reference's ``FrameManager`` parent/child): each node
is owned by its INNERMOST frame; a parent's body evaluator treats a
child frame as one fused sub-loop, executed when the child's Exit value
is demanded (see ``TFGraphModule._eval_interior``).

Loops whose trip count cannot be recovered statically stay
``lax.while_loop`` and are forward-only under reverse-mode AD (a JAX
fundamental) — the same contract as the reference's forward-only
``nn/ops`` execution.

:func:`extract_frames` groups a GraphDef's nodes by the Enter
``frame_name`` attr, builds the parent/child hierarchy, and returns the
per-frame wiring; the executor in ``tf_format`` uses it to run frames
as single fused steps.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np


def _attr_frame(node) -> Optional[str]:
    f = node["attrs"].get("frame_name")
    if isinstance(f, bytes):
        return f.decode()
    return f


class LoopFrame:
    """Wiring of one while-loop frame."""

    __slots__ = ("name", "interior", "enters", "merges", "switches",
                 "exits", "next_iterations", "loop_cond", "invariants",
                 "error", "externals", "parent", "children")

    def __init__(self, name: str):
        self.name = name
        self.externals: set = set()     # node names OUTSIDE the frame
        # that interior nodes read (the frame's data dependencies);
        # for a nested frame these include parent-interior names
        self.error: Optional[str] = None  # set instead of raising so an
        # UNREACHABLE malformed frame never blocks loading; the executor
        # raises only if a pruned path actually needs this frame
        self.interior: set = set()      # node names owned by THIS frame
        # (descendants' nodes excluded — innermost owner wins)
        self.enters: List[dict] = []
        self.merges: List[dict] = []    # aligned with loop-var enters
        self.switches: List[dict] = []
        self.exits: List[dict] = []
        self.next_iterations: List[dict] = []
        self.loop_cond: Optional[dict] = None
        self.invariants: List[dict] = []  # Enters with no Merge consumer
        self.parent: Optional["LoopFrame"] = None
        self.children: List["LoopFrame"] = []

    # -------------------------------------------------- nest aggregates
    def descendants(self) -> List["LoopFrame"]:
        out = []
        stack = list(self.children)
        while stack:
            f = stack.pop()
            out.append(f)
            stack.extend(f.children)
        return out

    def all_interior(self) -> set:
        out = set(self.interior)
        for d in self.descendants():
            out |= d.interior
        return out

    def all_externals(self) -> set:
        """External deps of the whole nest: union of per-frame externals
        minus every name owned inside the nest."""
        nest = self.all_interior()
        out = set(self.externals)
        for d in self.descendants():
            out |= d.externals
        return out - nest

    def nest_error(self) -> Optional[str]:
        if self.error:
            return self.error
        for d in self.descendants():
            if d.error:
                return d.error
        return None


def extract_frames(nodes: List[dict]) -> Dict[str, LoopFrame]:
    """Group control-flow nodes into frames (innermost ownership),
    recover per-variable wiring, and link parent/child frames.
    Unsupported shapes (missing LoopCond, odd merge wiring) set
    ``frame.error`` rather than raising, so they only fail if the
    requested outputs actually reach them."""
    by_name = {n["name"]: n for n in nodes}
    consumers: Dict[str, List[dict]] = {}
    for n in nodes:
        for inp in n["inputs"]:
            base = inp.split(":")[0].lstrip("^")
            consumers.setdefault(base, []).append(n)

    frames: Dict[str, LoopFrame] = {}
    frame_enters: Dict[str, List[dict]] = {}
    for n in nodes:
        if n["op"] == "Enter":
            fname = _attr_frame(n) or "frame"
            frames.setdefault(fname, LoopFrame(fname))
            frame_enters.setdefault(fname, []).append(n)

    # each Exit belongs to the frame its data chain entered: walk
    # Switch→Merge→Enter along input[0] to the Enter's frame_name
    def exit_frame(ex_node) -> Optional[str]:
        nm = ex_node["inputs"][0].split(":")[0]
        for _ in range(32):
            n = by_name.get(nm)
            if n is None or not n["inputs"] and n["op"] != "Enter":
                return None
            if n["op"] == "Enter":
                return _attr_frame(n) or "frame"
            nm = n["inputs"][0].split(":")[0]
        return None

    # ---- phase 1: flood each frame forward from its Enters, stopping
    # only at the frame's OWN Exits (a nested frame's Exit feeds nodes
    # that still belong to this frame)
    flood: Dict[str, set] = {}
    for fname, enters in frame_enters.items():
        stack = [e["name"] for e in enters]
        seen = set(stack)
        while stack:
            nm = stack.pop()
            node = by_name[nm]
            if node["op"] == "Exit" and exit_frame(node) == fname:
                continue
            for c in consumers.get(nm, []):
                if c["name"] not in seen:
                    seen.add(c["name"])
                    stack.append(c["name"])
        flood[fname] = seen

    # ---- phase 2: hierarchy (innermost ownership).  Frame B is nested
    # in A iff B's Enters lie inside A's flood; the innermost parent is
    # the candidate with the smallest flood.
    for bname, benters in frame_enters.items():
        # ANY enter inside A's flood marks nesting (loop-var enters whose
        # init is outer-frame data are flooded; counter enters fed by
        # consts are not)
        bnames = {e["name"] for e in benters}
        cands = [a for a in frames
                 if a != bname and (bnames & flood[a])]
        if cands:
            parent = min(cands, key=lambda a: len(flood[a]))
            frames[bname].parent = frames[parent]
            frames[parent].children.append(frames[bname])
    owner: Dict[str, str] = {}
    for fname in frames:
        others = set()
        for oname in frames:
            if oname != fname and frames[oname].parent is not None:
                # any frame nested (transitively) under fname claims its
                # nodes away from fname
                p = frames[oname]
                anc = p.parent
                while anc is not None:
                    if anc.name == fname:
                        others |= flood[oname]
                        break
                    anc = anc.parent
        frames[fname].interior = flood[fname] - others
        for nm in frames[fname].interior:
            owner[nm] = fname

    # ---- phase 3: per-frame classification over owned nodes
    for fname, frame in frames.items():
        for nm in frame.interior:
            node = by_name[nm]
            for inp in node["inputs"]:
                base = inp.split(":")[0]
                if base.startswith("^") or base in frame.interior:
                    continue
                own = owner.get(base)
                if own is not None and frames[own].parent is not None:
                    # owned by a DESCENDANT frame (child Exit): internal
                    # to the nest, resolved by the parent's evaluator
                    anc = frames[own].parent
                    nested = False
                    while anc is not None:
                        if anc is frame:
                            nested = True
                            break
                        anc = anc.parent
                    if nested:
                        continue
                frame.externals.add(base)
            op = node["op"]
            if op == "Merge":
                frame.merges.append(node)
            elif op == "Switch":
                frame.switches.append(node)
            elif op == "Exit":
                frame.exits.append(node)
            elif op == "NextIteration":
                frame.next_iterations.append(node)
            elif op == "LoopCond":
                frame.loop_cond = node

        # classify enters: loop variables feed a Merge; invariants don't
        enters = frame_enters[fname]
        merge_inputs = {inp.split(":")[0]
                        for m in frame.merges for inp in m["inputs"]}
        loop_vars = []
        for e in enters:
            (loop_vars if e["name"] in merge_inputs
             else frame.invariants).append(e)
        frame.enters = loop_vars
        if frame.loop_cond is None:
            frame.error = frame.error or (
                f"while frame {frame.name!r} has no LoopCond")
            continue

        # order merges to match their enter (merge inputs: [enter, nextit])
        enter_names = {e["name"]: i for i, e in enumerate(frame.enters)}
        ordered = [None] * len(frame.enters)
        for m in frame.merges:
            for inp in m["inputs"]:
                b = inp.split(":")[0]
                if b in enter_names:
                    ordered[enter_names[b]] = m
        if any(o is None for o in ordered):
            frame.error = frame.error or (
                f"while frame {frame.name!r}: merge/enter wiring "
                "unrecognized")
            continue
        frame.merges = ordered
    return frames


# --------------------------------------------------- static trip counts
def _resolve_to_merge(name: str, by_name, frame) -> Optional[str]:
    """Follow Identity/Switch/Enter passthroughs to a Merge of `frame`;
    return the merge's name, or None."""
    merge_names = {m["name"] for m in frame.merges}
    nm = name.split(":")[0]
    for _ in range(16):
        if nm in merge_names:
            return nm
        node = by_name.get(nm)
        if node is None or node["op"] not in ("Identity", "Switch",
                                              "NextIteration"):
            return None
        nm = node["inputs"][0].split(":")[0]
    return None


def static_trip_count(frame, by_name, const_eval) -> Optional[int]:
    """Recover a compile-time trip count from the canonical counter
    pattern: ``LoopCond(Less(i, K))`` with ``i`` initialized from a
    const-foldable Enter and stepped by ``Add(i, step)`` with const
    step.  Returns the trip count, or None (→ dynamic while_loop).

    This is what lets bounded imported loops compile to ``lax.scan``
    and therefore train under reverse-mode AD."""
    import math
    if frame.error or frame.loop_cond is None:
        return None
    cmp_nm = frame.loop_cond["inputs"][0].split(":")[0]
    cmp_node = by_name.get(cmp_nm)
    if cmp_node is None or cmp_node["op"] not in (
            "Less", "LessEqual", "Greater", "GreaterEqual"):
        return None
    lhs, rhs = cmp_node["inputs"][0], cmp_node["inputs"][1]
    merge_nm = _resolve_to_merge(lhs, by_name, frame)
    limit = const_eval(rhs.split(":")[0])
    if merge_nm is None or limit is None:
        return None
    # counter init: the merge's Enter input's outer value
    merge_ix = {m["name"]: i for i, m in enumerate(frame.merges)}
    ix = merge_ix[merge_nm]
    enter = frame.enters[ix]
    init = const_eval(enter["inputs"][0].split(":")[0])
    if init is None:
        return None
    # counter update: NextIteration input must be Add(counter, const)
    merge = frame.merges[ix]
    ni_nm = None
    for inp in merge["inputs"]:
        b = inp.split(":")[0]
        if b != enter["name"]:
            ni_nm = b
    if ni_nm is None:
        return None
    add = by_name.get(by_name[ni_nm]["inputs"][0].split(":")[0])
    if add is None or add["op"] not in ("Add", "AddV2", "Sub"):
        return None
    if add["op"] == "Sub" and _resolve_to_merge(
            add["inputs"][0].split(":")[0], by_name, frame) != merge_nm:
        # Sub(K, i) is NOT i-minus-step: modeling it as one would give a
        # wrong scan length — leave it to the dynamic while_loop
        return None
    step = None
    for inp in add["inputs"]:
        b = inp.split(":")[0]
        if _resolve_to_merge(b, by_name, frame) == merge_nm:
            continue
        step = const_eval(b)
    if step is None:
        return None
    # exact integer arithmetic when the counter is integral (int64
    # counters above 2^53 would round under float ceil/floor and the
    # scan rewrite would silently run a wrong-length loop); float
    # counters fall back to ceil/floor
    integral = all(np.asarray(v).dtype.kind in "iu"
                   for v in (init, limit, step))
    if integral:
        init, limit, step = int(init), int(limit), int(step)
    else:
        init, limit, step = float(init), float(limit), float(step)
    if add["op"] == "Sub":
        step = -step
    if step == 0:
        return None
    op = cmp_node["op"]
    if op == "Less" and step > 0:
        n = (limit - init + step - 1) // step if integral \
            else math.ceil((limit - init) / step)
    elif op == "LessEqual" and step > 0:
        n = (limit - init) // step + 1 if integral \
            else math.floor((limit - init) / step) + 1
    elif op == "Greater" and step < 0:
        n = (init - limit - step - 1) // (-step) if integral \
            else math.ceil((limit - init) / step)
    elif op == "GreaterEqual" and step < 0:
        n = (init - limit) // (-step) + 1 if integral \
            else math.floor((limit - init) / step) + 1
    else:
        return None
    return max(int(n), 0)
