"""TF v1 while-loop frame reconstruction → ``lax.while_loop``.

Reference: ``DL/nn/tf/ControlOps.scala`` (Enter/Exit/NextIteration/
LoopCondition/Switch/Merge) executed by the dataflow ``Scheduler``
(``DL/nn/Scheduler.scala:104-145``) with dead-token propagation.

TPU redesign: a loop frame compiles to ONE ``lax.while_loop``.  The v1
wiring per loop variable is

    outer ──Enter(frame)──▶ Merge ◀── NextIteration ◀── body value
                              │
                              ├──▶ (cond subgraph) ──▶ LoopCond
                              ▼
                           Switch(data, LoopCond)
                        port0=false ▶ Exit ▶ downstream
                        port1=true  ▶ (body subgraph)

so: carry = Merge values; ``cond`` evaluates the LoopCond input with
merges bound to the carry; ``body`` evaluates each NextIteration input
the same way; Exit yields the final carry.  Loop-invariant Enters (no
Merge consumer) bind straight to their outer value.

Imported loops are forward-only under reverse-mode AD (lax.while_loop
with a dynamic trip count is not reverse-differentiable) — the same
contract as the reference, whose ``nn/ops`` control-flow execution is
forward-only.

:func:`extract_frames` groups a GraphDef's nodes by the Enter
``frame_name`` attr and returns the per-frame wiring; the executor in
``tf_format`` uses it to run frames as single fused steps.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


def _attr_frame(node) -> Optional[str]:
    f = node["attrs"].get("frame_name")
    if isinstance(f, bytes):
        return f.decode()
    return f


class LoopFrame:
    """Wiring of one while-loop frame."""

    __slots__ = ("name", "interior", "enters", "merges", "switches",
                 "exits", "next_iterations", "loop_cond", "invariants",
                 "error", "externals")

    def __init__(self, name: str):
        self.name = name
        self.externals: set = set()     # node names OUTSIDE the frame
        # that interior nodes read (the frame's data dependencies)
        self.error: Optional[str] = None  # set instead of raising so an
        # UNREACHABLE malformed frame never blocks loading; the executor
        # raises only if a pruned path actually needs this frame
        self.interior: set = set()      # node names inside the frame
        self.enters: List[dict] = []
        self.merges: List[dict] = []    # aligned with loop-var enters
        self.switches: List[dict] = []
        self.exits: List[dict] = []
        self.next_iterations: List[dict] = []
        self.loop_cond: Optional[dict] = None
        self.invariants: List[dict] = []  # Enters with no Merge consumer


def extract_frames(nodes: List[dict]) -> Dict[str, LoopFrame]:
    """Group control-flow nodes into frames and recover per-variable
    wiring.  Unsupported shapes (nested frames, missing LoopCond, odd
    merge wiring) set ``frame.error`` rather than raising, so they only
    fail if the requested outputs actually reach them."""
    by_name = {n["name"]: n for n in nodes}
    consumers: Dict[str, List[dict]] = {}
    for n in nodes:
        for inp in n["inputs"]:
            base = inp.split(":")[0].lstrip("^")
            consumers.setdefault(base, []).append(n)

    frames: Dict[str, LoopFrame] = {}
    for n in nodes:
        if n["op"] == "Enter":
            fname = _attr_frame(n) or "frame"
            frames.setdefault(fname, LoopFrame(fname)).enters.append(n)

    for frame in frames.values():
        # frame membership: flood from the Enters forward until Exit
        stack = [e["name"] for e in frame.enters]
        seen = set(stack)
        while stack:
            nm = stack.pop()
            node = by_name[nm]
            frame.interior.add(nm)
            if node["op"] == "Exit":
                continue
            for c in consumers.get(nm, []):
                if c["name"] not in seen:
                    seen.add(c["name"])
                    stack.append(c["name"])
        for nm in frame.interior:
            node = by_name[nm]
            for inp in node["inputs"]:
                base = inp.split(":")[0]
                if not base.startswith("^") and \
                        base not in frame.interior:
                    frame.externals.add(base)
            op = node["op"]
            if op == "Merge":
                frame.merges.append(node)
            elif op == "Switch":
                frame.switches.append(node)
            elif op == "Exit":
                frame.exits.append(node)
            elif op == "NextIteration":
                frame.next_iterations.append(node)
            elif op == "LoopCond":
                frame.loop_cond = node
            elif op == "Enter" and (_attr_frame(node) or "frame") \
                    != frame.name:
                frame.error = (f"nested while-loop frames ({frame.name} "
                               f"contains {_attr_frame(node)})")

        # classify enters: loop variables feed a Merge; invariants don't
        merge_inputs = {inp.split(":")[0]
                        for m in frame.merges for inp in m["inputs"]}
        loop_vars = []
        for e in frame.enters:
            (loop_vars if e["name"] in merge_inputs
             else frame.invariants).append(e)
        frame.enters = loop_vars
        if frame.loop_cond is None:
            frame.error = frame.error or (
                f"while frame {frame.name!r} has no LoopCond")
            continue

        # order merges to match their enter (merge inputs: [enter, nextit])
        enter_names = {e["name"]: i for i, e in enumerate(frame.enters)}
        ordered = [None] * len(frame.enters)
        for m in frame.merges:
            for inp in m["inputs"]:
                b = inp.split(":")[0]
                if b in enter_names:
                    ordered[enter_names[b]] = m
        if any(o is None for o in ordered):
            frame.error = frame.error or (
                f"while frame {frame.name!r}: merge/enter wiring "
                "unrecognized")
            continue
        frame.merges = ordered
    return frames
