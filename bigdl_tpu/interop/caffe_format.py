"""Caffe model importer (prototxt + caffemodel).

Reference: ``DL/utils/caffe/CaffeLoader.scala:57,85-104`` +
``LayerConverter.scala`` (new-format ``layer``) /
``V1LayerConverter.scala`` — prototxt defines the net topology, the
binary caffemodel carries per-layer weight blobs matched by layer name;
the loader builds a BigDL ``Graph`` and offers ``customizedConverters``
for unknown layer types.

TPU redesign: the generated ``caffe/Caffe.java`` protos (the bulk of the
reference's 187k generated LoC) are replaced by the generic wire codec
(``utils/protowire``) for the caffemodel and the text-proto parser (from
``interop/tf_format``) for the prototxt; converted layers are the native
functional modules assembled into ``nn.Graph``.

Caffe proto field numbers used (from caffe.proto):
  NetParameter: name=1, input=3, input_dim=4, input_shape=8, layer=100
  LayerParameter: name=1, type=2, bottom=3, top=4, blobs=7,
    convolution_param=106, inner_product_param=117, pooling_param=121,
    lrn_param=118, dropout_param=108, concat_param=104,
    eltwise_param=110, batch_norm_param=139, reshape_param=133,
    input_param=143
  BlobProto: shape=7 {dim=1}, data=5 (packed float), num/chan/h/w=1..4
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.nn.graph import Graph, Input, Node
from bigdl_tpu.nn.module import Module
from bigdl_tpu.interop.tf_format import _parse_textproto, _tokenize
from bigdl_tpu.utils import protowire as pw


# ---------------------------------------------------------------- decoding
def _blob_to_array(data: bytes) -> np.ndarray:
    m = pw.decode_message(data)
    vals: List[float] = []
    for v in m.get(5, []):
        vals.extend(pw.unpack_packed(v, "float")
                    if isinstance(v, bytes) else [pw.as_float(v)])
    arr = np.asarray(vals, np.float32)
    if 7 in m:  # BlobShape
        sm = pw.decode_message(m[7][0])
        dims = [pw.as_sint(d) for d in pw.ints(sm, 1)]
        return arr.reshape(dims)
    legacy = [pw.ints(m, f)[0] if f in m else 1 for f in (1, 2, 3, 4)]
    if np.prod(legacy) == arr.size:
        return arr.reshape(legacy)
    return arr


def _decode_caffemodel(data: bytes) -> Dict[str, List[np.ndarray]]:
    """caffemodel → {layer name: [blobs]} (weights then bias)."""
    net = pw.decode_message(data)
    blobs: Dict[str, List[np.ndarray]] = {}
    for lay in net.get(100, []):   # new format LayerParameter
        lm = pw.decode_message(lay)
        name = pw.as_str(lm[1][0])
        if 7 in lm:
            blobs[name] = [_blob_to_array(b) for b in lm[7]]
    for lay in net.get(2, []):     # V1LayerParameter fallback
        lm = pw.decode_message(lay)
        if 4 in lm and 6 in lm:
            blobs[pw.as_str(lm[4][0])] = [_blob_to_array(b)
                                          for b in lm[6]]
    return blobs


def _parse_prototxt(text: str) -> dict:
    root = _parse_textproto(_tokenize(text))

    def dec(v):
        return v.decode() if isinstance(v, bytes) else v

    layers = []
    for key in ("layer", "layers"):
        for l in root.get(key, []):
            p: dict = {k: v for k, v in l.items()}
            layers.append({
                "name": dec(p["name"][0]),
                "type": dec(p["type"][0]),
                "bottom": [dec(b) for b in p.get("bottom", [])],
                "top": [dec(t) for t in p.get("top", [])],
                "params": p,
            })
    return {
        "name": dec(root.get("name", [b""])[0]),
        "inputs": [dec(i) for i in root.get("input", [])],
        "input_dims": [int(d) for d in root.get("input_dim", [])],
        "layers": layers,
    }


def _pick(p: dict, key: str, default=None):
    v = p.get(key)
    if not v:
        return default
    x = v[0]
    return x.decode() if isinstance(x, bytes) else x


# --------------------------------------------------------------- converters
def _conv_module(name, cp, blobs):
    num_out = int(_pick(cp, "num_output"))
    kh = int(_pick(cp, "kernel_h", _pick(cp, "kernel_size", 1)))
    kw = int(_pick(cp, "kernel_w", _pick(cp, "kernel_size", 1)))
    sh = int(_pick(cp, "stride_h", _pick(cp, "stride", 1)))
    sw = int(_pick(cp, "stride_w", _pick(cp, "stride", 1)))
    ph = int(_pick(cp, "pad_h", _pick(cp, "pad", 0)))
    pw_ = int(_pick(cp, "pad_w", _pick(cp, "pad", 0)))
    group = int(_pick(cp, "group", 1))
    dil = int(_pick(cp, "dilation", 1))
    bias = bool(_pick(cp, "bias_term", True))
    w = blobs[0]
    if w.ndim < 4:
        # reference CaffePersister writes only num/channels legacy dims
        # (h/w omitted), leaving the blob effectively flat: recover the
        # OIHW shape from the layer hyper-parameters
        w = w.reshape(num_out, w.size // (num_out * kh * kw), kh, kw)
    n_in = w.shape[1] * group
    m = nn.SpatialConvolution(n_in, num_out, kw, kh, sw, sh, pw_, ph,
                              n_group=group, with_bias=bias,
                              dilation_w=dil, dilation_h=dil, name=name)
    params = {"weight": w.reshape(num_out, w.shape[1],
                                  *w.shape[2:]).astype(np.float32)}
    if bias and len(blobs) > 1:
        params["bias"] = blobs[1].reshape(-1)
    return m, params


def _ip_module(name, ip, blobs):
    num_out = int(_pick(ip, "num_output"))
    bias = bool(_pick(ip, "bias_term", True))
    w = blobs[0].reshape(num_out, -1)
    # Caffe InnerProduct flattens its input implicitly
    lin = nn.Linear(w.shape[1], num_out, with_bias=bias, name=name)
    params = {"weight": w}
    if bias and len(blobs) > 1:
        params["bias"] = blobs[1].reshape(-1)
    return nn.Sequential(nn.Flatten(), lin, name=name), {"1": params}


def _pool_module(name, pp):
    mode = _pick(pp, "pool", 0)
    mode = {"MAX": 0, "AVE": 1}.get(mode, mode)
    k = int(_pick(pp, "kernel_size", 2))
    kh = int(_pick(pp, "kernel_h", k))
    kw = int(_pick(pp, "kernel_w", k))
    s = int(_pick(pp, "stride", 1))
    sh = int(_pick(pp, "stride_h", s))
    sw = int(_pick(pp, "stride_w", s))
    p = int(_pick(pp, "pad", 0))
    ph = int(_pick(pp, "pad_h", p))
    pw_ = int(_pick(pp, "pad_w", p))
    cls = nn.SpatialMaxPooling if int(mode) == 0 else nn.SpatialAveragePooling
    # Caffe pooling uses ceil mode
    return cls(kw, kh, sw, sh, pw_, ph, ceil_mode=True, name=name)


def _convert_layer(layer: dict, blobs: List[np.ndarray],
                   custom: Dict[str, Callable]):
    t = layer["type"]
    name = layer["name"]
    p = layer["params"]
    if t in custom:
        return custom[t](layer, blobs), None
    if t == "Convolution":
        return _conv_module(name, p["convolution_param"][0], blobs)
    if t == "InnerProduct":
        return _ip_module(name, p["inner_product_param"][0], blobs)
    if t == "Pooling":
        return _pool_module(name, p["pooling_param"][0]), None
    if t == "ReLU":
        return nn.ReLU(name=name), None
    if t == "TanH":
        return nn.Tanh(name=name), None
    if t == "Sigmoid":
        return nn.Sigmoid(name=name), None
    if t == "Softmax":
        return nn.SoftMax(name=name), None
    if t == "Dropout":
        ratio = float(_pick(p.get("dropout_param", [{}])[0],
                            "dropout_ratio", 0.5))
        return nn.Dropout(ratio, name=name), None
    if t == "LRN":
        lp = p.get("lrn_param", [{}])[0]
        return nn.SpatialCrossMapLRN(
            size=int(_pick(lp, "local_size", 5)),
            alpha=float(_pick(lp, "alpha", 1.0)),
            beta=float(_pick(lp, "beta", 0.75)),
            k=float(_pick(lp, "k", 1.0)), name=name), None
    if t == "Concat":
        cp = p.get("concat_param", [{}])[0]
        return nn.JoinTable(int(_pick(cp, "axis", 1)), name=name), None
    if t == "Eltwise":
        ep = p.get("eltwise_param", [{}])[0]
        op = _pick(ep, "operation", "SUM")
        op = {0: "PROD", 1: "SUM", 2: "MAX"}.get(op, op)
        if op == "SUM":
            return nn.CAddTable(name=name), None
        if op == "PROD":
            return nn.CMulTable(name=name), None
        return nn.CMaxTable(name=name), None
    if t == "Flatten":
        return nn.Flatten(name=name), None
    if t == "BatchNorm":
        bp = p.get("batch_norm_param", [{}])[0]
        n = blobs[0].size if blobs else 0
        m = nn.SpatialBatchNormalization(
            n, eps=float(_pick(bp, "eps", 1e-5)), affine=False, name=name)
        st = None
        if blobs:
            scale = blobs[2].reshape(-1)[0] if len(blobs) > 2 else 1.0
            scale = 1.0 / scale if scale != 0 else 0.0
            st = {"running_mean": blobs[0].reshape(-1) * scale,
                  "running_var": blobs[1].reshape(-1) * scale}
        return m, ("state", st)
    if t == "Scale":
        # affine per-channel y = gamma*x + beta (caffe pairs this after
        # BatchNorm; reference LayerConverter.fromCaffeScale)
        if not blobs:
            raise NotImplementedError(
                f"Scale layer {name!r} without blobs: channel count "
                "unknown (weights-free prototxt import)")
        c = blobs[0].size
        m = nn.Scale((c, 1, 1), name=name)
        # no bias blob (bias_term=false, the caffe default) -> bias must
        # be ZERO, not the CAdd random init
        beta = (blobs[1].reshape(c, 1, 1) if len(blobs) > 1
                else np.zeros((c, 1, 1), np.float32))
        w = {"mul": {"weight": blobs[0].reshape(c, 1, 1)},
             "add": {"bias": beta}}
        return m, w
    if t in ("Input", "Data", "DummyData"):
        return None, "input"   # registers its tops as graph inputs
    if t in ("SoftmaxWithLoss", "Accuracy", "Silence"):
        return None, "skip"    # training/diagnostic heads: dropped
    raise NotImplementedError(
        f"Caffe layer type {t!r} ({name}); pass custom={{'{t}': fn}} "
        "(reference customizedConverters, CaffeLoader.scala:85)")


# ------------------------------------------------------------------ loader
def load_caffe_model(def_path: str, model_path: str,
                     custom: Optional[Dict[str, Callable]] = None
                     ) -> Module:
    """prototxt + caffemodel → module graph with weights materialized
    (reference ``Module.loadCaffeModel`` → ``CaffeLoader.scala:85-104``).

    In-place layers (bottom == top, Caffe's ReLU idiom) chain naturally;
    multi-input layers (Concat/Eltwise) become table ops on a Graph.
    """
    custom = custom or {}
    with open(def_path) as f:
        net = _parse_prototxt(f.read())
    with open(model_path, "rb") as f:
        blobs = _decode_caffemodel(f.read())

    nodes: Dict[str, Node] = {}
    inputs: List[Node] = []
    for inp in net["inputs"]:
        n = Input()
        nodes[inp] = n
        inputs.append(n)

    weight_map = {}
    state_map = {}
    last: Optional[Node] = None
    for layer in net["layers"]:
        mod, extra = _convert_layer(layer, blobs.get(layer["name"], []),
                                    custom)
        if mod is None:
            if extra == "input":
                for top in layer["top"]:
                    if top not in nodes:
                        n = Input()
                        nodes[top] = n
                        inputs.append(n)
            continue  # "skip": training/diagnostic head, dropped
        bots = [nodes[b] for b in layer["bottom"] if b in nodes]
        if not bots:
            if layer["bottom"]:
                raise ValueError(f"layer {layer['name']} has unknown "
                                 f"bottoms {layer['bottom']}")
            # bottomless compute layer (reference persister emits the
            # first layer with no bottom and no input decl): implicit
            # graph input feeds it
            n = Input()
            inputs.append(n)
            bots = [n]
        node = mod(bots if len(bots) > 1 else bots[0])
        for top in layer["top"]:
            nodes[top] = node
        last = node
        if isinstance(extra, dict):
            weight_map[id(mod)] = extra
        elif isinstance(extra, tuple) and extra[0] == "state":
            state_map[id(mod)] = extra[1]
        elif extra is not None:
            weight_map[id(mod)] = extra

    out_node = last
    graph = Graph(inputs, [out_node], name=net["name"] or "CaffeNet")
    graph.initialize()

    # install converted weights: params are keyed by node order
    import jax
    import jax.numpy as jnp
    params = jax.tree_util.tree_map(np.asarray, graph._params)
    gstate = jax.tree_util.tree_map(np.asarray, graph._state)
    for i, (n, key) in enumerate(zip(graph._order, graph._param_keys)):
        mod = n.module
        w = weight_map.get(id(mod))
        if w is not None:
            _merge(params[key], w)
        st = state_map.get(id(mod))
        if st is not None and key in gstate:
            _merge(gstate[key], st)
    graph._params = jax.tree_util.tree_map(jnp.asarray, params)
    graph._state = jax.tree_util.tree_map(jnp.asarray, gstate)
    graph._grads = jax.tree_util.tree_map(jnp.zeros_like, graph._params)
    return graph


def _merge(dst: dict, src: dict) -> None:
    for k, v in src.items():
        if isinstance(v, dict):
            _merge(dst.setdefault(k, {}), v)
        else:
            dst[k] = np.asarray(v, np.float32)
