"""BigDL protobuf checkpoint reader/writer.

Reference: ``DL/utils/serializer/ModuleSerializer.scala:66,118`` +
``ModuleLoader.scala`` (a model file is ONE serialized ``BigDLModule``
message; schema ``spark/dl/src/main/resources/serialization/bigdl.proto``).
The reference decodes with 187k LoC of generated Java; here the generic
wire codec in ``utils/protowire`` plus the field numbers from the schema
do the whole job.

Serialization conventions reproduced (from ``ModuleSerializable.scala``):

- ``moduleType`` (field 7) is the Scala FQCN
  (``com.intel.analytics.bigdl.nn.Linear``); attr keys (field 8 map) are
  the Scala constructor parameter names (reflective serialization,
  ``ModuleSerializable.scala:117-145``);
- ``hasParameters``/``parameters`` (fields 15/16) carry the tensors in
  ``module.parameters()._1`` order — weight then bias
  (``copyFromBigDL``, ``ModuleSerializable.scala:363``);
- tensors reference storages that are deduplicated by id
  (``BigDLTensor.storage``/``TensorStorage.id``); the first occurrence
  carries the data (``ModuleLoader.initTensorStorage``);
- some modules add extra attrs via custom serializers — BatchNorm's
  ``runningMean``/``runningVar`` (``BatchNormalization.scala`` companion),
  max-pooling's ``ceil_mode``, Reshape's ``size``/``batchMode``.

Import maps onto the TPU-native modules; export writes files the
reference's ``Module.loadModule`` could read back (same schema, same
conventions).
"""

from __future__ import annotations
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.nn.module import Module
from bigdl_tpu.utils import protowire as pw

_NN = "com.intel.analytics.bigdl.nn."

# DataType enum (bigdl.proto)
DT_INT32, DT_INT64, DT_FLOAT, DT_DOUBLE = 0, 1, 2, 3
DT_STRING, DT_BOOL = 4, 5
DT_TENSOR = 10
DT_ARRAY_VALUE = 15


# ===========================================================================
# wire-level decode of the bigdl.proto messages
# ===========================================================================
def _decode_storage(data: bytes) -> dict:
    m = pw.decode_message(data)
    out = {"id": pw.ints(m, 9)[0] if 9 in m else 0, "data": None}
    if 2 in m:   # float_data (packed or not)
        vals: List[float] = []
        for v in m[2]:
            vals.extend(pw.unpack_packed(v, "float") if isinstance(v, bytes)
                        else [pw.as_float(v)])
        out["data"] = np.asarray(vals, np.float32)
    elif 3 in m:
        vals = []
        for v in m[3]:
            vals.extend(pw.unpack_packed(v, "double") if isinstance(v, bytes)
                        else [pw.as_double(v)])
        out["data"] = np.asarray(vals, np.float64)
    elif 6 in m:
        out["data"] = np.asarray(pw.ints(m, 6), np.int32)
    elif 7 in m:
        out["data"] = np.asarray([pw.as_sint(x) for x in pw.ints(m, 7)],
                                 np.int64)
    return out


def _decode_tensor(data: bytes, storages: Dict[int, np.ndarray]
                   ) -> Optional[np.ndarray]:
    m = pw.decode_message(data)
    size = pw.ints(m, 2)
    offset = pw.ints(m, 4)[0] if 4 in m else 0
    n = int(np.prod(size)) if size else 1
    arr = None
    if 8 in m:
        st = _decode_storage(m[8][0])
        if st["data"] is not None and len(st["data"]):
            storages.setdefault(st["id"], st["data"])
        arr = storages.get(st["id"])
    if arr is None:
        return None
    flat = arr[offset - 1 if offset >= 1 else 0:]
    flat = flat[:n]
    return np.asarray(flat, np.float32).reshape(size) if size else \
        np.asarray(flat[:1], np.float32).reshape(())


def _decode_attr(data: bytes, storages) -> Tuple[int, Any]:
    m = pw.decode_message(data)
    dtype = pw.ints(m, 1)[0] if 1 in m else 0
    if 3 in m:
        return dtype, pw.as_sint(m[3][0])
    if 4 in m:
        return dtype, pw.as_sint(m[4][0])
    if 5 in m:
        return dtype, pw.as_float(m[5][0])
    if 6 in m:
        return dtype, pw.as_double(m[6][0])
    if 7 in m:
        return dtype, pw.as_str(m[7][0])
    if 8 in m:
        return dtype, bool(m[8][0])
    if 9 in m:
        return dtype, _dec_regularizer(m[9][0])
    if 10 in m:
        return dtype, _decode_tensor(m[10][0], storages)
    if 15 in m:  # ArrayValue
        am = pw.decode_message(m[15][0])
        adt = pw.ints(am, 2)[0] if 2 in am else 0
        if adt == DT_INT32:
            return dtype, [pw.as_sint(v) for v in pw.ints(am, 3)]
        if adt == DT_FLOAT:
            vals = []
            for v in am.get(5, []):
                vals.extend(pw.unpack_packed(v, "float")
                            if isinstance(v, bytes) else [pw.as_float(v)])
            return dtype, vals
        if adt == DT_TENSOR:
            return dtype, [_decode_tensor(v, storages)
                           for v in am.get(10, [])]
        if adt == DT_STRING:
            return dtype, [pw.as_str(v) for v in am.get(7, [])]
        return dtype, None
    if 16 in m:  # DataFormat enum: 0 NCHW, 1 NHWC
        return dtype, "NCHW" if pw.ints(m, 16)[0] == 0 else "NHWC"
    # oneof absent (hand-written/partial file; genuine writers always set
    # it): fall back to the dataType's zero value so downstream int()/
    # float() coercions get a diagnosable default rather than None
    zero = {DT_INT32: 0, DT_INT64: 0, DT_FLOAT: 0.0, DT_DOUBLE: 0.0,
            DT_STRING: "", DT_BOOL: False}
    return dtype, zero.get(dtype)


def decode_bigdl_module(data: bytes,
                        storages: Optional[Dict[int, np.ndarray]] = None
                        ) -> dict:
    """Decode one BigDLModule message into a plain dict tree."""
    if storages is None:
        storages = {}
    m = pw.decode_message(data)
    attrs: Dict[str, Any] = {}
    for entry in m.get(8, []):
        em = pw.decode_message(entry)
        key = pw.as_str(em[1][0])
        attrs[key] = _decode_attr(em[2][0], storages)[1]
    return {
        "name": pw.as_str(m[1][0]) if 1 in m else "",
        "module_type": pw.as_str(m[7][0]) if 7 in m else "",
        "sub_modules": [decode_bigdl_module(s, storages)
                        for s in m.get(2, [])],
        "attrs": attrs,
        "has_parameters": bool(pw.ints(m, 15)[0]) if 15 in m else False,
        "parameters": [_decode_tensor(t, storages) for t in m.get(16, [])],
        # deprecated pre-hasParameters layout (BigDLModule weight=3/bias=4);
        # decoded so the loader can refuse loudly instead of silently
        # leaving random init weights in place
        "legacy_weight": _decode_tensor(m[3][0], storages) if 3 in m else None,
        "legacy_bias": _decode_tensor(m[4][0], storages) if 4 in m else None,
        "pre_modules": [pw.as_str(v) for v in m.get(5, [])],
        "next_modules": [pw.as_str(v) for v in m.get(6, [])],
        # unique instance id (bigdl.proto field 12) — shared-module marker
        "id": pw.ints(m, 12)[0] if 12 in m else None,
    }


# ===========================================================================
# module construction from the decoded tree
# ===========================================================================
def _build_children(node) -> List[Module]:
    return [_build(s) for s in node["sub_modules"]]


def _build(node: dict) -> Module:
    t = node["module_type"].rsplit(".", 1)[-1]
    a = node["attrs"]
    name = node["name"] or None

    def ctor() -> Module:
        if t in ("StaticGraph", "Graph", "DynamicGraph"):
            # reference GraphSerializable (Graph.scala:563): subModules
            # with preModules edges; inputNames/outputNames attrs.  A
            # repeated submodule NAME = shared instance (weight tying).
            from bigdl_tpu.nn.graph import Graph as GGraph, Input as GInput
            in_names = list(a.get("inputNames", []))
            out_names = list(a.get("outputNames", []))
            # shared instances are tied by the proto `id` field; a
            # repeated NAME (legacy writers without ids) ties too
            built_by_id: Dict[int, Module] = {}
            built_by_name: Dict[str, Module] = {}
            occurrence: Dict[str, Any] = {}
            inputs_by_name: Dict[str, Any] = {}
            for sub in node["sub_modules"]:
                st = sub["module_type"].rsplit(".", 1)[-1]
                nm = sub["name"]
                if st == "Input":
                    ph = GInput()
                    occurrence[nm] = ph
                    inputs_by_name[nm] = ph
                    continue
                iid = sub.get("id")
                mod = (built_by_id.get(iid) if iid is not None
                       else built_by_name.get(nm))
                if mod is None:
                    mod = _build(sub)
                    built_by_name[nm] = mod
                    if iid is not None:
                        built_by_id[iid] = mod
                pres = list(sub["pre_modules"])
                if not pres:
                    if nm not in in_names:
                        raise ValueError(
                            f"graph node {nm!r} has no preModules and is "
                            "not an input")
                    ph = inputs_by_name.setdefault(nm, GInput())
                    pres_nodes = [ph]
                else:
                    pres_nodes = [occurrence[p] for p in pres]
                occurrence[nm] = mod(pres_nodes if len(pres_nodes) > 1
                                     else pres_nodes[0])
            inputs = [inputs_by_name[n] for n in in_names]
            outputs = [occurrence[n] for n in out_names]
            return GGraph(inputs, outputs, name=name)
        if t == "Sequential":
            m = nn.Sequential(name=name)
            for c in _build_children(node):
                m.add(c)
            return m
        if t == "Concat":
            m = nn.Concat(dim=int(a.get("dimension", 2)) - 1, name=name)
            for c in _build_children(node):
                m.add(c)
            return m
        if t == "ConcatTable":
            m = nn.ConcatTable(name=name)
            for c in _build_children(node):
                m.add(c)
            return m
        if t == "Linear":
            return nn.Linear(int(a["inputSize"]), int(a["outputSize"]),
                             with_bias=bool(a.get("withBias", True)),
                             name=name)
        if t == "SpatialConvolution":
            return nn.SpatialConvolution(
                int(a["nInputPlane"]), int(a["nOutputPlane"]),
                int(a["kernelW"]), int(a["kernelH"]),
                int(a.get("strideW", 1)), int(a.get("strideH", 1)),
                int(a.get("padW", 0)), int(a.get("padH", 0)),
                n_group=int(a.get("nGroup", 1)),
                with_bias=bool(a.get("withBias", True)),
                dilation_w=int(a.get("dilationW", 1)),
                dilation_h=int(a.get("dilationH", 1)),
                format=a.get("format", "NCHW"), name=name)
        if t == "SpatialMaxPooling":
            return nn.SpatialMaxPooling(
                int(a["kW"]), int(a["kH"]), int(a.get("dW", 1)),
                int(a.get("dH", 1)), int(a.get("padW", 0)),
                int(a.get("padH", 0)),
                ceil_mode=bool(a.get("ceil_mode", False)),
                format=a.get("format", "NCHW"), name=name)
        if t == "SpatialAveragePooling":
            return nn.SpatialAveragePooling(
                int(a["kW"]), int(a["kH"]), int(a.get("dW", 1)),
                int(a.get("dH", 1)), int(a.get("padW", 0)),
                int(a.get("padH", 0)),
                ceil_mode=bool(a.get("ceil_mode", False)),
                count_include_pad=bool(a.get("countIncludePad", True)),
                format=a.get("format", "NCHW"), name=name)
        if t in ("SpatialBatchNormalization", "BatchNormalization"):
            cls = (nn.SpatialBatchNormalization
                   if t == "SpatialBatchNormalization"
                   else nn.BatchNormalization)
            return cls(int(a["nOutput"]), eps=float(a.get("eps", 1e-5)),
                       momentum=float(a.get("momentum", 0.1)),
                       affine=bool(a.get("affine", True)), name=name)
        if t == "SpatialCrossMapLRN":
            return nn.SpatialCrossMapLRN(
                size=int(a.get("size", 5)), alpha=float(a.get("alpha", 1.0)),
                beta=float(a.get("beta", 0.75)), k=float(a.get("k", 1.0)),
                format=a.get("format", "NCHW"), name=name)
        if t == "Dropout":
            return nn.Dropout(float(a.get("initP", 0.5)), name=name)
        if t == "Scale":
            return nn.Scale(tuple(int(v) for v in a["size"]), name=name)
        if t == "Reshape":
            return nn.Reshape(tuple(int(v) for v in a["size"]), name=name)
        if t == "View":
            sizes = a.get("sizes", a.get("size"))
            return nn.View(tuple(int(v) for v in sizes), name=name)
        if t == "LookupTable":
            return nn.LookupTable(int(a["nIndex"]), int(a["nOutput"]),
                                  name=name)
        if t == "JoinTable":
            return nn.JoinTable(int(a.get("dimension", 2)) - 1, name=name)
        if t == "CAddTable":
            return nn.CAddTable(name=name)
        if t == "TemporalConvolution":
            return nn.TemporalConvolution(
                int(a["inputFrameSize"]), int(a["outputFrameSize"]),
                int(a["kernelW"]), int(a.get("strideW", 1)), name=name)
        if t in ("QuantizedLinear", "QuantizedSpatialConvolution"):
            # quantized twins reconstruct straight from the node's
            # tensors (their init() is empty, so the generic
            # weights-to-params pass has nothing to do for them)
            from bigdl_tpu.nn.quantized import (
                QuantizedLinear, QuantizedSpatialConvolution)
            ps = [p for p in node["parameters"] if p is not None]
            if len(ps) < 2:
                raise ValueError(
                    f"quantized module {node['name']!r}: expected "
                    f"(weight_q, weight_scale[, bias]) tensors, got "
                    f"{len(ps)}")
            qmode = (a.get("quantMode") or ["weight_only"])[0]
            wq = np.asarray(ps[0], np.float32).astype(np.int8)
            ws = np.asarray(ps[1], np.float32)
            b = np.asarray(ps[2], np.float32) if len(ps) > 2 else None
            if t == "QuantizedLinear":
                return QuantizedLinear(wq, ws, b, name=name, mode=qmode)
            conv = nn.SpatialConvolution(
                int(a["nInputPlane"]), int(a["nOutputPlane"]),
                int(a["kernelW"]), int(a["kernelH"]),
                int(a.get("strideW", 1)), int(a.get("strideH", 1)),
                int(a.get("padW", 0)), int(a.get("padH", 0)),
                n_group=int(a.get("nGroup", 1)),
                with_bias=bool(a.get("withBias", True)),
                dilation_w=int(a.get("dilationW", 1)),
                dilation_h=int(a.get("dilationH", 1)),
                format=a.get("format", "NCHW"))
            return QuantizedSpatialConvolution(conv, wq, ws, b,
                                               name=name, mode=qmode)
        simple = {"ReLU": nn.ReLU, "Tanh": nn.Tanh, "Sigmoid": nn.Sigmoid,
                  "LogSoftMax": nn.LogSoftMax, "SoftMax": nn.SoftMax,
                  "Identity": nn.Identity, "Flatten": nn.Flatten,
                  "ELU": nn.ELU, "ReLU6": nn.ReLU6,
                  "SoftPlus": nn.SoftPlus, "Abs": nn.Abs,
                  "HardTanh": nn.HardTanh, "Square": nn.Square,
                  "Sqrt": nn.Sqrt, "Exp": nn.Exp}
        if t in simple:
            return simple[t](name=name)
        raise NotImplementedError(
            f"BigDL module type {node['module_type']!r} not mapped yet")

    m = ctor()
    # re-attach per-layer penalties (reference wRegularizer/bRegularizer)
    if a.get("wRegularizer") is not None:
        m.w_regularizer = a["wRegularizer"]
    if a.get("bRegularizer") is not None:
        m.b_regularizer = a["bRegularizer"]
    m._bigdl_node = node  # stash for weight loading
    return m


def _bigdl_weights_to_params(module: Module, node: dict, params, state):
    """Copy the node's serialized parameters into our (params, state),
    recursing through containers.  Handles the layout differences:
    conv weights are stored (nGroup, out/g, in/g, kH, kW) by the
    reference (``VariableFormat.GP_OUT_IN_KW_KH``) vs our OIHW."""
    t = node["module_type"].rsplit(".", 1)[-1]
    from bigdl_tpu.nn.graph import Graph as _GGraph
    if isinstance(module, _GGraph):
        # graph params are keyed by first-occurrence order index; each
        # built module stashed its decoded node (weights live on the
        # first occurrence of a shared name)
        for i, gnode in enumerate(module._order):
            sub = getattr(gnode.module, "_bigdl_node", None)
            if sub is not None:
                key = module._param_keys[i]
                _bigdl_weights_to_params(gnode.module, sub,
                                         params.get(key, {}),
                                         state.get(key, {}))
        return
    if t in ("Sequential", "Concat", "ConcatTable"):
        for i, sub in enumerate(node["sub_modules"]):
            _bigdl_weights_to_params(module.modules[i], sub,
                                     params.get(str(i), {}),
                                     state.get(str(i), {}))
        return
    ps = [p for p in node["parameters"] if p is not None]
    if not ps:
        lw, lb = node.get("legacy_weight"), node.get("legacy_bias")
        if lw is not None:
            # map the deprecated layout (weight=3/bias=4) through the same
            # per-type paths instead of dropping it on the floor
            ps = [lw] + ([lb] if lb is not None else [])
        elif lb is not None:
            raise ValueError(
                f"module {node['name']!r} ({t}): legacy bias (field 4) "
                "present but its weight (field 3) failed to decode — "
                "refusing to load a partially-decoded legacy checkpoint")
        else:
            return
    if t == "SpatialConvolution":
        w = ps[0]
        if w.ndim == 5:  # (g, out/g, in/g, kh, kw) -> (out, in/g, kh, kw)
            w = w.reshape(-1, *w.shape[2:])
        params["weight"] = w
        if len(ps) > 1 and "bias" in params:
            params["bias"] = ps[1]
    elif t == "Scale":
        params["mul"]["weight"] = ps[0].reshape(
            params["mul"]["weight"].shape)
        if len(ps) > 1:
            params["add"]["bias"] = ps[1].reshape(
                params["add"]["bias"].shape)
    elif t in ("Linear", "TemporalConvolution", "LookupTable"):
        params["weight"] = ps[0]
        if len(ps) > 1 and "bias" in params:
            params["bias"] = ps[1]
    elif t in ("SpatialBatchNormalization", "BatchNormalization"):
        if "weight" in params and len(ps) >= 1:
            params["weight"] = ps[0]
        if "bias" in params and len(ps) >= 2:
            params["bias"] = ps[1]
        rm = node["attrs"].get("runningMean")
        rv = node["attrs"].get("runningVar")
        if rm is not None:
            state["running_mean"] = rm
        if rv is not None:
            state["running_var"] = rv
    else:
        # generic positional copy over the param dict's sorted keys
        for key, val in zip(sorted(params.keys()), ps):
            params[key] = val


def load_bigdl_module(path: str) -> Module:
    """Load a reference-format BigDL model file (``Module.loadModule``
    analog).  Returns the module with weights materialized on the object
    (eager slots), ready for ``forward``/``Predictor``/``Optimizer``."""
    with open(path, "rb") as f:
        data = f.read()
    node = decode_bigdl_module(data)
    module = _build(node)
    import jax
    params, state = module.init(jax.random.PRNGKey(0))
    params = jax.tree_util.tree_map(np.asarray, params)
    state = jax.tree_util.tree_map(np.asarray, state)
    _bigdl_weights_to_params(module, node, params, state)
    import jax.numpy as jnp
    module._params = jax.tree_util.tree_map(jnp.asarray, params)
    module._state = jax.tree_util.tree_map(jnp.asarray, state)
    module._grads = jax.tree_util.tree_map(jnp.zeros_like, module._params)
    return module


# ===========================================================================
# export (writer) — files the reference's Module.loadModule can read
# ===========================================================================
def _enc_storage(arr: np.ndarray, sid: int) -> bytes:
    flat = np.asarray(arr, np.float32).reshape(-1)
    return (pw.enc_varint(1, DT_FLOAT)
            + pw.enc_packed_floats(2, flat.tolist())
            + pw.enc_varint(9, sid))


def _enc_tensor(arr: np.ndarray, sid: int) -> bytes:
    arr = np.asarray(arr)
    size = arr.shape
    stride = [int(np.prod(size[i + 1:])) for i in range(len(size))]
    body = pw.enc_varint(1, DT_FLOAT)
    body += pw.enc_packed_ints(2, list(size))
    body += pw.enc_packed_ints(3, stride)
    body += pw.enc_varint(4, 1)  # 1-based offset like the reference
    body += pw.enc_varint(5, len(size))
    body += pw.enc_varint(6, int(arr.size))
    body += pw.enc_bytes(8, _enc_storage(arr, sid))
    body += pw.enc_varint(9, sid)
    return body


def _enc_attr_int(v: int) -> bytes:
    return pw.enc_varint(1, DT_INT32) + pw.enc_varint(3, int(v))


def _enc_attr_double(v: float) -> bytes:
    return pw.enc_varint(1, DT_DOUBLE) + pw.enc_double(6, float(v))


def _enc_attr_bool(v: bool) -> bytes:
    return pw.enc_varint(1, DT_BOOL) + pw.enc_varint(8, 1 if v else 0)


def _enc_attr_int_array(vs) -> bytes:
    av = (pw.enc_varint(1, len(vs)) + pw.enc_varint(2, DT_INT32)
          + pw.enc_packed_ints(3, [int(v) for v in vs]))
    return pw.enc_varint(1, DT_ARRAY_VALUE) + pw.enc_bytes(15, av)


def _enc_attr_format(fmt: str) -> bytes:
    # DataType DATA_FORMAT=16; oneof field 16 = InputDataFormat enum
    return pw.enc_varint(1, 16) + pw.enc_varint(16,
                                                0 if fmt == "NCHW" else 1)


def _enc_attr_tensor(arr, sid) -> bytes:
    return pw.enc_varint(1, DT_TENSOR) + pw.enc_bytes(10, _enc_tensor(arr,
                                                                      sid))


def _enc_attr_str_array(vs) -> bytes:
    av = (pw.enc_varint(1, len(vs)) + pw.enc_varint(2, DT_STRING)
          + b"".join(pw.enc_str(7, str(v)) for v in vs))
    return pw.enc_varint(1, DT_ARRAY_VALUE) + pw.enc_bytes(15, av)


def _enc_attr_regularizer(reg) -> bytes:
    """Regularizer message (bigdl.proto): regularizerType=1 (0=L1L2,
    1=L1, 2=L2), regularData=2 repeated double; AttrValue dataType
    REGULARIZER=9, oneof field 9."""
    l1 = float(getattr(reg, "l1", 0.0))
    l2 = float(getattr(reg, "l2", 0.0))
    if l1 and not l2:
        rt, data = 1, [l1]
    elif l2 and not l1:
        rt, data = 2, [l2]
    else:
        rt, data = 0, [l1, l2]
    msg = pw.enc_varint(1, rt) + b"".join(pw.enc_double(2, d)
                                          for d in data)
    return pw.enc_varint(1, 9) + pw.enc_bytes(9, msg)


def _dec_regularizer(msg_bytes: bytes):
    from bigdl_tpu.nn.regularizers import L1L2Regularizer
    m = pw.decode_message(msg_bytes)
    rt = pw.ints(m, 1)[0] if 1 in m else 0
    data = [pw.as_double(v) for v in m.get(2, [])]
    if rt == 1:
        return L1L2Regularizer(l1=data[0] if data else 0.0)
    if rt == 2:
        return L1L2Regularizer(l2=data[0] if data else 0.0)
    return L1L2Regularizer(l1=data[0] if data else 0.0,
                           l2=data[1] if len(data) > 1 else 0.0)


class _Exporter:
    def __init__(self):
        self.next_id = 1

    def sid(self) -> int:
        i = self.next_id
        self.next_id += 1
        return i

    def module_attrs(self, m: Module) -> Dict[str, bytes]:
        t = type(m).__name__
        out: Dict[str, bytes] = {}
        # per-layer penalties (reference serializes wRegularizer/
        # bRegularizer on every layer that carries them)
        if getattr(m, "w_regularizer", None) is not None:
            out["wRegularizer"] = _enc_attr_regularizer(m.w_regularizer)
        if getattr(m, "b_regularizer", None) is not None:
            out["bRegularizer"] = _enc_attr_regularizer(m.b_regularizer)
        if t == "Linear":
            return {**out,
                    "inputSize": _enc_attr_int(m.input_size),
                    "outputSize": _enc_attr_int(m.output_size),
                    "withBias": _enc_attr_bool(m.with_bias)}
        if t == "SpatialConvolution":
            return {**out,
                    "nInputPlane": _enc_attr_int(m.n_input_plane),
                    "nOutputPlane": _enc_attr_int(m.n_output_plane),
                    "kernelW": _enc_attr_int(m.kernel[1]),
                    "kernelH": _enc_attr_int(m.kernel[0]),
                    "strideW": _enc_attr_int(m.stride[1]),
                    "strideH": _enc_attr_int(m.stride[0]),
                    "padW": _enc_attr_int(m.pad[1]),
                    "padH": _enc_attr_int(m.pad[0]),
                    "nGroup": _enc_attr_int(m.n_group),
                    "withBias": _enc_attr_bool(m.with_bias),
                    "format": _enc_attr_format(m.format),
                    "dilationW": _enc_attr_int(m.dilation[1]),
                    "dilationH": _enc_attr_int(m.dilation[0])}
        if t == "SpatialMaxPooling":
            return {"kW": _enc_attr_int(m.kernel[1]),
                    "kH": _enc_attr_int(m.kernel[0]),
                    "dW": _enc_attr_int(m.stride[1]),
                    "dH": _enc_attr_int(m.stride[0]),
                    "padW": _enc_attr_int(m.pad[1]),
                    "padH": _enc_attr_int(m.pad[0]),
                    "ceil_mode": _enc_attr_bool(m.ceil_mode),
                    "format": _enc_attr_format(m.format)}
        if t == "SpatialAveragePooling":
            return {"kW": _enc_attr_int(m.kernel[1]),
                    "kH": _enc_attr_int(m.kernel[0]),
                    "dW": _enc_attr_int(m.stride[1]),
                    "dH": _enc_attr_int(m.stride[0]),
                    "padW": _enc_attr_int(m.pad[1]),
                    "padH": _enc_attr_int(m.pad[0]),
                    "ceil_mode": _enc_attr_bool(m.ceil_mode),
                    "countIncludePad":
                        _enc_attr_bool(m.count_include_pad),
                    "format": _enc_attr_format(m.format)}
        if t in ("SpatialBatchNormalization", "BatchNormalization"):
            return {"nOutput": _enc_attr_int(m.n_output),
                    "eps": _enc_attr_double(m.eps),
                    "momentum": _enc_attr_double(m.momentum),
                    "affine": _enc_attr_bool(m.affine)}
        if t == "SpatialCrossMapLRN":
            return {"size": _enc_attr_int(m.size),
                    "alpha": _enc_attr_double(m.alpha),
                    "beta": _enc_attr_double(m.beta),
                    "k": _enc_attr_double(m.k),
                    "format": _enc_attr_format(m.format)}
        if t == "Dropout":
            return {"initP": _enc_attr_double(m.p)}
        if t == "Scale":
            return {"size": _enc_attr_int_array(m.cmul.size)}
        if t in ("Reshape", "View"):  # View subclasses Reshape
            return {"size": _enc_attr_int_array(m.size),
                    "batchMode": _enc_attr_int(0)}
        if t == "LookupTable":
            return {"nIndex": _enc_attr_int(m.n_index),
                    "nOutput": _enc_attr_int(m.n_output)}
        if t == "Concat":
            return {"dimension": _enc_attr_int(m.dim + 1)}
        if t == "JoinTable":
            return {"dimension": _enc_attr_int(m.dimension + 1)}
        if t == "TemporalConvolution":
            return {**out,
                    "inputFrameSize": _enc_attr_int(m.input_frame_size),
                    "outputFrameSize": _enc_attr_int(m.output_frame_size),
                    "kernelW": _enc_attr_int(m.kernel_w),
                    "strideW": _enc_attr_int(m.stride_w)}
        # int8 quantized twins (reference quantized/Linear.scala etc.):
        # structural attrs mirror the float layer, plus the activation
        # mode so a loaded model keeps its weight_only/dynamic choice
        if t == "QuantizedLinear":
            o, i = m.weight_q.shape
            return {**out,
                    "inputSize": _enc_attr_int(i),
                    "outputSize": _enc_attr_int(o),
                    "withBias": _enc_attr_bool(m.bias is not None),
                    "quantMode": _enc_attr_str_array([m.mode])}
        if t == "QuantizedSpatialConvolution":
            c = m.conv
            return {**out,
                    "nInputPlane": _enc_attr_int(c.n_input_plane),
                    "nOutputPlane": _enc_attr_int(c.n_output_plane),
                    "kernelW": _enc_attr_int(c.kernel[1]),
                    "kernelH": _enc_attr_int(c.kernel[0]),
                    "strideW": _enc_attr_int(c.stride[1]),
                    "strideH": _enc_attr_int(c.stride[0]),
                    "padW": _enc_attr_int(c.pad[1]),
                    "padH": _enc_attr_int(c.pad[0]),
                    "nGroup": _enc_attr_int(c.n_group),
                    "withBias": _enc_attr_bool(m.bias is not None),
                    "format": _enc_attr_format(c.format),
                    "dilationW": _enc_attr_int(c.dilation[1]),
                    "dilationH": _enc_attr_int(c.dilation[0]),
                    "quantMode": _enc_attr_str_array([m.mode])}
        return out

    def encode(self, m: Module, params, state, pre=(), nxt=(),
               name: Optional[str] = None, with_params: bool = True) -> bytes:
        from bigdl_tpu.nn.graph import Graph as _Graph
        from bigdl_tpu.nn.module import Remat as _Remat
        if isinstance(m, _Remat):
            # pure execution hint (recompute-in-backward): serialize the
            # wrapped module — params/state trees are identical
            return self.encode(m.inner, params, state, pre, nxt,
                               name=name or m.inner.name,
                               with_params=with_params)
        if isinstance(m, _Graph):
            return self.encode_graph(m, params, state, pre, nxt)
        t = type(m).__name__
        body = pw.enc_str(1, name or m.name or t)
        for p in pre:
            body += pw.enc_str(5, p)
        for nx in nxt:
            body += pw.enc_str(6, nx)
        body += pw.enc_str(7, _NN + t)
        body += pw.enc_str(9, "0.2.0")
        if not with_params:
            # shared-instance later occurrence: structure only, weights
            # ride the first occurrence (reference dedups via tensor ids)
            params, state = {}, {}

        if t in ("Sequential", "Concat", "ConcatTable"):
            for i, child in enumerate(m.modules):
                body += pw.enc_bytes(2, self.encode(
                    child, params.get(str(i), {}), state.get(str(i), {})))
        for key, attr in self.module_attrs(m).items():
            entry = pw.enc_str(1, key) + pw.enc_bytes(2, attr)
            body += pw.enc_bytes(8, entry)

        tensors = self.module_tensors(m, params)
        if tensors:
            body += pw.enc_varint(15, 1)  # hasParameters
            for arr in tensors:
                body += pw.enc_bytes(16, _enc_tensor(arr, self.sid()))
        if t in ("SpatialBatchNormalization", "BatchNormalization"):
            for key, skey in (("runningMean", "running_mean"),
                              ("runningVar", "running_var")):
                if skey in state:
                    entry = (pw.enc_str(1, key)
                             + pw.enc_bytes(2, _enc_attr_tensor(
                                 np.asarray(state[skey]), self.sid())))
                    body += pw.enc_bytes(8, entry)
        return body

    def encode_graph(self, g, params, state, pre=(), nxt=()) -> bytes:
        """Serialize :class:`nn.Graph` as the reference ``StaticGraph``
        scheme (``Graph.scala:563`` GraphSerializable): subModules carry
        ``preModules``/``nextModules`` edges, attrs carry
        ``inputNames``/``outputNames``.  The reference's redundant
        per-node ``<name>_edges`` NameAttrList map is not written —
        ``preModules`` order carries the same information and the loader
        here reads that.  Shared module instances: every graph
        OCCURRENCE gets its own (unique) submodule name so edges stay
        unambiguous; occurrences of one instance share the ``id`` field
        (bigdl.proto field 12, 'used for shared modules') and only the
        first carries the weights."""
        body = pw.enc_str(1, g.name or "Graph")
        for p in pre:
            body += pw.enc_str(5, p)
        for nx in nxt:
            body += pw.enc_str(6, nx)
        body += pw.enc_str(7, _NN + "StaticGraph")
        body += pw.enc_str(9, "0.2.0")

        # per-OCCURRENCE unique name; per-INSTANCE shared id
        node_names: Dict[int, str] = {}      # id(node) -> name
        inst_ids: Dict[int, int] = {}        # id(module) -> instance id
        used: Dict[str, int] = {}
        for node in g._order:
            mod = node.module
            base = mod.name or type(mod).__name__
            n = used.get(base, 0)
            used[base] = n + 1
            node_names[id(node)] = base if n == 0 else f"{base}@{n}"
            inst_ids.setdefault(id(mod), len(inst_ids) + 1)
        in_names = []
        for i, inp in enumerate(g.input_nodes):
            nm = f"graph_input_{i}"
            node_names[id(inp)] = nm
            in_names.append(nm)

        def node_name(n):
            return node_names[id(n)]

        # consumers per node (nextModules)
        consumers: Dict[int, List[str]] = {}
        for node in g._order:
            for p in node.inputs:
                consumers.setdefault(id(p), []).append(node_name(node))

        # Input placeholder submodules
        for i, inp in enumerate(g.input_nodes):
            sub = (pw.enc_str(1, in_names[i])
                   + b"".join(pw.enc_str(6, c)
                              for c in consumers.get(id(inp), []))
                   + pw.enc_str(7, _NN + "Input")
                   + pw.enc_str(9, "0.2.0"))
            body += pw.enc_bytes(2, sub)

        emitted: set = set()
        for node, key in zip(g._order, g._param_keys):
            mod = node.module
            first = id(mod) not in emitted
            emitted.add(id(mod))
            sub = self.encode(
                mod, params.get(key, {}), state.get(key, {}),
                pre=[node_name(p) for p in node.inputs],
                nxt=consumers.get(id(node), []),
                name=node_name(node), with_params=first)
            sub += pw.enc_varint(12, inst_ids[id(mod)])
            body += pw.enc_bytes(2, sub)

        for akey, aval in (("inputNames", in_names),
                           ("outputNames",
                            [node_name(n) for n in g.output_nodes])):
            entry = pw.enc_str(1, akey) + pw.enc_bytes(
                2, _enc_attr_str_array(aval))
            body += pw.enc_bytes(8, entry)
        return body

    @staticmethod
    def module_tensors(m: Module, params) -> List[np.ndarray]:
        t = type(m).__name__
        if t in ("QuantizedLinear", "QuantizedSpatialConvolution"):
            # quantized leaves carry buffers on the object (init() is
            # empty).  int8 panel values are small ints (-127..127),
            # exactly representable in the f32 tensor wire format —
            # the round trip is lossless
            out = [np.asarray(m.weight_q, np.float32),
                   np.asarray(m.weight_scale, np.float32)]
            if m.bias is not None:
                out.append(np.asarray(m.bias, np.float32))
            return out
        if not params or t in ("Sequential", "Concat", "ConcatTable"):
            return []
        if t == "SpatialConvolution":
            w = np.asarray(params["weight"])
            g = m.n_group
            out = [w.reshape(g, w.shape[0] // g, *w.shape[1:])]
            if "bias" in params:
                out.append(np.asarray(params["bias"]))
            return out
        if t == "Scale":
            # params nest under the CMul/CAdd children; reference Scale
            # parameters() order is (weight, bias)
            return [np.asarray(params["mul"]["weight"]),
                    np.asarray(params["add"]["bias"])]
        out = []
        if "weight" in params:
            out.append(np.asarray(params["weight"]))
        if "bias" in params:
            out.append(np.asarray(params["bias"]))
        if not out:  # fallback: sorted order, mirrors the generic reader
            out = [np.asarray(params[k]) for k in sorted(params.keys())
                   if not isinstance(params[k], dict)]
        return out


def save_bigdl_module(module: Module, path: str) -> None:
    """Write the module (+ its eager params/state) as a reference-format
    BigDL model file (``Module.saveModule`` analog)."""
    module._ensure_init()
    import jax
    params = jax.tree_util.tree_map(np.asarray, module._params)
    state = jax.tree_util.tree_map(np.asarray, module._state)
    data = _Exporter().encode(module, params, state)
    with open(path, "wb") as f:
        f.write(data)
