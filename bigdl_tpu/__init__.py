"""bigdl_tpu — a TPU-native deep-learning framework with BigDL's capabilities.

A from-scratch re-design of the BigDL (Intel Analytics, v0.x) training stack
for TPU hardware:

- the Torch-style ``Tensor``/MKL layer becomes jax.numpy + XLA fusion,
- hand-written per-layer backward passes become ``jax.grad`` over pure
  module functions,
- the Spark BlockManager parameter AllReduce becomes XLA collectives
  (``psum`` / ``reduce_scatter`` / ``all_gather``) over ICI inside a
  ``shard_map``-compiled train step,
- the Spark driver/executor topology becomes JAX multi-host SPMD over a
  ``jax.sharding.Mesh``.

Reference layer map: see SURVEY.md (reference at /root/reference,
``DL/`` = spark/dl/src/main/scala/com/intel/analytics/bigdl/).
"""

__version__ = "0.1.0"

from bigdl_tpu.engine import Engine
from bigdl_tpu import nn
from bigdl_tpu import optim
from bigdl_tpu import dataset
from bigdl_tpu import parallel
from bigdl_tpu import models
from bigdl_tpu import checkpoint
from bigdl_tpu import serving
from bigdl_tpu import telemetry
from bigdl_tpu import utils
