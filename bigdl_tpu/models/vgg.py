"""VGG (reference ``DL/models/vgg/VggForCifar10.scala`` — the CIFAR-10
VGG-16 with BatchNorm, plus the ImageNet VGG-16/19 of
``DL/models/utils/DistriOptimizerPerf`` configs)."""

from __future__ import annotations

from bigdl_tpu import nn


def _conv_bn_relu(model, in_c, out_c):
    (model
     .add(nn.SpatialConvolution(in_c, out_c, 3, 3, 1, 1, 1, 1))
     .add(nn.SpatialBatchNormalization(out_c, eps=1e-3))
     .add(nn.ReLU()))
    return out_c


def vgg_for_cifar10(class_num: int = 10) -> nn.Sequential:
    """(reference ``VggForCifar10.scala``: conv stacks 64-128-256-512-512,
    classifier 512→512→classNum with dropout)."""
    model = nn.Sequential(name="VggForCifar10")
    cfg = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
           512, 512, 512, "M", 512, 512, 512, "M"]
    in_c = 3
    for v in cfg:
        if v == "M":
            model.add(nn.SpatialMaxPooling(2, 2, 2, 2))
        else:
            in_c = _conv_bn_relu(model, in_c, v)
    (model
     .add(nn.Reshape((512,)))
     .add(nn.Dropout(0.5))
     .add(nn.Linear(512, 512))
     .add(nn.BatchNormalization(512))
     .add(nn.ReLU())
     .add(nn.Dropout(0.5))
     .add(nn.Linear(512, class_num))
     .add(nn.LogSoftMax()))
    return model


def vgg16(class_num: int = 1000) -> nn.Sequential:
    """ImageNet VGG-16 (throughput-harness model of
    ``DistriOptimizerPerf``)."""
    model = nn.Sequential(name="Vgg16")
    cfg = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
           512, 512, 512, "M", 512, 512, 512, "M"]
    in_c = 3
    for v in cfg:
        if v == "M":
            model.add(nn.SpatialMaxPooling(2, 2, 2, 2))
        else:
            model.add(nn.SpatialConvolution(in_c, v, 3, 3, 1, 1, 1, 1))
            model.add(nn.ReLU())
            in_c = v
    (model
     .add(nn.Reshape((512 * 7 * 7,)))
     .add(nn.Linear(512 * 7 * 7, 4096)).add(nn.ReLU()).add(nn.Dropout(0.5))
     .add(nn.Linear(4096, 4096)).add(nn.ReLU()).add(nn.Dropout(0.5))
     .add(nn.Linear(4096, class_num))
     .add(nn.LogSoftMax()))
    return model
