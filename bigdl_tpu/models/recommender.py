"""Recommender models: NCF and Wide&Deep.

Reference: the Wide&Deep / NCF workloads named in BASELINE.json ("Sparse
embedding allreduce"); BigDL ships these via its Zoo examples on
``SparseLinear``/``LookupTableSparse`` (SURVEY §2.1 sparse backend:
"recommender workloads").

Inputs are pytrees (BigDL ``Table``):
- NCF: (user_ids (N,), item_ids (N,))
- Wide&Deep: ((wide_ids, wide_weights), deep_categorical_ids, dense)
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.nn.module import Module
from bigdl_tpu.nn.sparse import LookupTableSparse, SparseLinear


class NeuralCF(Module):
    """Neural Collaborative Filtering (He et al.): GMF branch ⊙ of user/item
    embeddings + MLP branch on concatenated embeddings, fused head.
    Output: sigmoid score (N, 1)."""

    def __init__(self, user_count: int, item_count: int,
                 embed_dim: int = 16, mlp_dims: Sequence[int] = (64, 32, 16),
                 name: Optional[str] = None):
        super().__init__(name or "NeuralCF")
        self.user_count, self.item_count = user_count, item_count
        self.embed_dim = embed_dim
        self.user_gmf = nn.LookupTable(user_count, embed_dim)
        self.item_gmf = nn.LookupTable(item_count, embed_dim)
        self.user_mlp = nn.LookupTable(user_count, embed_dim)
        self.item_mlp = nn.LookupTable(item_count, embed_dim)
        mlp = nn.Sequential()
        prev = 2 * embed_dim
        for d in mlp_dims:
            mlp.add(nn.Linear(prev, d)).add(nn.ReLU())
            prev = d
        self.mlp = mlp
        self.head = nn.Linear(embed_dim + prev, 1)

    def spec_children(self):
        return {"user_gmf": self.user_gmf, "item_gmf": self.item_gmf,
                "user_mlp": self.user_mlp, "item_mlp": self.item_mlp,
                "mlp": self.mlp, "head": self.head}

    def init(self, rng):
        ks = jax.random.split(rng, 6)
        names = ["user_gmf", "item_gmf", "user_mlp", "item_mlp", "mlp",
                 "head"]
        params, state = {}, {}
        for n, k in zip(names, ks):
            p, s = getattr(self, n).init(k)
            params[n], state[n] = p, s
        return params, state

    def apply(self, params, state, input, *, training=False, rng=None):
        users, items = input
        ug, _ = self.user_gmf.apply(params["user_gmf"], {}, users)
        ig, _ = self.item_gmf.apply(params["item_gmf"], {}, items)
        um, _ = self.user_mlp.apply(params["user_mlp"], {}, users)
        im, _ = self.item_mlp.apply(params["item_mlp"], {}, items)
        gmf = ug * ig
        mlp_in = jnp.concatenate([um, im], axis=-1)
        mlp_out, _ = self.mlp.apply(params["mlp"], state["mlp"], mlp_in,
                                    training=training, rng=rng)
        fused = jnp.concatenate([gmf, mlp_out], axis=-1)
        score, _ = self.head.apply(params["head"], {}, fused)
        return jax.nn.sigmoid(score), state


class WideAndDeep(Module):
    """Wide&Deep (Cheng et al.): wide = SparseLinear over cross-feature id
    bags; deep = embedding bags + dense features through an MLP; summed
    logits → sigmoid.

    Input: ((wide_ids, wide_weights), deep_ids, dense) where deep_ids is
    (N, n_deep_fields) int and dense (N, dense_dim) float."""

    def __init__(self, wide_dim: int, deep_field_counts: Sequence[int],
                 dense_dim: int = 0, embed_dim: int = 16,
                 hidden: Sequence[int] = (100, 50),
                 name: Optional[str] = None,
                 kernel_impl: Optional[str] = None):
        super().__init__(name or "WideAndDeep")
        # kernel_impl: COO wide-path kernel choice (auto|pallas|xla,
        # None = Engine default) — "pallas" fuses the wide table's
        # gather + scale + segment-sum (ops/pallas_embed.py), the
        # entire Wide&Deep hot path per BENCH_r05
        self.wide = SparseLinear(wide_dim, 1, impl=kernel_impl)
        self.deep_field_counts = list(deep_field_counts)
        self.embeds = [nn.LookupTable(c, embed_dim)
                       for c in self.deep_field_counts]
        deep_in = embed_dim * len(self.deep_field_counts) + dense_dim
        deep = nn.Sequential()
        prev = deep_in
        for h in hidden:
            deep.add(nn.Linear(prev, h)).add(nn.ReLU())
            prev = h
        deep.add(nn.Linear(prev, 1))
        self.deep = deep
        self.dense_dim = dense_dim

    def spec_children(self):
        out = {"wide": self.wide, "deep": self.deep}
        for i, e in enumerate(self.embeds):
            out[f"embed{i}"] = e
        return out

    def init(self, rng):
        params, state = {}, {}
        rng, k = jax.random.split(rng)
        params["wide"], state["wide"] = self.wide.init(k)
        for i, e in enumerate(self.embeds):
            rng, k = jax.random.split(rng)
            params[f"embed{i}"], state[f"embed{i}"] = e.init(k)
        rng, k = jax.random.split(rng)
        params["deep"], state["deep"] = self.deep.init(k)
        return params, state

    def apply(self, params, state, input, *, training=False, rng=None):
        wide_in, deep_ids, dense = input
        wide_logit, _ = self.wide.apply(params["wide"], {}, wide_in)
        parts = []
        for i, e in enumerate(self.embeds):
            emb, _ = e.apply(params[f"embed{i}"], {}, deep_ids[:, i])
            parts.append(emb)
        if self.dense_dim:
            parts.append(dense)
        deep_logit, _ = self.deep.apply(params["deep"], state["deep"],
                                        jnp.concatenate(parts, axis=-1),
                                        training=training, rng=rng)
        return jax.nn.sigmoid(wide_logit + deep_logit), state
