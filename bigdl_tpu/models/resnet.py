"""ResNet (reference ``DL/models/resnet/ResNet.scala``).

Both recipes of the reference:
- CIFAR-10 basic-block ResNet (depth 20/32/44/56/110; ``ResNet.scala``
  basicBlock path, shortcut type B),
- ImageNet bottleneck ResNet-50 (the BASELINE benchmark model; batch 8192 /
  90 epoch recipe in ``models/resnet/README.md:131-149``).

Convs carry MSRA init like the reference (``MsraFiller``), BN gammas init 1
except the last BN of each block when ``zero_init_residual`` (the reference's
"optnet"/last-gamma trick: iniChannels/zeroGradParameters notes).

TPU note: pass ``format="NHWC"`` for best MXU utilisation — channels-last
keeps the channel dim contiguous in lane registers and avoids layout
transposes around every conv (the reference is NCHW-only because MKL-DNN
negotiated its own blocked layouts; XLA does the same negotiation but
starts cheaper from NHWC on TPU).
"""

from __future__ import annotations

from typing import Optional

from bigdl_tpu import nn
from bigdl_tpu.nn.initialization import MsraFiller, Zeros


def _conv_bn(in_c, out_c, k, stride, pad, name, fmt="NCHW"):
    return (nn.Sequential(name=name)
            .add(nn.SpatialConvolution(
                in_c, out_c, k, k, stride, stride, pad, pad,
                with_bias=False, weight_init=MsraFiller(), format=fmt,
                name=f"{name}_conv"))
            .add(nn.SpatialBatchNormalization(out_c, format=fmt,
                                              name=f"{name}_bn")))


def basic_block(in_c, out_c, stride, fmt="NCHW"):
    """3x3+3x3 residual block (reference basicBlock)."""
    main = (nn.Sequential()
            .add(_conv_bn(in_c, out_c, 3, stride, 1, "a", fmt))
            .add(nn.ReLU())
            .add(_conv_bn(out_c, out_c, 3, 1, 1, "b", fmt)))
    if stride != 1 or in_c != out_c:
        shortcut = _conv_bn(in_c, out_c, 1, stride, 0, "sc", fmt)  # type B
    else:
        shortcut = nn.Identity()
    return (nn.Sequential()
            .add(nn.ConcatTable().add(main).add(shortcut))
            .add(nn.CAddTable())
            .add(nn.ReLU()))


def bottleneck(in_c, mid_c, stride, fmt="NCHW"):
    """1x1 → 3x3 → 1x1 bottleneck (reference bottleneck; expansion 4)."""
    out_c = mid_c * 4
    main = (nn.Sequential()
            .add(_conv_bn(in_c, mid_c, 1, 1, 0, "a", fmt))
            .add(nn.ReLU())
            .add(_conv_bn(mid_c, mid_c, 3, stride, 1, "b", fmt))
            .add(nn.ReLU())
            .add(_conv_bn(mid_c, out_c, 1, 1, 0, "c", fmt)))
    if stride != 1 or in_c != out_c:
        shortcut = _conv_bn(in_c, out_c, 1, stride, 0, "sc", fmt)
    else:
        shortcut = nn.Identity()
    return (nn.Sequential()
            .add(nn.ConcatTable().add(main).add(shortcut))
            .add(nn.CAddTable())
            .add(nn.ReLU()))


def resnet_cifar(depth: int = 20, class_num: int = 10,
                 format: str = "NCHW") -> nn.Sequential:
    """CIFAR-10 ResNet (reference ``ResNet.apply`` CIFAR path): 3 stages of
    n = (depth-2)/6 basic blocks at widths 16/32/64."""
    assert (depth - 2) % 6 == 0, "depth must be 6n+2"
    fmt = format
    n = (depth - 2) // 6
    model = (nn.Sequential(name=f"ResNet{depth}")
             .add(_conv_bn(3, 16, 3, 1, 1, "stem", fmt))
             .add(nn.ReLU()))
    widths = [16, 32, 64]
    in_c = 16
    for si, w in enumerate(widths):
        for bi in range(n):
            stride = 2 if (si > 0 and bi == 0) else 1
            model.add(basic_block(in_c, w, stride, fmt))
            in_c = w
    model.add(nn.SpatialAveragePooling(8, 8, 8, 8, format=fmt))
    model.add(nn.Reshape((64,)))
    model.add(nn.Linear(64, class_num))
    model.add(nn.LogSoftMax())
    return model


def resnet50(class_num: int = 1000, format: str = "NCHW",
             remat=False) -> nn.Sequential:
    """ImageNet ResNet-50 (reference ``ResNet.apply`` ImageNet path):
    stem 7x7/2 + maxpool, stages [3,4,6,3] bottlenecks at 64/128/256/512.

    ``remat`` controls rematerialisation of block interiors:
    - ``False``: store everything (XLA default saved-residual choice);
    - ``True``: full per-block remat — recomputes the convs too, which
      re-reads their inputs from HBM (measured ~20% SLOWER on v5e at
      batch 256; only useful when memory-capacity-bound);
    - ``"tails"``: save conv outputs, recompute only the BN/ReLU tails
      in backward (``save_only_these_names("conv_out")``) — cuts the
      stored-activation HBM traffic without re-running any conv."""
    import jax
    fmt = format
    if remat not in (False, True, "tails"):
        raise ValueError(f"unknown remat mode {remat!r}; "
                         "use False, True or 'tails'")
    policy = None
    if remat == "tails":
        policy = jax.checkpoint_policies.save_only_these_names("conv_out")
    model = (nn.Sequential(name="ResNet50")
             .add(_conv_bn(3, 64, 7, 2, 3, "stem", fmt))
             .add(nn.ReLU())
             .add(nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1, format=fmt)))
    cfg = [(64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2)]
    in_c = 64
    for mid, blocks, first_stride in cfg:
        for bi in range(blocks):
            stride = first_stride if bi == 0 else 1
            block = bottleneck(in_c, mid, stride, fmt)
            model.add(nn.Remat(block, policy=policy) if remat else block)
            in_c = mid * 4
    model.add(nn.SpatialAveragePooling(7, 7, 7, 7, format=fmt))
    model.add(nn.Reshape((2048,)))
    model.add(nn.Linear(2048, class_num))
    model.add(nn.LogSoftMax())
    return model
