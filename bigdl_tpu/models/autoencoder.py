"""MNIST autoencoder (reference ``DL/models/autoencoder/Autoencoder.scala``:
784 → 32 → 784 with sigmoid output, trained with MSE)."""

from __future__ import annotations

from bigdl_tpu import nn


def autoencoder(class_num: int = 32) -> nn.Sequential:
    return (nn.Sequential(name="Autoencoder")
            .add(nn.Reshape((784,)))
            .add(nn.Linear(784, class_num))
            .add(nn.ReLU())
            .add(nn.Linear(class_num, 784))
            .add(nn.Sigmoid()))
