"""Recurrent language models.

Reference: ``DL/models/rnn/SimpleRNN.scala`` (tiny-Shakespeare char RNN)
and ``DL/example/languagemodel/PTBModel.scala`` (PTB word-level LSTM LM).
"""

from __future__ import annotations

from bigdl_tpu import nn
from bigdl_tpu.nn.recurrent import (
    LSTM, MultiRNNCell, Recurrent, RnnCell, TimeDistributed,
)


def simple_rnn(input_size: int = 128, hidden_size: int = 40,
               output_size: int = 128,
               scan_unroll: int = 1) -> nn.Sequential:
    """Char-level RNN (reference ``SimpleRNN.scala``): one-hot input
    (N, T, input_size) → Recurrent(RnnCell) → per-step Linear →
    LogSoftMax."""
    return (nn.Sequential(name="SimpleRNN")
            .add(Recurrent(RnnCell(input_size, hidden_size),
                           unroll=scan_unroll))
            .add(TimeDistributed(nn.Linear(hidden_size, output_size)))
            .add(nn.LogSoftMax()))


def ptb_model(vocab_size: int = 10000, embed_dim: int = 200,
              hidden_size: int = 200, num_layers: int = 2,
              dropout: float = 0.0,
              scan_unroll: int = 1,
              kernel_impl=None) -> nn.Sequential:
    """PTB word LM (reference ``PTBModel.scala``): embedding → stacked LSTM
    → per-step Linear → LogSoftMax.  Input: int tokens (N, T).

    ``scan_unroll`` unrolls the time loop (exact math) — small-batch
    LSTM steps are dispatch-bound on TPU; see Recurrent's docstring.
    ``kernel_impl`` (``auto|pallas|xla``, None = Engine default) selects
    the LSTM-cell kernel — ``"pallas"`` fuses the per-step gate chain
    into one VMEM-resident pass (ops/pallas_lstm.py)."""
    cells = [LSTM(embed_dim if i == 0 else hidden_size, hidden_size,
                  impl=kernel_impl)
             for i in range(num_layers)]
    m = (nn.Sequential(name="PTBModel")
         .add(nn.LookupTable(vocab_size, embed_dim)))
    if dropout > 0:
        m.add(nn.Dropout(dropout))
    m.add(Recurrent(MultiRNNCell(cells), unroll=scan_unroll))
    if dropout > 0:
        m.add(nn.Dropout(dropout))
    m.add(TimeDistributed(nn.Linear(hidden_size, vocab_size)))
    m.add(nn.LogSoftMax())
    return m
