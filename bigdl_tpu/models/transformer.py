"""Transformer language model.

No reference analog (BigDL predates transformers) — flagship for the TPU
build's first-class long-context/distributed capabilities: with
``shard=True`` the attention and MLP carry Megatron tensor-parallel specs
(``parallel/tensor_parallel.py``) and long sequences ride ring attention
(``parallel/ring_attention.py``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.nn.attention import LayerNorm, MultiHeadAttention


def transformer_block(embed_dim: int, num_heads: int, mlp_dim: int,
                      dropout: float = 0.0, causal: bool = True,
                      shard: bool = False) -> nn.Sequential:
    """Pre-norm block: x + MHA(LN(x)); x + MLP(LN(x)).  With ``shard``,
    MLP is column→row parallel (one all-reduce per block, Megatron)."""
    attn = (nn.Sequential()
            .add(LayerNorm(embed_dim))
            .add(MultiHeadAttention(embed_dim, num_heads, causal=causal,
                                    dropout=dropout, shard=shard)))
    mlp = (nn.Sequential()
           .add(LayerNorm(embed_dim))
           .add(nn.Linear(embed_dim, mlp_dim,
                          shard="column" if shard else None))
           .add(nn.GELU())
           .add(nn.Linear(mlp_dim, embed_dim,
                          shard="row" if shard else None)))
    return (nn.Sequential()
            .add(nn.Sequential()
                 .add(nn.ConcatTable().add(attn).add(nn.Identity()))
                 .add(nn.CAddTable()))
            .add(nn.Sequential()
                 .add(nn.ConcatTable().add(mlp).add(nn.Identity()))
                 .add(nn.CAddTable())))


class LearnedPositionalEmbedding(nn.Module):
    def __init__(self, max_len: int, embed_dim: int, name=None):
        super().__init__(name)
        self.max_len, self.embed_dim = max_len, embed_dim

    def init(self, rng):
        import jax
        w = 0.02 * jax.random.normal(rng, (self.max_len, self.embed_dim))
        return {"weight": w}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        T = input.shape[1]
        return input + params["weight"][:T].astype(input.dtype), state


def transformer_lm(vocab_size: int = 32000, embed_dim: int = 512,
                   num_heads: int = 8, num_layers: int = 6,
                   mlp_dim: Optional[int] = None, max_len: int = 2048,
                   dropout: float = 0.0, shard: bool = False):
    """Decoder-only LM: tokens (N, T) → log-probs (N, T, V)."""
    mlp_dim = mlp_dim or 4 * embed_dim
    m = (nn.Sequential(name="TransformerLM")
         .add(nn.LookupTable(vocab_size, embed_dim))
         .add(LearnedPositionalEmbedding(max_len, embed_dim)))
    for _ in range(num_layers):
        m.add(transformer_block(embed_dim, num_heads, mlp_dim, dropout,
                                causal=True, shard=shard))
    m.add(LayerNorm(embed_dim))
    m.add(nn.TimeDistributed(nn.Linear(embed_dim, vocab_size)))
    m.add(nn.LogSoftMax())
    return m


# --------------------------------------------------------------- decode path
#
# KV-cache carry for autoregressive serving (``serving/decode.py``).  The
# functions below re-run the exact per-layer math of the modules built by
# :func:`transformer_lm` — same projection weights, same f32 softmax/LN
# statistics — but carry per-layer K/V caches so a decode step touches one
# token instead of the whole context.  Equality with the full-context
# ``model.apply`` is tight-allclose, not bitwise: the attention GEMMs run
# at different shapes (Tq=1 vs Tq=T), so XLA's reduction order differs
# (the PR-16 cross-shape numerics precedent; gated in
# ``tests/test_decode_serving.py``).
#
# Cache layout: k/v each ``(L, S, H, T_max, Dh)`` — L layers, S slots,
# H heads.  ``lengths[s]`` tokens are valid in slot ``s``; positions at or
# beyond ``lengths[s]`` hold garbage (padded prefill leftovers) and are
# never attended because the causal mask cuts at the query's absolute
# position.

def lm_layout(model):
    """Structural handles into a :func:`transformer_lm` Sequential:
    ``(embed, pos, blocks, final_ln, head, mha0)`` module refs.  Raises
    if ``model`` does not have the transformer_lm layout."""
    mods = model.modules
    if len(mods) < 6:
        raise ValueError("not a transformer_lm: too few modules")
    embed, pos = mods[0], mods[1]
    blocks = mods[2:len(mods) - 3]
    final_ln, head = mods[-3], mods[-2]
    if not isinstance(embed, nn.LookupTable) or not blocks:
        raise ValueError("not a transformer_lm layout")
    # block = Seq[Seq[ConcatTable[attn_seq, Id], CAdd], Seq[...mlp...]]
    mha0 = blocks[0].modules[0].modules[0].modules[0].modules[1]
    if not isinstance(mha0, MultiHeadAttention):
        raise ValueError("not a transformer_lm layout (no MHA in block)")
    return embed, pos, blocks, final_ln, head, mha0


def kv_cache_spec(model, slots: int, max_len: int):
    """(shape, dtype) of ONE of the k/v caches for ``model``:
    ``(L, slots, H, max_len, Dh)`` f32.  The declared-budget sizing in
    ``serving/decode.py`` prices exactly two of these."""
    _, _, blocks, _, _, mha = lm_layout(model)
    return ((len(blocks), slots, mha.num_heads, max_len, mha.head_dim),
            jnp.float32)


def init_kv_cache(model, slots: int, max_len: int):
    """Zeroed (k, v) cache pair sized by :func:`kv_cache_spec`."""
    shape, dtype = kv_cache_spec(model, slots, max_len)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def _block_attn(mha: MultiHeadAttention, ap, h, k_cache, v_cache, pos_ids):
    """Cached multi-head attention for one block.  ``h`` (S, T, D) are the
    post-LN hiddens of the T NEW tokens at absolute positions ``pos_ids``
    (S, T); k/v for those tokens are written into the (S, H, Tmax, Dh)
    caches and the queries attend over the caches with a causal cut at
    each query's absolute position.  Returns (out, new_k, new_v)."""
    q = h @ ap["wq"]
    k = h @ ap["wk"]
    v = h @ ap["wv"]
    if mha.with_bias:
        q, k, v = q + ap["bq"], k + ap["bk"], v + ap["bv"]
    S, T, _ = h.shape
    H, Dh = mha.num_heads, mha.head_dim

    def split(x):
        return x.reshape(S, T, H, Dh).transpose(0, 2, 1, 3)  # (S,H,T,Dh)

    q, k, v = split(q), split(k), split(v)
    # write the T new tokens at pos_ids[:, 0] .. pos_ids[:, 0]+T-1
    # (positions within one call are consecutive by construction)
    start = pos_ids[:, 0]

    def write(cache_s, kv_s, s0):
        return jax.lax.dynamic_update_slice(cache_s, kv_s, (0, s0, 0))

    new_k = jax.vmap(write)(k_cache, k, start)
    new_v = jax.vmap(write)(v_cache, v, start)
    scale = 1.0 / (Dh ** 0.5)
    scores = jnp.einsum("shqd,shkd->shqk", q, new_k) \
        .astype(jnp.float32) * scale
    # causal over ABSOLUTE positions: query at position p sees cache
    # positions <= p; everything past the write head is garbage AND
    # masked (ki > p for all valid queries)
    ki = jnp.arange(new_k.shape[2])  # (Tmax,)
    mask = ki[None, None, None, :] <= pos_ids[:, None, :, None]
    scores = jnp.where(mask, scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1).astype(new_v.dtype)
    o = jnp.einsum("shqk,shkd->shqd", w, new_v)
    o = o.transpose(0, 2, 1, 3).reshape(S, T, H * Dh)
    out = o @ ap["wo"]
    if mha.with_bias:
        out = out + ap["bo"]
    return out, new_k, new_v


def decode_forward(model, params, tokens, pos_ids, k_caches, v_caches):
    """Cached forward of a :func:`transformer_lm`: the T tokens per slot
    are NEW tokens at absolute positions ``pos_ids`` (S, T) — prefill
    passes the whole prompt with positions 0..T-1 over empty caches, a
    decode step passes one token at its write position.  Returns
    ``(log_probs (S, T, V), new_k, new_v)`` with the new tokens' K/V
    written into the caches.  Pure function of its arguments (state-free:
    every transformer_lm layer is stateless)."""
    embed, pos, blocks, final_ln, head, mha = lm_layout(model)
    x, _ = embed.apply(params["0"], {}, tokens)
    # positional row per token's absolute position (the full-context
    # apply's [:T] slice is the pos_ids == arange(T) special case)
    x = x + params["1"]["weight"][pos_ids].astype(x.dtype)
    nk, nv = [], []
    for i, block in enumerate(blocks):
        bp = params[str(2 + i)]
        attn_seq = block.modules[0].modules[0].modules[0]
        mlp_seq = block.modules[1].modules[0].modules[0]
        ap = bp["0"]["0"]["0"]   # {"0": LN, "1": MHA}
        mp = bp["1"]["0"]["0"]   # {"0": LN, "1": Lin, "2": {}, "3": Lin}
        h, _ = attn_seq.modules[0].apply(ap["0"], {}, x)
        o, k_i, v_i = _block_attn(attn_seq.modules[1], ap["1"], h,
                                  k_caches[i], v_caches[i], pos_ids)
        x = x + o
        h, _ = mlp_seq.modules[0].apply(mp["0"], {}, x)
        h, _ = mlp_seq.modules[1].apply(mp["1"], {}, h)
        h, _ = mlp_seq.modules[2].apply(mp["2"], {}, h)
        h, _ = mlp_seq.modules[3].apply(mp["3"], {}, h)
        x = x + h
        nk.append(k_i)
        nv.append(v_i)
    x, _ = final_ln.apply(params[str(2 + len(blocks))], {}, x)
    x, _ = head.apply(params[str(3 + len(blocks))], {}, x)
    lp = jax.nn.log_softmax(x, axis=-1)
    return lp, jnp.stack(nk), jnp.stack(nv)


def transformer_lm_prefill(model, params, tokens):
    """Prefill ``tokens`` (S, T) from position 0: returns
    ``(log_probs (S, T, V), k, v)`` with caches sized (L, S, H, T, Dh) —
    exactly the prompt's K/V, ready to be spliced into a serving cache.
    Rows padded past their true length produce garbage log-probs and
    garbage cache ENTRIES at the padded positions; both are benign (the
    caller reads the last VALID position's logits, and decode overwrites
    pad positions before ever attending them)."""
    S, T = tokens.shape
    pos_ids = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :],
                               (S, T))
    k0, v0 = init_kv_cache(model, S, T)
    return decode_forward(model, params, tokens, pos_ids, k0, v0)


def transformer_lm_decode_step(model, params, tokens, lengths,
                               k_caches, v_caches):
    """One decode step over a slot batch: ``tokens`` (S,) are the last
    emitted token per slot, ``lengths`` (S,) the number of cached
    positions per slot.  Writes each token's K/V at position
    ``lengths[s]`` and returns ``(log_probs (S, V), new_k, new_v)`` —
    the next-token distribution per slot.  Inactive slots compute
    garbage that the caller discards; their writes land at their stale
    write head and are overwritten by the next prefill into that
    slot."""
    pos_ids = lengths.astype(jnp.int32)[:, None]  # (S, 1)
    lp, nk, nv = decode_forward(model, params, tokens[:, None], pos_ids,
                                k_caches, v_caches)
    return lp[:, 0], nk, nv
