"""Transformer language model.

No reference analog (BigDL predates transformers) — flagship for the TPU
build's first-class long-context/distributed capabilities: with
``shard=True`` the attention and MLP carry Megatron tensor-parallel specs
(``parallel/tensor_parallel.py``) and long sequences ride ring attention
(``parallel/ring_attention.py``).
"""

from __future__ import annotations

from typing import Optional

from bigdl_tpu import nn
from bigdl_tpu.nn.attention import LayerNorm, MultiHeadAttention


def transformer_block(embed_dim: int, num_heads: int, mlp_dim: int,
                      dropout: float = 0.0, causal: bool = True,
                      shard: bool = False) -> nn.Sequential:
    """Pre-norm block: x + MHA(LN(x)); x + MLP(LN(x)).  With ``shard``,
    MLP is column→row parallel (one all-reduce per block, Megatron)."""
    attn = (nn.Sequential()
            .add(LayerNorm(embed_dim))
            .add(MultiHeadAttention(embed_dim, num_heads, causal=causal,
                                    dropout=dropout, shard=shard)))
    mlp = (nn.Sequential()
           .add(LayerNorm(embed_dim))
           .add(nn.Linear(embed_dim, mlp_dim,
                          shard="column" if shard else None))
           .add(nn.GELU())
           .add(nn.Linear(mlp_dim, embed_dim,
                          shard="row" if shard else None)))
    return (nn.Sequential()
            .add(nn.Sequential()
                 .add(nn.ConcatTable().add(attn).add(nn.Identity()))
                 .add(nn.CAddTable()))
            .add(nn.Sequential()
                 .add(nn.ConcatTable().add(mlp).add(nn.Identity()))
                 .add(nn.CAddTable())))


class LearnedPositionalEmbedding(nn.Module):
    def __init__(self, max_len: int, embed_dim: int, name=None):
        super().__init__(name)
        self.max_len, self.embed_dim = max_len, embed_dim

    def init(self, rng):
        import jax
        w = 0.02 * jax.random.normal(rng, (self.max_len, self.embed_dim))
        return {"weight": w}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        T = input.shape[1]
        return input + params["weight"][:T].astype(input.dtype), state


def transformer_lm(vocab_size: int = 32000, embed_dim: int = 512,
                   num_heads: int = 8, num_layers: int = 6,
                   mlp_dim: Optional[int] = None, max_len: int = 2048,
                   dropout: float = 0.0, shard: bool = False):
    """Decoder-only LM: tokens (N, T) → log-probs (N, T, V)."""
    mlp_dim = mlp_dim or 4 * embed_dim
    m = (nn.Sequential(name="TransformerLM")
         .add(nn.LookupTable(vocab_size, embed_dim))
         .add(LearnedPositionalEmbedding(max_len, embed_dim)))
    for _ in range(num_layers):
        m.add(transformer_block(embed_dim, num_heads, mlp_dim, dropout,
                                causal=True, shard=shard))
    m.add(LayerNorm(embed_dim))
    m.add(nn.TimeDistributed(nn.Linear(embed_dim, vocab_size)))
    m.add(nn.LogSoftMax())
    return m
