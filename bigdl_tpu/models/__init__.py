"""Model zoo (reference ``DL/models/``)."""

from bigdl_tpu.models.lenet import lenet5
