"""Model zoo (reference ``DL/models/``)."""

from bigdl_tpu.models.lenet import lenet5
from bigdl_tpu.models.resnet import resnet_cifar, resnet50
from bigdl_tpu.models.vgg import vgg_for_cifar10, vgg16
from bigdl_tpu.models.inception import inception_v1
from bigdl_tpu.models.rnn import simple_rnn, ptb_model
from bigdl_tpu.models.autoencoder import autoencoder
from bigdl_tpu.models.transformer import (
    transformer_lm, transformer_block, LearnedPositionalEmbedding,
)
from bigdl_tpu.models.recommender import NeuralCF, WideAndDeep
