"""Inception-v1 / GoogLeNet (reference
``DL/models/inception/Inception_v1.scala`` — the north-star distributed
benchmark of the BigDL whitepaper, Figure 7 scaling study).

Topology matches the reference's no-aux-classifier variant
(``Inception_v1NoAuxClassifier``): stem (7x7/2, LRN, 1x1+3x3, LRN) then 9
inception modules 3a..5b with the canonical tower widths, global average
pool, dropout 0.4, linear classifier.
"""

from __future__ import annotations

from bigdl_tpu import nn
from bigdl_tpu.nn.initialization import Xavier


def _conv(in_c, out_c, k, stride=1, pad=0, name=""):
    return (nn.Sequential(name=name)
            .add(nn.SpatialConvolution(in_c, out_c, k, k, stride, stride,
                                       pad, pad, weight_init=Xavier(),
                                       name=f"{name}_conv"))
            .add(nn.ReLU()))


def inception_module(in_c, c1, c3r, c3, c5r, c5, pool_proj, name):
    """4-tower module concat'd on channels (reference ``Inception_Layer_v1``)."""
    return (nn.Concat(1, name=name)
            .add(_conv(in_c, c1, 1, name=f"{name}_1x1"))
            .add(nn.Sequential()
                 .add(_conv(in_c, c3r, 1, name=f"{name}_3x3r"))
                 .add(_conv(c3r, c3, 3, pad=1, name=f"{name}_3x3")))
            .add(nn.Sequential()
                 .add(_conv(in_c, c5r, 1, name=f"{name}_5x5r"))
                 .add(_conv(c5r, c5, 5, pad=2, name=f"{name}_5x5")))
            .add(nn.Sequential()
                 .add(nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1))
                 .add(_conv(in_c, pool_proj, 1, name=f"{name}_pool"))))


def inception_v1(class_num: int = 1000) -> nn.Sequential:
    m = (nn.Sequential(name="InceptionV1")
         .add(_conv(3, 64, 7, 2, 3, "conv1/7x7_s2"))
         .add(nn.SpatialMaxPooling(3, 3, 2, 2, ceil_mode=True))
         .add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
         .add(_conv(64, 64, 1, name="conv2/3x3_reduce"))
         .add(_conv(64, 192, 3, pad=1, name="conv2/3x3"))
         .add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
         .add(nn.SpatialMaxPooling(3, 3, 2, 2, ceil_mode=True)))
    # (in, 1x1, 3x3r, 3x3, 5x5r, 5x5, pool) — reference tower widths
    m.add(inception_module(192, 64, 96, 128, 16, 32, 32, "3a"))
    m.add(inception_module(256, 128, 128, 192, 32, 96, 64, "3b"))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2, ceil_mode=True))
    m.add(inception_module(480, 192, 96, 208, 16, 48, 64, "4a"))
    m.add(inception_module(512, 160, 112, 224, 24, 64, 64, "4b"))
    m.add(inception_module(512, 128, 128, 256, 24, 64, 64, "4c"))
    m.add(inception_module(512, 112, 144, 288, 32, 64, 64, "4d"))
    m.add(inception_module(528, 256, 160, 320, 32, 128, 128, "4e"))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2, ceil_mode=True))
    m.add(inception_module(832, 256, 160, 320, 32, 128, 128, "5a"))
    m.add(inception_module(832, 384, 192, 384, 48, 128, 128, "5b"))
    m.add(nn.SpatialAveragePooling(7, 7, 1, 1))
    m.add(nn.Dropout(0.4))
    m.add(nn.Reshape((1024,)))
    m.add(nn.Linear(1024, class_num, weight_init=Xavier()))
    m.add(nn.LogSoftMax())
    return m
