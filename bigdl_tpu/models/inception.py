"""Inception-v1 / GoogLeNet (reference
``DL/models/inception/Inception_v1.scala`` — the north-star distributed
benchmark of the BigDL whitepaper, Figure 7 scaling study).

Topology matches the reference's no-aux-classifier variant
(``Inception_v1NoAuxClassifier``): stem (7x7/2, LRN, 1x1+3x3, LRN) then 9
inception modules 3a..5b with the canonical tower widths, global average
pool, dropout 0.4, linear classifier.
"""

from __future__ import annotations

from bigdl_tpu import nn
from bigdl_tpu.nn.initialization import Xavier


def _conv(in_c, out_c, k, stride=1, pad=0, name="", format="NCHW"):
    return (nn.Sequential(name=name)
            .add(nn.SpatialConvolution(in_c, out_c, k, k, stride, stride,
                                       pad, pad, weight_init=Xavier(),
                                       format=format,
                                       name=f"{name}_conv"))
            .add(nn.ReLU()))


def inception_module(in_c, c1, c3r, c3, c5r, c5, pool_proj, name,
                     format="NCHW"):
    """4-tower module concat'd on channels (reference ``Inception_Layer_v1``)."""
    c_axis = 1 if format == "NCHW" else 3
    return (nn.Concat(c_axis, name=name)
            .add(_conv(in_c, c1, 1, name=f"{name}_1x1", format=format))
            .add(nn.Sequential()
                 .add(_conv(in_c, c3r, 1, name=f"{name}_3x3r",
                            format=format))
                 .add(_conv(c3r, c3, 3, pad=1, name=f"{name}_3x3",
                            format=format)))
            .add(nn.Sequential()
                 .add(_conv(in_c, c5r, 1, name=f"{name}_5x5r",
                            format=format))
                 .add(_conv(c5r, c5, 5, pad=2, name=f"{name}_5x5",
                            format=format)))
            .add(nn.Sequential()
                 .add(nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1, format=format))
                 .add(_conv(in_c, pool_proj, 1, name=f"{name}_pool",
                            format=format))))


def inception_v1(class_num: int = 1000,
                 format: str = "NCHW") -> nn.Sequential:
    f = format
    m = (nn.Sequential(name="InceptionV1")
         .add(_conv(3, 64, 7, 2, 3, "conv1/7x7_s2", format=f))
         .add(nn.SpatialMaxPooling(3, 3, 2, 2, ceil_mode=True, format=f))
         .add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75, format=f))
         .add(_conv(64, 64, 1, name="conv2/3x3_reduce", format=f))
         .add(_conv(64, 192, 3, pad=1, name="conv2/3x3", format=f))
         .add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75, format=f))
         .add(nn.SpatialMaxPooling(3, 3, 2, 2, ceil_mode=True, format=f)))
    # (in, 1x1, 3x3r, 3x3, 5x5r, 5x5, pool) — reference tower widths
    m.add(inception_module(192, 64, 96, 128, 16, 32, 32, "3a", f))
    m.add(inception_module(256, 128, 128, 192, 32, 96, 64, "3b", f))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2, ceil_mode=True, format=f))
    m.add(inception_module(480, 192, 96, 208, 16, 48, 64, "4a", f))
    m.add(inception_module(512, 160, 112, 224, 24, 64, 64, "4b", f))
    m.add(inception_module(512, 128, 128, 256, 24, 64, 64, "4c", f))
    m.add(inception_module(512, 112, 144, 288, 32, 64, 64, "4d", f))
    m.add(inception_module(528, 256, 160, 320, 32, 128, 128, "4e", f))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2, ceil_mode=True, format=f))
    m.add(inception_module(832, 256, 160, 320, 32, 128, 128, "5a", f))
    m.add(inception_module(832, 384, 192, 384, 48, 128, 128, "5b", f))
    m.add(nn.SpatialAveragePooling(7, 7, 1, 1, format=f))
    m.add(nn.Dropout(0.4))
    m.add(nn.Reshape((1024,)))
    m.add(nn.Linear(1024, class_num, weight_init=Xavier()))
    m.add(nn.LogSoftMax())
    return m


# ---------------------------------------------------------- Inception v2
def _conv_bn(in_c, out_c, k, stride=1, pad=0, name=""):
    """conv + BN(1e-3) + ReLU — the v2 building block (reference
    ``Inception_v2.scala`` pairs every conv with SpatialBatchNormalization)."""
    return (nn.Sequential(name=name)
            .add(nn.SpatialConvolution(in_c, out_c, k, k, stride, stride,
                                       pad, pad, with_bias=False,
                                       weight_init=Xavier(),
                                       name=f"{name}_conv"))
            .add(nn.SpatialBatchNormalization(out_c, eps=1e-3,
                                              name=f"{name}/bn"))
            .add(nn.ReLU()))


def inception_layer_v2(in_c, c1, c3, cd3, pool, name):
    """BN-Inception module (reference ``Inception_Layer_v2``).

    ``c1``: 1x1 tower width (0 = stride-2 reduction module, tower absent);
    ``c3``: (reduce, out) 3x3 tower; ``cd3``: (reduce, out) double-3x3
    tower; ``pool``: ("avg"|"max", proj) — proj 0 = bare pooling.
    The stride-2 form strides the 3x3 / second double-3x3 / pool.
    """
    stride = 1 if c1 > 0 else 2
    m = nn.Concat(1, name=name)
    if c1 > 0:
        m.add(_conv_bn(in_c, c1, 1, name=f"{name}1x1"))
    m.add(nn.Sequential()
          .add(_conv_bn(in_c, c3[0], 1, name=f"{name}3x3_reduce"))
          .add(_conv_bn(c3[0], c3[1], 3, stride, 1, name=f"{name}3x3")))
    m.add(nn.Sequential()
          .add(_conv_bn(in_c, cd3[0], 1, name=f"{name}double3x3_reduce"))
          .add(_conv_bn(cd3[0], cd3[1], 3, 1, 1, name=f"{name}double3x3a"))
          .add(_conv_bn(cd3[1], cd3[1], 3, stride, 1,
                        name=f"{name}double3x3b")))
    pool_type, proj = pool
    pool_mod = (nn.SpatialMaxPooling(3, 3, stride, stride,
                                     0 if stride == 2 else 1,
                                     0 if stride == 2 else 1,
                                     ceil_mode=True)
                if pool_type == "max"
                else nn.SpatialAveragePooling(3, 3, stride, stride, 1, 1,
                                              ceil_mode=True))
    tower = nn.Sequential().add(pool_mod)
    if proj > 0:
        tower.add(_conv_bn(in_c, proj, 1, name=f"{name}pool_proj"))
    m.add(tower)
    return m


def inception_v2(class_num: int = 1000) -> nn.Sequential:
    """BN-Inception / Inception-v2 (reference
    ``DL/models/inception/Inception_v2.scala:276`` no-aux variant)."""
    m = (nn.Sequential(name="InceptionV2")
         .add(_conv_bn(3, 64, 7, 2, 3, "conv1/7x7_s2"))
         .add(nn.SpatialMaxPooling(3, 3, 2, 2, ceil_mode=True))
         .add(_conv_bn(64, 64, 1, name="conv2/3x3_reduce"))
         .add(_conv_bn(64, 192, 3, 1, 1, "conv2/3x3"))
         .add(nn.SpatialMaxPooling(3, 3, 2, 2, ceil_mode=True)))
    m.add(inception_layer_v2(192, 64, (64, 64), (64, 96), ("avg", 32),
                             "3a/"))
    m.add(inception_layer_v2(256, 64, (64, 96), (64, 96), ("avg", 64),
                             "3b/"))
    m.add(inception_layer_v2(320, 0, (128, 160), (64, 96), ("max", 0),
                             "3c/"))
    m.add(inception_layer_v2(576, 224, (64, 96), (96, 128), ("avg", 128),
                             "4a/"))
    m.add(inception_layer_v2(576, 192, (96, 128), (96, 128), ("avg", 128),
                             "4b/"))
    m.add(inception_layer_v2(576, 160, (128, 160), (128, 160), ("avg", 96),
                             "4c/"))
    m.add(inception_layer_v2(576, 96, (128, 192), (160, 192), ("avg", 96),
                             "4d/"))
    m.add(inception_layer_v2(576, 0, (128, 192), (192, 256), ("max", 0),
                             "4e/"))
    m.add(inception_layer_v2(1024, 352, (192, 320), (160, 224),
                             ("avg", 128), "5a/"))
    m.add(inception_layer_v2(1024, 352, (192, 320), (192, 224),
                             ("max", 128), "5b/"))
    m.add(nn.SpatialAveragePooling(7, 7, 1, 1, ceil_mode=True))
    m.add(nn.Dropout(0.4))
    m.add(nn.Reshape((1024,)))
    m.add(nn.Linear(1024, class_num, weight_init=Xavier()))
    m.add(nn.LogSoftMax())
    return m
