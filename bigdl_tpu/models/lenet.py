"""LeNet-5 (reference ``DL/models/lenet/LeNet5.scala`` — the canonical MNIST
example and first judge-visible milestone per SURVEY.md §7 stage 3).

Same topology as the reference: conv5x5(6) → tanh → maxpool → conv5x5(12)
→ tanh → maxpool → fc(100) → tanh → fc(10) → logsoftmax.
"""

from __future__ import annotations

from bigdl_tpu import nn


def lenet5(class_num: int = 10) -> nn.Sequential:
    return (nn.Sequential(name="LeNet5")
            .add(nn.Reshape((1, 28, 28)))
            .add(nn.SpatialConvolution(1, 6, 5, 5, name="conv1_5x5"))
            .add(nn.Tanh())
            .add(nn.SpatialMaxPooling(2, 2, 2, 2))
            .add(nn.SpatialConvolution(6, 12, 5, 5, name="conv2_5x5"))
            .add(nn.Tanh())
            .add(nn.SpatialMaxPooling(2, 2, 2, 2))
            .add(nn.Reshape((12 * 4 * 4,)))
            .add(nn.Linear(12 * 4 * 4, 100, name="fc1"))
            .add(nn.Tanh())
            .add(nn.Linear(100, class_num, name="fc2"))
            .add(nn.LogSoftMax()))
