"""Pallas TPU kernel: fused LSTM cell (opt-in via ``impl``/kernel_impl).

Why this kernel exists: BENCH_r05 puts PTB-LSTM at 0.98 of its HBM
floor — the step is bytes-bound, and the bytes are the gate chain.
XLA lowers ``LSTM.step_hoisted`` (nn/recurrent.py) as a matmul followed
by a chain of entry-visible elementwise ops — the (N, 4H) pre-activation
``z``, four (N, H) gate slices, three sigmoids, two tanhs, and the
cell/hidden updates each materialize an HBM round-trip inside the scan
body.  This kernel computes the whole cell — recurrent matmul (MXU,
f32 accumulation in-register), all four gate nonlinearities, cell
update, and hidden output — in ONE VMEM-resident pass: HBM traffic per
step drops to the operands (zx, h, c, weight panel) plus the three
outputs (h', c', and the f32 ``z`` residual the backward needs).

Backward: ``lstm_cell`` is a ``jax.custom_vjp``.  The forward kernel
emits ``z`` (f32) as its residual; the backward's elementwise part —
gate derivatives, dz, dc_prev — is a second fused kernel, while the two
backward matmuls (dh_prev = dz @ Wh, dWh = hᵀ @ dz) stay on XLA: they
are MXU-bound, XLA schedules them fine, and keeping them outside the
kernel lets the scan transpose accumulate dWh across timesteps the
standard way.

Gating discipline (same as ``ops/pallas_pool.py``): strictly opt-in
behind ``impl="pallas"`` / ``Config.kernel_impl``, with a static
:func:`supported` gate and silent XLA fallback — unsupported shapes
take the reference path with identical semantics.  Bitwise-or-tolerance
parity (forward AND gradient, f32 and bf16) is gated in
``tests/test_pallas_kernels.py``, which runs the real kernel bodies in
interpret mode on CPU.

Constraints this design works around are canonical in
``bigdl_tpu/ops/PALLAS_NOTES.md`` (lane-width rules, per-block element
budget, wrapper-pads-kernel-assumes-alignment).  On-chip bytes/step for
the fused cell are carried measurement debt — the canned-HLO gate in
``tests/test_byte_audit.py`` proves the traffic model, interpret-mode
CPU numbers are correctness-only.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from bigdl_tpu.ops.pallas_util import (interpret_default as
                                       _interpret_default,
                                       lane_pad as _lane_pad,
                                       sublane_multiple)

# VMEM element budget for the resident recurrent weight panel
# (H_pad x 4*H_pad).  PTB-medium (H=650 -> 768x3072 = 2.36M elements,
# 9.4 MB f32) must pass; 16 MB/core VMEM also holds the per-block
# activations, so gate with headroom below the next power step
# (H=1024 -> 4.2M elements falls back to XLA).  PROVISIONAL pending
# on-chip validation (the carried measurement debt, ROADMAP item 2a):
# pallas_pool's measured 410K compile-abort budget was taken on its
# 5-D spatial blocks, and whether Mosaic treats a flat 2-D matmul
# panel the same is exactly what the on-chip round must answer — if it
# balks, lowering THIS constant is the one-line fix the supported()
# gate exists to make safe (oversize sites just fall back to XLA).
_W_ELEMENT_BUDGET = 3_000_000


def supported(batch: int, hidden: int, dtype) -> bool:
    """Whether the fused cell covers this (N, H, dtype) config.

    Static and conservative (PALLAS_NOTES.md "supported() is the
    opt-in gate"): float32/bfloat16 only, and the lane-padded recurrent
    weight panel must fit the measured VMEM element budget — oversized
    hidden sizes silently keep the XLA chain."""
    import numpy as np
    if np.dtype(dtype) not in (np.dtype(jnp.float32),
                               np.dtype(jnp.bfloat16)):
        return False
    if batch < 1 or hidden < 1:
        return False
    hp = _lane_pad(hidden)
    return hp * 4 * hp <= _W_ELEMENT_BUDGET


def _fwd_kernel(zx_ref, h_ref, c_ref, w_ref, h_out, c_out, z_out, *,
                H, forget_bias):
    # one VMEM-resident pass: recurrent matmul with f32 accumulation
    # in-register, then all four gates + cell/hidden updates in f32
    z = zx_ref[...].astype(jnp.float32) + jnp.dot(
        h_ref[...], w_ref[...], preferred_element_type=jnp.float32)
    z_out[...] = z  # f32 residual for the backward kernel
    i = jax.nn.sigmoid(z[:, :H])
    f = jax.nn.sigmoid(z[:, H:2 * H] + forget_bias)
    g = jnp.tanh(z[:, 2 * H:3 * H])
    o = jax.nn.sigmoid(z[:, 3 * H:4 * H])
    c_new = f * c_ref[...].astype(jnp.float32) + i * g
    h_new = o * jnp.tanh(c_new)
    h_out[...] = h_new.astype(h_out.dtype)
    c_out[...] = c_new.astype(c_out.dtype)


def _bwd_kernel(z_ref, c_ref, dh_ref, dc_ref, dz_out, dcp_out, *,
                H, forget_bias):
    # elementwise backward, fused: recompute gates from the f32 z
    # residual, emit dz (f32) and dc_prev; the two matmuls consuming dz
    # run on XLA outside (module docstring)
    z = z_ref[...]
    c = c_ref[...].astype(jnp.float32)
    dh = dh_ref[...].astype(jnp.float32)
    dc = dc_ref[...].astype(jnp.float32)
    i = jax.nn.sigmoid(z[:, :H])
    f = jax.nn.sigmoid(z[:, H:2 * H] + forget_bias)
    g = jnp.tanh(z[:, 2 * H:3 * H])
    o = jax.nn.sigmoid(z[:, 3 * H:4 * H])
    c_new = f * c + i * g
    tc = jnp.tanh(c_new)
    dct = dc + dh * o * (1.0 - tc * tc)
    # aligned lane-range stores (no in-kernel concatenate; NOTES.md)
    dz_out[:, :H] = dct * g * i * (1.0 - i)
    dz_out[:, H:2 * H] = dct * c * f * (1.0 - f)
    dz_out[:, 2 * H:3 * H] = dct * i * (1.0 - g * g)
    dz_out[:, 3 * H:4 * H] = dh * tc * o * (1.0 - o)
    dcp_out[...] = (dct * f).astype(dcp_out.dtype)


def _pad2(a, rows, cols):
    r, c = a.shape
    if r == rows and c == cols:
        return a
    return jnp.pad(a, ((0, rows - r), (0, cols - c)))


def _pad_gates(a, rows, H, Hp):
    """Pad (rows0, 4*H) gate-segmented arrays to (rows, 4*Hp): each of
    the i|f|g|o segments is padded independently so kernel-side lane
    slices stay 128-aligned."""
    r = a.shape[0]
    a = a.reshape(r, 4, H)
    a = jnp.pad(a, ((0, rows - r), (0, 0), (0, Hp - H)))
    return a.reshape(rows, 4 * Hp)


def _block_n(n_pad: int) -> int:
    """Batch block: whole batch when small, 128-row blocks otherwise
    (n_pad is a _SUBLANE multiple; 128 divides any larger multiple we
    pick because we round n_pad up to 128 past that point)."""
    return n_pad if n_pad <= 128 else 128


def _pallas_cell(zx, h, c, w_t, *, H, forget_bias, interpret):
    """Aligned-shape fused cell: returns (h', c', z_residual)."""
    N, H4 = zx.shape
    bn = _block_n(N)
    kern = functools.partial(_fwd_kernel, H=H, forget_bias=forget_bias)
    return pl.pallas_call(
        kern,
        grid=(N // bn,),
        in_specs=[
            pl.BlockSpec((bn, H4), lambda n: (n, 0)),
            pl.BlockSpec((bn, H), lambda n: (n, 0)),
            pl.BlockSpec((bn, H), lambda n: (n, 0)),
            pl.BlockSpec((H, H4), lambda n: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, H), lambda n: (n, 0)),
            pl.BlockSpec((bn, H), lambda n: (n, 0)),
            pl.BlockSpec((bn, H4), lambda n: (n, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, H), zx.dtype),
            jax.ShapeDtypeStruct((N, H), zx.dtype),
            jax.ShapeDtypeStruct((N, H4), jnp.float32),
        ],
        interpret=interpret,
    )(zx, h, c, w_t)


def _pallas_cell_bwd(z, c, dh, dc, *, H, forget_bias, interpret):
    """Aligned-shape fused elementwise backward: (dz_f32, dc_prev)."""
    N, H4 = z.shape
    bn = _block_n(N)
    kern = functools.partial(_bwd_kernel, H=H, forget_bias=forget_bias)
    return pl.pallas_call(
        kern,
        grid=(N // bn,),
        in_specs=[
            pl.BlockSpec((bn, H4), lambda n: (n, 0)),
            pl.BlockSpec((bn, H), lambda n: (n, 0)),
            pl.BlockSpec((bn, H), lambda n: (n, 0)),
            pl.BlockSpec((bn, H), lambda n: (n, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, H4), lambda n: (n, 0)),
            pl.BlockSpec((bn, H), lambda n: (n, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, H4), jnp.float32),
            jax.ShapeDtypeStruct((N, H), dc.dtype),
        ],
        interpret=interpret,
    )(z, c, dh, dc)


def _unpad_gates(a, rows, H, Hp):
    """Slice a (*, 4*Hp) gate-segmented array back to (rows, 4*H)."""
    return a.reshape(a.shape[0], 4, Hp)[:rows, :, :H].reshape(rows, 4 * H)


@functools.lru_cache(maxsize=32)
def _cell_fn(H: int, forget_bias: float, interpret: bool):
    """Build (and cache) the custom-vjp fused cell for one static
    config — a fresh custom_vjp per call would defeat jit caching.

    Residual discipline: the per-step residuals are the f32 ``z`` and
    the (padded) ``h``/``c`` — the same order of state XLA saves for the
    scan transpose anyway.  The padded weight panel rides the residuals
    too, but it is a pure function of the loop-invariant weight, so the
    scan partial-eval hoists it out of the stacked extensive outputs
    (verified on the pinned jax: invariant residuals are NOT stacked
    per step)."""

    Hp = _lane_pad(H)

    @jax.custom_vjp
    def cell(zx, h, c, w_t):
        return _fwd(zx, h, c, w_t)[0]

    def _fwd(zx, h, c, w_t):
        N = zx.shape[0]
        # batch padded to the DTYPE's sublane tile minimum — (8, 128)
        # f32, (16, 128) bf16 (PALLAS_NOTES.md)
        sub = sublane_multiple(zx.dtype)
        Np = -(-N // sub) * sub
        if Np > 128:
            Np = -(-Np // 128) * 128  # keep 128-row blocks exact
        zxp = _pad_gates(zx, Np, H, Hp)
        hp = _pad2(h, Np, Hp)
        cp = _pad2(c, Np, Hp)
        wp = _pad_gates(w_t, Hp, H, Hp)
        h_new, c_new, z = _pallas_cell(zxp, hp, cp, wp, H=Hp,
                                       forget_bias=forget_bias,
                                       interpret=interpret)
        out = (h_new[:N, :H], c_new[:N, :H])
        return out, (z, cp, hp, wp)

    def _bwd(res, grads):
        z, cp, hp, wp = res
        dh, dc = grads
        # static facts recovered from the cotangents (residuals must
        # stay arrays-only): N from the unpadded shape, and the zx
        # cotangent dtype — the primal outputs carried zx's dtype, so
        # the incoming cotangents carry it too
        N, zx_dtype = dh.shape[0], dh.dtype
        dhp = _pad2(dh.astype(jnp.float32), z.shape[0], Hp)
        dcp = _pad2(dc.astype(jnp.float32), z.shape[0], Hp)
        dz, dc_prev = _pallas_cell_bwd(z, cp, dhp, dcp, H=Hp,
                                       forget_bias=forget_bias,
                                       interpret=interpret)
        # MXU-bound transposes stay on XLA (module docstring)
        dh_prev = jnp.dot(dz, wp.T.astype(jnp.float32),
                          preferred_element_type=jnp.float32)
        dw_t = jnp.dot(hp.T.astype(jnp.float32), dz,
                       preferred_element_type=jnp.float32)
        # cotangent avals must match the primals' (dtype included)
        return (_unpad_gates(dz, N, H, Hp).astype(zx_dtype),
                dh_prev[:N, :H].astype(hp.dtype),
                dc_prev[:N, :H].astype(cp.dtype),
                _unpad_gates(dw_t, H, H, Hp).astype(wp.dtype))

    cell.defvjp(_fwd, _bwd)
    return cell


def lstm_cell(zx, h, c, w_t, *, forget_bias: float = 0.0,
              interpret=None):
    """Fused LSTM cell: ``z = zx + h @ w_t`` then gates/cell/hidden in
    one VMEM pass.

    Args mirror ``nn.recurrent.LSTM.step_hoisted``: ``zx`` (N, 4H) is
    the hoisted input projection + bias, ``h``/``c`` (N, H) the carried
    state, ``w_t`` (H, 4H) the transposed recurrent weight slice.
    Returns ``(h_new, c_new)``; differentiable (custom VJP, fused
    backward).  Caller is responsible for checking :func:`supported`.

    Backward math runs in f32 (gate derivatives from the f32 ``z``
    residual, f32-accumulated matmuls); each cotangent is then cast to
    its primal's dtype, as the custom-vjp contract requires — under
    mixed precision the f32 upcast happens where it always does, in
    the transpose of the loss path's downcast."""
    H = h.shape[-1]
    if interpret is None:
        interpret = _interpret_default()
    cell = _cell_fn(H, float(forget_bias), bool(interpret))
    h_new, c_new = cell(zx, h, c, w_t)
    return h_new, c_new
