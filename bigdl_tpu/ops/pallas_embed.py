"""Pallas TPU kernel: fused embedding-bag / COO segment-sum (opt-in).

Why this kernel exists: BENCH_r05 has Wide&Deep at MFU 0.0035 — the
step is pure gather/segment-sum traffic over the wide table
(``nn/sparse.py`` ``coo_spmm``).  XLA lowers that path as
``take`` → ``multiply`` → ``scatter-add``, materializing the
``(nnz, D)`` gathered-and-scaled intermediate in HBM twice (the gather
write and the multiply) before the segment reduction reads it again.
This kernel runs gather + scale + segment-accumulate in ONE pass: the
output accumulator lives in VMEM for the whole kernel, table rows are
double-buffered per-row async DMAs from HBM, and the per-chunk
row/col/value streams ride SMEM block specs — the ``(nnz, D)``
intermediate never exists.  HBM traffic per step drops to the gathered
table rows + the flat index/value streams + one output write.

Accumulation is f32 in VMEM regardless of operand dtype; the output is
cast to the same promoted dtype the XLA path produces.  Because the
accumulator is read-modify-write on a resident ref, ROW ORDER DOES NOT
MATTER — unsorted COO, padding entries (row 0, col 0, value 0), empty
rows and duplicate (row, col) pairs all accumulate correctly, so this
kernel accepts exactly what ``coo_spmm`` accepts.

Backward: ``jax.custom_vjp``.  The weight gradient deliberately stays
on XLA's scatter-add — the r5 on-chip ablation measured XLA's scatter
as the best known formulation for the random-update weight grad
(sort+segsum measured worse; see bench.py Wide&Deep notes) — and
``d_values`` is a row-dot also left to XLA.  The forward is where the
fused win lives.

Gating discipline: opt-in behind ``impl``/``Config.kernel_impl`` with
a static :func:`supported` gate and silent XLA fallback, parity gated
bitwise-or-tolerance (fwd + grad) in ``tests/test_pallas_kernels.py``
under interpret mode on CPU.  Constraint provenance:
``bigdl_tpu/ops/PALLAS_NOTES.md`` (no scatter-add primitive → VMEM
accumulator; SMEM is KBs → per-chunk scalar streams; gather = per-row
DMA).  On-chip bytes/step are carried measurement debt; the canned-HLO
byte gate lives in ``tests/test_byte_audit.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bigdl_tpu.ops.pallas_util import (interpret_default as
                                       _interpret_default,
                                       lane_pad as _lane_pad)

# nnz entries processed per grid step; the SMEM footprint per step is
# 3 streams x _CHUNK x 4 B = 3 KB (SMEM is small — never block a whole
# nnz stream into it, PALLAS_NOTES.md)
_CHUNK = 256

# VMEM element budget for the resident (n_rows, lane-padded D) output
# accumulator: the census Wide&Deep wide path (8192 x pad(1)=128 =
# 1.05M elements, 4.2 MB f32) must pass with headroom for the DMA
# buffers; bigger outputs silently keep the XLA segment-sum.
# PROVISIONAL pending on-chip validation (carried measurement debt,
# ROADMAP item 2a): pallas_pool's 410K compile-abort budget was
# measured on 5-D spatial blocks, not a flat 2-D accumulator — and the
# D=1 wide path's padded count is tile padding, not live data (8192
# rows x 128 lanes = 4.2 MB physical, far under VMEM).  If on-chip
# Mosaic balks, lowering THIS constant is the one-line fix the
# supported() gate makes safe (oversize sites fall back to XLA).
_OUT_ELEMENT_BUDGET = 1_300_000


def supported(nnz: int, n_rows: int, table_shape, dtype) -> bool:
    """Whether the fused bag covers this (nnz, N, table, dtype) config.

    Static and conservative: f32/bf16 tables, feature dim either
    lane-aligned or within one lane group (narrow-D rows ride the DMA
    path, which is byte- not lane-granular), and the VMEM output
    accumulator within the element budget."""
    if np.dtype(dtype) not in (np.dtype(jnp.float32),
                               np.dtype(jnp.bfloat16)):
        return False
    if nnz < 1 or n_rows < 1:
        return False
    V, D = table_shape
    if not (D % 128 == 0 or D <= 128):
        return False
    return n_rows * _lane_pad(D) <= _OUT_ELEMENT_BUDGET


def _bag_kernel(rows_ref, cols_ref, vals_ref, table_ref, out_ref, buf,
                sem, *, chunk):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        # the accumulator block is VMEM-resident across every grid step
        # (constant index_map); zero it exactly once
        out_ref[...] = jnp.zeros(out_ref.shape, out_ref.dtype)

    def dma(slot, j):
        # one table row HBM -> VMEM; byte-granular, so any D is legal
        return pltpu.make_async_copy(
            table_ref.at[pl.ds(cols_ref[j], 1), :],
            buf.at[slot], sem.at[slot])

    dma(0, 0).start()

    def body(j, _):
        slot = jax.lax.rem(j, 2)
        nxt = jax.lax.rem(j + 1, 2)

        @pl.when(j + 1 < chunk)
        def _():
            dma(nxt, j + 1).start()  # overlap the next gather

        dma(slot, j).wait()
        r = rows_ref[j]
        contrib = vals_ref[j] * buf[slot].astype(jnp.float32)
        # read-modify-write on an unstrided (1, D) sub-range — the
        # Mosaic-legal accumulate (no scatter-add primitive)
        out_ref[pl.ds(r, 1), :] = out_ref[pl.ds(r, 1), :] + contrib
        return 0

    jax.lax.fori_loop(0, chunk, body, 0)


@functools.lru_cache(maxsize=32)
def _bag_fn(n_rows: int, interpret: bool):
    """Cached custom-vjp fused bag for one static (n_rows, interpret)."""

    @jax.custom_vjp
    def bag(rows, cols, values, table):
        return _fwd(rows, cols, values, table)[0]

    def _run_kernel(rows, cols, values, table):
        # promoted output dtype from the ORIGINAL operand dtypes (the
        # XLA chain's result dtype); accumulation itself is f32
        out_dtype = jnp.result_type(table.dtype, values.dtype)
        values = values.astype(jnp.float32)
        nnz = rows.shape[0]
        pad = -nnz % _CHUNK
        if pad:
            # padding entries (row 0, col 0, value 0) contribute nothing
            rows = jnp.pad(rows, (0, pad))
            cols = jnp.pad(cols, (0, pad))
            values = jnp.pad(values, (0, pad))
        D = table.shape[1]
        grid = (rows.shape[0] // _CHUNK,)
        kern = functools.partial(_bag_kernel, chunk=_CHUNK)
        out = pl.pallas_call(
            kern,
            grid=grid,
            in_specs=[
                pl.BlockSpec((_CHUNK,), lambda i: (i,),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((_CHUNK,), lambda i: (i,),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((_CHUNK,), lambda i: (i,),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.ANY),  # table stays HBM
            ],
            out_specs=pl.BlockSpec((n_rows, D), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((n_rows, D), jnp.float32),
            scratch_shapes=[pltpu.VMEM((2, 1, D), table.dtype),
                            pltpu.SemaphoreType.DMA((2,))],
            interpret=interpret,
        )(rows, cols, values, table)
        return out.astype(out_dtype)

    def _fwd(rows, cols, values, table):
        out = _run_kernel(rows, cols, values, table)
        return out, (rows, cols, values, table)

    def _bwd(res, g):
        rows, cols, values, table = res
        gf = g.astype(jnp.float32)
        g_rows = jnp.take(gf, rows, axis=0)  # (nnz, D)
        # weight grad: XLA's scatter-add — measured best-known for the
        # random-update pattern (module docstring / bench r5 notes)
        d_table = jnp.zeros(table.shape, jnp.float32).at[cols].add(
            values.astype(jnp.float32)[:, None] * g_rows)
        d_values = jnp.sum(
            g_rows * jnp.take(table, cols, axis=0).astype(jnp.float32),
            axis=1)
        int0 = lambda a: np.zeros(a.shape, jax.dtypes.float0)  # noqa: E731
        return (int0(rows), int0(cols), d_values.astype(values.dtype),
                d_table.astype(table.dtype))

    bag.defvjp(_fwd, _bwd)
    return bag


def embedding_bag_coo(rows, cols, values, table, n_rows: int, *,
                      interpret=None):
    """Fused COO embedding-bag: ``out[r] += values[k] * table[cols[k]]``
    for every non-zero ``k`` with ``rows[k] == r``, in one pass.

    Drop-in for the ``coo_spmm`` gather→scale→segment_sum chain
    (identical semantics for unsorted rows, duplicates, padding zeros
    and empty segments).  Differentiable; the weight grad keeps XLA's
    scatter-add.  Caller is responsible for checking :func:`supported`.
    """
    if interpret is None:
        interpret = _interpret_default()
    fn = _bag_fn(int(n_rows), bool(interpret))
    return fn(rows.astype(jnp.int32), cols.astype(jnp.int32), values,
              table)
