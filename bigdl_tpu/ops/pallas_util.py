"""Shared helpers for the ops/pallas_* kernel wrappers.

One definition site so the padding/interpret conventions cannot drift
between kernels (PALLAS_NOTES.md "wrapper pads, kernel assumes
alignment").  NOTE: ``ops/pallas_pool.py`` keeps its own ``_lane_pad``
deliberately — its semantics differ (no 128-lane minimum: channels
C <= 128 stay unpadded because its lane axis carries ``sw*C`` groups);
don't "unify" them without re-deriving that kernel's slicing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def lane_pad(n: int) -> int:
    """Smallest 128-lane multiple >= n (min one full lane group)."""
    return max(128, -(-n // 128) * 128)


def sublane_multiple(dtype) -> int:
    """Minimum sublane multiple for a dtype's vreg tile: (8, 128) f32,
    (16, 128) bf16 (PALLAS_NOTES.md tiling minimums)."""
    import numpy as np
    return 16 if np.dtype(dtype) == np.dtype(jnp.bfloat16) else 8


def interpret_default() -> bool:
    """Run the real kernel body under the Pallas interpreter off-TPU so
    tier-1 (CPU) exercises this code path."""
    return jax.default_backend() != "tpu"
