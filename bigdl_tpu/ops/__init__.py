"""bigdl_tpu.ops — forward-only TF op execution layer.

Reference: ``DL/nn/ops/`` (71 files) + ``DL/nn/tf/`` (18 files): each TF
op the importer can meet is a forward-only ``Operation`` module executing
Torch-tensor math.  TPU redesign: an op is a pure function
``(attrs, *input_arrays) -> array`` registered by TF op name — the
imported graph executes as ONE jit-traced composition of these, so XLA
fuses the whole imported model instead of interpreting op-by-op.

The op set is scoped to what the importer needs for the benchmark-model
graphs (SURVEY §7 stage 10: "only as far as the TF importer needs"),
and grows with it.
"""

from bigdl_tpu.ops.registry import OPS, register_op, get_op


def resolve_kernel_impl(override=None, workload=None) -> str:
    """Resolve the effective custom-kernel backend: ``"pallas"`` or
    ``"xla"``.

    Per-layer ``impl=`` override wins; otherwise ``Engine.kernel_impl()``
    (explicit ``Engine.set_kernel_impl`` > ``Config.kernel_impl`` /
    ``BIGDL_TPU_KERNEL_IMPL`` > a ``tuned_configs.json`` entry for
    ``workload`` — or the process-wide ``Engine.set_workload`` tag —
    > the dataclass default).  ``"auto"`` means pallas-if-supported on
    a TPU backend and xla elsewhere — interpret-mode kernels are
    correctness emulation, not a speedup, so auto never engages them on
    CPU hosts (force with ``"pallas"``, which tests and the bench
    entries do).  Runs at trace time on the host — the choice is
    static per compiled program, one more knob the autotuner sweeps
    (tools/autotune.py)."""
    from bigdl_tpu.engine import Engine
    impl = override if override is not None \
        else Engine.kernel_impl(workload=workload)
    if impl not in ("auto", "pallas", "xla"):
        raise ValueError(
            f"kernel impl must be auto|pallas|xla, got {impl!r}")
    if impl == "auto":
        import jax
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    return impl


__all__ = ["OPS", "register_op", "get_op", "resolve_kernel_impl"]
