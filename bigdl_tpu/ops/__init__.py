"""bigdl_tpu.ops — forward-only TF op execution layer.

Reference: ``DL/nn/ops/`` (71 files) + ``DL/nn/tf/`` (18 files): each TF
op the importer can meet is a forward-only ``Operation`` module executing
Torch-tensor math.  TPU redesign: an op is a pure function
``(attrs, *input_arrays) -> array`` registered by TF op name — the
imported graph executes as ONE jit-traced composition of these, so XLA
fuses the whole imported model instead of interpreting op-by-op.

The op set is scoped to what the importer needs for the benchmark-model
graphs (SURVEY §7 stage 10: "only as far as the TF importer needs"),
and grows with it.
"""

from bigdl_tpu.ops.registry import OPS, register_op, get_op

__all__ = ["OPS", "register_op", "get_op"]
