"""Pallas TPU kernel for max-pool backward (opt-in; see verdict below).

Why this kernel exists: XLA lowers the gradient of
``lax.reduce_window(max)`` to ``select-and-scatter``, which on TPU runs
far below HBM bandwidth.  Measured on v5e at batch 256 (Inception-v1,
NHWC): the full training step takes 55.1 ms with select-and-scatter
backward vs 46.5 ms with an equal-traffic elementwise backward — ~8.6 ms
of pure lowering waste per step (the reference hits the same op count in
its MKL maxpool backward, ``DL/nn/SpatialMaxPooling.scala``
updateGradInput).

The kernel computes the same first-match semantics as
select-and-scatter / the reference's argmax backward: each output
window routes its gradient to the FIRST position (row-major scan order)
equal to the window max.

Measured verdict (r4): the kernel itself is correct and VMEM-resident,
but pallas only accepts default (row-major) layouts while XLA lays the
surrounding activations out batch-minor (``{0,3,2,1}``) — so XLA
inserts full-tensor layout copies around every call, costing ~3× more
than the select-and-scatter waste the kernel removes (Inception-v1
bytes/step 37.3→80.4 GB).  Until pallas grows input-layout control,
``SpatialMaxPooling`` keeps ``reduce_window`` as its default and this
kernel is opt-in (``impl="pallas_bwd"``), retained as the reference
first-match implementation and for layout-friendly call-sites.

r5 addendum, refreshed round-10 (the fused-kernel PR): the PINNED
toolchain — whichever jax/jaxlib the bench ``toolchain`` stamp names
for a given capture; cross-version claims were the r4→r5 trap — still
has NO pallas input-layout control, so the copy penalty around
batch-minor conv activations stands, and its Mosaic rejects the
large-spatial blocks an earlier toolchain accepted (see
:func:`supported`, which gates on the measured 410K per-block ELEMENT
budget and falls back).  Re-verify BOTH facts per toolchain bump; the
2-D-activation kernels (``pallas_lstm.py``, ``pallas_embed.py``) are
unaffected by the layout issue because their operands use default
row-major layouts.

The Mosaic lowering constraints that shape this design (no
scatter-add, lane-width/strided-access rules, the element budget,
f32-compare masks) are canonical in ``bigdl_tpu/ops/PALLAS_NOTES.md``
— kept there so every ops/pallas_* kernel cites ONE constraints doc
instead of restating and drifting.  Specific to this kernel: all
strided window access is factored out as free XLA reshapes
(``(N, H, W, C) -> (N, H/sh, sh, W/sw, sw*C)`` regroups contiguous
memory, so a window offset ``d = q*s + r`` becomes an UNSTRIDED slice
``[i+q, r]`` with the ``r``-selection a 128-aligned lane-range slice),
and gradient accumulation is read-modify-write on the output ref over
those unstrided sub-ranges.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _bwd_kernel(x_ref, y_ref, g_ref, gi_ref, taken_ref, *, kh, kw, sh, sw,
                ph, pw, GH, GW, OH, OW, C):
    gi_ref[0] = jnp.zeros(gi_ref.shape[1:], gi_ref.dtype)
    # "window already matched" mask lives in a VMEM scratch ref so it
    # can be updated on the same sub-ranges the windows touch (a
    # functional value would need pads, which Mosaic cannot lower for
    # bf16/i1 vectors here).  Float 0/1 rather than bool: reused i1
    # vectors force failing relayouts.
    taken_ref[...] = jnp.zeros(taken_ref.shape, taken_ref.dtype)
    for dh in range(kh):
        # offset relative to the unpadded input: divmod handles the
        # negative (lo-padding) side correctly
        qh, rh = divmod(dh - ph, sh)
        i0, i1 = max(0, -qh), min(OH, GH - qh)
        if i0 >= i1:
            continue
        for dw in range(kw):
            qw, rw = divmod(dw - pw, sw)
            j0, j1 = max(0, -qw), min(OW, GW - qw)
            if j0 >= j1:
                continue
            cand = x_ref[0, i0 + qh:i1 + qh, rh:rh + 1,
                         j0 + qw:j1 + qw, rw * C:(rw + 1) * C]
            # compared in f32: the VPU has no bf16 vector compare, and
            # i1 masks born from packed-bf16 compares force Mosaic
            # relayouts that fail to lower.  Single boolean use, float
            # thereafter.
            hitf = jnp.where(
                cand.astype(jnp.float32) ==
                y_ref[0, i0:i1, :, j0:j1, :].astype(jnp.float32),
                jnp.float32(1.0), jnp.float32(0.0)).astype(x_ref.dtype)
            tsub = taken_ref[i0:i1, :, j0:j1, :]
            fresh = hitf * (jnp.ones((), tsub.dtype) - tsub)
            contrib = g_ref[0, i0:i1, :, j0:j1, :] * fresh.astype(
                gi_ref.dtype)
            taken_ref[i0:i1, :, j0:j1, :] = jnp.maximum(tsub, hitf)
            cur = gi_ref[0, i0 + qh:i1 + qh, rh:rh + 1,
                         j0 + qw:j1 + qw, rw * C:(rw + 1) * C]
            gi_ref[0, i0 + qh:i1 + qh, rh:rh + 1,
                   j0 + qw:j1 + qw, rw * C:(rw + 1) * C] = cur + contrib


def _lane_pad(C: int) -> int:
    """Channels after vreg lane alignment (shared with the kernel's
    padding rule in :func:`maxpool_bwd_nhwc`)."""
    return C if C <= 128 else -(-C // 128) * 128


def supported(x_shape, kernel, stride, pads):
    """Whether the pallas backward covers this pooling config.

    Besides the structural conditions, a per-block ELEMENT budget gate:
    the pinned toolchain's Mosaic aborts compilation (compile-helper
    exit 1, no diagnostic) for the large-spatial blocks an earlier
    toolchain accepted — re-verify per bump, keyed to the bench
    ``toolchain`` stamp.  The limit is element count, not bytes —
    measured on v5e: 802,816-element blocks fail in BOTH f32 (112²×64,
    56²×192) and bf16 (112²×64, i.e. half the bytes), while
    401,408-element blocks (28²×480-pad-512, 56²×128) compile in both
    dtypes — consistent with bf16's (2,1) sublane packing keeping vreg
    footprint proportional to elements (the canonical budget note
    lives in ops/PALLAS_NOTES.md).  Gate at 410,000 elements (just
    above the largest measured-good block) so bigger sites silently
    take the documented reduce_window fallback instead of a runtime
    compile error."""
    _, H, W, C = x_shape
    (kh, kw), (sh, sw) = kernel, stride
    if not (H % sh == 0 and W % sw == 0 and kh >= sh and kw >= sw):
        return False
    return H * W * _lane_pad(C) <= 410_000


def maxpool_bwd_nhwc(x, y, g, kernel, stride, pads):
    """First-match max-pool input-gradient, NHWC.

    ``pads`` is ((ph_lo, ph_hi), (pw_lo, pw_hi)) as given to
    reduce_window; only the lo values matter for indexing (hi padding
    never matches a window max)."""
    N, H, W, C = x.shape
    _, OH, OW, _ = y.shape
    (kh, kw), (sh, sw) = kernel, stride
    (ph, _), (pw, _) = pads

    # lane alignment: pad channels to a 128 multiple so every lane
    # slice in the kernel is vreg-aligned (only the branchy concat
    # widths 192/480/528/832 pay this, and those tensors are small)
    C_eff = _lane_pad(C)
    if C_eff != C:
        x = jnp.pad(x, ((0, 0),) * 3 + ((0, C_eff - C),),
                    constant_values=-jnp.inf)
        y = jnp.pad(y, ((0, 0),) * 3 + ((0, C_eff - C),),
                    constant_values=-jnp.inf)
        g = jnp.pad(g, ((0, 0),) * 3 + ((0, C_eff - C),))

    GH, GW = H // sh, W // sw
    x5 = x.reshape(N, GH, sh, GW, sw * C_eff)    # free: contiguous regroup
    y5 = y.reshape(N, OH, 1, OW, C_eff)
    g5 = g.reshape(N, OH, 1, OW, C_eff)

    kern = functools.partial(_bwd_kernel, kh=kh, kw=kw, sh=sh, sw=sw,
                             ph=ph, pw=pw, GH=GH, GW=GW, OH=OH, OW=OW,
                             C=C_eff)
    gi5 = pl.pallas_call(
        kern,
        grid=(N,),
        in_specs=[
            pl.BlockSpec((1, GH, sh, GW, sw * C_eff),
                         lambda n: (n, 0, 0, 0, 0)),
            pl.BlockSpec((1, OH, 1, OW, C_eff), lambda n: (n, 0, 0, 0, 0)),
            pl.BlockSpec((1, OH, 1, OW, C_eff), lambda n: (n, 0, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, GH, sh, GW, sw * C_eff),
                               lambda n: (n, 0, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(x5.shape, x.dtype),
        scratch_shapes=[pltpu.VMEM((OH, 1, OW, C_eff), x.dtype)],
    )(x5, y5, g5)
    gi = gi5.reshape(N, H, W, C_eff)
    return gi[..., :C] if C_eff != C else gi


def maxpool_nhwc_with_pallas_bwd(x, dims, strides, pads):
    """reduce_window(max) forward + pallas first-match backward.

    Drop-in for the NHWC max-pool forward; the fwd op is XLA's own
    (near bandwidth), only the pathological select-and-scatter backward
    is replaced.  Falls back to plain reduce_window (select-and-scatter
    backward) when :func:`supported` says no."""
    kernel = (dims[1], dims[2])
    stride = (strides[1], strides[2])
    hw_pads = (pads[1], pads[2])

    if not supported(x.shape, kernel, stride, hw_pads):
        return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pads)

    @jax.custom_vjp
    def pool(x):
        return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pads)

    def fwd(x):
        y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pads)
        return y, (x, y)

    def bwd(res, g):
        x, y = res
        return (maxpool_bwd_nhwc(x, y, g, kernel, stride, hw_pads),)

    pool.defvjp(fwd, bwd)
    return pool(x)
