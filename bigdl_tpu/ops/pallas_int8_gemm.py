"""Pallas TPU kernel: int8 mixed-precision GEMM (quantized inference).

Reference: BigQuant's JNI int8 GEMM (``DL/nn/quantized/Linear.scala:
79-90`` — int8 weights per output channel, activations quantized on the
fly, int32 accumulate, dequantize).  Until this kernel, the TPU port
only SIMULATED that backend: ``nn/quantized.py`` issued an ordinary XLA
``dot_general`` on int8 operands, so ``deploy(quantize=True)`` saved
weight memory but bought zero serving speed.  Small-batch inference is
weight-panel-bytes-bound — the (K, O) panel is re-read from HBM every
dispatch while the activation block is tiny — so an int8-resident panel
is a 4x (vs f32) / 2x (vs bf16) cut in the dominant traffic term.  This
kernel keeps the int8 panel VMEM-resident across the row-block grid and
fuses the whole quantized epilogue (dequantize by the per-output-channel
f32 scale, bias add) in-register.

Two per-layer modes share ONE math definition (:func:`_matmul_math`,
used verbatim by the kernel body and the XLA fallback so the two cannot
drift):

- ``weight_only``: f32/bf16 activations against the int8 panel upcast
  in-register, f32 MXU accumulation (``preferred_element_type=f32``) —
  no activation quantization error, the serving default;
- ``dynamic``: activations quantized on the fly per-tensor
  (:func:`dyn_quantize`, BigQuant's runtime scheme), int8 x int8 MXU
  issue with int32 accumulation (``preferred_element_type=int32`` —
  Mosaic requires an int accumulator for int operands), dequantize by
  the combined ``x_scale * w_scale_o``.

Gating discipline (PR-8, same as ``ops/pallas_lstm.py``): strictly
opt-in behind ``impl="pallas"`` / ``Config.kernel_impl``, static
:func:`supported` gate, silent XLA fallback.  The fallback here is
BITWISE-identical, not merely tolerance-close: ``supported()`` requires
K and O already 128-lane-aligned, so the wrapper never pads the
contraction or output dims (padding K would perturb f32 accumulation
order); only batch rows are padded, and the fallback replicates the
kernel's row grid exactly (:func:`_pad_plan` + one dot per block via
``lax.map``) because the host gemm's reduction order depends on the M
it is handed.  Forward-only by design — quantized
modules are inference twins (no ``custom_vjp``), which is what keeps
the builder a plain ``lru_cache``.

Constraints are canonical in ``bigdl_tpu/ops/PALLAS_NOTES.md`` (int8
(32, 128) tile minimum, accumulate dtype rules, VMEM budget
provenance).  All gating below is host code — static ``supported()``
decisions at trace time, never data-dependent dispatch (graftlint
catalog note).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from bigdl_tpu.ops.pallas_util import (interpret_default as
                                       _interpret_default,
                                       sublane_multiple)

# VMEM element budget for the resident int8 weight panel (K x O int8 =
# 1 byte/element, vs 4 for pallas_lstm's f32 panel).  6M elements = 6 MB
# of the ~16 MB/core VMEM, leaving room for the <=128-row activation and
# f32 output blocks (128 x (K + O) elements at the gated sizes).
# PROVISIONAL pending on-chip validation, same provenance trail as
# pallas_lstm._W_ELEMENT_BUDGET: lowering this constant is the one-line
# fix the supported() gate makes safe (oversize panels fall back to the
# bitwise-identical XLA path).  Documented in ops/PALLAS_NOTES.md §int8.
_W_ELEMENT_BUDGET_INT8 = 6_000_000

MODES = ("weight_only", "dynamic")

# int8 vreg tile minimum is (32, 128) (PALLAS_NOTES.md): dynamic-mode
# activation blocks are int8, so their row padding uses this sublane
# multiple instead of the f32/bf16 ones pallas_util knows about
_INT8_SUBLANE = 32


def _sublane(dtype) -> int:
    if np.dtype(dtype) == np.dtype(jnp.int8):
        return _INT8_SUBLANE
    return sublane_multiple(dtype)


def supported(batch: int, in_features: int, out_features: int, x_dtype,
              mode: str = "weight_only") -> bool:
    """Whether the fused GEMM covers this (N, K, O, dtype, mode) config.

    Static and conservative (PALLAS_NOTES.md "supported() is the opt-in
    gate"), decided on the host at trace time.  K and O must ALREADY be
    128-lane multiples — the wrapper refuses to pad the contraction or
    output dims so the pallas path stays bitwise-identical to the XLA
    fallback (module docstring); odd shapes silently keep the XLA
    quantized chain.  f32/bf16 activations only, and the int8 weight
    panel must fit the PROVISIONAL VMEM element budget."""
    if mode not in MODES:
        return False
    if np.dtype(x_dtype) not in (np.dtype(jnp.float32),
                                 np.dtype(jnp.bfloat16)):
        return False
    if batch < 1 or in_features < 1 or out_features < 1:
        return False
    if in_features % 128 != 0 or out_features % 128 != 0:
        return False
    return in_features * out_features <= _W_ELEMENT_BUDGET_INT8


def dyn_quantize(x: jnp.ndarray):
    """Per-tensor dynamic symmetric int8 activation quantization
    (traced; the scale is a runtime value, exactly BigQuant's on-the-fly
    scheme).  Returns ``(int8 values, scale)``; the scale keeps ``x``'s
    dtype-promotion behaviour so downstream ``x_scale * w_scale``
    lands in f32."""
    amax = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    scale = amax / 127.0
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _matmul_math(xin, wq_t, scale_row, bias_row, mode):
    """THE quantized GEMM math — single definition site shared by the
    kernel body (on block refs) and the XLA fallback (on full arrays),
    so the two paths cannot drift.  ``xin`` is f32/bf16 (weight_only)
    or already-quantized int8 (dynamic); ``wq_t`` is the (K, O) int8
    panel; ``scale_row``/``bias_row`` are (1, O) f32.  Returns f32."""
    if mode == "weight_only":
        acc = jnp.dot(xin.astype(jnp.float32),
                      wq_t.astype(jnp.float32),
                      preferred_element_type=jnp.float32)
    else:  # dynamic: int8 x int8 -> int32 accumulate (Mosaic rule)
        acc = jnp.dot(xin, wq_t,
                      preferred_element_type=jnp.int32
                      ).astype(jnp.float32)
    y = acc * scale_row
    if bias_row is not None:
        y = y + bias_row
    return y


def _kernel_bias(x_ref, w_ref, s_ref, b_ref, o_ref, *, mode):
    o_ref[...] = _matmul_math(x_ref[...], w_ref[...], s_ref[...],
                              b_ref[...], mode)


def _kernel_nobias(x_ref, w_ref, s_ref, o_ref, *, mode):
    o_ref[...] = _matmul_math(x_ref[...], w_ref[...], s_ref[...],
                              None, mode)


def _auto_block(n_pad: int) -> int:
    """Row block: whole batch when small, 128-row blocks otherwise
    (n_pad is already a sublane multiple; past 128 it is rounded to a
    128 multiple so the grid divides exactly)."""
    return n_pad if n_pad <= 128 else 128


def _pad_plan(N: int, dtype, block_rows: int):
    """(n_pad, bn) row padding/blocking for a batch — ONE definition
    shared by the kernel wrapper and the XLA fallback, because the
    fallback must replicate the kernel's grid exactly: the host gemm's
    f32 reduction order depends on the M it is handed (XLA CPU blocks
    a 304-row gemm differently from a 128-row one under intra-op
    threading), so bitwise parity requires identical per-block dots,
    not merely identical math."""
    sub = _sublane(dtype)
    if block_rows > 0:
        bn = -(-block_rows // sub) * sub
        n_pad = -(-N // bn) * bn
    else:
        n_pad = -(-N // sub) * sub
        if n_pad > 128:
            n_pad = -(-n_pad // 128) * 128
        bn = _auto_block(n_pad)
    return n_pad, bn


@functools.lru_cache(maxsize=64)
def _gemm_fn(K: int, O: int, mode: str, has_bias: bool,
             block_rows: int, interpret: bool):
    """Build (and cache) the padded-shape pallas caller for one static
    (K, O, mode, bias, block, interpret) config.  Forward-only — no
    custom_vjp — so the cache is a plain memo keeping wrapper identity
    stable across trace sites."""

    def run(xin, wq_t, scale_row, bias_row):
        N = xin.shape[0]
        # batch rows pad to the INPUT dtype's sublane tile minimum —
        # (8,128) f32 / (16,128) bf16 / (32,128) int8 (PALLAS_NOTES.md);
        # an explicit block_rows (autotune knob) is itself rounded to
        # that multiple and the batch pads up to a whole block count
        n_pad, bn = _pad_plan(N, xin.dtype, block_rows)
        if n_pad != N:
            xin = jnp.pad(xin, ((0, n_pad - N), (0, 0)))
        ins = [xin, wq_t, scale_row]
        in_specs = [
            pl.BlockSpec((bn, K), lambda n: (n, 0)),
            pl.BlockSpec((K, O), lambda n: (0, 0)),
            pl.BlockSpec((1, O), lambda n: (0, 0)),
        ]
        if has_bias:
            ins.append(bias_row)
            in_specs.append(pl.BlockSpec((1, O), lambda n: (0, 0)))
            kern = functools.partial(_kernel_bias, mode=mode)
        else:
            kern = functools.partial(_kernel_nobias, mode=mode)
        out = pl.pallas_call(
            kern,
            grid=(n_pad // bn,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((bn, O), lambda n: (n, 0)),
            out_shape=jax.ShapeDtypeStruct((n_pad, O), jnp.float32),
            interpret=interpret,
        )(*ins)
        return out[:N]

    return run


def int8_matmul(x, wq, wscale, bias=None, *, mode: str = "weight_only",
                impl=None, workload=None, block_rows=None,
                interpret=None):
    """Quantized ``x @ wq.T (+ bias)`` — the kernel-backed inference
    primitive behind ``nn/quantized.py``.

    Args:
      x: (N, K) f32/bf16 activations.
      wq: (O, K) int8 weights (symmetric per-output-channel).
      wscale: (O,) or (O, 1) f32 per-output-channel scales.
      bias: optional (O,) f32.
      mode: ``"weight_only"`` (f32-accumulated, no activation error) or
        ``"dynamic"`` (on-the-fly int8 activations, int32 accumulate).
      impl: per-call kernel_impl override; None defers to
        ``resolve_kernel_impl`` (Engine/Config/tuned chain).
      block_rows: row-block size (autotune knob); None defers to the
        config chain (explicit ``configure()`` > env > tuned
        ``int8_gemm@backend`` entry > 0 = auto (<=128 whole-batch)).
      interpret: pallas interpret override; None = auto (True off-TPU).

    Returns f32 (N, O).  Unsupported shapes/modes silently take the
    BITWISE-identical XLA fallback (module docstring).
    """
    if mode not in MODES:
        raise ValueError(
            f"int8 activation mode must be one of {MODES}, got {mode!r}")
    from bigdl_tpu.ops import resolve_kernel_impl
    eff = resolve_kernel_impl(impl, workload)
    if block_rows is None:
        from bigdl_tpu.utils.tuned import resolve_default
        block_rows, _src = resolve_default(
            "int8_block_rows", workload=workload or "int8_gemm")
    N, K = x.shape
    O = wq.shape[0]
    wscale_f = wscale.reshape(-1).astype(jnp.float32)
    if mode == "dynamic":
        xin, xs = dyn_quantize(x)
        scale_row = (xs * wscale_f).astype(jnp.float32).reshape(1, O)
    else:
        xin = x
        scale_row = wscale_f.reshape(1, O)
    bias_row = None if bias is None \
        else bias.astype(jnp.float32).reshape(1, O)
    wq_t = wq.T
    if eff != "pallas" or not supported(N, K, O, x.dtype, mode):
        # canonical XLA path.  For shapes the kernel covers, replicate
        # the kernel's EXACT row grid (_pad_plan + one dot per block
        # via lax.map): the host gemm's f32 reduction order depends on
        # the M it is handed, so a single big gemm over the whole
        # padded batch is NOT bitwise-equal to the kernel's per-block
        # dots once the grid has >1 block (and an unpadded N=1 dot
        # lowers as a gemv with yet another order).  lax.map serializes
        # the blocks — the documented price of the bitwise-fallback
        # contract on multi-block batches; each block is still a full
        # (bn, K) x (K, O) gemm.
        if supported(N, K, O, x.dtype, mode):
            n_pad, bn = _pad_plan(N, xin.dtype, int(block_rows))
            if n_pad != N:
                xin = jnp.pad(xin, ((0, n_pad - N), (0, 0)))
            if n_pad == bn:
                return _matmul_math(xin, wq_t, scale_row, bias_row,
                                    mode)[:N]
            yb = jax.lax.map(
                lambda xb: _matmul_math(xb, wq_t, scale_row, bias_row,
                                        mode),
                xin.reshape(n_pad // bn, bn, K))
            return yb.reshape(n_pad, O)[:N]
        return _matmul_math(xin, wq_t, scale_row, bias_row, mode)
    if interpret is None:
        interpret = _interpret_default()
    fn = _gemm_fn(K, O, mode, bias is not None, int(block_rows),
                  bool(interpret))
    return fn(xin, wq_t, scale_row, bias_row)
