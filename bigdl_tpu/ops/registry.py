"""TF op implementations (forward-only), keyed by TF op name.

Reference: ``DL/nn/ops/*.scala`` — e.g. ``MatMul``, ``BiasAdd``, ``Cast``,
``OneHot``, ``Select``, ``TopK`` — and the layout notes in
``DL/utils/tf/loaders/``.  Each op here is ``fn(attrs, *inputs) -> out``
over jnp arrays; ``attrs`` is the decoded NodeDef attr dict.

Conventions: TF convs/pools default NHWC (attr ``data_format``), SAME/
VALID padding strings map straight onto lax's; reductions take the axis
tensor as a runtime input but it must be constant-foldable (the importer
feeds numpy for Const-derived inputs, so plain int conversion works under
trace).
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

OPS: Dict[str, Callable] = {}


def register_op(name: str):
    def deco(fn):
        OPS[name] = fn
        return fn
    return deco


def get_op(name: str) -> Callable:
    if name not in OPS:
        raise NotImplementedError(
            f"TF op {name!r} not implemented (bigdl_tpu.ops registry has "
            f"{len(OPS)} ops; reference analog DL/nn/ops/)")
    return OPS[name]


def _axes(axis_input) -> tuple:
    a = np.asarray(axis_input).reshape(-1)
    return tuple(int(v) for v in a)


# ------------------------------------------------------------- passthrough
@register_op("Identity")
@register_op("StopGradient")
@register_op("PreventGradient")
def _identity(attrs, x):
    return x


@register_op("Cast")
def _cast(attrs, x):
    dt = attrs.get("DstT", attrs.get("dstT", 1))
    # TF DT_DOUBLE Cast target: best-available float is intended (f32
    # when x64 is off)
    mapping = {1: jnp.float32, 2: jnp.float64, 3: jnp.int32, 9: jnp.int64,
               10: jnp.bool_, 14: jnp.bfloat16}
    return jnp.asarray(x).astype(mapping.get(int(dt), jnp.float32))


# ------------------------------------------------------------------- math
_BINOPS = {
    "Add": jnp.add, "AddV2": jnp.add, "Sub": jnp.subtract,
    "Mul": jnp.multiply, "RealDiv": jnp.divide, "Div": jnp.divide,
    "Maximum": jnp.maximum, "Minimum": jnp.minimum, "Pow": jnp.power,
    "FloorDiv": jnp.floor_divide, "Mod": jnp.mod,
    "SquaredDifference": lambda a, b: (a - b) ** 2,
    "Equal": lambda a, b: jnp.equal(a, b),
    "NotEqual": lambda a, b: jnp.not_equal(a, b),
    "Greater": jnp.greater, "GreaterEqual": jnp.greater_equal,
    "Less": jnp.less, "LessEqual": jnp.less_equal,
    "LogicalAnd": jnp.logical_and, "LogicalOr": jnp.logical_or,
}
for _name, _fn in _BINOPS.items():
    OPS[_name] = (lambda f: lambda attrs, a, b: f(a, b))(_fn)

_UNOPS = {
    "Neg": jnp.negative, "Abs": jnp.abs, "Exp": jnp.exp, "Log": jnp.log,
    "Sqrt": jnp.sqrt, "Rsqrt": lambda x: 1.0 / jnp.sqrt(x),
    "Square": jnp.square, "Floor": jnp.floor, "Ceil": jnp.ceil,
    "Round": jnp.round, "Sign": jnp.sign, "Reciprocal": jnp.reciprocal,
    "Tanh": jnp.tanh, "Sigmoid": jax.nn.sigmoid, "Relu": jax.nn.relu,
    "Relu6": lambda x: jnp.clip(x, 0.0, 6.0), "Elu": jax.nn.elu,
    "Softplus": jax.nn.softplus, "Softsign": jax.nn.soft_sign,
    "LogicalNot": jnp.logical_not, "Erf": jax.scipy.special.erf,
    "Selu": jax.nn.selu,
}
for _name, _fn in _UNOPS.items():
    OPS[_name] = (lambda f: lambda attrs, x: f(x))(_fn)


@register_op("AddN")
def _addn(attrs, *xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


@register_op("MatMul")
def _matmul(attrs, a, b):
    if attrs.get("transpose_a", False):
        a = a.T
    if attrs.get("transpose_b", False):
        b = b.T
    return a @ b


@register_op("BatchMatMul")
@register_op("BatchMatMulV2")
def _batch_matmul(attrs, a, b):
    if attrs.get("adj_x", False):
        a = jnp.swapaxes(a, -1, -2)
    if attrs.get("adj_y", False):
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


@register_op("Softmax")
def _softmax(attrs, x):
    return jax.nn.softmax(x, axis=-1)


@register_op("LogSoftmax")
def _log_softmax(attrs, x):
    return jax.nn.log_softmax(x, axis=-1)


@register_op("L2Loss")
def _l2loss(attrs, x):
    return jnp.sum(x * x) / 2.0


@register_op("Select")
@register_op("SelectV2")
def _select(attrs, c, a, b):
    return jnp.where(c, a, b)


# ------------------------------------------------------------- reductions
def _make_reduce(fn):
    def op(attrs, x, axis):
        keep = bool(attrs.get("keep_dims", attrs.get("keepdims", False)))
        ax = _axes(axis)
        if not ax and np.asarray(axis).size == 0:
            ax = tuple(range(jnp.ndim(x)))
        return fn(x, axis=ax, keepdims=keep)
    return op


OPS["Sum"] = _make_reduce(jnp.sum)
OPS["Mean"] = _make_reduce(jnp.mean)
OPS["Max"] = _make_reduce(jnp.max)
OPS["Min"] = _make_reduce(jnp.min)
OPS["Prod"] = _make_reduce(jnp.prod)
OPS["All"] = _make_reduce(jnp.all)
OPS["Any"] = _make_reduce(jnp.any)


@register_op("ArgMax")
def _argmax(attrs, x, axis):
    return jnp.argmax(x, axis=int(np.asarray(axis)))


@register_op("ArgMin")
def _argmin(attrs, x, axis):
    return jnp.argmin(x, axis=int(np.asarray(axis)))


# ------------------------------------------------------------ shape ops
@register_op("Reshape")
def _reshape(attrs, x, shape):
    return jnp.reshape(x, tuple(int(v) for v in np.asarray(shape)))


@register_op("Squeeze")
def _squeeze(attrs, x):
    dims = attrs.get("squeeze_dims", attrs.get("axis", []))
    if dims:
        return jnp.squeeze(x, axis=tuple(int(d) for d in dims))
    return jnp.squeeze(x)


@register_op("ExpandDims")
def _expand_dims(attrs, x, axis):
    return jnp.expand_dims(x, int(np.asarray(axis)))


@register_op("Shape")
def _shape(attrs, x):
    return jnp.asarray(x.shape, jnp.int32)


@register_op("Rank")
def _rank(attrs, x):
    return jnp.asarray(jnp.ndim(x), jnp.int32)


@register_op("Size")
def _size(attrs, x):
    return jnp.asarray(jnp.size(x), jnp.int32)


@register_op("Fill")
def _fill(attrs, shape, value):
    return jnp.full(tuple(int(v) for v in np.asarray(shape)),
                    jnp.asarray(value))


@register_op("Pack")
def _pack(attrs, *xs):
    return jnp.stack(xs, axis=int(attrs.get("axis", 0)))


@register_op("Unpack")
def _unpack(attrs, x):
    return tuple(jnp.moveaxis(x, int(attrs.get("axis", 0)), 0))


@register_op("ConcatV2")
def _concat_v2(attrs, *args):
    *xs, axis = args
    return jnp.concatenate(xs, axis=int(np.asarray(axis)))


@register_op("Concat")
def _concat(attrs, axis, *xs):
    return jnp.concatenate(xs, axis=int(np.asarray(axis)))


@register_op("Slice")
def _slice(attrs, x, begin, size):
    begin = [int(v) for v in np.asarray(begin)]
    size = [int(v) for v in np.asarray(size)]
    size = [x.shape[i] - begin[i] if s == -1 else s
            for i, s in enumerate(size)]
    return lax.slice(x, begin, [b + s for b, s in zip(begin, size)])


@register_op("StridedSlice")
def _strided_slice(attrs, x, begin, end, strides):
    # basic masks only (begin/end masks as bit fields)
    if int(attrs.get("ellipsis_mask", 0)) or \
            int(attrs.get("new_axis_mask", 0)):
        raise NotImplementedError(
            "StridedSlice ellipsis_mask/new_axis_mask not supported")
    begin = [int(v) for v in np.asarray(begin)]
    end = [int(v) for v in np.asarray(end)]
    strides = [int(v) for v in np.asarray(strides)]
    bm = int(attrs.get("begin_mask", 0))
    em = int(attrs.get("end_mask", 0))
    sa = int(attrs.get("shrink_axis_mask", 0))
    idx = []
    for i in range(len(begin)):
        b = None if (bm >> i) & 1 else begin[i]
        e = None if (em >> i) & 1 else end[i]
        if (sa >> i) & 1:
            idx.append(begin[i])
        else:
            idx.append(slice(b, e, strides[i]))
    return x[tuple(idx)]


@register_op("Transpose")
def _transpose(attrs, x, perm):
    return jnp.transpose(x, tuple(int(v) for v in np.asarray(perm)))


@register_op("Pad")
@register_op("PadV2")
def _pad(attrs, x, paddings, *rest):
    pads = [(int(a), int(b)) for a, b in np.asarray(paddings)]
    cv = float(np.asarray(rest[0])) if rest else 0.0
    return jnp.pad(x, pads, constant_values=cv)


@register_op("Tile")
def _tile(attrs, x, multiples):
    return jnp.tile(x, tuple(int(v) for v in np.asarray(multiples)))


@register_op("GatherV2")
@register_op("Gather")
def _gather(attrs, params, indices, *axis):
    ax = int(np.asarray(axis[0])) if axis else 0
    return jnp.take(params, jnp.asarray(indices).astype(jnp.int32), axis=ax)


@register_op("OneHot")
def _one_hot(attrs, indices, depth, on_value, off_value):
    d = int(np.asarray(depth))
    on = jnp.asarray(on_value)
    off = jnp.asarray(off_value)
    oh = jax.nn.one_hot(jnp.asarray(indices).astype(jnp.int32), d)
    return oh * on + (1.0 - oh) * off


# --------------------------------------------------------- nn/image ops
def _data_format(attrs) -> str:
    df = attrs.get("data_format", b"NHWC")
    if isinstance(df, bytes):
        df = df.decode()
    return df or "NHWC"


@register_op("BiasAdd")
def _bias_add(attrs, x, b):
    if _data_format(attrs) == "NCHW" and jnp.ndim(x) == 4:
        return x + b[None, :, None, None]
    return x + b


# the TF-0.x name: no data_format attr, always channel-last broadcast
# (reference loaders/BiasAddV1.scala:27 → same BiasAddOp)
OPS["BiasAddV1"] = lambda attrs, x, b: x + b


@register_op("Conv2D")
def _conv2d(attrs, x, w):
    # w: HWIO (TF kernel layout)
    df = _data_format(attrs)
    strides = [int(s) for s in attrs.get("strides", [1, 1, 1, 1])]
    dil = [int(d) for d in attrs.get("dilations", [1, 1, 1, 1])]
    pad = attrs.get("padding", b"SAME")
    pad = pad.decode() if isinstance(pad, bytes) else pad
    if df == "NHWC":
        dn = ("NHWC", "HWIO", "NHWC")
        ws, rd = (strides[1], strides[2]), (dil[1], dil[2])
    else:
        dn = ("NCHW", "HWIO", "NCHW")
        ws, rd = (strides[2], strides[3]), (dil[2], dil[3])
    return lax.conv_general_dilated(x, w, window_strides=ws, padding=pad,
                                    rhs_dilation=rd,
                                    dimension_numbers=dn)


@register_op("DepthwiseConv2dNative")
def _depthwise_conv(attrs, x, w):
    df = _data_format(attrs)
    strides = [int(s) for s in attrs.get("strides", [1, 1, 1, 1])]
    dil = [int(d) for d in attrs.get("dilations", [1, 1, 1, 1])]
    pad = attrs.get("padding", b"SAME")
    pad = pad.decode() if isinstance(pad, bytes) else pad
    H, W, C, M = w.shape
    w2 = jnp.reshape(jnp.transpose(w, (0, 1, 3, 2)), (H, W, 1, C * M))
    if df == "NHWC":
        dn = ("NHWC", "HWIO", "NHWC")
        ws, rd = (strides[1], strides[2]), (dil[1], dil[2])
    else:
        dn = ("NCHW", "HWIO", "NCHW")
        ws, rd = (strides[2], strides[3]), (dil[2], dil[3])
    return lax.conv_general_dilated(x, w2, window_strides=ws, padding=pad,
                                    rhs_dilation=rd,
                                    dimension_numbers=dn,
                                    feature_group_count=C)


def _pool(attrs, x, reducer, init, avg=False):
    # ksize/strides already arrive in the graph's data-format order, so
    # no layout branch is needed
    ks = [int(v) for v in attrs.get("ksize", [1, 2, 2, 1])]
    st = [int(v) for v in attrs.get("strides", [1, 2, 2, 1])]
    pad = attrs.get("padding", b"VALID")
    pad = pad.decode() if isinstance(pad, bytes) else pad
    dims, strides = tuple(ks), tuple(st)
    out = lax.reduce_window(x, init, reducer, dims, strides, pad)
    if avg:
        ones = jnp.ones_like(x)
        cnt = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pad)
        out = out / cnt
    return out


@register_op("MaxPool")
def _max_pool(attrs, x):
    return _pool(attrs, x, lax.max, -jnp.inf)


@register_op("AvgPool")
def _avg_pool(attrs, x):
    return _pool(attrs, x, lax.add, 0.0, avg=True)


@register_op("FusedBatchNorm")
@register_op("FusedBatchNormV2")
@register_op("FusedBatchNormV3")
def _fused_bn(attrs, x, scale, offset, mean, var):
    eps = float(attrs.get("epsilon", 1e-3))
    df = _data_format(attrs)
    if df == "NCHW":
        shape = (1, -1, 1, 1)
    else:
        shape = (1, 1, 1, -1)
    inv = 1.0 / jnp.sqrt(var + eps)
    return ((x - mean.reshape(shape)) * inv.reshape(shape)
            * scale.reshape(shape) + offset.reshape(shape))


@register_op("SoftmaxCrossEntropyWithLogits")
def _softmax_ce(attrs, logits, labels):
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.sum(labels * logp, axis=-1)


# -------------------------------------------------------------- random ops
def _op_key(attrs) -> jax.Array:
    """Deterministic key from the node's seed attrs AND its graph name
    (the executor injects ``_node_name``): TF graphs usually leave
    seed/seed2 at 0, and identical keys would give every same-shape
    random-init variable byte-identical weights (symmetric branches).
    Reference ``DL/nn/ops/RandomUniform`` similarly seeds per node."""
    import zlib
    s = int(attrs.get("seed", 0)) * 2654435761 + int(attrs.get("seed2", 0))
    s ^= zlib.crc32(str(attrs.get("_node_name", "")).encode())
    return jax.random.PRNGKey(s & 0x7FFFFFFF)


@register_op("RandomUniform")
def _random_uniform(attrs, shape):
    return jax.random.uniform(_op_key(attrs),
                              tuple(int(v) for v in np.asarray(shape)))


@register_op("RandomStandardNormal")
def _random_normal(attrs, shape):
    return jax.random.normal(_op_key(attrs),
                             tuple(int(v) for v in np.asarray(shape)))


@register_op("TruncatedNormal")
def _truncated_normal(attrs, shape):
    return jax.random.truncated_normal(
        _op_key(attrs), -2.0, 2.0, tuple(int(v) for v in np.asarray(shape)))


# ----------------------------------------------------- r3 op-surface sweep
# (reference loaders DL/utils/tf/loaders/ — VERDICT r2 missing #2)
_UNOPS_R3 = {
    "Log1p": jnp.log1p, "Expm1": jnp.expm1,
    "Erfc": jax.scipy.special.erfc,
    "Lgamma": jax.scipy.special.gammaln,
    "Digamma": jax.scipy.special.digamma,
    "IsNan": jnp.isnan, "IsInf": jnp.isinf, "IsFinite": jnp.isfinite,
    "Rint": jnp.rint, "Sin": jnp.sin, "Cos": jnp.cos, "Tan": jnp.tan,
    "Asin": jnp.arcsin, "Acos": jnp.arccos, "Atan": jnp.arctan,
    "Sinh": jnp.sinh, "Cosh": jnp.cosh,
    "Inv": jnp.reciprocal,
}
for _name, _fn in _UNOPS_R3.items():
    OPS[_name] = (lambda f: lambda attrs, x: f(x))(_fn)
OPS["TruncateDiv"] = lambda attrs, a, b: jnp.trunc(a / b).astype(
    jnp.result_type(a, b))
OPS["TruncateMod"] = lambda attrs, a, b: jnp.fmod(a, b)
# TF FloorMod is floored modulo — result takes the divisor's sign,
# exactly jnp.mod (reference loaders/FloorMod.scala:28 → FloorModOps)
OPS["FloorMod"] = lambda attrs, a, b: jnp.mod(a, b)


@register_op("Range")
def _range(attrs, start, limit, delta):
    # shape is value-dependent: inputs must be const-foldable (the
    # importer feeds numpy for Const-derived inputs)
    return jnp.arange(np.asarray(start).item(), np.asarray(limit).item(),
                      np.asarray(delta).item())


@register_op("LinSpace")
def _linspace(attrs, start, stop, num):
    return jnp.linspace(np.asarray(start).item(), np.asarray(stop).item(),
                        int(np.asarray(num)))


@register_op("TopK")
@register_op("TopKV2")
def _top_k(attrs, x, *k):
    kk = int(np.asarray(k[0])) if k else int(attrs.get("k", 1))
    vals, idx = lax.top_k(x, kk)
    if not bool(attrs.get("sorted", True)):
        pass  # lax.top_k is always sorted; sorted=False allows any order
    return vals, idx.astype(jnp.int32)


@register_op("InTopK")
@register_op("InTopKV2")
def _in_top_k(attrs, predictions, targets, *k):
    kk = int(np.asarray(k[0])) if k else int(attrs.get("k", 1))
    # TF semantics: target is in top-k if fewer than k classes score
    # strictly higher (ties broken in the target's favor)
    tgt = jnp.take_along_axis(
        predictions, jnp.asarray(targets).astype(jnp.int32)[:, None],
        axis=1)
    higher = jnp.sum(predictions > tgt, axis=1)
    # TF returns False ("cannot say") when ANY prediction in the row
    # is non-finite, not just the target's
    return (higher < kk) & jnp.all(jnp.isfinite(predictions), axis=1)


@register_op("Split")
def _split(attrs, axis, value):
    n = int(attrs.get("num_split", 1))
    return tuple(jnp.split(value, n, axis=int(np.asarray(axis))))


@register_op("SplitV")
def _split_v(attrs, value, size_splits, axis):
    sizes = [int(v) for v in np.asarray(size_splits)]
    ax = int(np.asarray(axis))
    if -1 in sizes:
        rest = value.shape[ax] - sum(s for s in sizes if s >= 0)
        sizes = [rest if s == -1 else s for s in sizes]
    splits = np.cumsum(sizes)[:-1]
    return tuple(jnp.split(value, splits, axis=ax))


@register_op("SegmentSum")
def _segment_sum(attrs, data, segment_ids):
    ids = np.asarray(segment_ids)  # must be const-foldable (shape dep.)
    num = int(ids.max()) + 1 if ids.size else 0
    return jax.ops.segment_sum(jnp.asarray(data), jnp.asarray(ids), num)


@register_op("UnsortedSegmentSum")
def _unsorted_segment_sum(attrs, data, segment_ids, num_segments):
    return jax.ops.segment_sum(jnp.asarray(data),
                               jnp.asarray(segment_ids).reshape(-1)
                               if jnp.ndim(data) == 1 else
                               jnp.asarray(segment_ids),
                               int(np.asarray(num_segments)))


@register_op("Cumsum")
def _cumsum(attrs, x, axis):
    ax = int(np.asarray(axis))
    rev = bool(attrs.get("reverse", False))
    ex = bool(attrs.get("exclusive", False))
    if rev:
        x = jnp.flip(x, ax)
    out = jnp.cumsum(x, axis=ax)
    if ex:
        out = out - x
    if rev:
        out = jnp.flip(out, ax)
    return out


@register_op("LRN")
def _lrn(attrs, x):
    # TF LRN is NHWC-only; denom = (bias + alpha*sqsum)^beta with alpha
    # NOT pre-divided by the window size (unlike torch)
    dr = int(attrs.get("depth_radius", 5))
    bias = float(attrs.get("bias", 1.0))
    alpha = float(attrs.get("alpha", 1.0))
    beta = float(attrs.get("beta", 0.5))
    size = 2 * dr + 1
    sq = x * x
    acc = lax.reduce_window(
        sq, 0.0, lax.add, window_dimensions=(1, 1, 1, size),
        window_strides=(1, 1, 1, 1),
        padding=((0, 0), (0, 0), (0, 0), (dr, dr)))
    return x / jnp.power(bias + alpha * acc, beta)


@register_op("Conv3D")
def _conv3d(attrs, x, w):
    # w: DHWIO (TF 3-D kernel layout); x NDHWC (TF Conv3D default)
    strides = [int(s) for s in attrs.get("strides", [1, 1, 1, 1, 1])]
    pad = attrs.get("padding", b"SAME")
    pad = pad.decode() if isinstance(pad, bytes) else pad
    dn = ("NDHWC", "DHWIO", "NDHWC")
    return lax.conv_general_dilated(
        x, w, window_strides=tuple(strides[1:4]), padding=pad,
        dimension_numbers=dn)


@register_op("ResizeBilinear")
def _resize_bilinear(attrs, x, size):
    """TF1 coordinate semantics: src = dst*scale (align_corners=False,
    the default) or src = dst*(in-1)/(out-1) (align_corners=True) — NOT
    jax.image.resize's half-pixel centers."""
    out_h, out_w = (int(v) for v in np.asarray(size))
    n, in_h, in_w, c = x.shape
    align = bool(attrs.get("align_corners", False))
    x = jnp.asarray(x, jnp.float32)  # TF always returns float32

    def coords(out_n, in_n):
        if align and out_n > 1:
            return jnp.arange(out_n) * ((in_n - 1) / (out_n - 1))
        return jnp.arange(out_n) * (in_n / out_n)

    def interp(v, src, axis, in_n):
        lo = jnp.clip(jnp.floor(src).astype(jnp.int32), 0, in_n - 1)
        hi = jnp.clip(lo + 1, 0, in_n - 1)
        frac = (src - lo).astype(v.dtype)
        shape = [1] * v.ndim
        shape[axis] = -1
        a = jnp.take(v, lo, axis=axis)
        b = jnp.take(v, hi, axis=axis)
        return a + (b - a) * frac.reshape(shape)

    y = interp(x, coords(out_h, in_h), 1, in_h)
    return interp(y, coords(out_w, in_w), 2, in_w)


@register_op("ResizeNearestNeighbor")
def _resize_nn(attrs, x, size):
    out_h, out_w = (int(v) for v in np.asarray(size))
    n, in_h, in_w, c = x.shape
    align = bool(attrs.get("align_corners", False))

    def idx(out_n, in_n):
        if align and out_n > 1:
            return jnp.round(jnp.arange(out_n)
                             * ((in_n - 1) / (out_n - 1))).astype(jnp.int32)
        return jnp.floor(jnp.arange(out_n)
                         * (in_n / out_n)).astype(jnp.int32)

    y = jnp.take(x, jnp.clip(idx(out_h, in_h), 0, in_h - 1), axis=1)
    return jnp.take(y, jnp.clip(idx(out_w, in_w), 0, in_w - 1), axis=2)


@register_op("ReverseV2")
def _reverse_v2(attrs, x, axis):
    return jnp.flip(x, _axes(axis))


@register_op("InvertPermutation")
def _invert_permutation(attrs, x):
    return jnp.argsort(jnp.asarray(x)).astype(jnp.int32)


@register_op("Where")
def _where(attrs, c):
    # value-dependent shape: const-foldable input required
    return jnp.asarray(np.argwhere(np.asarray(c)), jnp.int64)


# ----------------------------------------------- host-side decode/parsing
# These run EAGERLY over numpy/bytes (input-pipeline ops; not jittable) —
# the reference analogs (loaders/DecodeJpeg.scala, ParsingOps.scala) are
# likewise CPU-side graph sources.
def _to_bytes_list(x):
    if isinstance(x, (bytes, bytearray)):
        return [bytes(x)]
    arr = np.asarray(x, dtype=object).reshape(-1)
    return [bytes(v) for v in arr]


# TF DataType enum → numpy dtype (one map for every op that reads a
# dtype/out_type attr)
# wire-format enum: DT_DOUBLE must map to f64 here, consumers downcast
_TF_DT_NP = {1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8,
             5: np.int16, 6: np.int8, 9: np.int64, 10: np.bool_,
             14: jnp.bfloat16, 17: np.uint16, 19: np.float16,
             22: np.uint32}


@register_op("DecodeRaw")
def _decode_raw(attrs, data):
    dt = int(attrs.get("out_type", 1))
    if dt not in _TF_DT_NP:
        raise NotImplementedError(f"DecodeRaw out_type {dt}")
    dtype = np.dtype(_TF_DT_NP[dt])
    if not bool(attrs.get("little_endian", True)) and dtype.itemsize > 1:
        dtype = dtype.newbyteorder(">")
    payloads = _to_bytes_list(data)
    out = [np.frombuffer(p, dtype=dtype) for p in payloads]
    return np.stack(out) if len(out) > 1 else out[0]


def _decode_image(attrs, contents, channels_default=0):
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover - PIL is in the image
        raise NotImplementedError(
            "DecodeJpeg/DecodePng need Pillow") from e
    import io
    channels = int(attrs.get("channels", channels_default))
    img = Image.open(io.BytesIO(_to_bytes_list(contents)[0]))
    if channels == 0:
        # TF default: preserve the source image's channel count
        channels = {"L": 1, "LA": 2, "RGBA": 4}.get(img.mode, 3)
    mode = {1: "L", 2: "LA", 3: "RGB", 4: "RGBA"}.get(channels)
    if mode is None:
        raise NotImplementedError(f"decode with channels={channels}")
    arr = np.asarray(img.convert(mode), np.uint8)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


@register_op("DecodeJpeg")
def _decode_jpeg(attrs, contents):
    return _decode_image(attrs, contents)


@register_op("DecodePng")
def _decode_png(attrs, contents):
    return _decode_image(attrs, contents)


@register_op("DecodeImage")
def _decode_any_image(attrs, contents):
    """Format-sniffing decode (TF DecodeImage); PIL sniffs the container
    itself.  GIF payloads come back (frames, H, W, C) like TF unless
    ``expand_animations=False`` (first frame, rank 3).  ``dtype``
    converts like TF's convert_image_dtype (uint8 ints, [0,1] floats)."""
    data = _to_bytes_list(contents)[0]
    if data[:6] in (b"GIF87a", b"GIF89a"):
        out = _decode_gif(attrs, data)
        if not bool(attrs.get("expand_animations", True)):
            out = out[0]
    else:
        out = _decode_image(attrs, data)
    dt = int(attrs.get("dtype", 4))  # DT_UINT8=4
    if dt in (1, 2, 19):             # float32/float64/half → [0, 1]
        # DecodeImage honors the graph's requested wire dtype (host-side
        # image decode, converted on feed)
        out = (out.astype({1: np.float32, 2: np.float64,
                           19: np.float16}[dt]) / 255.0)
    elif dt != 4:
        raise NotImplementedError(f"DecodeImage dtype {dt}")
    return out


@register_op("DecodeGif")
def _decode_gif(attrs, contents):
    """All frames, (num_frames, H, W, 3) uint8 (TF DecodeGif)."""
    from PIL import Image, ImageSequence
    import io
    img = Image.open(io.BytesIO(_to_bytes_list(contents)[0]))
    frames = [np.asarray(f.convert("RGB"), np.uint8)
              for f in ImageSequence.Iterator(img)]
    return np.stack(frames)


@register_op("ApproximateEqual")
def _approximate_equal(attrs, x, y):
    tol = float(attrs.get("tolerance", 1e-5))
    return jnp.abs(x - y) < tol


@register_op("Dilation2D")
def _dilation2d(attrs, input, filter):
    """Grayscale morphological dilation (TF Dilation2D; reference
    loader ``utils/tf/loaders/Dilation2D``): per channel,
    out[b,y,x,c] = max_{dy,dx} input[b, y*s+dy*r, x*s+dx*r, c]
    + filter[dy,dx,c].  NHWC only, like TF."""
    strides = [int(v) for v in attrs.get("strides", [1, 1, 1, 1])]
    rates = [int(v) for v in attrs.get("rates", [1, 1, 1, 1])]
    padding = attrs.get("padding", b"SAME")
    padding = padding.decode() if isinstance(padding, bytes) else padding
    N, H, W, C = input.shape
    KH, KW, _ = filter.shape
    sh, sw = strides[1], strides[2]
    rh, rw = rates[1], rates[2]
    eff_kh, eff_kw = (KH - 1) * rh + 1, (KW - 1) * rw + 1
    if padding == "SAME":
        OH, OW = -(-H // sh), -(-W // sw)
        ph = max((OH - 1) * sh + eff_kh - H, 0)
        pw = max((OW - 1) * sw + eff_kw - W, 0)
        pads = ((ph // 2, ph - ph // 2), (pw // 2, pw - pw // 2))
    else:
        OH = (H - eff_kh) // sh + 1
        OW = (W - eff_kw) // sw + 1
        pads = ((0, 0), (0, 0))
    xp = jnp.pad(input, ((0, 0), pads[0], pads[1], (0, 0)),
                 constant_values=-jnp.inf)
    out = None
    for dy in range(KH):
        for dx in range(KW):
            win = lax.slice(
                xp, (0, dy * rh, dx * rw, 0),
                (N, dy * rh + (OH - 1) * sh + 1,
                 dx * rw + (OW - 1) * sw + 1, C),
                (1, sh, sw, 1))
            cand = win + filter[dy, dx]
            out = cand if out is None else jnp.maximum(out, cand)
    return out


@register_op("RandomShuffle")
def _random_shuffle(attrs, value):
    """Shuffle along dim 0 (TF RandomShuffle), seeded from the node's
    seed attrs + name like the other random ops (``_op_key``)."""
    return jax.random.permutation(_op_key(attrs), value, axis=0)


@register_op("Substr")
def _substr(attrs, input, pos, length):
    """Substring of byte strings (TF Substr; host-side, strings never
    enter device code)."""
    shape = np.shape(input)
    flat = np.asarray(input, object).reshape(-1)
    p = np.broadcast_to(np.asarray(pos), shape).reshape(-1)
    n = np.broadcast_to(np.asarray(length), shape).reshape(-1)
    out = []
    for s, pi, ni in zip(flat, p, n):
        b = s if isinstance(s, bytes) else str(s).encode()
        out.append(b[int(pi):int(pi) + int(ni)])
    return np.asarray(out, object).reshape(np.shape(input))


@register_op("Assert")
def _assert(attrs, condition, *data):
    """TF Assert: under jit a data-dependent host assert cannot fire;
    the op is a no-op pass-through (use BIGDL_TPU_DEBUG_NANS for
    numeric sanitizing).  Eager numpy inputs DO check."""
    c = np.asarray(condition) if not hasattr(condition, "aval") else None
    if c is not None and not bool(c.all()):
        raise AssertionError(
            f"imported TF Assert failed: {[np.asarray(d) for d in data]}")
    return condition


@register_op("NoOp")
def _noop(attrs):
    return ()


# --------------------------------------------------------- TensorArray
# (reference ``DL/nn/tf/DataFlowOps.scala``: TensorArray read/write/
# gather/scatter used by dynamic-RNN exports.)
#
# TPU redesign: a TensorArray IS its storage.  The op family threads a
# "flow" value; here the flow VALUE is the (size, *elem) stacked array,
# so writes are functional .at[].set updates and the array can be a
# loop-carried variable of the imported while frame.  Element shape is
# unknown until the first write — ``TAPending`` defers allocation, and
# the frame executor (tf_format._run_frame) probes the loop body once
# to resolve pending flows into zero-initialised storage.


class TAHandle:
    """Opaque handle value of TensorArrayV3:0 (size/dtype metadata)."""

    __slots__ = ("name", "size", "dtype")

    def __init__(self, name, size, dtype):
        self.name, self.size, self.dtype = name, size, dtype


class TAPending:
    """Flow of a TensorArray whose element shape is not yet known."""

    __slots__ = ("size", "dtype")

    def __init__(self, size, dtype):
        self.size, self.dtype = size, dtype


def _ta_alloc(flow, value, leading_from_value=False):
    if not isinstance(flow, TAPending):
        return flow
    elem = value.shape[1:] if leading_from_value else value.shape
    return jnp.zeros((flow.size,) + tuple(elem), value.dtype)


@register_op("TensorArrayV3")
def _tensor_array(attrs, size):
    size = int(np.asarray(size))
    dt = _TF_DT_NP.get(int(attrs.get("dtype", 1)), np.float32)
    return (TAHandle(attrs.get("_node_name"), size, dt),
            TAPending(size, dt))


@register_op("TensorArrayWriteV3")
def _ta_write(attrs, handle, index, value, flow):
    flow = _ta_alloc(flow, value)
    return flow.at[jnp.asarray(index)].set(value)


@register_op("TensorArrayReadV3")
def _ta_read(attrs, handle, index, flow):
    if isinstance(flow, TAPending):
        raise NotImplementedError(
            "TensorArrayReadV3 before any write: element shape unknown")
    return jnp.take(flow, jnp.asarray(index), axis=0)


@register_op("TensorArrayGatherV3")
def _ta_gather(attrs, handle, indices, flow):
    if isinstance(flow, TAPending):
        raise NotImplementedError(
            "TensorArrayGatherV3 before any write: element shape unknown")
    return jnp.take(flow, jnp.asarray(indices).astype(jnp.int32), axis=0)


@register_op("TensorArrayScatterV3")
def _ta_scatter(attrs, handle, indices, value, flow):
    flow = _ta_alloc(flow, value, leading_from_value=True)
    return flow.at[jnp.asarray(indices).astype(jnp.int32)].set(value)


@register_op("TensorArraySizeV3")
def _ta_size(attrs, handle, flow):
    return jnp.asarray(handle.size, jnp.int32)


@register_op("TensorArrayCloseV3")
def _ta_close(attrs, handle):
    return jnp.zeros((), jnp.float32)


@register_op("ParseExample")
def _parse_example(attrs, serialized, names, *keys_and_defaults):
    """Dense-feature subset of TF's ParseExample (reference
    ``ParsingOps.scala`` / ``loaders/ParseExample.scala``): inputs are
    (serialized, names, sparse_keys..., dense_keys..., dense_defaults...)
    with counts in attrs Nsparse/Ndense; returns one batched dense
    tensor per dense key.  Sparse features are not supported (the
    fixed-width id-bag sparse redesign consumes pre-batched arrays)."""
    from bigdl_tpu.dataset.tfrecord import decode_example
    n_sparse = int(attrs.get("Nsparse", 0))
    n_dense = int(attrs.get("Ndense", 0))
    if n_sparse:
        raise NotImplementedError("ParseExample sparse features")
    dense_keys = [k.decode() if isinstance(k, bytes) else str(k)
                  for k in (np.asarray(keys_and_defaults[i]).item()
                            for i in range(n_dense))]
    dense_shapes = attrs.get("dense_shapes", [()] * n_dense)
    records = _to_bytes_list(serialized)
    outs = []
    for ki, key in enumerate(dense_keys):
        rows = []
        for rec in records:
            feats = decode_example(rec)
            if key not in feats:
                raise KeyError(f"feature {key!r} missing from Example")
            v = feats[key]
            if isinstance(v, list):  # bytes feature
                v = np.asarray(v, dtype=object)
            shape = dense_shapes[ki] if ki < len(dense_shapes) else ()
            if shape:
                v = np.asarray(v).reshape(
                    [int(d) for d in np.asarray(shape).reshape(-1)])
            rows.append(v)
        outs.append(np.stack(rows))
    return tuple(outs) if len(outs) > 1 else outs[0]
