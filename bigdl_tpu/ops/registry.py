"""TF op implementations (forward-only), keyed by TF op name.

Reference: ``DL/nn/ops/*.scala`` — e.g. ``MatMul``, ``BiasAdd``, ``Cast``,
``OneHot``, ``Select``, ``TopK`` — and the layout notes in
``DL/utils/tf/loaders/``.  Each op here is ``fn(attrs, *inputs) -> out``
over jnp arrays; ``attrs`` is the decoded NodeDef attr dict.

Conventions: TF convs/pools default NHWC (attr ``data_format``), SAME/
VALID padding strings map straight onto lax's; reductions take the axis
tensor as a runtime input but it must be constant-foldable (the importer
feeds numpy for Const-derived inputs, so plain int conversion works under
trace).
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

OPS: Dict[str, Callable] = {}


def register_op(name: str):
    def deco(fn):
        OPS[name] = fn
        return fn
    return deco


def get_op(name: str) -> Callable:
    if name not in OPS:
        raise NotImplementedError(
            f"TF op {name!r} not implemented (bigdl_tpu.ops registry has "
            f"{len(OPS)} ops; reference analog DL/nn/ops/)")
    return OPS[name]


def _axes(axis_input) -> tuple:
    a = np.asarray(axis_input).reshape(-1)
    return tuple(int(v) for v in a)


# ------------------------------------------------------------- passthrough
@register_op("Identity")
@register_op("StopGradient")
@register_op("PreventGradient")
def _identity(attrs, x):
    return x


@register_op("Cast")
def _cast(attrs, x):
    dt = attrs.get("DstT", attrs.get("dstT", 1))
    mapping = {1: jnp.float32, 2: jnp.float64, 3: jnp.int32, 9: jnp.int64,
               10: jnp.bool_, 14: jnp.bfloat16}
    return jnp.asarray(x).astype(mapping.get(int(dt), jnp.float32))


# ------------------------------------------------------------------- math
_BINOPS = {
    "Add": jnp.add, "AddV2": jnp.add, "Sub": jnp.subtract,
    "Mul": jnp.multiply, "RealDiv": jnp.divide, "Div": jnp.divide,
    "Maximum": jnp.maximum, "Minimum": jnp.minimum, "Pow": jnp.power,
    "FloorDiv": jnp.floor_divide, "Mod": jnp.mod,
    "SquaredDifference": lambda a, b: (a - b) ** 2,
    "Equal": lambda a, b: jnp.equal(a, b),
    "NotEqual": lambda a, b: jnp.not_equal(a, b),
    "Greater": jnp.greater, "GreaterEqual": jnp.greater_equal,
    "Less": jnp.less, "LessEqual": jnp.less_equal,
    "LogicalAnd": jnp.logical_and, "LogicalOr": jnp.logical_or,
}
for _name, _fn in _BINOPS.items():
    OPS[_name] = (lambda f: lambda attrs, a, b: f(a, b))(_fn)

_UNOPS = {
    "Neg": jnp.negative, "Abs": jnp.abs, "Exp": jnp.exp, "Log": jnp.log,
    "Sqrt": jnp.sqrt, "Rsqrt": lambda x: 1.0 / jnp.sqrt(x),
    "Square": jnp.square, "Floor": jnp.floor, "Ceil": jnp.ceil,
    "Round": jnp.round, "Sign": jnp.sign, "Reciprocal": jnp.reciprocal,
    "Tanh": jnp.tanh, "Sigmoid": jax.nn.sigmoid, "Relu": jax.nn.relu,
    "Relu6": lambda x: jnp.clip(x, 0.0, 6.0), "Elu": jax.nn.elu,
    "Softplus": jax.nn.softplus, "Softsign": jax.nn.soft_sign,
    "LogicalNot": jnp.logical_not, "Erf": jax.scipy.special.erf,
    "Selu": jax.nn.selu,
}
for _name, _fn in _UNOPS.items():
    OPS[_name] = (lambda f: lambda attrs, x: f(x))(_fn)


@register_op("AddN")
def _addn(attrs, *xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


@register_op("MatMul")
def _matmul(attrs, a, b):
    if attrs.get("transpose_a", False):
        a = a.T
    if attrs.get("transpose_b", False):
        b = b.T
    return a @ b


@register_op("BatchMatMul")
@register_op("BatchMatMulV2")
def _batch_matmul(attrs, a, b):
    if attrs.get("adj_x", False):
        a = jnp.swapaxes(a, -1, -2)
    if attrs.get("adj_y", False):
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


@register_op("Softmax")
def _softmax(attrs, x):
    return jax.nn.softmax(x, axis=-1)


@register_op("LogSoftmax")
def _log_softmax(attrs, x):
    return jax.nn.log_softmax(x, axis=-1)


@register_op("L2Loss")
def _l2loss(attrs, x):
    return jnp.sum(x * x) / 2.0


@register_op("Select")
@register_op("SelectV2")
def _select(attrs, c, a, b):
    return jnp.where(c, a, b)


# ------------------------------------------------------------- reductions
def _make_reduce(fn):
    def op(attrs, x, axis):
        keep = bool(attrs.get("keep_dims", attrs.get("keepdims", False)))
        ax = _axes(axis)
        if not ax and np.asarray(axis).size == 0:
            ax = tuple(range(jnp.ndim(x)))
        return fn(x, axis=ax, keepdims=keep)
    return op


OPS["Sum"] = _make_reduce(jnp.sum)
OPS["Mean"] = _make_reduce(jnp.mean)
OPS["Max"] = _make_reduce(jnp.max)
OPS["Min"] = _make_reduce(jnp.min)
OPS["Prod"] = _make_reduce(jnp.prod)
OPS["All"] = _make_reduce(jnp.all)
OPS["Any"] = _make_reduce(jnp.any)


@register_op("ArgMax")
def _argmax(attrs, x, axis):
    return jnp.argmax(x, axis=int(np.asarray(axis)))


@register_op("ArgMin")
def _argmin(attrs, x, axis):
    return jnp.argmin(x, axis=int(np.asarray(axis)))


# ------------------------------------------------------------ shape ops
@register_op("Reshape")
def _reshape(attrs, x, shape):
    return jnp.reshape(x, tuple(int(v) for v in np.asarray(shape)))


@register_op("Squeeze")
def _squeeze(attrs, x):
    dims = attrs.get("squeeze_dims", attrs.get("axis", []))
    if dims:
        return jnp.squeeze(x, axis=tuple(int(d) for d in dims))
    return jnp.squeeze(x)


@register_op("ExpandDims")
def _expand_dims(attrs, x, axis):
    return jnp.expand_dims(x, int(np.asarray(axis)))


@register_op("Shape")
def _shape(attrs, x):
    return jnp.asarray(x.shape, jnp.int32)


@register_op("Rank")
def _rank(attrs, x):
    return jnp.asarray(jnp.ndim(x), jnp.int32)


@register_op("Size")
def _size(attrs, x):
    return jnp.asarray(jnp.size(x), jnp.int32)


@register_op("Fill")
def _fill(attrs, shape, value):
    return jnp.full(tuple(int(v) for v in np.asarray(shape)),
                    jnp.asarray(value))


@register_op("Pack")
def _pack(attrs, *xs):
    return jnp.stack(xs, axis=int(attrs.get("axis", 0)))


@register_op("Unpack")
def _unpack(attrs, x):
    return tuple(jnp.moveaxis(x, int(attrs.get("axis", 0)), 0))


@register_op("ConcatV2")
def _concat_v2(attrs, *args):
    *xs, axis = args
    return jnp.concatenate(xs, axis=int(np.asarray(axis)))


@register_op("Concat")
def _concat(attrs, axis, *xs):
    return jnp.concatenate(xs, axis=int(np.asarray(axis)))


@register_op("Slice")
def _slice(attrs, x, begin, size):
    begin = [int(v) for v in np.asarray(begin)]
    size = [int(v) for v in np.asarray(size)]
    size = [x.shape[i] - begin[i] if s == -1 else s
            for i, s in enumerate(size)]
    return lax.slice(x, begin, [b + s for b, s in zip(begin, size)])


@register_op("StridedSlice")
def _strided_slice(attrs, x, begin, end, strides):
    # basic masks only (begin/end masks as bit fields)
    if int(attrs.get("ellipsis_mask", 0)) or \
            int(attrs.get("new_axis_mask", 0)):
        raise NotImplementedError(
            "StridedSlice ellipsis_mask/new_axis_mask not supported")
    begin = [int(v) for v in np.asarray(begin)]
    end = [int(v) for v in np.asarray(end)]
    strides = [int(v) for v in np.asarray(strides)]
    bm = int(attrs.get("begin_mask", 0))
    em = int(attrs.get("end_mask", 0))
    sa = int(attrs.get("shrink_axis_mask", 0))
    idx = []
    for i in range(len(begin)):
        b = None if (bm >> i) & 1 else begin[i]
        e = None if (em >> i) & 1 else end[i]
        if (sa >> i) & 1:
            idx.append(begin[i])
        else:
            idx.append(slice(b, e, strides[i]))
    return x[tuple(idx)]


@register_op("Transpose")
def _transpose(attrs, x, perm):
    return jnp.transpose(x, tuple(int(v) for v in np.asarray(perm)))


@register_op("Pad")
@register_op("PadV2")
def _pad(attrs, x, paddings, *rest):
    pads = [(int(a), int(b)) for a, b in np.asarray(paddings)]
    cv = float(np.asarray(rest[0])) if rest else 0.0
    return jnp.pad(x, pads, constant_values=cv)


@register_op("Tile")
def _tile(attrs, x, multiples):
    return jnp.tile(x, tuple(int(v) for v in np.asarray(multiples)))


@register_op("GatherV2")
@register_op("Gather")
def _gather(attrs, params, indices, *axis):
    ax = int(np.asarray(axis[0])) if axis else 0
    return jnp.take(params, jnp.asarray(indices).astype(jnp.int32), axis=ax)


@register_op("OneHot")
def _one_hot(attrs, indices, depth, on_value, off_value):
    d = int(np.asarray(depth))
    on = jnp.asarray(on_value)
    off = jnp.asarray(off_value)
    oh = jax.nn.one_hot(jnp.asarray(indices).astype(jnp.int32), d)
    return oh * on + (1.0 - oh) * off


# --------------------------------------------------------- nn/image ops
def _data_format(attrs) -> str:
    df = attrs.get("data_format", b"NHWC")
    if isinstance(df, bytes):
        df = df.decode()
    return df or "NHWC"


@register_op("BiasAdd")
def _bias_add(attrs, x, b):
    if _data_format(attrs) == "NCHW" and jnp.ndim(x) == 4:
        return x + b[None, :, None, None]
    return x + b


@register_op("Conv2D")
def _conv2d(attrs, x, w):
    # w: HWIO (TF kernel layout)
    df = _data_format(attrs)
    strides = [int(s) for s in attrs.get("strides", [1, 1, 1, 1])]
    dil = [int(d) for d in attrs.get("dilations", [1, 1, 1, 1])]
    pad = attrs.get("padding", b"SAME")
    pad = pad.decode() if isinstance(pad, bytes) else pad
    if df == "NHWC":
        dn = ("NHWC", "HWIO", "NHWC")
        ws, rd = (strides[1], strides[2]), (dil[1], dil[2])
    else:
        dn = ("NCHW", "HWIO", "NCHW")
        ws, rd = (strides[2], strides[3]), (dil[2], dil[3])
    return lax.conv_general_dilated(x, w, window_strides=ws, padding=pad,
                                    rhs_dilation=rd,
                                    dimension_numbers=dn)


@register_op("DepthwiseConv2dNative")
def _depthwise_conv(attrs, x, w):
    df = _data_format(attrs)
    strides = [int(s) for s in attrs.get("strides", [1, 1, 1, 1])]
    dil = [int(d) for d in attrs.get("dilations", [1, 1, 1, 1])]
    pad = attrs.get("padding", b"SAME")
    pad = pad.decode() if isinstance(pad, bytes) else pad
    H, W, C, M = w.shape
    w2 = jnp.reshape(jnp.transpose(w, (0, 1, 3, 2)), (H, W, 1, C * M))
    if df == "NHWC":
        dn = ("NHWC", "HWIO", "NHWC")
        ws, rd = (strides[1], strides[2]), (dil[1], dil[2])
    else:
        dn = ("NCHW", "HWIO", "NCHW")
        ws, rd = (strides[2], strides[3]), (dil[2], dil[3])
    return lax.conv_general_dilated(x, w2, window_strides=ws, padding=pad,
                                    rhs_dilation=rd,
                                    dimension_numbers=dn,
                                    feature_group_count=C)


def _pool(attrs, x, reducer, init, avg=False):
    # ksize/strides already arrive in the graph's data-format order, so
    # no layout branch is needed
    ks = [int(v) for v in attrs.get("ksize", [1, 2, 2, 1])]
    st = [int(v) for v in attrs.get("strides", [1, 2, 2, 1])]
    pad = attrs.get("padding", b"VALID")
    pad = pad.decode() if isinstance(pad, bytes) else pad
    dims, strides = tuple(ks), tuple(st)
    out = lax.reduce_window(x, init, reducer, dims, strides, pad)
    if avg:
        ones = jnp.ones_like(x)
        cnt = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pad)
        out = out / cnt
    return out


@register_op("MaxPool")
def _max_pool(attrs, x):
    return _pool(attrs, x, lax.max, -jnp.inf)


@register_op("AvgPool")
def _avg_pool(attrs, x):
    return _pool(attrs, x, lax.add, 0.0, avg=True)


@register_op("FusedBatchNorm")
@register_op("FusedBatchNormV2")
@register_op("FusedBatchNormV3")
def _fused_bn(attrs, x, scale, offset, mean, var):
    eps = float(attrs.get("epsilon", 1e-3))
    df = _data_format(attrs)
    if df == "NCHW":
        shape = (1, -1, 1, 1)
    else:
        shape = (1, 1, 1, -1)
    inv = 1.0 / jnp.sqrt(var + eps)
    return ((x - mean.reshape(shape)) * inv.reshape(shape)
            * scale.reshape(shape) + offset.reshape(shape))


@register_op("SoftmaxCrossEntropyWithLogits")
def _softmax_ce(attrs, logits, labels):
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.sum(labels * logp, axis=-1)


# -------------------------------------------------------------- random ops
def _op_key(attrs) -> jax.Array:
    """Deterministic key from the node's seed attrs AND its graph name
    (the executor injects ``_node_name``): TF graphs usually leave
    seed/seed2 at 0, and identical keys would give every same-shape
    random-init variable byte-identical weights (symmetric branches).
    Reference ``DL/nn/ops/RandomUniform`` similarly seeds per node."""
    import zlib
    s = int(attrs.get("seed", 0)) * 2654435761 + int(attrs.get("seed2", 0))
    s ^= zlib.crc32(str(attrs.get("_node_name", "")).encode())
    return jax.random.PRNGKey(s & 0x7FFFFFFF)


@register_op("RandomUniform")
def _random_uniform(attrs, shape):
    return jax.random.uniform(_op_key(attrs),
                              tuple(int(v) for v in np.asarray(shape)))


@register_op("RandomStandardNormal")
def _random_normal(attrs, shape):
    return jax.random.normal(_op_key(attrs),
                             tuple(int(v) for v in np.asarray(shape)))


@register_op("TruncatedNormal")
def _truncated_normal(attrs, shape):
    return jax.random.truncated_normal(
        _op_key(attrs), -2.0, 2.0, tuple(int(v) for v in np.asarray(shape)))
