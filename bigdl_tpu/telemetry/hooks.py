"""DriverTelemetry — the bundle the training driver carries.

One object holding the tracer, the metric registry, and the three
watchdogs, so ``Optimizer._train_driver`` stays readable: every
telemetry call site in the driver is ``tel.<thing>`` behind a single
``if tel is not None`` discipline (the driver holds ``None`` when
telemetry is off — the off path is UNTOUCHED, which is half of the
inertness proof; the other half is that the on path only reads clocks).

Round 2 (the admin-plane PR): the bundle also carries the run's
**trace context** — one ``trace_id`` minted per training run, stamped
on checkpoint commits, rollbacks, numeric-guard and preemption events
in both the tracer and the (optional) flight recorder, so a crash dump
and a trace file join into one story (``tools/obs_report.py``).
"""

from __future__ import annotations

from typing import Optional

from bigdl_tpu.telemetry.context import new_trace_id
from bigdl_tpu.telemetry.registry import MetricRegistry
from bigdl_tpu.telemetry.tracer import Tracer
from bigdl_tpu.telemetry.watchdog import (MemoryWatermark,
                                          RecompileWatchdog, StallDetector)


class DriverTelemetry:
    """Tracer + registry + watchdogs (+ run trace context) for one
    training run.

    ``registry`` defaults to a fresh :class:`MetricRegistry`; the driver
    passes its ``Metrics`` registry so phase accumulators, watchdog
    counters, and stall gauges land in ONE snapshot.  ``flight`` is the
    optional :class:`~bigdl_tpu.telemetry.flight.FlightRecorder` —
    recompile events land there too (with the run's trace_id), so the
    black box records the GL106-at-runtime verdicts alongside the
    resilience story.
    """

    def __init__(self, registry: Optional[MetricRegistry] = None,
                 trace_capacity: int = 200_000,
                 trace_path: Optional[str] = None, flight=None):
        self.registry = registry if registry is not None else MetricRegistry()
        self.tracer = Tracer(enabled=True, capacity=trace_capacity)
        self.flight = flight
        self.trace_id = new_trace_id()  # the RUN's trace context
        self.recompile = RecompileWatchdog(self.registry, self.tracer,
                                           flight=flight,
                                           trace_id=self.trace_id)
        self.stalls = StallDetector(self.registry, self.tracer)
        self.memory = MemoryWatermark(self.registry)
        self.trace_path = trace_path

    def snapshot(self) -> dict:
        """Registry snapshot plus watchdog verdicts — the JSON export."""
        snap = self.registry.snapshot()
        snap["trace_id"] = self.trace_id
        snap["watchdogs"] = {
            "recompile_events": [
                {"key": str(k), "from": old, "to": new}
                for k, old, new in self.recompile.events],
            "stager_starvation_events": self.stalls.starvation_count,
            "host_sync_stall_events": self.stalls.sync_stall_count,
            "blocks_observed": self.stalls.blocks_observed,
            "phase_fractions": self.stalls.fractions(),
            "memory_stats_available": self.memory.available,
        }
        snap["trace"] = {"span_count": len(self.tracer.events()),
                         "dropped_events": self.tracer.dropped_events}
        return snap

    def health_snapshot(self) -> dict:
        """The ``/healthz`` provider for a training run: watchdog
        verdicts; ``ok`` = no steady-state recompile and no host-sync
        stall observed."""
        return {
            "ok": (self.recompile.silent
                   and self.stalls.sync_stall_count == 0),
            "trace_id": self.trace_id,
            "recompiles": self.recompile.recompile_count,
            "stager_starvations": self.stalls.starvation_count,
            "host_sync_stalls": self.stalls.sync_stall_count,
            "blocks_observed": self.stalls.blocks_observed,
        }

    def finalize(self) -> Optional[str]:
        """Dump the Chrome trace if a path was configured."""
        if self.trace_path:
            return self.tracer.dump(self.trace_path)
        return None
