"""bigdl_tpu.telemetry — tracing, metrics, and runtime watchdogs.

The observability substrate under the training driver and the serving
engine (ISSUE 6; the foundation BigDL 2.0's cluster pipeline and TVM's
measurement-driven tuning both stand on):

- :class:`Tracer` — step-timeline spans (host-stack, H2D staging, jit
  dispatch, device wait, one-block-behind loss fetch, triggers),
  exported as Chrome-trace JSON; summarize with
  ``python -m tools.trace_report trace.json``;
- :class:`MetricRegistry` — counters, gauges, reservoir histograms with
  p50/p95/p99; ``utils/metrics.Metrics`` and
  ``serving/metrics.ServingMetrics`` are veneers over it;
- watchdogs — :class:`RecompileWatchdog` (GL106 discipline at runtime),
  :class:`StallDetector` (stager starvation / host-sync stalls),
  :class:`MemoryWatermark` (device allocator gauges where available).

Enable for training via ``Config.telemetry_enabled`` /
``BIGDL_TPU_TELEMETRY=1`` or per-run with
``optimizer.set_telemetry(True, trace_path="trace.json")``.

The whole package is host-side: enabling telemetry adds no dispatch, no
host↔device sync, and leaves the loss sequence bitwise unchanged
(gated in ``tests/test_telemetry.py``).
"""

from bigdl_tpu.telemetry.hooks import DriverTelemetry
from bigdl_tpu.telemetry.registry import (Counter, Gauge, Histogram,
                                          MetricRegistry, Reservoir)
from bigdl_tpu.telemetry.tracer import NULL_SPAN, PHASE_CATS, Tracer
from bigdl_tpu.telemetry.watchdog import (MemoryWatermark,
                                          RecompileWatchdog, StallDetector,
                                          jit_cache_size)

__all__ = [
    "Counter", "DriverTelemetry", "Gauge", "Histogram", "MemoryWatermark",
    "MetricRegistry", "NULL_SPAN", "PHASE_CATS", "RecompileWatchdog",
    "Reservoir", "StallDetector", "Tracer", "jit_cache_size",
]
