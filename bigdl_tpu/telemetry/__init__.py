"""bigdl_tpu.telemetry — tracing, metrics, and runtime watchdogs.

The observability substrate under the training driver and the serving
engine (ISSUE 6; the foundation BigDL 2.0's cluster pipeline and TVM's
measurement-driven tuning both stand on):

- :class:`Tracer` — step-timeline spans (host-stack, H2D staging, jit
  dispatch, device wait, one-block-behind loss fetch, triggers),
  exported as Chrome-trace JSON; summarize with
  ``python -m tools.trace_report trace.json``;
- :class:`MetricRegistry` — counters, gauges, reservoir histograms with
  p50/p95/p99; ``utils/metrics.Metrics`` and
  ``serving/metrics.ServingMetrics`` are veneers over it;
- watchdogs — :class:`RecompileWatchdog` (GL106 discipline at runtime),
  :class:`StallDetector` (stager starvation / host-sync stalls),
  :class:`MemoryWatermark` (device allocator gauges where available).

Round 2 (ISSUE 11) made the stack externally visible and
request-scoped:

- :class:`RequestContext` — per-request trace context (trace_id,
  tenant, deadline, ReplicaSet hop history) minted at ``submit()``,
  fan-in flow arrows in the Chrome trace;
- :class:`AdminServer` — ``/metrics`` (Prometheus text), ``/healthz``,
  ``/trace``, ``/flight``, ``/profile?seconds=N`` on a loopback-only
  stdlib http thread (``Config.admin_port``, off by default);
- :class:`FlightRecorder` — crash-surviving structured-event JSONL
  stream + bounded ring (``Config.flight_recorder_path``), joined with
  traces by ``python -m tools.obs_report``.

Enable for training via ``Config.telemetry_enabled`` /
``BIGDL_TPU_TELEMETRY=1`` or per-run with
``optimizer.set_telemetry(True, trace_path="trace.json")``.

The whole package is host-side: enabling telemetry adds no dispatch, no
host↔device sync, and leaves the loss sequence bitwise unchanged
(gated in ``tests/test_telemetry.py``).
"""

from bigdl_tpu.telemetry.admin import AdminServer, render_prometheus
from bigdl_tpu.telemetry.context import RequestContext, new_trace_id
from bigdl_tpu.telemetry.flight import FlightRecorder
from bigdl_tpu.telemetry.hooks import DriverTelemetry
from bigdl_tpu.telemetry.registry import (Counter, Gauge, Histogram,
                                          MetricRegistry, Reservoir)
from bigdl_tpu.telemetry.tracer import NULL_SPAN, PHASE_CATS, Tracer
from bigdl_tpu.telemetry.watchdog import (MemoryWatermark,
                                          RecompileWatchdog, StallDetector,
                                          jit_cache_size)

__all__ = [
    "AdminServer", "Counter", "DriverTelemetry", "FlightRecorder", "Gauge",
    "Histogram", "MemoryWatermark", "MetricRegistry", "NULL_SPAN",
    "PHASE_CATS", "RecompileWatchdog", "RequestContext", "Reservoir",
    "StallDetector", "Tracer", "jit_cache_size", "new_trace_id",
    "render_prometheus",
]
