"""Flight recorder — a crash-surviving structured-event black box.

The tracer (PR 6) answers "where did the time go" but dies with the
process; the flight recorder answers "what happened, in what order,
to which request" and SURVIVES the process: every recorded event is
appended to a JSONL file and flushed immediately, so even a SIGKILL'd
process leaves its event history on disk (gated by the subprocess kill
test in ``tests/test_obs_plane.py``).  Recorded events are the *rare,
load-bearing* state changes of the stack — health transitions, breaker
trips, failovers, sheds, rollbacks, recompiles, checkpoint commits,
preemption — each optionally carrying a ``trace_id`` so
``tools/obs_report.py`` can join the dump with a telemetry trace into
one post-mortem timeline.

Design rules (house discipline):

- **Provably inert when off.**  ``from_config()`` returns ``None`` for
  an empty ``Config.flight_recorder_path`` — every call site guards on
  ``flight is not None``, so the disabled path allocates nothing,
  opens nothing, and starts no thread.
- **Bounded.**  In memory: a ``deque(maxlen=capacity)`` ring.  On
  disk: the JSONL stream rotates to ``<path>.1`` past
  ``max_bytes`` — an always-on recorder may not grow without bound.
- **Host-side only.**  No jax import, no device work, no syncs —
  events ride boundaries the stack already crosses (a failover, a
  checkpoint commit), never add one (graftlint catalog note "events
  ride existing boundaries").
- **Clock-anchored.**  The meta header records a paired
  ``(unix_ns, perf_ns)`` sample so obs_report can place tracer spans
  (``perf_counter_ns`` time base) and flight events on ONE wall-clock
  axis.

Writing from a signal handler is deliberately NOT done here (fsync in
a handler is how files get torn — the preemption lesson of PR 7); the
driver records its ``preemption`` event on the driver thread after the
flag-only handler fires, and crashes are covered by the append-per-
event stream plus the driver's ``run_crash`` event in its ``finally``.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

logger = logging.getLogger("bigdl_tpu.telemetry")

SCHEMA_VERSION = 1


class FlightRecorder:
    """Bounded structured-event ring with an append-and-flush JSONL
    stream (see module docstring).

    ``path=None`` keeps the recorder memory-only (tests, ad-hoc use);
    ``dump()`` then writes a one-shot snapshot.  With ``path`` set,
    the stream IS the dump — obs_report reads either format.
    """

    def __init__(self, path: Optional[str] = None, capacity: int = 4096,
                 max_bytes: int = 8 << 20):
        self.path = path or None
        self.capacity = max(1, int(capacity))
        self.max_bytes = max(1 << 16, int(max_bytes))
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)  # guarded-by: _lock
        self._file = None   # guarded-by: _lock
        self._bytes = 0     # guarded-by: _lock
        self.meta = {
            "schema": SCHEMA_VERSION,
            "pid": os.getpid(),
            "unix_ns": time.time_ns(),
            "perf_ns": time.perf_counter_ns(),
        }
        if self.path:
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            self._file = open(self.path, "a", buffering=1)
            # count what a previous process already appended, or the
            # rotation bound silently stops holding across restarts
            self._bytes = self._file.tell()
            try:
                self._write_line({"meta": self.meta})
            except OSError as e:
                self._disable_stream_locked(e)

    # ----------------------------------------------------------- record
    def record(self, event: str, cat: str = "event",
               trace_id: Optional[str] = None, **fields) -> dict:
        """Append one event (thread-safe; flushed to disk before
        returning when streaming).  ``fields`` must be JSON-able cheap
        scalars — this runs on failure paths, keep it allocation-light.

        Disk trouble NEVER propagates: record() is called from the
        ReplicaSet supervisor, the checkpoint writer, and the driver's
        crash ``finally`` — an OSError escaping here would kill the
        supervisor (stranding requests) or mask the training exception
        it was recording.  On a write failure the stream is disabled
        with one warning and the recorder degrades to memory-only."""
        entry = {"event": event, "cat": cat,
                 "t_unix": time.time(),
                 "perf_ns": time.perf_counter_ns()}
        if trace_id is not None:
            entry["trace_id"] = trace_id
        if fields:
            entry.update(fields)
        with self._lock:
            self._ring.append(entry)
            if self._file is not None:
                try:
                    self._write_line(entry)
                except OSError as e:
                    self._disable_stream_locked(e)
        return entry

    # guarded-by: _lock  (also reached from __init__, pre-sharing)
    def _disable_stream_locked(self, exc: OSError) -> None:
        logger.warning(
            "flight recorder stream to %s failed (%s) — disk recording "
            "disabled, in-memory ring continues", self.path, exc)
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
        self._file = None

    # guarded-by: _lock  (or __init__, before the object is shared)
    def _write_line(self, obj: dict) -> None:
        # line-buffered file + explicit flush → a SIGKILL loses at most
        # the in-flight line
        line = json.dumps(obj, default=str) + "\n"
        self._file.write(line)
        self._file.flush()
        self._bytes += len(line)
        if self._bytes > self.max_bytes:
            self._rotate_locked()

    # guarded-by: _lock
    def _rotate_locked(self) -> None:
        try:
            self._file.close()
            os.replace(self.path, self.path + ".1")
        except OSError:  # rotation is best-effort, never fatal
            pass
        self._file = open(self.path, "a", buffering=1)
        self._bytes = 0
        self._write_header_after_rotate()

    # guarded-by: _lock
    def _write_header_after_rotate(self) -> None:
        line = json.dumps({"meta": self.meta, "rotated": True}) + "\n"
        self._file.write(line)
        self._file.flush()
        self._bytes += len(line)

    # ------------------------------------------------------------- read
    def events(self) -> List[dict]:
        with self._lock:
            return [dict(e) for e in self._ring]

    def events_for(self, trace_id: str) -> List[dict]:
        """The retained events carrying one trace id — the in-process
        version of the obs_report request story."""
        return [e for e in self.events() if e.get("trace_id") == trace_id]

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for e in self.events():
            out[e["event"]] = out.get(e["event"], 0) + 1
        return out

    # ------------------------------------------------------------- dump
    def dump(self, path: str) -> str:
        """One-shot ring snapshot as a JSON object (atomic tmp+rename;
        the streamed JSONL at ``self.path`` is independent of this)."""
        blob = {"meta": self.meta, "events": self.events()}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(blob, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                finally:
                    self._file = None


# ---------------------------------------------------------------- loading
def load_dump(path: str) -> dict:
    """Read a flight dump — streamed JSONL (meta header line + one
    event per line; torn final lines from a crash are skipped) or the
    one-shot ``dump()`` JSON object.  Returns ``{"meta": {...},
    "events": [...]}``."""
    with open(path) as f:
        head = f.read(1)
        f.seek(0)
        if head == "{" and _looks_like_object_dump(path):
            blob = json.load(f)
            return {"meta": blob.get("meta", {}),
                    "events": blob.get("events", [])}
        meta: dict = {}
        events: List[dict] = []
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail of a killed process — expected
            if "meta" in obj and "event" not in obj:
                meta = obj["meta"]
            else:
                events.append(obj)
        return {"meta": meta, "events": events}


def _looks_like_object_dump(path: str) -> bool:
    """A ``dump()`` file is ONE json object spanning the whole file; a
    JSONL stream is one object per line.  Distinguish by whether the
    first line parses alone."""
    with open(path) as f:
        first = f.readline()
    try:
        obj = json.loads(first)
    except json.JSONDecodeError:
        return True  # multi-line object
    return isinstance(obj, dict) and "events" in obj


# ------------------------------------------------- process-wide singleton
_install_lock = threading.Lock()
# write-guarded-by: _install_lock
_installed: Optional[FlightRecorder] = None


def install(recorder: Optional[FlightRecorder]) -> None:
    """Install (or clear, with None) the process-wide recorder that
    ``from_config()`` call sites pick up."""
    global _installed
    with _install_lock:
        _installed = recorder


def current() -> Optional[FlightRecorder]:
    return _installed


def from_config() -> Optional[FlightRecorder]:
    """The process-wide recorder per ``Config.flight_recorder_path``
    ("" = off → None, the provably-inert state).  First live call
    creates and installs the singleton; an explicitly ``install()``-ed
    recorder always wins (tests, embedders)."""
    global _installed
    if _installed is not None:
        return _installed
    from bigdl_tpu.utils.config import get_config
    cfg = get_config()
    path = getattr(cfg, "flight_recorder_path", "") or ""
    if not path:
        return None
    with _install_lock:
        if _installed is None:
            _installed = FlightRecorder(
                path, capacity=cfg.flight_recorder_capacity)
    return _installed


def reset() -> None:
    """Drop the singleton (tests)."""
    global _installed
    with _install_lock:
        if _installed is not None:
            _installed.close()
        _installed = None
